"""SQL frontend end-to-end: parse -> plan -> run -> compare with the
hand-built pipelines / pandas oracles (reference: planner tests +
e2e sqllogictest, SURVEY §4)."""

import pandas as pd
import pytest

from risingwave_tpu.connectors.nexmark import (
    AUCTION_SCHEMA,
    BID_SCHEMA,
    PERSON_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
)
from risingwave_tpu.sql import Catalog, StreamPlanner, parse
from risingwave_tpu.sql import parser as P


@pytest.fixture
def catalog():
    return Catalog(
        {"bid": BID_SCHEMA, "person": PERSON_SCHEMA, "auction": AUCTION_SCHEMA}
    )


def test_parse_shapes():
    stmt = parse(
        "CREATE MATERIALIZED VIEW mv AS "
        "SELECT auction, count(*) AS cnt "
        "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
        "WHERE price > 100 GROUP BY auction, window_start"
    )
    assert isinstance(stmt, P.CreateMaterializedView)
    sel = stmt.select
    assert isinstance(sel.from_, P.WindowTVF)
    assert sel.from_.slide_ms == 2000 and sel.from_.size_ms == 10000
    assert sel.group_by == (P.Ident("auction"), P.Ident("window_start"))
    assert isinstance(sel.where, P.BinaryOp)


def test_sql_q5_lite_matches_pandas(catalog):
    planner = StreamPlanner(catalog, capacity=1 << 12)
    mv = planner.plan(
        "CREATE MATERIALIZED VIEW q5 AS "
        "SELECT auction, window_start, count(*) AS num "
        "FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
        "GROUP BY auction, window_start"
    )
    assert mv.inputs == {"bid": "single"}
    gen = NexmarkGenerator(NexmarkConfig())
    rows = {"auction": [], "date_time": []}
    for _ in range(3):
        bid = gen.next_chunks(1500, 2048)["bid"]
        d = bid.to_numpy(False)
        rows["auction"].extend(d["auction"].tolist())
        rows["date_time"].extend(d["date_time"].tolist())
        mv.pipeline.push(bid)
        mv.pipeline.barrier()

    df = pd.DataFrame(rows)
    parts = []
    for k in range(5):
        ws = ((df.date_time - 10_000) // 2000 + 1) * 2000 + k * 2000
        sub = df[ws <= df.date_time].copy()
        sub["window_start"] = ws[ws <= df.date_time]
        parts.append(sub)
    allw = pd.concat(parts)
    want = {
        (int(a), int(w)): (int(c),)
        for (a, w), c in allw.groupby(["auction", "window_start"]).size().items()
    }
    assert mv.mview.snapshot() == want


def test_sql_filter_project_rowid(catalog):
    planner = StreamPlanner(catalog, capacity=1 << 12)
    mv = planner.plan(
        "CREATE MATERIALIZED VIEW cheap AS "
        "SELECT auction, price * 2 AS dbl FROM bid WHERE price < 500"
    )
    gen = NexmarkGenerator(NexmarkConfig())
    bid = gen.next_chunks(1000, 1024)["bid"]
    d = bid.to_numpy(False)
    mv.pipeline.push(bid)
    mv.pipeline.barrier()
    snap = mv.mview.snapshot()
    keep = d["price"] < 500
    assert len(snap) == int(keep.sum())
    got_pairs = sorted((v[0], v[1]) for v in snap.values())
    want_pairs = sorted(
        zip(d["auction"][keep].tolist(), (d["price"][keep] * 2).tolist())
    )
    assert got_pairs == want_pairs


def test_sql_q8_join_matches_pandas(catalog):
    planner = StreamPlanner(catalog, capacity=1 << 12)
    mv = planner.plan(
        "CREATE MATERIALIZED VIEW q8 AS "
        "SELECT p.id, p.name, p.starttime FROM "
        "(SELECT id, name, window_start AS starttime "
        " FROM TUMBLE(person, date_time, INTERVAL '10' SECOND) "
        " GROUP BY id, name, window_start) AS p "
        "JOIN "
        "(SELECT seller, window_start AS astarttime "
        " FROM TUMBLE(auction, date_time, INTERVAL '10' SECOND) "
        " GROUP BY seller, window_start) AS a "
        "ON p.id = a.seller AND p.starttime = a.astarttime"
    )
    assert mv.inputs == {"person": "left", "auction": "right"}

    gen = NexmarkGenerator(NexmarkConfig())
    all_p = {"id": [], "name": [], "date_time": []}
    all_a = {"seller": [], "date_time": []}
    for _ in range(6):
        chunks = gen.next_chunks(2000, 2048)
        if chunks["person"] is not None:
            d = chunks["person"].to_numpy(False)
            for k in all_p:
                all_p[k].extend(d[k].tolist())
            mv.pipeline.push_left(chunks["person"])
        if chunks["auction"] is not None:
            d = chunks["auction"].to_numpy(False)
            for k in all_a:
                all_a[k].extend(d[k].tolist())
            mv.pipeline.push_right(chunks["auction"])
        mv.pipeline.barrier()

    pdf = pd.DataFrame(all_p)
    adf = pd.DataFrame(all_a)
    pdf["starttime"] = (pdf.date_time // 10_000) * 10_000
    adf["astarttime"] = (adf.date_time // 10_000) * 10_000
    p = pdf[["id", "name", "starttime"]].drop_duplicates()
    a = adf[["seller", "astarttime"]].drop_duplicates()
    m = p.merge(
        a, left_on=["id", "starttime"], right_on=["seller", "astarttime"]
    )
    # mv pk = left pk + right pk
    want = {
        (int(r.id), int(r.name), int(r.starttime), int(r.seller),
         int(r.astarttime)): ()
        for r in m.itertuples()
    }
    got = mv.mview.snapshot()
    assert len(want) > 20
    assert set(got) == set(want)


def _q8ish_inputs():
    # low event rate -> event time spans several 10s tumble windows
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=400))
    all_p = {"id": [], "name": [], "date_time": []}
    all_a = {"seller": [], "date_time": []}
    feeds = []
    for _ in range(6):
        chunks = gen.next_chunks(2000, 2048)
        feeds.append(chunks)
        if chunks["person"] is not None:
            d = chunks["person"].to_numpy(False)
            for k in all_p:
                all_p[k].extend(d[k].tolist())
        if chunks["auction"] is not None:
            d = chunks["auction"].to_numpy(False)
            for k in all_a:
                all_a[k].extend(d[k].tolist())
    pdf = pd.DataFrame(all_p)
    adf = pd.DataFrame(all_a)
    pdf["starttime"] = (pdf.date_time // 10_000) * 10_000
    adf["astarttime"] = (adf.date_time // 10_000) * 10_000
    p = pdf[["id", "name", "starttime"]].drop_duplicates()
    a = adf[["seller", "astarttime"]].drop_duplicates()
    return feeds, p, a


def _feed(mv, feeds):
    for chunks in feeds:
        if chunks["person"] is not None:
            mv.pipeline.push_left(chunks["person"])
        if chunks["auction"] is not None:
            mv.pipeline.push_right(chunks["auction"])
        mv.pipeline.barrier()


_JOIN_SQL = (
    "CREATE MATERIALIZED VIEW j AS "
    "SELECT p.id, p.name, p.starttime{sel_a} FROM "
    "(SELECT id, name, window_start AS starttime "
    " FROM TUMBLE(person, date_time, INTERVAL '10' SECOND) "
    " GROUP BY id, name, window_start) AS p "
    "{jt} JOIN "
    "(SELECT seller, window_start AS astarttime "
    " FROM TUMBLE(auction, date_time, INTERVAL '10' SECOND) "
    " GROUP BY seller, window_start) AS a "
    "ON p.id = a.seller AND p.starttime = a.astarttime"
)


def test_sql_left_outer_join_matches_pandas(catalog):
    planner = StreamPlanner(catalog, capacity=1 << 12)
    mv = planner.plan(_JOIN_SQL.format(jt="LEFT OUTER", sel_a=", a.seller"))
    feeds, p, a = _q8ish_inputs()
    _feed(mv, feeds)
    m = p.merge(
        a, left_on=["id", "starttime"], right_on=["seller", "astarttime"],
        how="left",
    )
    # pk = left pk + right pk; unmatched rows carry NULL (None) right pks
    want = {}
    for r in m.itertuples():
        if pd.isna(r.seller):
            want[(int(r.id), int(r.name), int(r.starttime), None, None)] = ()
        else:
            want[
                (int(r.id), int(r.name), int(r.starttime), int(r.seller),
                 int(r.astarttime))
            ] = ()
    got = mv.mview.snapshot()
    assert len(want) > 20 and any(k[3] is None for k in want)
    assert got == want


def test_sql_left_semi_anti_join_matches_pandas(catalog):
    feeds, p, a = _q8ish_inputs()
    matched = p.merge(
        a, left_on=["id", "starttime"], right_on=["seller", "astarttime"]
    )[["id", "name", "starttime"]].drop_duplicates()
    mkey = {
        (int(r.id), int(r.name), int(r.starttime))
        for r in matched.itertuples()
    }
    allp = {
        (int(r.id), int(r.name), int(r.starttime)) for r in p.itertuples()
    }
    for jt, want_keys in (("LEFT SEMI", mkey), ("LEFT ANTI", allp - mkey)):
        planner = StreamPlanner(Catalog(catalog.tables), capacity=1 << 12)
        mv = planner.plan(_JOIN_SQL.format(jt=jt, sel_a=""))
        _feed(mv, feeds)
        got = mv.mview.snapshot()
        assert set(got) == want_keys, jt
    # anti+semi partition the left side
    assert mkey and (allp - mkey)


def test_sql_group_by_over_left_join_matches_pandas(catalog):
    """The q7 shape: HashAgg over a (retractable) join output —
    previously rejected with 'GROUP BY over a join not supported'."""
    planner = StreamPlanner(catalog, capacity=1 << 12)
    mv = planner.plan(
        "CREATE MATERIALIZED VIEW g AS "
        "SELECT p.starttime, count(*) AS cnt, max(a.seller) AS mx FROM "
        "(SELECT id, name, window_start AS starttime "
        " FROM TUMBLE(person, date_time, INTERVAL '10' SECOND) "
        " GROUP BY id, name, window_start) AS p "
        "LEFT JOIN "
        "(SELECT seller, window_start AS astarttime "
        " FROM TUMBLE(auction, date_time, INTERVAL '10' SECOND) "
        " GROUP BY seller, window_start) AS a "
        "ON p.id = a.seller AND p.starttime = a.astarttime "
        "GROUP BY p.starttime"
    )
    feeds, p, a = _q8ish_inputs()
    _feed(mv, feeds)
    m = p.merge(
        a, left_on=["id", "starttime"], right_on=["seller", "astarttime"],
        how="left",
    )
    grp = m.groupby("starttime").agg(
        cnt=("id", "size"), mx=("seller", "max")
    )
    want = {
        (int(w),): (
            int(r.cnt),
            None if pd.isna(r.mx) else int(r.mx),
        )
        for w, r in grp.iterrows()
    }
    got = mv.mview.snapshot()
    assert len(want) > 2
    assert got == want


def test_sql_semi_join_rejects_other_side_columns(catalog):
    planner = StreamPlanner(catalog, capacity=1 << 12)
    with pytest.raises(ValueError, match="not emitted"):
        planner.plan(_JOIN_SQL.format(jt="LEFT SEMI", sel_a=", a.seller"))
    # ... and in WHERE (would KeyError at runtime if planned)
    with pytest.raises(ValueError, match="not emitted"):
        planner.plan(
            _JOIN_SQL.format(jt="LEFT SEMI", sel_a="")
            + " WHERE a.astarttime > 0"
        )


def test_join_words_stay_contextual():
    """LEFT/RIGHT/FULL/OUTER/SEMI/ANTI are not reserved: still valid as
    column names and aliases elsewhere."""
    sel = parse("SELECT anti, semi FROM t WHERE outer > 1")
    assert sel.items[0].expr == P.Ident("anti")
    sel = parse("SELECT x FROM t AS left")  # AS forces the alias
    assert sel.from_.alias == "left"
    assert (
        parse("SELECT x FROM t LEFT OUTER JOIN u ON t.a = u.b").from_.join_type
        == "left"
    )


def test_sql_errors(catalog):
    planner = StreamPlanner(catalog)
    with pytest.raises(ValueError, match="not in GROUP BY"):
        planner.plan("SELECT price, count(*) c FROM bid GROUP BY auction")
    with pytest.raises(KeyError, match="unknown column"):
        planner.plan("SELECT nope FROM bid")
    with pytest.raises(SyntaxError):
        parse("SELECT FROM bid")


def test_having_and_distinct():
    """HAVING over streaming + batch group-bys; SELECT DISTINCT as a
    dedup rewrite (VERDICT r3 missing #10: SQL breadth)."""
    from risingwave_tpu.frontend.session import SqlSession

    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "INSERT INTO t VALUES (1, 10), (1, 20), (2, 5), (2, 1), (3, 100),"
        " (1, 10)"
    )
    # batch HAVING
    out, _ = s.execute(
        "SELECT k, sum(v) AS s FROM t GROUP BY k HAVING s > 10 ORDER BY k"
    )
    assert list(out["k"]) == [1, 3] and list(out["s"]) == [40, 100]
    # streaming HAVING: the MV holds only groups past the threshold,
    # and groups FALL OUT when retractions drop them below it
    s.execute(
        "CREATE MATERIALIZED VIEW big AS "
        "SELECT k, sum(v) AS s, count(*) AS c FROM t GROUP BY k "
        "HAVING s > 10"
    )
    out, _ = s.execute("SELECT k, s FROM big ORDER BY k")
    assert list(out["k"]) == [1, 3] and list(out["s"]) == [40, 100]
    s.execute("INSERT INTO t VALUES (2, 50)")
    out, _ = s.execute("SELECT k, s FROM big ORDER BY k")
    assert list(out["k"]) == [1, 2, 3]
    # batch DISTINCT
    out, _ = s.execute("SELECT DISTINCT k FROM t ORDER BY k")
    assert list(out["k"]) == [1, 2, 3]
    # streaming DISTINCT MV (dedup rewrite)
    s.execute("CREATE MATERIALIZED VIEW dk AS SELECT DISTINCT k FROM t")
    out, _ = s.execute("SELECT k FROM dk ORDER BY k")
    assert list(out["k"]) == [1, 2, 3]


def test_having_decimal_group_key_scales_literal():
    """HAVING literals compared against DECIMAL group KEYS rewrite into
    the scaled-int lane domain (review r4: raw literals would compare
    at the wrong magnitude and pass every group)."""
    from risingwave_tpu.frontend.session import SqlSession

    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE pay (uid BIGINT, amt DECIMAL(10,2))")
    s.execute(
        "INSERT INTO pay VALUES (1, 0.50), (2, 0.50), (3, 2.00), (4, 9.99)"
    )
    out, _ = s.execute(
        "SELECT amt, count(*) AS c FROM pay GROUP BY amt "
        "HAVING amt > 1.5 ORDER BY c"
    )
    # unscaled comparison (raw 0.50-lane=50 > 1.5) would keep ALL groups
    assert len(out["c"]) == 2 and sorted(out["c"].tolist()) == [1, 1]


def test_having_null_aggregate_follows_sql_null_semantics():
    """A NULL aggregate output (sum over an all-NULL group) must make
    the HAVING predicate NULL -> group dropped, not compare the lane's
    numeric fill value (advisor r4: batch _having_filter stripped the
    __null companions before evaluation)."""
    from risingwave_tpu.frontend.session import SqlSession

    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, NULL), (1, NULL), (2, 5)")
    # group 1's sum is SQL NULL: HAVING s >= 0 must drop it, and
    # HAVING s = 0 must NOT resurrect it via the zero fill value
    out, _ = s.execute(
        "SELECT k, sum(v) AS s FROM t GROUP BY k HAVING s >= 0 ORDER BY k"
    )
    assert list(out["k"]) == [2]
    out, _ = s.execute(
        "SELECT k, sum(v) AS s FROM t GROUP BY k HAVING s = 0 ORDER BY k"
    )
    assert list(out["k"]) == []


def test_order_by_null_aggregate_sorts_last():
    """NULL aggregate outputs follow Postgres placement under ORDER BY:
    larger than every value — last under ASC, first under DESC — and a
    LIMIT must not let the numeric fill value beat a real group."""
    from risingwave_tpu.frontend.session import SqlSession

    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "INSERT INTO t VALUES (1, NULL), (1, NULL), (2, 5), (3, -2)"
    )
    out, _ = s.execute(
        "SELECT k, sum(v) AS s FROM t GROUP BY k ORDER BY s LIMIT 2"
    )
    assert list(out["k"]) == [3, 2] and list(out["s"]) == [-2, 5]
    out, _ = s.execute(
        "SELECT k, sum(v) AS s FROM t GROUP BY k ORDER BY s DESC"
    )
    assert list(out["k"]) == [1, 2, 3]
    assert out["s"][0] is None
