"""Batch FROM-subqueries (derived tables in batch SELECT).

Reference: the batch planner's derived-table scans — inner select
runs fully (WHERE/GROUP BY/ORDER BY/LIMIT), the outer scans its
result; NULL aggregate outputs stay SQL NULL through the nesting.
"""

import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def _sess():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10), (1, 20), (2, 5), (3, 7)")
    return s


def test_agg_over_derived_filter():
    s = _sess()
    out, _ = s.execute(
        "SELECT k, sum(v) AS sv FROM "
        "(SELECT k, v FROM t WHERE v > 6) AS d GROUP BY k ORDER BY k"
    )
    assert list(out["k"]) == [1, 3]
    assert list(out["sv"]) == [30, 7]


def test_derived_agg_then_outer_filter():
    s = _sess()
    out, _ = s.execute(
        "SELECT k2, sv FROM (SELECT k AS k2, sum(v) AS sv FROM t "
        "GROUP BY k) AS g WHERE sv > 6 ORDER BY k2"
    )
    assert list(out["k2"]) == [1, 3]
    assert list(out["sv"]) == [30, 7]


def test_nested_star_over_subquery_batch():
    s = _sess()
    out, _ = s.execute(
        "SELECT * FROM (SELECT * FROM t) AS s2 ORDER BY v"
    )
    assert list(out["v"]) == [5, 7, 10, 20]


def test_null_agg_output_through_nesting():
    s = _sess()
    out, _ = s.execute(
        "SELECT mn FROM (SELECT min(v) AS mn FROM t WHERE v > 99) AS e"
    )
    v = out["mn"][0]
    assert v is None or (not isinstance(v, str) and np.isnan(float(v)))


def test_inner_limit_applies_before_outer_agg():
    s = _sess()
    out, _ = s.execute(
        "SELECT count(*) AS n FROM "
        "(SELECT v FROM t ORDER BY v LIMIT 2) AS small"
    )
    assert out["n"][0] == 2


def test_order_by_nullable_subquery_lane():
    """Outer ORDER BY on a NULL-carrying subquery lane sorts NULLS
    LAST (review finding r5: it used to TypeError on None < int)."""
    s = _sess()
    out, _ = s.execute(
        "SELECT k, pv FROM (SELECT k, lag(v, 1) "
        "OVER (PARTITION BY k ORDER BY v) AS pv FROM t) AS d "
        "ORDER BY pv"
    )
    vals = list(out["pv"])
    nls = list(out.get("pv__null", [False] * len(vals)))
    non_null = [v for v, m in zip(vals, nls) if not m and v is not None]
    assert non_null == sorted(non_null)
    # NULLs sorted last
    tail_nulls = [m or v is None for v, m in zip(vals, nls)]
    assert tail_nulls == sorted(tail_nulls)


def test_group_by_null_key_from_subquery():
    """GROUP BY over a nullable subquery column keeps the NULL group
    (review finding r5: pandas' dropna default silently dropped it)."""
    s = _sess()
    out, _ = s.execute(
        "SELECT mn, count(*) AS c FROM "
        "(SELECT min(v) AS mn FROM t WHERE v > 99) AS e GROUP BY mn"
    )
    assert len(out["c"]) == 1 and out["c"][0] == 1
    assert out["mn"][0] is None or bool(
        np.asarray(out.get("mn__null", [False]))[0]
    ) or np.isnan(float(out["mn"][0]))
