"""Device hash table tests vs a Python-dict oracle.

Covers the scatter-claim-verify insert, duplicate keys inside one batch,
delete/re-insert (tombstones), read-only lookup, and multi-column keys.
"""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.ops import hash_table as ht


import pytest as _pytest

pytestmark = _pytest.mark.smoke


def _mk(capacity=256, dtypes=(jnp.int32,)):
    return ht.HashTable.create(capacity, dtypes)


def _insert(table, keys_np):
    keys = (jnp.asarray(keys_np, jnp.int32),)
    valid = jnp.ones(len(keys_np), jnp.bool_)
    table, slots, found, inserted = ht.lookup_or_insert(table, keys, valid)
    table = ht.set_live(table, slots, jnp.ones(len(keys_np), jnp.bool_))
    return table, np.asarray(slots), np.asarray(found), np.asarray(inserted)


def test_insert_and_find(rng):
    table = _mk()
    keys = rng.choice(10_000, size=100, replace=False).astype(np.int32)
    table, slots, found, inserted = _insert(table, keys)
    assert (slots >= 0).all()
    assert not found.any()
    # all distinct keys claimed distinct slots
    assert len(np.unique(slots)) == 100
    # second insert of the same keys: all found, same slots
    table2, slots2, found2, _ = _insert(table, keys)
    assert found2.all()
    np.testing.assert_array_equal(slots, slots2)


def test_duplicate_keys_in_batch(rng):
    table = _mk()
    keys = np.array([7, 7, 7, 9, 9, 11], np.int32)
    table, slots, found, inserted = _insert(table, keys)
    # duplicates resolve to the same slot
    assert slots[0] == slots[1] == slots[2]
    assert slots[3] == slots[4]
    assert slots[5] not in (slots[0], slots[3])
    assert len({slots[0], slots[3], slots[5]}) == 3


def test_delete_and_lookup():
    table = _mk()
    keys = np.arange(10, dtype=np.int32)
    table, slots, _, _ = _insert(table, keys)
    # delete even keys
    even = jnp.asarray(slots[::2], jnp.int32)
    table = ht.set_live(table, even, jnp.zeros(5, jnp.bool_))
    q = (jnp.asarray(keys, jnp.int32),)
    s, found = ht.lookup(table, q, jnp.ones(10, jnp.bool_))
    found = np.asarray(found)
    np.testing.assert_array_equal(found, [False, True] * 5)
    # slots still resolvable (tombstoned): re-insert flips live back
    table, slots2, found2, _ = _insert(table, keys[::2])
    s, found = ht.lookup(table, q, jnp.ones(10, jnp.bool_))
    assert np.asarray(found).all()


def test_absent_lookup():
    table = _mk()
    table, _, _, _ = _insert(table, np.arange(5, dtype=np.int32))
    s, found = ht.lookup(
        table, (jnp.asarray([100, 200], jnp.int32),), jnp.ones(2, jnp.bool_)
    )
    assert not np.asarray(found).any()
    np.testing.assert_array_equal(np.asarray(s), [-1, -1])


def test_multi_column_keys(rng):
    table = ht.HashTable.create(512, (jnp.int32, jnp.int32))
    a = rng.integers(0, 50, 200).astype(np.int32)
    b = rng.integers(0, 50, 200).astype(np.int32)
    keys = (jnp.asarray(a), jnp.asarray(b))
    valid = jnp.ones(200, jnp.bool_)
    table, slots, found, ins = ht.lookup_or_insert(table, keys, valid)
    slots = np.asarray(slots)
    assert (slots >= 0).all()
    oracle = {}
    for i, (x, y) in enumerate(zip(a, b)):
        oracle.setdefault((x, y), slots[i])
        assert oracle[(x, y)] == slots[i], "same key must map to same slot"
    assert len(set(oracle.values())) == len(oracle)


def test_high_load(rng):
    # fill to 50% load; all inserts must land within MAX_PROBE
    table = _mk(capacity=1024)
    keys = rng.choice(1 << 20, size=512, replace=False).astype(np.int32)
    table, slots, _, _ = _insert(table, keys)
    assert (slots >= 0).all()
    assert len(np.unique(slots)) == 512


def test_no_torn_slots_under_contention(rng):
    # Many distinct keys fighting for slots in a small table: every
    # claimed slot must hold the fp+keys of ONE real inserted key (the r1
    # four-scatter claim could interleave lanes from different rows).
    table = _mk(capacity=128)
    keys = rng.choice(1 << 20, size=64, replace=False).astype(np.int32)
    table, slots, _, inserted = _insert(table, keys)
    assert (slots >= 0).all() and inserted.all()
    claimed = np.flatnonzero(np.asarray(table.fp1) != 0)
    stored = np.asarray(table.keys[0])[claimed]
    assert set(stored) <= set(keys.tolist()), "chimera slot detected"
    # every claimed slot is one a row actually resolved to — no leaks
    assert set(claimed.tolist()) == set(slots.tolist())


def test_int64_keys_distinct_above_bit32():
    # BIGINT keys differing only in the high word must not merge
    table = ht.HashTable.create(256, (jnp.int64,))
    keys = np.array([5, 2**33 + 5, 2**40 + 5], np.int64)
    k = (jnp.asarray(keys),)
    valid = jnp.ones(3, jnp.bool_)
    table, slots, found, ins = ht.lookup_or_insert(table, k, valid)
    slots = np.asarray(slots)
    assert len(np.unique(slots)) == 3
    assert not np.asarray(found).any()
    stored = np.asarray(table.keys[0])[slots]
    np.testing.assert_array_equal(stored, keys)


def test_nan_float_keys_resolve():
    # NaN group keys must behave as ONE group (ordered-float totality);
    # IEEE NaN != NaN would livelock the claim-verify loop and leak slots
    table = ht.HashTable.create(256, (jnp.float64,))
    keys = np.array([np.nan, 1.5, np.nan, -0.0], np.float64)
    k = (jnp.asarray(keys),)
    table, slots, found, ins = ht.lookup_or_insert(table, k, jnp.ones(4, bool))
    slots = np.asarray(slots)
    assert (slots >= 0).all()
    assert slots[0] == slots[2], "all NaNs are one key"
    assert len({slots[0], slots[1], slots[3]}) == 3
    # exactly 3 slots claimed — no leaked chimera/NaN-retry slots
    assert int(np.sum(np.asarray(table.fp1) != 0)) == 3
    s2, f2 = ht.lookup(table, k, jnp.ones(4, bool))
    np.testing.assert_array_equal(np.asarray(s2), slots)


def test_first_occurrence_mask():
    slots = jnp.asarray(np.array([3, 5, 3, 7, 5, 3], np.int32))
    valid = jnp.asarray(np.array([1, 1, 1, 1, 1, 0], np.bool_))
    m = np.asarray(ht.first_occurrence_mask(slots, valid))
    np.testing.assert_array_equal(m, [True, True, False, True, False, False])
