"""TPC-H q3 / q17 streaming MVs (BASELINE.md config 5, VERDICT r4
missing #3: multi-way joins + scalar subqueries).

- q3: 3-way join + grouped agg. The planner lowers the nested join
  into a tree of hidden 2-way-join MVs connected by subscription edges
  (the reference fragments an n-way join into a tree of 2-way
  StreamHashJoins, optimizer over e2e_test/tpch).
- q17: correlated scalar subquery (``l_quantity < (SELECT 0.2 *
  avg(l_quantity) ... WHERE l_partkey = p_partkey)``) decorrelated
  into a join against a grouped sum/count MV with the comparison
  multiplied through — exact integer algebra, no division
  (binder/expr/subquery.rs:22 apply→join rewrite, narrowed).

Monetary values are integer cents; dates are yyyymmdd ints.
"""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke

Q3_SQL = (
    "CREATE MATERIALIZED VIEW q3 AS "
    "SELECT l.l_orderkey, sum(l.rev) AS revenue, o.o_orderdate, "
    "o.o_shippriority "
    "FROM (SELECT o_orderkey, o_custkey, o_orderdate, o_shippriority "
    "      FROM orders WHERE o_orderdate < 19950315) AS o "
    "JOIN (SELECT c_custkey FROM customer WHERE c_mktsegment = 1) AS c "
    "  ON c.c_custkey = o.o_custkey "
    "JOIN (SELECT l_orderkey, l_extendedprice * (100 - l_discount) AS rev, "
    "             l_shipdate "
    "      FROM lineitem WHERE l_shipdate > 19950315) AS l "
    "  ON l.l_orderkey = o.o_orderkey "
    "GROUP BY l.l_orderkey, o.o_orderdate, o.o_shippriority"
)

Q17_SQL = (
    "CREATE MATERIALIZED VIEW q17 AS "
    "SELECT sum(l.l_extendedprice) / 7 AS avg_yearly "
    "FROM (SELECT l_partkey, l_quantity, l_extendedprice FROM lineitem) AS l "
    "JOIN (SELECT p_partkey FROM part "
    "      WHERE p_brand = 23 AND p_container = 5) AS p "
    "  ON p.p_partkey = l.l_partkey "
    "WHERE l.l_quantity < (SELECT 0.2 * avg(l_quantity) FROM lineitem "
    "                      WHERE l_partkey = p.p_partkey)"
)


def _session():
    return SqlSession(Catalog({}), capacity=1 << 10)


def test_tpch_q3_three_way_join_agg():
    s = _session()
    s.execute(
        "CREATE TABLE customer (c_custkey BIGINT PRIMARY KEY, "
        "c_mktsegment BIGINT)"
    )
    s.execute(
        "CREATE TABLE orders (o_orderkey BIGINT PRIMARY KEY, "
        "o_custkey BIGINT, o_orderdate BIGINT, o_shippriority BIGINT)"
    )
    s.execute(
        "CREATE TABLE lineitem (l_orderkey BIGINT, l_extendedprice BIGINT, "
        "l_discount BIGINT, l_shipdate BIGINT)"
    )
    s.execute(Q3_SQL)
    s.execute("INSERT INTO customer VALUES (1, 1), (2, 2), (3, 1)")
    s.execute(
        "INSERT INTO orders VALUES (10, 1, 19950101, 0), "
        "(11, 2, 19950101, 0), (12, 3, 19950110, 1), (13, 1, 19960101, 0)"
    )
    s.execute(
        "INSERT INTO lineitem VALUES (10, 1000, 10, 19950401), "
        "(10, 500, 0, 19950501), (11, 700, 0, 19950401), "
        "(12, 200, 50, 19960101), (13, 900, 0, 19970101), "
        "(10, 100, 0, 19940101)"
    )
    out, _ = s.execute(
        "SELECT l_orderkey, revenue, o_orderdate, o_shippriority "
        "FROM q3 ORDER BY l_orderkey"
    )
    # order 10 (cust 1 / seg 1 / date ok): 1000*90 + 500*100 = 140000
    # (the 19940101 shipment is too early); order 11: wrong segment;
    # order 12: 200*50; order 13: order date too late
    assert list(out["l_orderkey"]) == [10, 12]
    assert list(out["revenue"]) == [140000, 10000]
    assert list(out["o_shippriority"]) == [0, 1]
    # incremental: a new qualifying shipment updates order 10's revenue
    s.execute("INSERT INTO lineitem VALUES (10, 10, 0, 19950601)")
    out, _ = s.execute(
        "SELECT l_orderkey, revenue FROM q3 ORDER BY l_orderkey"
    )
    assert list(out["revenue"]) == [141000, 10000]


def test_tpch_q17_correlated_scalar_subquery():
    s = _session()
    s.execute(
        "CREATE TABLE lineitem (l_partkey BIGINT, l_quantity BIGINT, "
        "l_extendedprice BIGINT)"
    )
    s.execute(
        "CREATE TABLE part (p_partkey BIGINT PRIMARY KEY, p_brand BIGINT, "
        "p_container BIGINT)"
    )
    s.execute(Q17_SQL)
    s.execute("INSERT INTO part VALUES (1, 23, 5), (2, 23, 5), (3, 9, 9)")
    # part 1: qty 10,100,100 -> 0.2*avg = 14 -> qty 10 counts (111)
    # part 2: qty 50,50 -> threshold 10 -> none; part 3: wrong brand
    s.execute(
        "INSERT INTO lineitem VALUES (1, 10, 111), (1, 100, 222), "
        "(1, 100, 333), (2, 50, 444), (2, 50, 555), (3, 1, 666)"
    )
    out, _ = s.execute("SELECT avg_yearly FROM q17")
    assert list(out["avg_yearly"]) == [111 // 7]
    # new cheap lineitem drags part 1's avg to 53.5 -> threshold 10.7:
    # qty 10 stays, qty 4 joins -> (111 + 777) / 7
    s.execute("INSERT INTO lineitem VALUES (1, 4, 777)")
    out, _ = s.execute("SELECT avg_yearly FROM q17")
    assert list(out["avg_yearly"]) == [(111 + 777) // 7]


def test_four_way_join_lowers_to_mv_tree():
    """Left-deep 4-way join: two levels of hidden aux MVs."""
    s = _session()
    s.execute("CREATE TABLE a (ak BIGINT, av BIGINT)")
    s.execute("CREATE TABLE b (bk BIGINT, bv BIGINT)")
    s.execute("CREATE TABLE c (ck BIGINT, cv BIGINT)")
    s.execute("CREATE TABLE d (dk BIGINT, dv BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW j4 AS "
        "SELECT a.av, b.bv, c.cv, d.dv FROM "
        "(SELECT ak, av FROM a) AS a "
        "JOIN (SELECT bk, bv FROM b) AS b ON a.ak = b.bk "
        "JOIN (SELECT ck, cv FROM c) AS c ON c.ck = a.ak "
        "JOIN (SELECT dk, dv FROM d) AS d ON d.dk = a.ak"
    )
    aux = [f for f in s.runtime.fragments if f.startswith("j4__j")]
    assert len(aux) == 2  # ((a JOIN b) JOIN c) and its inner join
    s.execute("INSERT INTO a VALUES (1, 10), (2, 20)")
    s.execute("INSERT INTO b VALUES (1, 11), (3, 31)")
    s.execute("INSERT INTO c VALUES (1, 12), (2, 22)")
    s.execute("INSERT INTO d VALUES (1, 13)")
    out, _ = s.execute("SELECT av, bv, cv, dv FROM j4")
    assert list(out["av"]) == [10]
    assert (
        list(out["bv"]),
        list(out["cv"]),
        list(out["dv"]),
    ) == ([11], [12], [13])


def test_tpch_q17_graph_mode_matches_serial():
    """exec_mode='graph': the fragmenter must NOT drop the planner's
    aux MVs (review r5: decorrelated plans silently returned NULL in
    graph mode — the flat 2-way FROM dodges the session's syntactic
    nested-join gate, so the fragmenter itself falls back)."""
    s = SqlSession(Catalog({}), capacity=1 << 10, exec_mode="graph")
    s.execute(
        "CREATE TABLE lineitem (l_partkey BIGINT, l_quantity BIGINT, "
        "l_extendedprice BIGINT)"
    )
    s.execute(
        "CREATE TABLE part (p_partkey BIGINT PRIMARY KEY, p_brand BIGINT, "
        "p_container BIGINT)"
    )
    s.execute(Q17_SQL)
    s.execute("INSERT INTO part VALUES (1, 23, 5)")
    s.execute(
        "INSERT INTO lineitem VALUES (1, 10, 111), (1, 100, 222), "
        "(1, 100, 333)"
    )
    out, _ = s.execute("SELECT avg_yearly FROM q17")
    assert list(out["avg_yearly"]) == [111 // 7]


def test_tpch_q1_pricing_summary():
    """TPC-H q1 (pricing summary report): grouped sums, averages, and
    counts with extended aggregates — the canonical wide-agg shape
    (reference e2e_test/tpch q1; avg decomposes onto sum/count)."""
    from risingwave_tpu.frontend.session import SqlSession

    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute(
        "CREATE TABLE lineitem (l_returnflag BIGINT, l_linestatus BIGINT, "
        "l_quantity BIGINT, l_extendedprice BIGINT, l_discount BIGINT)"
    )
    s.execute(
        "CREATE MATERIALIZED VIEW q1 AS SELECT "
        "l_returnflag, l_linestatus, "
        "sum(l_quantity) AS sum_qty, "
        "sum(l_extendedprice) AS sum_base_price, "
        "avg(l_quantity) AS avg_qty, "
        "avg(l_extendedprice) AS avg_price, "
        "avg(l_discount) AS avg_disc, "
        "count(*) AS count_order "
        "FROM lineitem GROUP BY l_returnflag, l_linestatus"
    )
    import numpy as np

    rng = np.random.default_rng(17)
    rows = []
    for _ in range(200):
        rows.append((
            int(rng.integers(0, 2)), int(rng.integers(0, 2)),
            int(rng.integers(1, 50)), int(rng.integers(100, 10000)),
            int(rng.integers(0, 10)),
        ))
    vals = ", ".join(str(r) for r in rows)
    s.execute(f"INSERT INTO lineitem VALUES {vals}")
    out, _ = s.execute(
        "SELECT l_returnflag, l_linestatus, sum_qty, avg_qty, "
        "avg_price, avg_disc, count_order FROM q1 "
        "ORDER BY l_returnflag, l_linestatus"
    )
    # numpy oracle
    import collections

    groups = collections.defaultdict(list)
    for r in rows:
        groups[(r[0], r[1])].append(r)
    for i in range(len(out["count_order"])):
        key = (int(out["l_returnflag"][i]), int(out["l_linestatus"][i]))
        g = groups[key]
        assert out["count_order"][i] == len(g)
        assert out["sum_qty"][i] == sum(r[2] for r in g)
        assert out["avg_qty"][i] == pytest.approx(
            sum(r[2] for r in g) / len(g)
        )
        assert out["avg_price"][i] == pytest.approx(
            sum(r[3] for r in g) / len(g)
        )
        assert out["avg_disc"][i] == pytest.approx(
            sum(r[4] for r in g) / len(g)
        )
    assert len(out["count_order"]) == len(groups)
