"""End-to-end Nexmark q5-lite: generator -> hop window -> hash agg -> MV,
replayed through a pandas oracle (reference test discipline:
executor chain tests vs expected chunks, src/stream/src/executor/
test_utils.rs; e2e nexmark slt, e2e_test/nexmark/).
"""

import pandas as pd

from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.queries.nexmark_q import Q5_SLIDE_MS, Q5_WINDOW_MS, build_q5_lite


def _oracle_counts(bids: pd.DataFrame) -> dict:
    """Expand each bid into its hop windows and count per (auction, ws)."""
    size, slide = Q5_WINDOW_MS, Q5_SLIDE_MS
    factor = size // slide
    rows = []
    ts = bids["date_time"].to_numpy()
    first = ((ts - size) // slide + 1) * slide
    for k in range(factor):
        ws = first + k * slide
        ok = ws <= ts
        rows.append(
            pd.DataFrame(
                {"auction": bids["auction"].to_numpy()[ok], "window_start": ws[ok]}
            )
        )
    expanded = pd.concat(rows)
    g = expanded.groupby(["auction", "window_start"]).size()
    return {k: (v,) for k, v in g.items()}


def _run_pipeline(q5, gen, *, epochs, events_per_epoch, chunk_events, cap):
    all_bids = []
    for _ in range(epochs):
        done = 0
        while done < events_per_epoch:
            n = min(chunk_events, events_per_epoch - done)
            done += n
            chunks = gen.next_chunks(n, cap)
            if chunks["bid"] is not None:
                q5.pipeline.push(chunks["bid"])
                all_bids.append(
                    pd.DataFrame(
                        {
                            k: v
                            for k, v in chunks["bid"].to_numpy().items()
                            if k != "__op__"
                        }
                    )
                )
        q5.pipeline.barrier()
    return pd.concat(all_bids) if all_bids else pd.DataFrame()


def test_q5_lite_matches_pandas_oracle():
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    q5 = build_q5_lite(capacity=1 << 14, state_cleaning=False)
    bids = _run_pipeline(
        q5, gen, epochs=4, events_per_epoch=2000, chunk_events=500, cap=512
    )
    assert len(bids) > 1000
    assert q5.mview.snapshot() == _oracle_counts(bids)


def test_q5_lite_rehash_growth_preserves_results():
    """Tiny initial table forces repeated 2x rehash mid-stream."""
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=200_000))
    q5 = build_q5_lite(capacity=1 << 8, state_cleaning=False)
    bids = _run_pipeline(
        q5, gen, epochs=3, events_per_epoch=3000, chunk_events=600, cap=600
    )
    assert q5.agg.table.capacity > 1 << 8  # growth actually happened
    assert q5.mview.snapshot() == _oracle_counts(bids)


def test_q5_lite_state_cleaning_frees_closed_windows():
    """Watermarks close old windows: MV keeps their final counts while
    live device state shrinks (reference: watermark state cleaning)."""
    # 200 ev/s -> each 2000-event batch spans 10s of event time, so six
    # batches cover 60s and most 10s windows close under the watermark
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=200))
    q5 = build_q5_lite(capacity=1 << 14, state_cleaning=True)
    all_bids = []
    max_ts = 0
    for _ in range(6):
        chunks = gen.next_chunks(2000, 2048)
        bid = chunks["bid"]
        if bid is not None:
            q5.pipeline.push(bid)
            data = bid.to_numpy()
            max_ts = max(max_ts, int(data["date_time"].max()))
            all_bids.append(
                pd.DataFrame({k: v for k, v in data.items() if k != "__op__"})
            )
        q5.pipeline.barrier()
        # event-time watermark: HopWindowExecutor translates it into a
        # window_start watermark for the agg's state cleaning
        q5.pipeline.watermark("date_time", max_ts)
    bids = pd.concat(all_bids)
    # results still exact: closed windows keep final counts in the MV
    assert q5.mview.snapshot() == _oracle_counts(bids)
    # state actually freed: live groups only cover the last window span
    live = int(q5.agg.table.num_live())
    total = len(q5.mview.snapshot())
    assert live < total


def test_q5_lite_mid_epoch_watermark_loses_nothing():
    """A watermark arriving BETWEEN barriers (the normal streaming case)
    must not discard dirty un-flushed updates on expiring windows
    (code-review r2 finding #1)."""
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=200))
    q5 = build_q5_lite(capacity=1 << 14, state_cleaning=True)
    all_bids = []
    max_ts = 0
    for i in range(6):
        chunks = gen.next_chunks(2000, 2048)
        bid = chunks["bid"]
        if bid is not None:
            q5.pipeline.push(bid)
            data = bid.to_numpy()
            max_ts = max(max_ts, int(data["date_time"].max()))
            all_bids.append(
                pd.DataFrame({k: v for k, v in data.items() if k != "__op__"})
            )
        # watermark BEFORE the barrier — dirty groups expire mid-epoch
        q5.pipeline.watermark("date_time", max_ts)
        if i % 2 == 1:
            q5.pipeline.barrier()
    q5.pipeline.barrier()
    bids = pd.concat(all_bids)
    assert q5.mview.snapshot() == _oracle_counts(bids)


def test_q5_lite_no_recompile_across_epochs():
    """The fixed-capacity design must compile once and replay every
    epoch with zero recompiles (chunk.py design premise; VERDICT r1
    weak #8)."""
    from risingwave_tpu.executors import hash_agg, hop_window
    from risingwave_tpu.ops import agg as agg_ops

    kernels = (hash_agg._agg_step, hop_window._hop_step, agg_ops.flush)

    def cache_sizes():
        return tuple(k._cache_size() for k in kernels)

    gen = NexmarkGenerator(NexmarkConfig())
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    # warm up: one chunk + one barrier compiles everything
    chunks = gen.next_chunks(500, 512)
    q5.pipeline.push(chunks["bid"])
    q5.pipeline.barrier()
    before = cache_sizes()
    for _ in range(3):
        chunks = gen.next_chunks(500, 512)
        if chunks["bid"] is not None:
            q5.pipeline.push(chunks["bid"])
        q5.pipeline.barrier()
    assert cache_sizes() == before
