"""Sharded overflow -> grow/rescale instead of job death (VERDICT r4
next #7): a hot-key epoch that overflows a sharded op's static
capacity (exchange bucket / probe chain / emission cap) is healed by
the watchdog — the op rebuilds at 2x, durable state restores, and the
epoch replays to the exact result. No caller intervention.

Reference: the reschedule path of src/meta/src/stream/scale.rs:453
(capacity is the per-shard analogue of parallelism)."""

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.parallel import (
    ShardedDedup,
    ShardedHashAgg,
    flatten_stacked,
    make_mesh,
)
from risingwave_tpu.parallel.sharded_agg import stack_chunks
from risingwave_tpu.runtime import Pipeline
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.storage.object_store import MemObjectStore

pytestmark = pytest.mark.smoke

N = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


def _hot_chunks(rng, n_rows, hot_key=7):
    """Stacked (N, 64) chunk where ONE shard carries n_rows rows of a
    single key — the skew that overflows a static exchange bucket."""
    per_shard = []
    for i in range(N):
        if i == 0:
            cols = {
                "k": np.full(n_rows, hot_key, np.int64),
                "v": rng.integers(0, 10, n_rows).astype(np.int64),
            }
        else:
            cols = {"k": np.zeros(0, np.int64), "v": np.zeros(0, np.int64)}
        per_shard.append(StreamChunk.from_numpy(cols, 64))
    return per_shard


@pytest.mark.slow
def test_hot_key_overflow_heals_via_growth(mesh):
    """bucket_cap=8 cannot absorb a 64-row single-key epoch; the
    watchdog must double capacities until the replay commits, with the
    exact aggregate."""
    agg = ShardedHashAgg(
        mesh,
        ("k",),
        (AggCall("sum", "v", "s"), AggCall("count_star", None, "c")),
        {"k": jnp.int64, "v": jnp.int64},
        capacity=1 << 8,
        out_cap=1 << 8,
        bucket_cap=8,
        table_id="ovf.agg",
    )
    mview = MaterializeExecutor(
        pk=("k",), columns=("s", "c"), table_id="ovf.mview"
    )
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    rt.register("ovf", Pipeline([agg, mview]))

    rng = np.random.default_rng(5)
    per_shard = _hot_chunks(rng, 48)
    stacked = stack_chunks(per_shard)
    want_sum = int(np.sum(np.asarray(per_shard[0].to_numpy()["v"])))

    for _attempt in range(6):
        rt.push("ovf", stacked)
        before = rt.mgr.max_committed_epoch
        rt.barrier()
        if rt.mgr.max_committed_epoch > before:
            break
    else:
        raise AssertionError("hot epoch never committed")

    assert rt.auto_recoveries >= 1, "no overflow recovery ever fired"
    assert agg.bucket_cap >= 48, f"bucket never grew: {agg.bucket_cap}"
    got = {k[0]: v for k, v in mview.snapshot().items()}
    assert got == {7: (want_sum, 48)}

    # a second hot epoch at the grown shape commits first try
    before_recoveries = rt.auto_recoveries
    per_shard2 = _hot_chunks(rng, 48)
    want_sum2 = want_sum + int(
        np.sum(np.asarray(per_shard2[0].to_numpy()["v"]))
    )
    rt.push("ovf", stack_chunks(per_shard2))
    before = rt.mgr.max_committed_epoch
    rt.barrier()
    assert rt.mgr.max_committed_epoch > before
    assert rt.auto_recoveries == before_recoveries
    got = {k[0]: v for k, v in mview.snapshot().items()}
    assert got == {7: (want_sum2, 96)}


@pytest.mark.slow
def test_dedup_overflow_heals_and_keeps_exactness(mesh):
    """ShardedDedup with a tiny exchange bucket: the hot epoch heals by
    growth and the first-seen semantics stay exact across the replay
    (durable keys from earlier epochs are NOT re-emitted)."""
    dd = ShardedDedup(
        mesh,
        ("k",),
        {"k": jnp.int64},
        capacity=1 << 8,
        bucket_cap=8,
        table_id="ovfd.dd",
    )
    mview = MaterializeExecutor(pk=("k",), columns=(), table_id="ovfd.mv")

    class Flatten:
        def apply(self, chunk):
            return [flatten_stacked(chunk)]

        def on_barrier(self, b):
            return []

        def emit_watermark(self):
            return None

        def finish_barrier(self):
            return None

    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    rt.register("ovfd", Pipeline([dd, Flatten(), mview]))

    # epoch 1: smooth keys 0..31, commits clean
    smooth = [
        StreamChunk.from_numpy(
            {"k": np.arange(i * 4, i * 4 + 4, dtype=np.int64)}, 64
        )
        for i in range(N)
    ]
    rt.push("ovfd", stack_chunks(smooth))
    rt.barrier()
    assert len(mview.snapshot()) == 32

    # epoch 2: 48 duplicate rows of one NEW key + dups of old keys
    hot = []
    for i in range(N):
        if i == 0:
            ks = np.full(48, 999, np.int64)
        elif i == 1:
            ks = np.arange(0, 16, dtype=np.int64)  # all durable dups
        else:
            ks = np.zeros(0, np.int64)
        hot.append(StreamChunk.from_numpy({"k": ks}, 64))
    stacked = stack_chunks(hot)
    for _attempt in range(6):
        rt.push("ovfd", stacked)
        before = rt.mgr.max_committed_epoch
        rt.barrier()
        if rt.mgr.max_committed_epoch > before:
            break
    else:
        raise AssertionError("hot epoch never committed")

    assert rt.auto_recoveries >= 1
    snap = {k[0] for k in mview.snapshot()}
    assert snap == set(range(32)) | {999}


def test_growth_gives_up_after_bound(mesh):
    """An overflow that growth cannot cure (here: artificially pinned
    growth rounds) surfaces instead of looping forever."""
    agg = ShardedHashAgg(
        mesh,
        ("k",),
        (AggCall("count_star", None, "c"),),
        {"k": jnp.int64},
        capacity=1 << 8,
        bucket_cap=8,
        table_id="ovfg.agg",
    )
    agg._growth_rounds = 5  # pretend five doublings already happened
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    rt.register("ovfg", Pipeline([agg]))
    rng = np.random.default_rng(9)
    rt.push("ovfg", stack_chunks(_hot_chunks(rng, 48)))
    with pytest.raises(RuntimeError, match="capacity doublings"):
        rt.barrier()
