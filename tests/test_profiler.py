"""Dispatch-wall profiler: per-executor attribution, device-dispatch /
transfer accounting, Perfetto export (named threads, epoch flows),
slow-barrier auto-capture, stall-dump fallback, and the perf gate."""

import glob
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from risingwave_tpu import utils_sync_point as sync_point
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.metrics import REGISTRY
from risingwave_tpu.profiler import PROFILER, device_forensics
from risingwave_tpu.queries.nexmark_q import build_q5_lite
from risingwave_tpu.runtime import StreamingRuntime
from risingwave_tpu.storage.object_store import MemObjectStore

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    yield
    PROFILER.disable()
    PROFILER.reset()
    PROFILER.slow_barrier_ms = None
    PROFILER.capture_dir = None
    PROFILER._auto_captures = 0
    sync_point.reset()
    EVENT_LOG.clear()


def _rt_with_q5():
    rt = StreamingRuntime(MemObjectStore(), async_checkpoint=False)
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    rt.register("q5", q5.pipeline)
    return rt, q5


def _steady_chunk(events=2_000):
    gen = NexmarkGenerator(NexmarkConfig(first_event_rate=50_000))
    return gen.next_chunks(events, 1 << 11)["bid"].select(
        ["auction", "date_time"]
    )


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------


def test_executor_attribution_covers_dispatch_stage():
    """The dispatch stage decomposes into per-executor executor_ms
    entries (flush + barrier_apply + device wait) summing to within ε
    of the parent stage total — attribution, not decoration."""
    rt, q5 = _rt_with_q5()
    bid = _steady_chunk()
    rt.push("q5", bid)
    rt.barrier()  # warmup (compiles) stays unprofiled
    REGISTRY.histograms.pop("barrier_stage_ms", None)
    PROFILER.reset()
    PROFILER.enable(fence=True)
    for _ in range(3):
        rt.push("q5", bid)
        rt.barrier()
    PROFILER.disable()
    bd = REGISTRY.histograms["barrier_stage_ms"].summary()
    disp = sum(
        v["sum"]
        for k, v in bd.items()
        if "stage=dispatch" in k and "fragment=q5" in k
    )
    assert disp > 0
    h = REGISTRY.histograms["executor_ms"]
    covered = sum(
        v
        for k, v in h._sum.items()
        if dict(k)["phase"] in ("flush", "barrier_apply")
    )
    dw = REGISTRY.histograms.get("executor_device_wait_ms")
    if dw is not None:
        covered += sum(
            v
            for k, v in dw._sum.items()
            if dict(k)["phase"] in ("flush", "barrier_apply")
        )
    assert covered >= 0.85 * disp, (covered, disp, bd)
    assert covered <= disp * 1.05 + 1.0  # cannot exceed its parent
    # every label set carries the full (executor, fragment, phase) key
    for labels in h._sum:
        assert {k for k, _ in labels} == {"executor", "fragment", "phase"}


def test_dispatch_and_transfer_counters():
    """Kernel interposer: jitted-kernel calls land in
    device_dispatches_total{executor} with per-kernel detail; the
    barrier's staged-scalar materialization counts as a d2h transfer."""
    rt, q5 = _rt_with_q5()
    bid = _steady_chunk()
    rt.push("q5", bid)
    rt.barrier()
    PROFILER.reset()
    PROFILER.enable(fence=False)
    rt.push("q5", bid)
    rt.barrier()
    PROFILER.disable()
    counts = PROFILER.dispatch_counts()
    assert counts.get("HashAggExecutor", 0) >= 1
    kernels = PROFILER.kernel_counts()
    assert any(k.startswith("_agg") for k in kernels), kernels
    # finish_scalars runs jax.device_get at the barrier fence
    assert PROFILER.transfer_counts()["d2h"] >= 1
    # disable restores the patched kernels (no proxies left behind)
    import risingwave_tpu.executors.hash_agg as hash_agg_mod
    from risingwave_tpu.profiler import _KernelProxy

    assert not isinstance(hash_agg_mod._agg_step, _KernelProxy)


def test_dispatch_counts_deterministic_and_flat_in_steady_state():
    """Same seeded workload, fresh pipeline: identical per-epoch
    dispatch counts across runs, and flat across steady epochs (ties
    into the zero-recompile steady-state contract)."""
    bid = _steady_chunk()

    def run_once():
        q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
        q5.pipeline.push(bid)
        q5.pipeline.barrier()  # warm: compiles + first flush
        PROFILER.reset()
        PROFILER.enable(fence=False)
        per_epoch = []
        for _ in range(3):
            base = PROFILER.total_dispatches()
            q5.pipeline.push(bid)
            q5.pipeline.barrier()
            per_epoch.append(PROFILER.total_dispatches() - base)
        PROFILER.disable()
        return per_epoch

    a, b = run_once(), run_once()
    assert a == b, (a, b)
    assert len(set(a)) == 1, f"steady-state dispatch count drifted: {a}"


def test_profile_mode_off_overhead_under_1pct():
    """Profile-mode-off is one attribute check per call site: its
    measured unit cost times a generous per-barrier call count must be
    <1% of the steady-state barrier wall. And nothing may be recorded
    while off."""
    rt, q5 = _rt_with_q5()
    bid = _steady_chunk()
    rt.push("q5", bid)
    rt.barrier()  # warm
    REGISTRY.histograms.pop("executor_ms", None)
    t0 = time.perf_counter()
    n = 3
    for _ in range(n):
        rt.push("q5", bid)
        rt.barrier()
    steady_ms = (time.perf_counter() - t0) / n * 1e3
    assert "executor_ms" not in REGISTRY.histograms  # off records nothing
    # unit cost of the disabled hook (the _pcall branch)
    from risingwave_tpu.runtime.pipeline import _pcall

    ex = q5.pipeline.executors[0]
    sink = []

    def f(x=None):
        sink.append(None)
        sink.clear()
        return ()

    loops = 20_000
    t0 = time.perf_counter()
    for _ in range(loops):
        f(None)
    raw_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(loops):
        _pcall(ex, "apply", f, None)
    hook_s = time.perf_counter() - t0
    per_call_ms = max(hook_s - raw_s, 0.0) / loops * 1e3
    # ~4 hook sites per executor per barrier is well above reality
    calls = 4 * len(q5.pipeline.executors)
    assert per_call_ms * calls < 0.01 * steady_ms, (
        per_call_ms,
        calls,
        steady_ms,
    )


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------


def test_chrome_trace_thread_names_fragment_lanes_and_epoch_flows():
    """Satellite: stable tids + thread_name metadata (actor names show
    in Perfetto), fragments on distinct pid lanes, and flow events
    linking one barrier's spans across actor threads."""
    from risingwave_tpu.runtime.graph import FragmentSpec, GraphRuntime
    from risingwave_tpu.trace import TRACER

    TRACER.clear()
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    g = GraphRuntime(
        [
            FragmentSpec("src", lambda i: []),
            FragmentSpec(
                "agg",
                lambda i: list(q5.pipeline.executors),
                inputs=[("src", 0)],
            ),
        ]
    ).start()
    try:
        c = _steady_chunk(1_000)
        g.inject_chunk("src", c)
        g.inject_barrier()
        g.inject_barrier()
    finally:
        g.stop(timeout=5.0)
    doc = json.loads(TRACER.chrome_trace())
    evs = doc["traceEvents"]
    # named actor threads via ph:"M" metadata
    tnames = {
        e["args"]["name"]
        for e in evs
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert any(n.startswith("actor-") for n in tnames), tnames
    # fragments get their own pid lanes, named via process_name
    pnames = {
        e["args"]["name"]: e["pid"]
        for e in evs
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert "host" in pnames
    frag_lanes = {k: v for k, v in pnames.items() if k.startswith("fragment:")}
    assert len(frag_lanes) >= 2  # src#0 + agg#0 lanes
    assert len(set(frag_lanes.values())) == len(frag_lanes)
    # epoch flow events: one barrier = one flow id across >1 thread
    flows = [e for e in evs if e["ph"] in ("s", "t") and e.get("cat") == "epoch"]
    assert flows, "no epoch flow events"
    by_id = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
    linked = [fl for fl in by_id.values() if len(fl) >= 2]
    assert linked, by_id
    assert any(
        len({(e["pid"], e["tid"]) for e in fl}) >= 2 for fl in linked
    ), "flow never crosses a thread"
    # exactly one flow-start per epoch
    for fl in by_id.values():
        assert sum(1 for e in fl if e["ph"] == "s") == 1


def test_stable_tids_no_collisions_across_threads():
    from risingwave_tpu.trace import TRACER, span

    TRACER.clear()

    def work(name):
        with span(f"unit.{name}"):
            time.sleep(0.01)

    ts = [
        threading.Thread(target=work, args=(i,), name=f"unit-worker-{i}")
        for i in range(3)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    doc = json.loads(TRACER.chrome_trace())
    spans = [
        e for e in doc["traceEvents"]
        if e["ph"] == "X" and e["name"].startswith("unit.")
    ]
    tids = {e["tid"] for e in spans}
    assert len(tids) == 3  # one stable tid per thread, no collisions
    named = {
        e["tid"]: e["args"]["name"]
        for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    for tid in tids:
        assert named.get(tid, "").startswith("unit-worker-")


# ---------------------------------------------------------------------------
# capture windows + forensics
# ---------------------------------------------------------------------------


def test_slow_barrier_auto_capture_and_forensic_dump(tmp_path, monkeypatch):
    """A barrier over the profile threshold auto-emits a PROFILE_*
    artifact (executor breakdown + device forensics) and a stall dump
    carrying device memory stats — the q7-wedge evidence path."""
    monkeypatch.setenv("RW_STALL_DIR", str(tmp_path))
    rt, q5 = _rt_with_q5()
    bid = _steady_chunk()
    rt.push("q5", bid)
    rt.barrier()
    PROFILER.reset()
    PROFILER.enable(
        fence=True, slow_barrier_ms=10.0, capture_dir=str(tmp_path)
    )
    sync_point.activate(
        "before_manifest_commit", lambda: time.sleep(0.05)
    )
    rt.push("q5", bid)
    rt.barrier()  # slow: over the 10ms threshold
    profs = glob.glob(str(tmp_path / "PROFILE_slow_barrier_*.json"))
    assert profs, "no PROFILE_* artifact"
    doc = json.loads(open(profs[-1]).read())
    assert doc["barrier_wall_ms"] >= 10.0
    assert "executor_ms" in doc and doc["device_dispatches_total"]
    assert "memory_stats" in doc["device"]  # None on CPU, key present
    assert doc["device"]["live_arrays"]["total_count"] > 0
    dumps = glob.glob(str(tmp_path / "STALL_DUMP_*.json"))
    assert dumps, "no forensic stall dump"
    sdoc = json.loads(open(dumps[-1]).read())
    assert "memory_stats" in sdoc["device"]
    assert "profiler" in sdoc["device"]
    # window bookkeeping: capture closed, event recorded
    assert PROFILER.active_captures == []
    assert EVENT_LOG.events(kind="profile_capture")
    # bounded: a persistently slow run cannot flood the dir, and
    # manual captures never consume the auto budget
    assert PROFILER._auto_captures <= PROFILER.max_auto_captures
    before = PROFILER._auto_captures
    PROFILER.end_capture(PROFILER.start_capture(tag="manual"))
    assert PROFILER._auto_captures == before


def test_recovery_aborts_open_capture_windows():
    """PR-5 orphan-audit extension: a recovery mid-capture must close
    the profiler window (an orphaned jax.profiler session would hold
    the device)."""
    rt, q5 = _rt_with_q5()
    rt.push("q5", _steady_chunk())
    rt.barrier()
    PROFILER.enable(fence=False)
    PROFILER.start_capture(tag="unit")
    assert len(PROFILER.active_captures) == 1
    rt.recover()
    assert PROFILER.active_captures == []


def test_stall_dump_falls_back_to_tempdir(tmp_path, monkeypatch):
    """Satellite: RW_STALL_DIR unwritable no longer returns "" silently
    — the dump lands in the system temp dir and the failure is event-
    logged; a writable dir still takes precedence."""
    from risingwave_tpu.epoch_trace import dump_stalls

    # a FILE as the stall dir: os.path.join(file, name) cannot open
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("x")
    monkeypatch.setenv("RW_STALL_DIR", str(blocker))
    EVENT_LOG.clear()
    path = dump_stalls("unit: unwritable dir")
    try:
        assert path, "fallback did not produce an artifact"
        import tempfile

        assert os.path.dirname(path) == tempfile.gettempdir()
        assert json.loads(open(path).read())["reason"].startswith("unit")
        fb = EVENT_LOG.events(kind="stall_dump_fallback")
        assert fb and fb[-1]["path"] == path
        assert EVENT_LOG.events(kind="stall_dump")[-1]["path"] == path
    finally:
        if path and os.path.exists(path):
            os.remove(path)
    # the writable path still lands where asked, no fallback event
    monkeypatch.setenv("RW_STALL_DIR", str(tmp_path))
    EVENT_LOG.clear()
    path2 = dump_stalls("unit: writable dir")
    assert os.path.dirname(path2) == str(tmp_path)
    assert not EVENT_LOG.events(kind="stall_dump_fallback")


def test_device_forensics_shape():
    d = device_forensics()
    assert d["platform"] == "cpu"
    assert "memory_stats" in d and "live_arrays" in d
    assert "state_tables" in d and "profiler" in d


# ---------------------------------------------------------------------------
# perf gate
# ---------------------------------------------------------------------------


def _gate(args):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "perf_gate.py"),
         *args],
        capture_output=True,
        text=True,
        cwd=ROOT,
    )


def test_perf_gate_clean_on_committed_baseline():
    """The committed BENCH artifact must pass the committed budgets —
    the gate's green state is reproducible from the repo alone."""
    r = _gate(["--bench", os.path.join(ROOT, "BENCH_partial.json")])
    assert r.returncode == 0, r.stdout + r.stderr


def test_perf_gate_fails_on_injected_dispatch_regression(tmp_path):
    bench = json.load(open(os.path.join(ROOT, "BENCH_partial.json")))
    bench["q5u_dispatches_per_row"] = 99.0  # per-op dispatch storm
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(bench))
    r = _gate(["--bench", str(bad)])
    assert r.returncode == 1
    assert "dispatches/row" in r.stderr
    # and a blown stage p99 also trips it
    bench = json.load(open(os.path.join(ROOT, "BENCH_partial.json")))
    bench.setdefault("barrier_stage_ms", {})[
        "fragment=mv#0,stage=dispatch"
    ] = {"p50": 9000.0, "p99": 9000.0, "count": 2, "sum": 18000.0}
    bad.write_text(json.dumps(bench))
    r = _gate(["--bench", str(bad)])
    assert r.returncode == 1


def test_perf_gate_smoke_budgets_in_process():
    """The CI smoke microbench (in-process here to skip a cold jax
    import): steady-state dispatches/barrier and host-python ms/row
    within committed budgets, dispatch count stable across epochs."""
    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    try:
        import perf_gate
    finally:
        sys.path.pop(0)
    budgets = json.load(
        open(os.path.join(ROOT, "scripts", "perf_budgets.json"))
    )
    violations, report = perf_gate.run_smoke(budgets, epochs=3)
    assert violations == [], (violations, report)
    assert report["smoke_dispatches_per_barrier"]
    assert (
        max(report["smoke_dispatches_per_barrier"])
        <= budgets["smoke"]["dispatches_per_barrier_max"]
    )
    # the fused leg: one donated program per barrier, actually fused
    assert report["fused_whole_chain"] is True
    assert (
        max(report["fused_dispatches_per_barrier"])
        <= budgets["smoke"]["fused_dispatches_per_barrier_max"]
    )


def test_profiler_config_section():
    """[profiler] TOML section parses into ProfilerConfig and unknown
    keys stay non-fatal."""
    from risingwave_tpu.config import load_config

    import tempfile

    with tempfile.NamedTemporaryFile(
        "w", suffix=".toml", delete=False
    ) as f:
        f.write(
            "[profiler]\nenabled = false\nslow_barrier_capture_ms = 250.0\n"
            "jax_trace = false\nmystery = 1\n"
        )
        p = f.name
    try:
        cfg = load_config(p)
        assert cfg.profiler.enabled is False
        assert cfg.profiler.slow_barrier_capture_ms == 250.0
        assert cfg.unrecognized.get("profiler.mystery") == 1
    finally:
        os.remove(p)


def test_env_rw_profile_0_disables_config_enabled_profiler(monkeypatch):
    """The env knob wins in BOTH directions: RW_PROFILE=0 disarms a
    config-enabled profiler (the operator's no-restart escape hatch)."""
    from risingwave_tpu.config import ProfilerConfig

    monkeypatch.setenv("RW_PROFILE", "0")
    PROFILER.configure(ProfilerConfig(enabled=True, fence=False))
    assert PROFILER.enabled is False
