"""rwlint (analysis/): plan-graph verifier + JAX compilation sanitizer.

Positive half: every built-in Nexmark query and graph-mode SQL plan
lints clean, and the DDL-time budget holds. Negative half: ~10 seeded
malformed plans, each rejected AT CREATE-MV TIME with its exact
RW-E### code and fragment/executor provenance — never a runtime crash
or wrong result.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from risingwave_tpu.analysis import PlanLintError, lint_all_nexmark
from risingwave_tpu.analysis.diagnostics import Diagnostic, LintReport
from risingwave_tpu.analysis.jax_sanitizer import (
    RecompileWatch,
    SignatureWatch,
    check_donation,
    check_hash_path_32bit,
    check_promotions,
    sanitize_executors,
    sanitize_hash_kernels,
)
from risingwave_tpu.analysis.lint import lint_pipeline, lint_planned
from risingwave_tpu.analysis.plan_verifier import verify_planned
from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors import HashAggExecutor, ProjectExecutor
from risingwave_tpu.executors.materialize import DeviceMaterializeExecutor
from risingwave_tpu.expr import expr as E
from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime import Pipeline, StreamingRuntime
from risingwave_tpu.runtime.graph import FragmentSpec
from risingwave_tpu.sql import Catalog
from risingwave_tpu.sql.planner import PlannedMV
from risingwave_tpu.types import DataType, Field, Schema

pytestmark = pytest.mark.smoke

I64 = jnp.int64


def _agg(keys=("a",), tid="t.agg", dtypes=None, window_key=None, cap=64):
    return HashAggExecutor(
        group_keys=keys,
        calls=(AggCall("count_star", None, "n"),),
        schema_dtypes=dtypes or {k: I64 for k in keys},
        capacity=cap,
        out_cap=cap,
        table_id=tid,
        window_key=window_key,
    )


def _src_catalog(cols=("a", "b")):
    return Catalog(
        {"src": Schema([Field(c, DataType.INT64) for c in cols])}
    )


def _session(catalog=None, strict=True):
    return SqlSession(
        catalog or _src_catalog(),
        StreamingRuntime(store=None),
        strict_lint=strict,
    )


def _planned(pipeline, name="bad"):
    return PlannedMV(
        name, pipeline, None, {"src": "single"}, schema={"a": I64}
    )


def _ddl_reject(pipeline, code, *, fragment=None, catalog=None):
    """The malformed plan must be refused AT CREATE-MV TIME with the
    exact diagnostic — DDL raises, nothing registers."""
    session = _session(catalog=catalog)
    session.planner.plan = lambda sql: _planned(pipeline)
    with pytest.raises(PlanLintError) as ei:
        session.execute("CREATE MATERIALIZED VIEW bad AS SELECT a FROM src")
    msg = str(ei.value)
    assert code in msg
    if fragment is not None:
        assert f"frag={fragment}" in msg
    assert "bad" not in session.runtime.fragments  # nothing registered
    return msg


class _FakeGraph:
    """GraphPipeline-shaped stub: specs without spawning actor threads
    (a genuinely mis-wired GraphRuntime would crash in _build before
    lint could speak — the verifier runs on the SPEC level)."""

    def __init__(self, specs, sources=None, out="mv"):
        self._specs = list(specs)
        self.graph = None
        self._sources = sources or {"single": specs[0].name}
        self._out = out


# ---------------------------------------------------------------------------
# positive: the shipped plans lint clean
# ---------------------------------------------------------------------------


def test_all_nexmark_builders_clean():
    out = lint_all_nexmark(strict=True)  # strict: errors would raise
    assert set(out) == {"q5", "q7", "q8"}
    assert all(not diags for diags in out.values())


def test_sql_create_mv_lints_clean_and_under_budget():
    session = _session(
        Catalog(
            {
                "bid": Schema(
                    [
                        Field("auction", DataType.INT64),
                        Field("price", DataType.INT64),
                        Field("date_time", DataType.INT64),
                    ]
                )
            }
        )
    )
    from risingwave_tpu.metrics import REGISTRY

    before = REGISTRY.histogram("lint_ms").count()
    session.execute(
        "CREATE MATERIALIZED VIEW v AS SELECT auction, count(*) AS n "
        "FROM bid GROUP BY auction"
    )
    assert not [d for _n, d in session.lint_findings]
    h = REGISTRY.histogram("lint_ms")
    assert h.count() > before  # the DDL hook really ran
    # PROFILE budget: <50ms per CREATE MV (pure metadata walking)
    t0 = time.perf_counter()
    planned = session.catalog.mvs["v"]
    lint_planned(planned, catalog=session.catalog, strict=True)
    assert (time.perf_counter() - t0) * 1e3 < 50


def test_graph_mode_create_mv_lints_clean():
    session = SqlSession(
        Catalog(
            {
                "bid": Schema(
                    [
                        Field("auction", DataType.INT64),
                        Field("price", DataType.INT64),
                    ]
                )
            }
        ),
        StreamingRuntime(store=None),
        exec_mode="graph",
        parallelism=2,
        strict_lint=True,
    )
    session.execute(
        "CREATE MATERIALIZED VIEW g AS SELECT auction, count(*) AS n "
        "FROM bid GROUP BY auction"
    )
    assert not [d for _n, d in session.lint_findings]


# ---------------------------------------------------------------------------
# negative: seeded malformed plans -> exact RW-E### at DDL time
# ---------------------------------------------------------------------------


def test_e101_schema_mismatch_project_drops_column():
    chain = [
        ProjectExecutor({"x": E.col("a")}),  # drops 'b'
        _agg(keys=("b",)),
    ]
    msg = _ddl_reject(Pipeline(chain), "RW-E101", fragment="bad")
    assert "1:HashAggExecutor" in msg  # executor provenance


def test_e102_dtype_mismatch_vs_declared():
    chain = [_agg(keys=("a",), dtypes={"a": jnp.int32})]  # src says int64
    msg = _ddl_reject(Pipeline(chain), "RW-E102", fragment="bad")
    assert "int32" in msg and "int64" in msg


def test_e201_dispatch_key_missing_upstream():
    specs = [
        FragmentSpec("src", lambda i: [], dispatch=("hash", ["zz"])),
        FragmentSpec(
            "par",
            lambda i: [_agg(keys=("a",))],
            inputs=[("src", 0)],
            parallelism=2,
        ),
    ]
    _ddl_reject(_FakeGraph(specs, out="par"), "RW-E201", fragment="src")


def test_e202_key_misalignment_across_exchange():
    # dispatch hashes 'a' but the parallel agg groups by 'b': rows of
    # one group land on different instances -> split state
    specs = [
        FragmentSpec("src", lambda i: [], dispatch=("hash", ["a"])),
        FragmentSpec(
            "par",
            lambda i: [_agg(keys=("b",))],
            inputs=[("src", 0)],
            parallelism=2,
        ),
    ]
    msg = _ddl_reject(_FakeGraph(specs, out="par"), "RW-E202", fragment="src")
    assert "'a'" in msg and "par" in msg


def test_e203_round_robin_into_keyed_state():
    specs = [
        FragmentSpec("src", lambda i: [], dispatch="round_robin"),
        FragmentSpec(
            "par",
            lambda i: [_agg(keys=("a",))],
            inputs=[("src", 0)],
            parallelism=2,
        ),
    ]
    _ddl_reject(_FakeGraph(specs, out="par"), "RW-E203", fragment="src")


def test_e204_join_key_dtype_mismatch():
    # the real HashJoinExecutor refuses this in its constructor; the
    # verifier must still catch a join-like executor that declares it
    class _BadJoin:
        table_id = "bad.join"

        def lint_info(self):
            return {
                "left_keys": ("k",),
                "right_keys": ("j",),
                "expects_left": {"k": jnp.int64},
                "expects_right": {"j": jnp.int32},
                "emits": {"k": jnp.int64, "j": jnp.int32},
            }

    from risingwave_tpu.runtime.pipeline import TwoInputPipeline

    tp = TwoInputPipeline([], [], _BadJoin(), [])
    rep = [
        d
        for d in verify_planned(
            _planned(tp),
            source_schemas={
                "left": {"k": jnp.int64},
                "right": {"j": jnp.int32},
            },
        )
    ]
    assert any(d.code == "RW-E204" for d in rep)


def test_e501_window_key_unreachable_by_watermarks():
    # 'w' is a COMPUTED project output (not a rename, not a hop window
    # start): no watermark can ever reach it, state grows forever
    chain = [
        ProjectExecutor({"w": E.col("a") + E.col("b"), "g": E.col("b")}),
        _agg(keys=("g", "w"), window_key=("w", 0, False)),
    ]
    _ddl_reject(Pipeline(chain), "RW-E501", fragment="bad")


def test_e601_dangling_channel():
    specs = [
        FragmentSpec("mv", lambda i: [], inputs=[("ghost", 0)]),
    ]
    _ddl_reject(_FakeGraph(specs, out="mv"), "RW-E601", fragment="mv")


def test_e602_duplicate_edge():
    specs = [
        FragmentSpec("src", lambda i: []),
        FragmentSpec(
            "mv", lambda i: [], inputs=[("src", 0), ("src", 0)]
        ),
    ]
    _ddl_reject(_FakeGraph(specs, out="mv"), "RW-E602", fragment="mv")


def test_e603_cyclic_fragment_graph():
    specs = [
        FragmentSpec("x", lambda i: [], inputs=[("y", 0)]),
        FragmentSpec("y", lambda i: [], inputs=[("x", 0)]),
    ]
    msg = _ddl_reject(
        _FakeGraph(specs, sources={"single": "x"}, out="x"), "RW-E603"
    )
    assert "'x'" in msg and "'y'" in msg


def test_e604_unconsumed_fragment():
    specs = [
        FragmentSpec("src", lambda i: []),
        FragmentSpec("mv", lambda i: [], inputs=[("src", 0)]),
        FragmentSpec("stray", lambda i: [], inputs=[("src", 0)]),
    ]
    _ddl_reject(_FakeGraph(specs, out="mv"), "RW-E604", fragment="stray")


def test_e605_missing_out_fragment():
    specs = [FragmentSpec("src", lambda i: [])]
    _ddl_reject(_FakeGraph(specs, out="ghost"), "RW-E605", fragment="ghost")


def test_e606_stateful_fragment_without_rebuildable_boundary():
    """A GraphPipeline whose checkpoint registry does not cover a
    fragment's stateful executor can never be PARTIALLY recovered (its
    state checkpoints nowhere restorable) — refused at DDL time."""
    from risingwave_tpu.runtime.fragmenter import GraphPipeline

    agg = _agg(keys=("a",), tid="orphan.agg")
    specs = [
        FragmentSpec("src", lambda i: []),
        FragmentSpec(
            "work", lambda i, a=agg: [a], inputs=[("src", 0)]
        ),
    ]
    # registry deliberately omits the agg: nothing can restore it
    gp = GraphPipeline(
        specs, {"single": "src"}, "work", [], ckpt_fragments=[]
    )
    try:
        msg = _ddl_reject(gp, "RW-E606", fragment="work")
        assert "orphan.agg" in msg
    finally:
        gp.close()


def test_e606_registry_entry_without_restore_state():
    """A checkpoint-registry entry that checkpoints but never
    implements restore_state is flagged too (its deltas persist into a
    table no recovery path can read back)."""
    from risingwave_tpu.runtime.fragmenter import GraphPipeline
    from risingwave_tpu.storage.state_table import Checkpointable

    class WriteOnlyState(Checkpointable):
        table_id = "writeonly.t"

        def checkpoint_delta(self):
            return []

        # restore_state deliberately NOT implemented

    wo = WriteOnlyState()
    specs = [
        FragmentSpec("src", lambda i: []),
        FragmentSpec("work", lambda i: [], inputs=[("src", 0)]),
    ]
    gp = GraphPipeline(
        specs, {"single": "src"}, "work", [wo], ckpt_fragments=["work"]
    )
    try:
        msg = _ddl_reject(gp, "RW-E606")
        assert "restore_state" in msg and "WriteOnlyState" in msg
    finally:
        gp.close()


def test_e606_negative_fragmenter_plans_are_rebuildable():
    """The fragmenter's own graph plans always carry a complete
    restorable registry — no E606 on the real CREATE-MV path."""
    from risingwave_tpu.runtime.fragmenter import graph_planned_mv
    from risingwave_tpu.sql.planner import StreamPlanner

    catalog = _src_catalog(("a", "b"))
    planned = graph_planned_mv(
        lambda: StreamPlanner(catalog),
        "CREATE MATERIALIZED VIEW g AS SELECT a, count(*) AS n "
        "FROM src GROUP BY a",
        parallelism=2,
    )
    try:
        diags = lint_planned(planned, catalog=catalog, strict=True)
        assert not [d for d in diags if d.code == "RW-E606"]
    finally:
        planned.pipeline.close()


def test_e701_state_pk_not_covered():
    mv = DeviceMaterializeExecutor(
        pk=("missing",),
        columns=("a",),
        schema_dtypes={"missing": I64, "a": I64},
        table_id="bad.mview",
        capacity=64,
    )
    msg = _ddl_reject(Pipeline([mv]), "RW-E701", fragment="bad")
    assert "missing" in msg


def test_e702_duplicate_table_id():
    chain = [
        _agg(keys=("a",), tid="dup.table"),
        _agg(keys=("a",), tid="dup.table"),
    ]
    _ddl_reject(Pipeline(chain), "RW-E702", fragment="bad")


from risingwave_tpu.executors.base import Executor as _ExecutorBase


class _GhostState(_ExecutorBase):
    """Registers a state table but is INVISIBLE to the memory ledger:
    no state_nbytes()/state_bytes() contract, no allocator capacity
    note. The RW-E708 target."""

    def apply(self, chunk):
        return [chunk]

    def lint_info(self):
        return {"table_ids": ("ghost.t",)}


def test_e708_unaccounted_state_reports_only_by_default(monkeypatch):
    """RW-E708 defaults to report-only even in strict sessions
    (promoting it would refuse pre-existing DDL): the CREATE MV goes
    through, the finding lands in lint_findings as a warning."""
    monkeypatch.delenv("RW_STRICT_LINT", raising=False)
    session = _session()
    chain = [_GhostState(), _agg(keys=("a",))]
    session.planner.plan = lambda sql: _planned(Pipeline(chain))
    session.execute("CREATE MATERIALIZED VIEW bad AS SELECT a FROM src")
    assert "bad" in session.runtime.fragments  # DDL accepted
    found = [d for _n, d in session.lint_findings if d.code == "RW-E708"]
    assert found and found[0].severity == "warning"
    assert "ghost.t" in found[0].message


def test_e708_refused_under_explicit_strict_lint(monkeypatch):
    """An EXPLICITLY-set truthy RW_STRICT_LINT (the __main__ opt-in)
    promotes unaccounted state to a refusal."""
    monkeypatch.setenv("RW_STRICT_LINT", "1")
    chain = [_GhostState(), _agg(keys=("a",))]
    msg = _ddl_reject(Pipeline(chain), "RW-E708", fragment="bad")
    assert "ghost.t" in msg and "ledger" in msg


def test_e708_builtin_stateful_executors_are_ledger_visible():
    """Every shipped stateful executor exposes the accounting contract
    the governor budgets from — the Nexmark corpus must walk free of
    RW-E708 (covered by test_all_nexmark_builders_clean) and the
    canonical state-holders answer state_nbytes() directly."""
    from risingwave_tpu.executors.materialize import MaterializeExecutor

    agg = _agg(keys=("a",))
    assert int(agg.state_nbytes()) >= 0
    mv = MaterializeExecutor(pk=("a",), columns=("n",), table_id="m.t")
    assert int(mv.state_nbytes()) >= 0
    dmv = DeviceMaterializeExecutor(
        pk=("a",),
        columns=("n",),
        schema_dtypes={"a": I64, "n": I64},
        table_id="m.d",
        capacity=64,
    )
    assert int(dmv.state_nbytes()) > 0


def test_non_strict_records_instead_of_raising():
    session = _session(strict=False)
    chain = [_agg(keys=("zz",))]  # 'zz' not in src
    session.planner.plan = lambda sql: _planned(Pipeline(chain))
    # non-strict: the DDL goes through, the finding is RECORDED
    session.execute("CREATE MATERIALIZED VIEW bad AS SELECT a FROM src")
    assert any(d.code == "RW-E101" for _n, d in session.lint_findings)
    assert "bad" in session.runtime.fragments


# ---------------------------------------------------------------------------
# Part B: compilation sanitizer
# ---------------------------------------------------------------------------


def test_hash_kernels_are_32bit_clean():
    assert sanitize_hash_kernels() == []


def test_e302_catches_64bit_hash_arithmetic():
    def bad_hash(ks):
        u = ks[0].astype(jnp.uint64)
        return ((u * jnp.uint64(0x9E3779B9)) >> jnp.uint64(32)).astype(
            jnp.uint32
        )

    diags = check_hash_path_32bit(
        bad_hash, (jnp.zeros(8, jnp.int64),), name="bad_hash"
    )
    assert any(d.code == "RW-E302" for d in diags)


def test_e301_catches_implicit_widening():
    def widens(x):
        return x.astype(jnp.int64) * 2

    diags = check_promotions(widens, jnp.zeros(8, jnp.int32), name="w")
    assert [d.code for d in diags] == ["RW-E301"]
    # and an all-64-bit step is NOT flagged (no promotion happened)
    assert check_promotions(lambda x: x * 2, jnp.zeros(8, jnp.int64)) == []


def test_q7_q8_sanitizer_clean():
    """Acceptance: dtype-promotion rules run clean on the q7/q8
    pipelines (every executor exposing a pure step)."""
    from risingwave_tpu.queries.nexmark_q import build_q7, build_q8

    q7 = build_q7(
        capacity=1 << 10,
        agg_capacity=1 << 10,
        filter_capacity=1 << 10,
        out_cap=1 << 10,
    )
    q8 = build_q8(capacity=1 << 10, out_cap=1 << 10)
    assert sanitize_executors(q7.pipeline.executors) == []
    assert sanitize_executors(q8.pipeline.executors) == []


def test_q7_pipeline_runs_clean_under_transfer_guard(monkeypatch):
    """Acceptance: the per-barrier device step holds no implicit
    host transfers (conftest arms RW_TRANSFER_GUARD globally; pin it
    here so the test is self-contained)."""
    monkeypatch.setenv("RW_TRANSFER_GUARD", "1")
    from risingwave_tpu.queries.nexmark_q import build_q7

    q7 = build_q7(
        capacity=1 << 10,
        agg_capacity=1 << 10,
        filter_capacity=1 << 10,
        out_cap=1 << 10,
    )
    rng = np.random.default_rng(11)
    cols = {
        "auction": rng.integers(0, 50, 128).astype(np.int64),
        "bidder": rng.integers(0, 50, 128).astype(np.int64),
        "price": rng.integers(1, 10_000, 128).astype(np.int64),
        "date_time": np.sort(rng.integers(0, 30_000, 128)).astype(np.int64),
    }
    c = StreamChunk.from_numpy(cols, 128)
    q7.pipeline.push_left(c)
    q7.pipeline.push_right(c)
    q7.pipeline.barrier()  # device fence runs under the armed guard
    q7.pipeline.watermark("date_time", 20_000)
    q7.pipeline.barrier()
    assert q7.mview.snapshot() is not None


def test_e401_donation():
    from risingwave_tpu.ops.hash_table import HashTable, lookup_or_insert

    t = HashTable.create(64, (jnp.dtype(jnp.int64),))
    keys = (jnp.zeros(8, jnp.int64),)
    valid = jnp.ones(8, jnp.bool_)
    # the state kernel donates its table: clean
    assert check_donation(lookup_or_insert, t, keys, valid) == []
    # an undonated twin is flagged
    undonated = jax.jit(lambda a, b: a + b)
    diags = check_donation(
        undonated, jnp.zeros(8), jnp.zeros(8), name="undonated"
    )
    assert [d.code for d in diags] == ["RW-E401"]


def test_e403_signature_watch_flags_shape_instability():
    from risingwave_tpu.metrics import REGISTRY

    watch = SignatureWatch().start()
    ex = ProjectExecutor({"x": E.col("a")})
    watch.observe(ex, StreamChunk.from_numpy({"a": np.arange(4)}, 4))
    watch.mark_stable()
    watch.observe(ex, StreamChunk.from_numpy({"a": np.arange(4)}, 4))
    assert watch.report() == []  # same signature: stable
    before = REGISTRY.counter("recompile_hazard_total").get(
        executor="ProjectExecutor"
    )
    watch.observe(ex, StreamChunk.from_numpy({"a": np.arange(8)}, 8))
    diags = watch.report()
    assert [d.code for d in diags] == ["RW-E403"]
    assert "ProjectExecutor" in diags[0].executor
    assert (
        REGISTRY.counter("recompile_hazard_total").get(
            executor="ProjectExecutor"
        )
        == before + 1
    )
    watch.stop()


def test_recompile_watch_counts_new_compiles():
    from risingwave_tpu.metrics import REGISTRY

    @jax.jit
    def f(x):
        return x + 1

    w = RecompileWatch([("f", f)])
    f(jnp.zeros(4))
    w.snapshot()
    assert w.deltas() == {}
    before = REGISTRY.counter("recompiles_total").get(fn="f")
    f(jnp.zeros(8))  # new shape -> new compile
    assert w.deltas(record=True) == {"f": 1}
    assert REGISTRY.counter("recompiles_total").get(fn="f") == before + 1
    # recording consumed the window: a second read never double-counts
    assert w.deltas(record=True) == {}
    assert w.total() == 0
    assert REGISTRY.counter("recompiles_total").get(fn="f") == before + 1


# ---------------------------------------------------------------------------
# CLI + SQL-file surface
# ---------------------------------------------------------------------------


def test_cli_all_nexmark_exits_zero():
    import argparse

    from risingwave_tpu.analysis.lint import run_cli

    rc = run_cli(
        argparse.Namespace(
            paths=[], all_nexmark=True, deep=True, json=True
        )
    )
    assert rc == 0


def test_lint_sql_file(tmp_path):
    from risingwave_tpu.analysis.lint import lint_sql_file

    p = tmp_path / "plan.sql"
    p.write_text(
        "CREATE TABLE bid (auction BIGINT, price BIGINT);\n"
        "CREATE MATERIALIZED VIEW v AS "
        "SELECT auction, count(*) AS n FROM bid GROUP BY auction;\n"
    )
    findings = lint_sql_file(str(p))
    assert all(not diags for diags in findings.values())


def test_lint_sql_file_comment_lines_do_not_swallow_ddl(tmp_path):
    """A `--` comment line shares its ';'-segment with the statement
    that follows it; the segment must still execute AND lint."""
    from risingwave_tpu.analysis.lint import lint_sql_file

    p = tmp_path / "plan.sql"
    p.write_text(
        "-- base tables; with a semicolon in the comment\n"
        "CREATE TABLE bid (auction BIGINT, price BIGINT);\n"
        "CREATE MATERIALIZED VIEW v AS "
        "SELECT auction, count(*) AS n FROM bid GROUP BY auction;\n"
    )
    # pre-fix the whole first segment (comment + CREATE TABLE) was
    # skipped and the MV blew up on the unknown relation
    findings = lint_sql_file(str(p))
    assert all(not diags for diags in findings.values())
    # and a statement directly behind a comment line is NOT silently
    # skipped: it executes (here: surfacing its unknown relation)
    p2 = tmp_path / "hidden.sql"
    p2.write_text(
        "-- hidden\nCREATE MATERIALIZED VIEW w AS SELECT x FROM nope;\n"
    )
    with pytest.raises(Exception, match="nope"):
        lint_sql_file(str(p2))


def test_cli_missing_sql_file_is_usage_error(tmp_path):
    """Exit-code contract: 2 = usage (vs 1 = lint errors), never a raw
    traceback, so CI wrappers can tell the cases apart."""
    import argparse

    from risingwave_tpu.analysis.lint import run_cli

    rc = run_cli(
        argparse.Namespace(
            paths=[str(tmp_path / "typo.sql")],
            all_nexmark=False,
            deep=False,
            json=False,
        )
    )
    assert rc == 2
    # same contract for a file whose SQL the session cannot execute
    bad = tmp_path / "bad.sql"
    bad.write_text("CREATE MATERIALIZED VIEW v AS SELECT x FROM nope;\n")
    rc = run_cli(
        argparse.Namespace(
            paths=[str(bad)], all_nexmark=False, deep=False, json=False
        )
    )
    assert rc == 2


def test_cli_bad_path_keeps_other_findings(tmp_path, capsys):
    """A later unreadable path must not drop findings already
    collected for other targets: exit 2, but the JSON still carries
    every linted target plus the errors."""
    import argparse
    import json as _json

    from risingwave_tpu.analysis.lint import run_cli

    rc = run_cli(
        argparse.Namespace(
            paths=[str(tmp_path / "typo.sql")],
            all_nexmark=True,
            deep=False,
            json=True,
        )
    )
    out = _json.loads(capsys.readouterr().out)
    assert rc == 2
    assert {"q5", "q7", "q8"} <= set(out)
    assert out["__errors__"] and "typo.sql" in out["__errors__"][0]


def test_lint_sql_file_skips_dml(tmp_path):
    """lint runs DDL only: INSERT seeds / smoke SELECTs in a deploy
    file must not execute (or abort the lint)."""
    from risingwave_tpu.analysis.lint import lint_sql_file

    p = tmp_path / "deploy.sql"
    p.write_text(
        "CREATE TABLE t (a BIGINT);\n"
        "INSERT INTO missing_elsewhere VALUES (1);\n"  # would raise
        "SELECT * FROM also_missing;\n"  # would raise
        "CREATE MATERIALIZED VIEW v AS "
        "SELECT a, count(*) AS n FROM t GROUP BY a;\n"
    )
    findings = lint_sql_file(str(p))  # must not abort on the DML
    assert all(not diags for diags in findings.values())


def test_restore_replay_is_never_refused_by_strict_lint(tmp_path):
    """DDL-log replay runs lint in record-only mode: a statement the
    store accepted must restore even under strict_lint (a lint-rule
    change must not brick recovery), and restore() threads the
    configured strictness into the session it returns."""
    from risingwave_tpu.storage.object_store import MemObjectStore

    store = MemObjectStore()
    s = SqlSession(Catalog({}), StreamingRuntime(store), strict_lint=True)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW mv AS "
        "SELECT k, sum(v) AS sv FROM t GROUP BY k"
    )
    s.runtime.wait_checkpoints()

    s2 = SqlSession.restore(StreamingRuntime(store), strict_lint=True)
    assert s2.strict_lint is True
    assert "mv" in s2.runtime.fragments
    # replayed DDL linted in record-only mode: strict flag preserved,
    # no PlanLintError even if a (hypothetical) new rule now fires —
    # simulate by replaying a session whose planner yields a bad plan
    bad = PlannedMV(
        "bad2",
        Pipeline([_agg(keys=("missing",), dtypes={"missing": I64})]),
        None,
        {"t": "single"},  # `t` IS in the restored catalog -> E101 fires
        schema={"k": I64},
    )
    s2._replaying = True
    try:
        s2._lint_planned(bad)  # must record, not raise
    finally:
        s2._replaying = False
    assert any(d.code == "RW-E101" for _n, d in s2.lint_findings)
    # same plan outside replay IS refused — strictness survived restore
    with pytest.raises(PlanLintError):
        s2._lint_planned(bad)


def test_graph_duplicate_create_reaps_actor_threads():
    """Graph pipelines spawn actor threads at PLAN time: a CREATE
    refused for ANY reason (here: duplicate name) must reap the doomed
    plan's actors, not leak them for the process lifetime."""
    import threading

    session = SqlSession(
        Catalog({"bid": Schema([Field("auction", DataType.INT64)])}),
        StreamingRuntime(store=None),
        exec_mode="graph",
        parallelism=2,
        strict_lint=True,
    )
    ddl = (
        "CREATE MATERIALIZED VIEW g AS SELECT auction, count(*) AS n "
        "FROM bid GROUP BY auction"
    )
    session.execute(ddl)
    n_live = lambda: sum(
        1 for t in threading.enumerate() if t.name.startswith("actor-")
    )
    before = n_live()
    with pytest.raises(ValueError, match="already exists"):
        session.execute(ddl)  # second plan spawned actors -> reaped
    deadline = time.perf_counter() + 5.0
    while n_live() > before and time.perf_counter() < deadline:
        time.sleep(0.02)
    assert n_live() <= before


def test_broken_lint_info_degrades_loudly_not_silently():
    """An executor whose lint_info() RAISES is not the same as one that
    advertises none: the verifier must surface an RW-E001 warning (not
    refuse the DDL, not stay silent) and go opaque past it."""

    class _Broken(ProjectExecutor):
        def lint_info(self):
            raise AttributeError("_dtypes gone")

    p = Pipeline([_Broken({"a": E.Col("a")})])
    diags = lint_pipeline(
        p, {"single": {"a": I64}}, name="mv", strict=True
    )  # strict: a warning must NOT raise
    assert [d.code for d in diags] == ["RW-E001"]
    assert diags[0].severity == "warning"
    assert "AttributeError" in diags[0].message
    assert "_Broken" in diags[0].executor

    # a JOIN executor's broken lint_info degrades just as loudly
    from risingwave_tpu.analysis.plan_verifier import (
        _TableIds,
        _verify_join,
    )

    class _BrokenJoin:
        def lint_info(self):
            raise RuntimeError("join metadata drifted")

    rep = LintReport()
    _verify_join(
        _BrokenJoin(), {"a": I64}, {"a": I64}, None, None,
        "mv", rep, _TableIds(rep),
    )
    jcodes = [d.code for d in rep.diagnostics]
    assert jcodes == ["RW-E001"], jcodes
    assert "join:_BrokenJoin" in rep.diagnostics[0].executor


def test_diagnostic_codes_are_closed_set():
    with pytest.raises(ValueError):
        Diagnostic("RW-E999", "no such code")
    rep = LintReport()
    rep.add("RW-E101", "x", fragment="f", executor="0:X")
    assert "RW-E101 [frag=f ex=0:X]" in rep.render()
