"""Avro binary parsing (VERDICT missing #6 remainder): dependency-free
decoder for records of the engine's lane types, verified against a
hand-encoded corpus (zigzag varints, unions-with-null, arrays, enum,
Confluent wire framing)."""

import struct

import pytest

from risingwave_tpu.connectors.avro import AvroParser, decode_record
from risingwave_tpu.types import DataType, Field, Schema

pytestmark = pytest.mark.smoke


def zz(n: int) -> bytes:
    """Encode an Avro zigzag varint (test-side oracle encoder)."""
    u = (n << 1) ^ (n >> 63) if n >= 0 else ((-n) << 1) - 1
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def avro_str(s: str) -> bytes:
    b = s.encode()
    return zz(len(b)) + b


SCHEMA = {
    "type": "record",
    "name": "ev",
    "fields": [
        {"name": "id", "type": "long"},
        {"name": "name", "type": "string"},
        {"name": "score", "type": "double"},
        {"name": "note", "type": ["null", "string"]},
        {"name": "tags", "type": {"type": "array", "items": "long"}},
        {"name": "kind", "type": {"type": "enum", "name": "k",
                                  "symbols": ["A", "B"]}},
    ],
}


def _record(id_, name, score, note, tags, kind_idx):
    b = zz(id_) + avro_str(name) + struct.pack("<d", score)
    if note is None:
        b += zz(0)  # union branch 0 = null
    else:
        b += zz(1) + avro_str(note)
    if tags:
        b += zz(len(tags)) + b"".join(zz(t) for t in tags)
    b += zz(0)  # array end
    b += zz(kind_idx)
    return b


def test_decode_record_round_trip():
    blob = _record(-42, "hi", 1.5, "n", [3, -7], 1)
    rec = decode_record(blob, SCHEMA)
    assert rec == {
        "id": -42, "name": "hi", "score": 1.5, "note": "n",
        "tags": [3, -7], "kind": "B",
    }
    # null union branch
    rec = decode_record(_record(7, "x", 0.0, None, [], 0), SCHEMA)
    assert rec["note"] is None and rec["tags"] == []
    # truncated input -> None (non-strict drop)
    assert decode_record(blob[:3], SCHEMA) is None


def test_confluent_wire_framing_is_explicit():
    blob = _record(1, "y", 2.0, None, [], 0)
    framed = b"\x00" + (1234).to_bytes(4, "big") + blob
    rec = decode_record(framed, SCHEMA, framed=True)
    assert rec is not None and rec["id"] == 1 and rec["name"] == "y"
    # framing is DECLARED, never sniffed: an unframed record whose
    # first field encodes as byte 0 (id=0) must decode as itself
    tricky = _record(0, "ABC", 2.0, None, [], 0)
    assert tricky[0] == 0
    rec = decode_record(tricky, SCHEMA)
    assert rec is not None and rec["id"] == 0 and rec["name"] == "ABC"
    # declared-framed input missing the magic byte is rejected
    assert decode_record(blob, SCHEMA, framed=True) is None
    # trailing garbage is rejected (single-record contract)
    assert decode_record(blob + b"x", SCHEMA) is None


def test_avro_parser_lane_coercion():
    schema = Schema([
        Field("id", DataType.INT64),
        Field("name", DataType.VARCHAR),
        Field("score", DataType.FLOAT64),
        Field("note", DataType.VARCHAR),
    ])
    p = AvroParser(schema, SCHEMA)
    row = p.parse(_record(9, "bob", 2.25, None, [1], 0))
    assert row == (9, "bob", 2.25, None)
    assert p.parse(b"\xff") is None
    # hex text form (file-log carried)
    row = p.parse(_record(3, "z", 0.5, "q", [], 1).hex())
    assert row == (3, "z", 0.5, "q")
