"""Nexmark q4 from SQL: average closing price per category.

Reference: e2e_test/nexmark/ q4 — AVG over each auction's max bid,
grouped by category. The shape composes pieces this round completed:
a grouped MAX over a join (auction x bid) lowered to an MV, and an
avg() MV over it (MV-on-MV + extended aggregates).
"""

import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def test_q4_avg_of_per_auction_max():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE auction (aid BIGINT, category BIGINT)")
    s.execute("CREATE TABLE bid (auction BIGINT, price BIGINT)")
    # per-auction winning bid, carrying the category through the join
    s.execute(
        "CREATE MATERIALIZED VIEW winning AS "
        "SELECT aid, category, max(price) AS final_p "
        "FROM (SELECT aid, category FROM auction) AS a "
        "JOIN (SELECT auction, price FROM bid) AS b "
        "ON a.aid = b.auction "
        "GROUP BY aid, category"
    )
    # q4: category-level average of the winning bids (MV-on-MV)
    s.execute(
        "CREATE MATERIALIZED VIEW q4 AS "
        "SELECT category, avg(final_p) AS avg_final "
        "FROM winning GROUP BY category"
    )
    s.execute(
        "INSERT INTO auction VALUES (1, 10), (2, 10), (3, 20)"
    )
    s.execute(
        "INSERT INTO bid VALUES (1, 100), (1, 300), (2, 50), "
        "(3, 700), (3, 900)"
    )
    out, _ = s.execute("SELECT category, avg_final FROM q4 ORDER BY category")
    # cat 10: max(1)=300, max(2)=50 -> avg 175; cat 20: max(3)=900
    assert list(out["category"]) == [10, 20]
    assert list(out["avg_final"]) == pytest.approx([175.0, 900.0])
    # a higher bid arrives for auction 2: the winning bid RISES and
    # the category average follows incrementally
    s.execute("INSERT INTO bid VALUES (2, 250)")
    out, _ = s.execute(
        "SELECT category, avg_final FROM q4 ORDER BY category"
    )
    assert list(out["avg_final"]) == pytest.approx([275.0, 900.0])


def test_q4_differential_vs_batch():
    """The same q4 aggregate computed by the batch engine over the
    winning MV agrees with the streaming q4 MV."""
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE auction (aid BIGINT, category BIGINT)")
    s.execute("CREATE TABLE bid (auction BIGINT, price BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW winning AS "
        "SELECT aid, category, max(price) AS final_p "
        "FROM (SELECT aid, category FROM auction) AS a "
        "JOIN (SELECT auction, price FROM bid) AS b "
        "ON a.aid = b.auction "
        "GROUP BY aid, category"
    )
    s.execute(
        "CREATE MATERIALIZED VIEW q4 AS "
        "SELECT category, avg(final_p) AS avg_final "
        "FROM winning GROUP BY category"
    )
    rng = np.random.default_rng(5)
    aucs = ", ".join(
        f"({i}, {int(rng.integers(0, 4))})" for i in range(1, 21)
    )
    s.execute(f"INSERT INTO auction VALUES {aucs}")
    bids = ", ".join(
        f"({int(rng.integers(1, 21))}, {int(rng.integers(1, 1000))})"
        for _ in range(120)
    )
    s.execute(f"INSERT INTO bid VALUES {bids}")
    stream, _ = s.execute("SELECT category, avg_final FROM q4")
    batch, _ = s.execute(
        "SELECT category, avg(final_p) AS avg_final FROM winning "
        "GROUP BY category"
    )
    sm = dict(zip(stream["category"], stream["avg_final"]))
    bm = dict(zip(batch["category"], batch["avg_final"]))
    assert set(sm) == set(bm)
    for c in sm:
        assert sm[c] == pytest.approx(bm[c])
