"""Source offset checkpoint/resume: a recovered source must replay the
EXACT committed-offset suffix of the stream (reference: split offset
state, source_executor.rs + state_table_handler.rs)."""

import numpy as np

from risingwave_tpu.connectors import NexmarkConfig, NexmarkSourceExecutor
from risingwave_tpu.connectors.nexmark import NexmarkGenerator
from risingwave_tpu.queries.nexmark_q import build_q5_lite
from risingwave_tpu.runtime import StreamingRuntime
from risingwave_tpu.storage import MemObjectStore


def test_generator_is_offset_deterministic():
    dicts = NexmarkGenerator.make_dictionaries()
    a = NexmarkGenerator(NexmarkConfig(), dictionaries=dicts)
    a.next_events(700)  # advance with a different batching pattern
    a.next_events(300)
    b = NexmarkGenerator(NexmarkConfig(), dictionaries=dicts)
    b.seek(1000)
    ea, eb = a.next_events(500), b.next_events(500)
    for stream in ("person", "auction", "bid"):
        for col in ea[stream]:
            assert np.array_equal(ea[stream][col], eb[stream][col]), (
                stream, col
            )


def test_source_offsets_resume_through_recovery():
    store = MemObjectStore()
    src = NexmarkSourceExecutor(NexmarkConfig(), split_num=2)
    q5 = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    rt = StreamingRuntime(store, async_checkpoint=False)
    rt.register("q5", q5.pipeline)
    rt.register_state(src)

    for _ in range(4):
        for bid in src.poll(1000, 1024)["bid"]:
            q5.pipeline.push(bid.select(["auction", "date_time"]))
        rt.barrier()
    snap = q5.mview.snapshot()
    offsets = [g.offset for g in src.splits]

    # kill + recover: fresh source resumes at the committed offsets
    src2 = NexmarkSourceExecutor(NexmarkConfig(), split_num=2)
    q5b = build_q5_lite(capacity=1 << 12, state_cleaning=False)
    rt2 = StreamingRuntime(store, async_checkpoint=False)
    rt2.register("q5", q5b.pipeline)
    rt2.register_state(src2)
    rt2.recover()
    assert [g.offset for g in src2.splits] == offsets
    assert q5b.mview.snapshot() == snap

    # continuing both produces identical MVs
    for rt_i, q_i, s_i in ((rt, q5, src), (rt2, q5b, src2)):
        for _ in range(2):
            for bid in s_i.poll(1000, 1024)["bid"]:
                q_i.pipeline.push(bid.select(["auction", "date_time"]))
            rt_i.barrier()
    assert q5b.mview.snapshot() == q5.mview.snapshot()
