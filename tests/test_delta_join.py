"""Lookup/delta join over shared CREATE INDEX arrangements (VERDICT r4
missing #5; reference: lookup.rs + frontend delta-join rule gated on a
session variable)."""

import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def test_create_index_and_delta_join_from_sql():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (k BIGINT, x BIGINT)")
    s.execute("CREATE TABLE b (k BIGINT, y BIGINT)")
    # pre-index + pre-join data: index backfills, join seeds
    s.execute("INSERT INTO a VALUES (1, 10), (2, 20)")
    s.execute("INSERT INTO b VALUES (1, 100), (3, 300)")
    s.execute("CREATE INDEX ia ON a (k)")
    s.execute("CREATE INDEX ib ON b (k)")
    s.execute("SET enable_delta_join = true")
    s.execute(
        "CREATE MATERIALIZED VIEW dj AS "
        "SELECT a.k AS k, x, y FROM a JOIN b ON a.k = b.k"
    )
    # the join SHARES the index arrangements (no duplicated state)
    planned = s.catalog.mvs["dj"]
    from risingwave_tpu.executors.lookup import DeltaJoinExecutor

    join = planned.pipeline.join
    assert isinstance(join, DeltaJoinExecutor)
    assert join.left_arr is s.catalog.indexes["ia"]["arrangement"]
    assert join.right_arr is s.catalog.indexes["ib"]["arrangement"]

    out, _ = s.execute("SELECT k, x, y FROM dj")
    assert sorted(zip(out["k"], out["x"], out["y"])) == [(1, 10, 100)]

    # deltas on both sides join against the other's arrangement
    s.execute("INSERT INTO a VALUES (3, 30)")
    s.execute("INSERT INTO b VALUES (2, 200), (1, 101)")
    out, _ = s.execute("SELECT k, x, y FROM dj ORDER BY k")
    assert sorted(zip(out["k"], out["x"], out["y"])) == [
        (1, 10, 100),
        (1, 10, 101),
        (2, 20, 200),
        (3, 30, 300),
    ]


def test_without_session_var_or_index_no_delta_join():
    """The delta rule declines without the session variable or the
    indexes; the bare-table join then falls to the hash path (which
    requires subquery-form sides — its existing contract)."""
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (k BIGINT, x BIGINT)")
    s.execute("CREATE TABLE b (k BIGINT, y BIGINT)")
    s.execute("CREATE INDEX ia ON a (k)")
    s.execute("CREATE INDEX ib ON b (k)")
    sql = (
        "CREATE MATERIALIZED VIEW hj AS "
        "SELECT a.k AS k, x, y FROM a JOIN b ON a.k = b.k"
    )
    with pytest.raises(TypeError, match="subqueries"):
        s.execute(sql)  # var off -> hash path -> bare tables rejected
    s.execute("SET enable_delta_join = true")
    # no index covers (x)/(y): the delta rule declines
    with pytest.raises(TypeError, match="subqueries"):
        s.execute(
            "CREATE MATERIALIZED VIEW hj2 AS "
            "SELECT a.k AS k, x, y FROM a JOIN b ON a.x = b.y"
        )
    # subquery-form joins never take the delta path
    s.execute(
        "CREATE MATERIALIZED VIEW hj3 AS SELECT l.k AS k, x, y FROM "
        "(SELECT k, x FROM a) AS l JOIN (SELECT k AS k2, y FROM b) AS r "
        "ON l.k = r.k2"
    )
    from risingwave_tpu.executors.lookup import DeltaJoinExecutor

    join = getattr(s.catalog.mvs["hj3"].pipeline, "join", None)
    assert not isinstance(join, DeltaJoinExecutor)


def test_delta_join_retractions_match_hash_join_oracle():
    """Random insert/delete streams on both sides: the delta join's
    maintained MV equals a HashJoin-maintained oracle."""
    import jax.numpy as jnp

    from risingwave_tpu.array.chunk import StreamChunk
    from risingwave_tpu.executors.hash_join import HashJoinExecutor
    from risingwave_tpu.executors.lookup import (
        DeltaJoinExecutor,
        IndexArrangement,
    )

    la = IndexArrangement(("k",), ("lid",), ("x",), "dja.l")
    ra = IndexArrangement(("k",), ("rid",), ("y",), "dja.r")
    dj = DeltaJoinExecutor(
        la, ra, ("k",), ("k",),
        [("k", "k"), ("x", "x"), ("lid", "lid")],
        [("y", "y"), ("rid", "rid")],
    )
    hj = HashJoinExecutor(
        ("k",), ("k2",),
        {"k": jnp.int64, "x": jnp.int64, "lid": jnp.int64},
        {"k2": jnp.int64, "y": jnp.int64, "rid": jnp.int64},
        capacity=1 << 10, fanout=16, out_cap=1 << 12,
        table_id="djo",
    )

    def mv_apply(mv, chunks, names):
        for c in chunks:
            d = c.to_numpy(with_ops=True)
            for i in range(len(d["__op__"])):
                row = tuple(int(d[n][i]) for n in names)
                if int(d["__op__"][i]) in (1, 3):
                    mv.discard(row)
                else:
                    mv.add(row)

    rng = np.random.default_rng(17)
    dmv, hmv = set(), set()
    live_l, live_r = {}, {}
    names = ("k", "x", "lid", "y", "rid")
    lid = rid = 0
    for epoch in range(40):
        for _ in range(int(rng.integers(1, 4))):
            side = rng.random() < 0.5
            delete = rng.random() < 0.35
            if side:
                if delete and live_l:
                    key = rng.choice(list(live_l))
                    k, x = live_l.pop(int(key))
                    rows = {"k": [k], "x": [x], "lid": [int(key)]}
                    ops = np.asarray([1], np.int32)
                else:
                    k = int(rng.integers(0, 6))
                    x = int(rng.integers(0, 100))
                    live_l[lid] = (k, x)
                    rows = {"k": [k], "x": [x], "lid": [lid]}
                    ops = np.asarray([0], np.int32)
                    lid += 1
                c = StreamChunk.from_numpy(
                    {n: np.asarray(v, np.int64) for n, v in rows.items()},
                    4, ops=ops,
                )
                # arrangement FIRST (runtime routing order), then join
                la.apply(c)
                mv_apply(dmv, dj.apply_left(c), names)
                mv_apply(hmv, hj.apply_left(c), names)
            else:
                if delete and live_r:
                    key = rng.choice(list(live_r))
                    k, y = live_r.pop(int(key))
                    rows = {"k": [k], "y": [y], "rid": [int(key)]}
                    ops = np.asarray([1], np.int32)
                else:
                    k = int(rng.integers(0, 6))
                    y = int(rng.integers(0, 100))
                    live_r[rid] = (k, y)
                    rows = {"k": [k], "y": [y], "rid": [rid]}
                    ops = np.asarray([0], np.int32)
                    rid += 1
                c = StreamChunk.from_numpy(
                    {n: np.asarray(v, np.int64) for n, v in rows.items()},
                    4, ops=ops,
                )
                c2 = StreamChunk.from_numpy(
                    {
                        ("k2" if n == "k" else n): np.asarray(v, np.int64)
                        for n, v in rows.items()
                    },
                    4, ops=ops,
                )
                ra.apply(c)
                mv_apply(dmv, dj.apply_right(c), names)
                mv_apply(hmv, hj.apply_right(c2), names)
        hj.on_barrier(None)
        assert dmv == hmv, f"diverged at epoch {epoch}"
    assert len(dmv) > 3
