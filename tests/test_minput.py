"""Materialized-input MIN/MAX (ops/minput.py; VERDICT r2 #5) — exact
retractable extremes vs a python multiset oracle, incl. the case that
used to raise at the barrier (reference: aggregation/minput.rs)."""

from collections import Counter, defaultdict

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.types import Op

DT = {"g": jnp.int64, "v": jnp.int64}
CAP = 32


def _chunk(rows):
    g = np.array([r[0] for r in rows], np.int64)
    v = np.array([r[1] for r in rows], np.int64)
    ops = np.array([r[2] for r in rows], np.int32)
    return StreamChunk.from_numpy({"g": g, "v": v}, CAP, ops=ops)


def _replay(snap, chunks, keys, outs):
    for c in chunks:
        d = c.to_numpy(with_ops=True)
        for i in range(len(d["__op__"])):
            k = tuple(int(d[n][i]) for n in keys)
            if d["__op__"][i] in (Op.DELETE, Op.UPDATE_DELETE):
                snap.pop(k, None)
            else:
                row = []
                for n in outs:
                    nl = d.get(n + "__isnull")
                    row.append(
                        None if nl is not None and nl[i] else int(d[n][i])
                    )
                snap[k] = tuple(row)
    return snap


def _mk(materialized=True, **kw):
    return HashAggExecutor(
        group_keys=("g",),
        calls=(
            AggCall("count_star", None, "cnt"),
            AggCall("min", "v", "mn", materialized=materialized),
            AggCall("max", "v", "mx", materialized=materialized),
        ),
        schema_dtypes=DT,
        capacity=64,
        out_cap=64,
        **kw,
    )


def _oracle(mult):
    out = {}
    for g, vals in mult.items():
        live = [v for v, c in vals.items() if c > 0]
        n = sum(c for c in vals.values() if c > 0)
        if n:
            out[(g,)] = (n, min(live), max(live))
    return out


def test_retract_current_extreme_falls_back():
    """Delete the max -> flush emits the next-best value (used to raise
    'requires materialized-input extremes')."""
    ex = _mk()
    snap = {}
    _replay(snap, ex.apply(_chunk([(1, 10, Op.INSERT), (1, 30, Op.INSERT),
                                   (1, 20, Op.INSERT)])), ("g",), ("cnt", "mn", "mx"))
    _replay(snap, ex.on_barrier(None), ("g",), ("cnt", "mn", "mx"))
    assert snap == {(1,): (3, 10, 30)}
    _replay(snap, ex.apply(_chunk([(1, 30, Op.DELETE)])), ("g",), ("cnt", "mn", "mx"))
    _replay(snap, ex.on_barrier(None), ("g",), ("cnt", "mn", "mx"))
    assert snap == {(1,): (2, 10, 20)}
    _replay(snap, ex.apply(_chunk([(1, 10, Op.DELETE), (1, 20, Op.DELETE)])),
            ("g",), ("cnt", "mn", "mx"))
    _replay(snap, ex.on_barrier(None), ("g",), ("cnt", "mn", "mx"))
    assert snap == {}


@pytest.mark.parametrize("mode", ["chunk", "stacked"])
def test_random_stream_matches_oracle(mode):
    rng = np.random.default_rng(11)
    ex = _mk()
    mult = defaultdict(Counter)
    snap = {}
    for _ in range(25):
        rows = []
        for _ in range(int(rng.integers(1, 12))):
            g = int(rng.integers(0, 6))
            live = [
                (vv, c) for vv, c in mult[g].items() if c > 0
            ]
            if live and rng.random() < 0.4:
                vv = live[int(rng.integers(len(live)))][0]
                rows.append((g, vv, Op.DELETE))
                mult[g][vv] -= 1
            else:
                vv = int(rng.integers(0, 15))
                rows.append((g, vv, Op.INSERT))
                mult[g][vv] += 1
        if mode == "chunk":
            outs = ex.apply(_chunk(rows))
        else:
            from risingwave_tpu.parallel.sharded_agg import stack_chunks

            outs = ex.apply_stacked(stack_chunks([_chunk(rows)]))
        _replay(snap, outs, ("g",), ("cnt", "mn", "mx"))
        _replay(snap, ex.on_barrier(None), ("g",), ("cnt", "mn", "mx"))
    assert snap == _oracle(mult)


def test_minput_checkpoint_roundtrip():
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import CheckpointManager

    store = MemObjectStore()
    mgr = CheckpointManager(store)
    ex = _mk(table_id="mi1")
    snap = {}
    _replay(snap, ex.apply(_chunk([(1, 10, Op.INSERT), (1, 30, Op.INSERT),
                                   (2, 5, Op.INSERT)])), ("g",), ("cnt", "mn", "mx"))
    _replay(snap, ex.on_barrier(None), ("g",), ("cnt", "mn", "mx"))
    mgr.commit_epoch(1 << 16, [ex])

    ex2 = _mk(table_id="mi1")
    CheckpointManager(store).recover([ex2])
    # retracting the max AFTER recovery must fall back to 10 — only
    # possible if the multiset state survived the checkpoint
    _replay(snap, ex2.apply(_chunk([(1, 30, Op.DELETE)])), ("g",), ("cnt", "mn", "mx"))
    _replay(snap, ex2.on_barrier(None), ("g",), ("cnt", "mn", "mx"))
    assert snap[(1,)] == (1, 10, 10)
    assert snap[(2,)] == (1, 5, 5)


def test_minput_overflow_and_inconsistency_latch():
    ex = HashAggExecutor(
        group_keys=("g",),
        calls=(AggCall("max", "v", "mx", materialized=True),),
        schema_dtypes=DT,
        capacity=64,
        out_cap=64,
        minput_k=4,
    )
    # 5 distinct values > K=4 latches overflow
    ex.apply(_chunk([(1, v, Op.INSERT) for v in range(5)]))
    with pytest.raises(RuntimeError, match="minput_k|retracted"):
        ex.on_barrier(None)
        ex.finish_barrier()

    ex2 = HashAggExecutor(
        group_keys=("g",),
        calls=(AggCall("max", "v", "mx", materialized=True),),
        schema_dtypes=DT,
        capacity=64,
        out_cap=64,
    )
    ex2.apply(_chunk([(1, 7, Op.DELETE)]))  # never inserted
    with pytest.raises(RuntimeError):
        ex2.on_barrier(None)
        ex2.finish_barrier()


def test_minput_survives_rehash():
    ex = HashAggExecutor(
        group_keys=("g",),
        calls=(AggCall("min", "v", "mn", materialized=True),),
        schema_dtypes=DT,
        capacity=8,  # tiny: force growth
        out_cap=256,
        minput_k=8,
    )
    snap = {}
    rows = [(g, g * 10 + j, Op.INSERT) for g in range(10) for j in range(2)]
    for i in range(0, len(rows), 4):
        _replay(snap, ex.apply(_chunk(rows[i : i + 4])), ("g",), ("mn",))
    _replay(snap, ex.on_barrier(None), ("g",), ("mn",))
    assert ex.table.capacity > 8
    # retract each group's current min; falls back to the +1 value
    for g in range(10):
        _replay(snap, ex.apply(_chunk([(g, g * 10, Op.DELETE)])), ("g",), ("mn",))
    _replay(snap, ex.on_barrier(None), ("g",), ("mn",))
    assert snap == {(g,): (g * 10 + 1,) for g in range(10)}
