"""Meta persistence (DDL log + dictionary) and backup/restore.

Reference: meta store (src/meta/src/storage/), cluster bootstrap
(barrier/recovery.rs:353), backup (src/storage/backup/).
"""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.runtime import StreamingRuntime
from risingwave_tpu.sql import Catalog
from risingwave_tpu.storage.meta_backup import (
    create_backup,
    list_backups,
    restore_backup,
)
from risingwave_tpu.storage.object_store import MemObjectStore


def _seed_session(store):
    rt = StreamingRuntime(store)
    s = SqlSession(Catalog({}), rt)
    s.execute("CREATE TABLE pay (uid BIGINT, name VARCHAR, amt BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW spend AS "
        "SELECT uid, sum(amt) AS total FROM pay GROUP BY uid"
    )
    s.execute(
        "INSERT INTO pay VALUES (1, 'alice', 10), (2, 'bob', 20), "
        "(1, 'alice', 5)"
    )
    rt.wait_checkpoints()
    return s, rt


def test_session_restore_replays_ddl_and_recovers_state():
    store = MemObjectStore()
    s1, rt1 = _seed_session(store)
    out, _ = s1.execute("SELECT uid, total FROM spend ORDER BY uid")
    want = (list(out["uid"]), list(out["total"]))

    # cold restart: fresh runtime + session from the same store
    rt2 = StreamingRuntime(store)
    s2 = SqlSession.restore(rt2)
    out, _ = s2.execute("SELECT uid, total FROM spend ORDER BY uid")
    assert (list(out["uid"]), list(out["total"])) == want

    # varchar codes survived: string columns decode identically and
    # NEW inserts of old strings reuse old codes
    out, _ = s2.execute("SELECT uid, name FROM pay ORDER BY uid")
    assert set(out["name"]) == {"alice", "bob"}
    s2.execute("INSERT INTO pay VALUES (3, 'alice', 7)")
    out, _ = s2.execute(
        "SELECT uid, amt FROM pay WHERE name = 'alice' ORDER BY uid"
    )
    assert list(out["uid"]) == [1, 1, 3]

    # and the stream keeps flowing into the recovered MV
    out, _ = s2.execute("SELECT uid, total FROM spend ORDER BY uid")
    assert list(out["total"]) == [15, 20, 7]


def test_restore_does_not_double_count_via_backfill():
    """Replayed CREATE MV must not snapshot-backfill (recovery restores
    its state): rows would double otherwise."""
    store = MemObjectStore()
    s1, rt1 = _seed_session(store)
    rt2 = StreamingRuntime(store)
    s2 = SqlSession.restore(rt2)
    out, _ = s2.execute("SELECT total FROM spend ORDER BY total")
    assert list(out["total"]) == [15, 20]  # not [30, 40]


def test_backup_restore_into_empty_store():
    src = MemObjectStore()
    s1, rt1 = _seed_session(src)
    summary = create_backup(src, "b1")
    assert summary["ssts"] > 0
    assert list_backups(src) == ["b1"]

    dst = MemObjectStore()
    restore_backup(src, "b1", dst)
    rt = StreamingRuntime(dst)
    s = SqlSession.restore(rt)
    out, _ = s.execute("SELECT uid, total FROM spend ORDER BY uid")
    assert list(out["total"]) == [15, 20]

    with pytest.raises(KeyError):
        restore_backup(src, "nope", dst)


def test_backup_survives_post_backup_writes():
    """The backup is a SNAPSHOT: later writes to the live store do not
    leak in (self-contained prefix)."""
    src = MemObjectStore()
    s1, rt1 = _seed_session(src)
    create_backup(src, "b1")
    s1.execute("INSERT INTO pay VALUES (9, 'eve', 99)")
    rt1.wait_checkpoints()

    dst = MemObjectStore()
    restore_backup(src, "b1", dst)
    s = SqlSession.restore(StreamingRuntime(dst))
    out, _ = s.execute("SELECT uid FROM pay ORDER BY uid")
    assert 9 not in list(out["uid"])
