"""Wide SQL types on fixed-width device lanes: decimal, interval,
jsonb, struct, list — round-trips, SQL DDL/DML/SELECT, exactness.

Reference: src/common/src/types/ (ScalarImpl variants) and the arrays
in src/common/src/array/{struct_array,list_array,jsonb_array}.rs.
"""

from decimal import Decimal

import pytest

from risingwave_tpu.array.composite import (
    decode_column,
    encode_column,
    encode_rows,
    expand_field,
)
from risingwave_tpu.array.dictionary import StringDictionary
from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog
from risingwave_tpu.types import DataType, Field, Interval, Schema


def _roundtrip(field, values, strings=None):
    lanes, nulls = encode_column(field, values, strings)
    null_of = lambda ln: (nulls or {}).get(ln)
    return decode_column(field, lanes, null_of, strings)


def test_decimal_roundtrip_exact():
    f = Field("amt", DataType.DECIMAL, scale=2)
    vals = [Decimal("1.23"), Decimal("-0.01"), "99.99", 7, None]
    got = _roundtrip(f, vals)
    assert got == [
        Decimal("1.23"),
        Decimal("-0.01"),
        Decimal("99.99"),
        Decimal("7.00"),
        None,
    ]
    # scaled-int lanes sum exactly (0.1 + 0.2 == 0.3, no float drift)
    lanes, _ = encode_column(f, [Decimal("0.1"), Decimal("0.2")])
    assert int(lanes["amt"].sum()) == 30  # 0.30 at scale 2


def test_interval_roundtrip():
    f = Field("dur", DataType.INTERVAL)
    vals = [
        Interval.of(months=2, days=1),
        Interval.of(hours=3, seconds=1.5),
        None,
    ]
    got = _roundtrip(f, vals)
    assert got[0] == Interval(2, 86_400_000_000)
    assert got[1] == Interval(0, 3 * 3_600_000_000 + 1_500_000)
    assert got[2] is None
    assert [ln for ln, _ in expand_field(f)] == ["dur.months", "dur.usecs"]


def test_jsonb_roundtrip_and_equality_codes():
    f = Field("doc", DataType.JSONB)
    d = StringDictionary()
    vals = [{"b": 1, "a": [1, 2]}, {"a": [1, 2], "b": 1}, None, 42]
    lanes, nulls = encode_column(f, vals, d)
    # canonical serialization: key order does not matter -> same code
    assert lanes["doc"][0] == lanes["doc"][1]
    got = decode_column(f, lanes, lambda ln: (nulls or {}).get(ln), d)
    assert got[0] == {"a": [1, 2], "b": 1}
    assert got[2] is None and got[3] == 42


def test_struct_decomposes_to_child_lanes():
    f = Field(
        "addr",
        DataType.STRUCT,
        children=Schema([("zip", DataType.INT32), ("street", DataType.VARCHAR)]),
    )
    d = StringDictionary()
    vals = [
        {"zip": 94110, "street": "valencia"},
        {"zip": 10001, "street": None},
        None,
    ]
    lanes, nulls = encode_column(f, vals, d)
    assert set(lanes) == {"addr.zip", "addr.street"}
    got = decode_column(f, lanes, lambda ln: (nulls or {}).get(ln), d)
    assert got[0] == {"zip": 94110, "street": "valencia"}
    assert got[1]["zip"] == 10001 and got[1]["street"] is None
    # NULL struct == all children NULL (no struct-level lane)
    assert got[2] == {"zip": None, "street": None}


def test_list_pads_to_cap_and_errors_past_it():
    f = Field("xs", DataType.LIST, elem=DataType.INT64, list_cap=4)
    vals = [[1, 2, 3], [], None, [9, 9, 9, 9]]
    got = _roundtrip(f, vals)
    assert got == [[1, 2, 3], [], None, [9, 9, 9, 9]]
    with pytest.raises(ValueError, match="cap"):
        encode_column(f, [[1, 2, 3, 4, 5]])


def test_encode_rows_mixed_schema():
    schema = Schema(
        [
            Field("k", DataType.INT64),
            Field("amt", DataType.DECIMAL, scale=3),
            Field("tag", DataType.VARCHAR),
        ]
    )
    d = StringDictionary()
    lanes, nulls = encode_rows(
        schema, [(1, "2.5", "a"), (2, None, "b")], d
    )
    assert lanes["amt"].tolist() == [2500, 0]
    assert nulls["amt"].tolist() == [False, True]
    assert d.decode(lanes["tag"]).tolist() == ["a", "b"]


# -- SQL surface ----------------------------------------------------------


@pytest.fixture
def session():
    return SqlSession(Catalog({}), capacity=1 << 10)


def test_sql_decimal_end_to_end(session):
    session.execute("CREATE TABLE pay (uid BIGINT, amount DECIMAL(10,2))")
    session.execute(
        "INSERT INTO pay VALUES (1, 0.10), (1, 0.20), (2, 99.99)"
    )
    out, _ = session.execute("SELECT uid, amount FROM pay ORDER BY uid")
    assert sorted(out["amount"][:2]) == [Decimal("0.10"), Decimal("0.20")]

    # streaming MV: SUM over DECIMAL stays exact (no 0.30000000004)
    session.execute(
        "CREATE MATERIALIZED VIEW spend AS "
        "SELECT uid, sum(amount) AS total FROM pay GROUP BY uid"
    )
    out, _ = session.execute("SELECT uid, total FROM spend ORDER BY uid")
    assert list(out["total"]) == [Decimal("0.30"), Decimal("99.99")]

    session.execute("INSERT INTO pay VALUES (1, 0.40)")
    out, _ = session.execute("SELECT uid, total FROM spend ORDER BY uid")
    assert out["total"][0] == Decimal("0.70")


def test_sql_varchar_end_to_end(session):
    session.execute("CREATE TABLE ev (name VARCHAR, n BIGINT)")
    session.execute(
        "INSERT INTO ev VALUES ('click', 1), ('view', 2), ('click', 3)"
    )
    out, _ = session.execute("SELECT name, n FROM ev ORDER BY n")
    assert list(out["name"]) == ["click", "view", "click"]

    session.execute(
        "CREATE MATERIALIZED VIEW byname AS "
        "SELECT name, count(*) AS c FROM ev GROUP BY name"
    )
    out, _ = session.execute("SELECT name, c FROM byname ORDER BY c DESC")
    assert list(out["name"]) == ["click", "view"]
    assert list(out["c"]) == [2, 1]


def test_sql_jsonb_roundtrip(session):
    session.execute("CREATE TABLE logs (id BIGINT, doc JSONB)")
    session.execute(
        'INSERT INTO logs VALUES (1, \'{"k": [1, 2]}\'), (2, NULL)'
    )
    out, _ = session.execute("SELECT id, doc FROM logs ORDER BY id")
    assert out["doc"][0] == {"k": [1, 2]}
    assert out["doc"][1] is None


def test_sql_nulls_decode_as_none(session):
    session.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    session.execute("INSERT INTO t VALUES (1, NULL), (2, 5)")
    out, _ = session.execute("SELECT k, v FROM t ORDER BY k")
    assert out["v"][0] is None and out["v"][1] == 5
