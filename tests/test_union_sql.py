"""UNION ALL from SQL (reference: set-operation binder + the stream
UnionExecutor, union.rs — here the runtime's multi-subscription IS the
union merge; branches lower to hidden MVs like the join tree does)."""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def test_union_all_two_tables():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE clicks (uid BIGINT, ts BIGINT)")
    s.execute("CREATE TABLE taps (uid BIGINT, ts BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW events AS "
        "SELECT uid, ts FROM clicks UNION ALL SELECT uid, ts FROM taps"
    )
    s.execute("INSERT INTO clicks VALUES (1, 100), (2, 200)")
    s.execute("INSERT INTO taps VALUES (1, 150)")
    out, _ = s.execute("SELECT uid, ts FROM events ORDER BY ts")
    assert list(out["ts"]) == [100, 150, 200]
    assert list(out["uid"]) == [1, 1, 2]
    # MV-on-MV over the union works (count per uid)
    s.execute(
        "CREATE MATERIALIZED VIEW per_uid AS "
        "SELECT uid, count(*) AS n FROM events GROUP BY uid"
    )
    s.execute("INSERT INTO taps VALUES (2, 250)")
    out, _ = s.execute("SELECT uid, n FROM per_uid ORDER BY uid")
    assert list(out["n"]) == [2, 2]


def test_union_all_with_branch_transforms():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (v BIGINT)")
    s.execute("CREATE TABLE b (w BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW u AS "
        "SELECT v AS x FROM a WHERE v > 10 "
        "UNION ALL SELECT w + 1 AS x FROM b"
    )
    s.execute("INSERT INTO a VALUES (5), (20)")
    s.execute("INSERT INTO b VALUES (99)")
    out, _ = s.execute("SELECT x FROM u ORDER BY x")
    assert list(out["x"]) == [20, 100]


def test_union_three_branches():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    for t in ("p", "q", "r"):
        s.execute(f"CREATE TABLE {t} (v BIGINT)")
        s.execute(f"INSERT INTO {t} VALUES ({ord(t)})")
    s.execute(
        "CREATE MATERIALIZED VIEW u AS SELECT v FROM p "
        "UNION ALL SELECT v FROM q UNION ALL SELECT v FROM r"
    )
    out, _ = s.execute("SELECT v FROM u ORDER BY v")
    assert list(out["v"]) == [ord("p"), ord("q"), ord("r")]


def test_union_schema_mismatch_rejected():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (v BIGINT)")
    s.execute("CREATE TABLE b (w BIGINT)")
    with pytest.raises(ValueError, match="identical schemas"):
        s.execute(
            "CREATE MATERIALIZED VIEW u AS "
            "SELECT v FROM a UNION ALL SELECT w FROM b"
        )


def test_union_retracting_branch_rejected():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (k BIGINT, v BIGINT)")
    with pytest.raises(NotImplementedError, match="append-only"):
        s.execute(
            "CREATE MATERIALIZED VIEW u AS SELECT k FROM a "
            "UNION ALL SELECT k FROM (SELECT k, count(*) AS c FROM a "
            "GROUP BY k) AS g"
        )


def test_plain_union_distinct_rejected():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (v BIGINT)")
    with pytest.raises(SyntaxError, match="UNION ALL"):
        s.execute(
            "CREATE MATERIALIZED VIEW u AS "
            "SELECT v FROM a UNION SELECT v FROM a"
        )


def test_union_varchar_decodes():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (name VARCHAR)")
    s.execute("CREATE TABLE b (name VARCHAR)")
    s.execute(
        "CREATE MATERIALIZED VIEW u AS "
        "SELECT name FROM a UNION ALL SELECT name FROM b"
    )
    s.execute("INSERT INTO a VALUES ('x')")
    s.execute("INSERT INTO b VALUES ('y')")
    out, _ = s.execute("SELECT name FROM u")
    assert sorted(out["name"]) == ["x", "y"]


def test_union_retractions_route_to_their_branch():
    """DELETE/UPDATE on a base table retracts EXACTLY its branch's
    rows in the union MV (review finding r5: fresh union-level row
    ids made deletes miss forever)."""
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (v BIGINT)")
    s.execute("CREATE TABLE b (v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW u AS "
        "SELECT v FROM a UNION ALL SELECT v FROM b"
    )
    s.execute("INSERT INTO a VALUES (1), (2)")
    s.execute("INSERT INTO b VALUES (1)")  # same VALUE, other branch
    out, _ = s.execute("SELECT v FROM u ORDER BY v")
    assert list(out["v"]) == [1, 1, 2]
    s.execute("DELETE FROM a WHERE v = 1")
    out, _ = s.execute("SELECT v FROM u ORDER BY v")
    assert list(out["v"]) == [1, 2]  # b's 1 survives; a's is gone
    s.execute("UPDATE b SET v = 9 WHERE v = 1")
    out, _ = s.execute("SELECT v FROM u ORDER BY v")
    assert list(out["v"]) == [2, 9]


def test_union_swapped_columns_rejected():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
    with pytest.raises(ValueError, match="order"):
        s.execute(
            "CREATE MATERIALIZED VIEW u AS "
            "SELECT a, b FROM t UNION ALL SELECT b, a FROM t"
        )


def test_union_failed_plan_leaks_nothing():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (v BIGINT)")
    s.execute("CREATE TABLE b (w BIGINT)")
    with pytest.raises(ValueError):
        s.execute(
            "CREATE MATERIALIZED VIEW u AS "
            "SELECT v FROM a UNION ALL SELECT w FROM b"
        )
    assert not any(n.startswith("__u") for n in s.catalog.mvs)
    assert not any(n.startswith("__u") for n in s.catalog.tables)


def test_adhoc_union_rejected_cleanly():
    s = SqlSession(Catalog({}), capacity=1 << 10)
    s.execute("CREATE TABLE a (v BIGINT)")
    with pytest.raises(NotImplementedError, match="MATERIALIZED"):
        s.execute("SELECT v FROM a UNION ALL SELECT v FROM a")
