"""Python UDFs (pure_callback under jit) + temporal joins
(FOR SYSTEM_TIME AS OF PROCTIME) + CREATE TABLE PRIMARY KEY.

Reference: src/expr/impl/src/udf/python.rs, executor/temporal_join.rs:44.
"""

import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog


@pytest.fixture
def session():
    return SqlSession(Catalog({}), capacity=1 << 10)


def test_python_udf_in_select_and_mv(session):
    session.execute("CREATE TABLE t (k BIGINT, x BIGINT)")
    session.execute("INSERT INTO t VALUES (1, 3), (2, 10), (3, 0)")
    session.execute(
        "CREATE FUNCTION triple(x BIGINT) RETURNS BIGINT LANGUAGE python "
        "AS $$\ndef triple(x):\n    return x * 3\n$$"
    )
    out, _ = session.execute("SELECT k, triple(x) AS t3 FROM t ORDER BY k")
    assert list(out["t3"]) == [9, 30, 0]

    # UDF inside a streaming MV: the pure_callback traces into the
    # jitted project program and keeps working on later inserts
    session.execute(
        "CREATE MATERIALIZED VIEW m AS SELECT k, triple(x) AS t3 FROM t"
    )
    session.execute("INSERT INTO t VALUES (4, 7)")
    out, _ = session.execute("SELECT k, t3 FROM m ORDER BY k")
    assert list(out["t3"]) == [9, 30, 0, 21]


def test_python_udf_row_error_becomes_null(session):
    session.execute("CREATE TABLE t (k BIGINT, x BIGINT)")
    session.execute("INSERT INTO t VALUES (1, 2), (2, 0)")
    session.execute(
        "CREATE FUNCTION inv100(x BIGINT) RETURNS BIGINT LANGUAGE python "
        "AS $$\ndef inv100(x):\n    return 100 // x\n$$"
    )
    out, _ = session.execute("SELECT k, inv100(x) AS v FROM t ORDER BY k")
    assert out["v"][0] == 50
    assert out["v"][1] is None  # div-by-zero row -> SQL NULL

    with pytest.raises(KeyError):
        session.execute("DROP FUNCTION nosuch")
    session.execute("DROP FUNCTION inv100")
    with pytest.raises(ValueError, match="unknown function"):
        session.execute("SELECT inv100(x) AS v FROM t")


def test_temporal_join_enriches_stream(session):
    """Orders stream probes a currencies dimension table at proctime:
    updates to the table affect FUTURE rows only (temporal_join.rs)."""
    session.execute(
        "CREATE TABLE rates (cur BIGINT PRIMARY KEY, rate BIGINT)"
    )
    session.execute("INSERT INTO rates VALUES (1, 100), (2, 200)")
    session.execute("CREATE TABLE orders (oid BIGINT, cur2 BIGINT, amt BIGINT)")
    session.execute(
        "CREATE MATERIALIZED VIEW enriched AS "
        "SELECT oid, amt, rate FROM orders "
        "JOIN rates FOR SYSTEM_TIME AS OF PROCTIME() "
        "ON orders.cur2 = rates.cur"
    )
    session.execute("INSERT INTO orders VALUES (10, 1, 5), (11, 2, 6)")
    out, _ = session.execute("SELECT oid, amt, rate FROM enriched ORDER BY oid")
    assert list(out["oid"]) == [10, 11]
    assert list(out["rate"]) == [100, 200]

    # rate update: already-joined rows keep the OLD rate; new rows see
    # the new one (processing-time semantics)
    session.execute("INSERT INTO rates VALUES (1, 150)")
    session.execute("INSERT INTO orders VALUES (12, 1, 7)")
    out, _ = session.execute("SELECT oid, rate FROM enriched ORDER BY oid")
    assert list(out["rate"]) == [100, 200, 150]


def test_temporal_inner_drops_misses_left_pads(session):
    session.execute("CREATE TABLE dim (k BIGINT PRIMARY KEY, v BIGINT)")
    session.execute("INSERT INTO dim VALUES (1, 11)")
    session.execute("CREATE TABLE s (sk BIGINT, n BIGINT)")
    session.execute(
        "CREATE MATERIALIZED VIEW inner_j AS "
        "SELECT sk, n, v FROM s JOIN dim FOR SYSTEM_TIME AS OF PROCTIME() "
        "ON s.sk = dim.k"
    )
    session.execute(
        "CREATE MATERIALIZED VIEW left_j AS "
        "SELECT sk, n, v FROM s LEFT JOIN dim "
        "FOR SYSTEM_TIME AS OF PROCTIME() ON s.sk = dim.k"
    )
    session.execute("INSERT INTO s VALUES (1, 100), (9, 900)")
    out, _ = session.execute("SELECT sk, v FROM inner_j")
    assert list(out["sk"]) == [1]  # miss dropped
    out, _ = session.execute("SELECT sk, v FROM left_j ORDER BY sk")
    assert list(out["sk"]) == [1, 9]
    assert out["v"][0] == 11 and out["v"][1] is None  # miss NULL-padded


def test_pk_table_upserts(session):
    session.execute("CREATE TABLE kv (k BIGINT PRIMARY KEY, v BIGINT)")
    session.execute("INSERT INTO kv VALUES (1, 10), (2, 20)")
    session.execute("INSERT INTO kv VALUES (1, 99)")  # overwrite
    out, _ = session.execute("SELECT k, v FROM kv ORDER BY k")
    assert list(out["k"]) == [1, 2]
    assert list(out["v"]) == [99, 20]


def test_varchar_udf_args_and_return(session):
    session.execute("CREATE TABLE ev (name VARCHAR, n BIGINT)")
    session.execute("INSERT INTO ev VALUES ('click', 2), ('view', 3)")
    session.execute(
        "CREATE FUNCTION shout(s VARCHAR, n BIGINT) RETURNS VARCHAR "
        "LANGUAGE python AS $$\ndef shout(s, n):\n"
        "    return s.upper() + '!' * n\n$$"
    )
    out, _ = session.execute(
        "SELECT n, shout(name, n) AS s FROM ev ORDER BY n"
    )
    assert list(out["s"]) == ["CLICK!!", "VIEW!!!"]


def test_temporal_join_right_qualifier_with_left_alias(session):
    session.execute("CREATE TABLE dim (id BIGINT PRIMARY KEY, price BIGINT)")
    session.execute("INSERT INTO dim VALUES (1, 11)")
    session.execute("CREATE TABLE src (k BIGINT, q BIGINT)")
    session.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT s.k, d.price FROM src AS s "
        "JOIN dim FOR SYSTEM_TIME AS OF PROCTIME() AS d ON s.k = d.id"
    )
    session.execute("INSERT INTO src VALUES (1, 0)")
    out, _ = session.execute("SELECT k, price FROM m")
    assert list(out["price"]) == [11]


def test_zero_arg_udf_rejected(session):
    with pytest.raises(NotImplementedError, match="zero-argument"):
        session.execute(
            "CREATE FUNCTION one() RETURNS BIGINT LANGUAGE python "
            "AS $$\ndef one():\n    return 1\n$$"
        )


def test_temporal_join_null_key_never_matches(session):
    """SQL: NULL = anything is unknown — a NULL stream key must not
    match a real pk=0 row (lane padding value)."""
    session.execute("CREATE TABLE dim0 (k BIGINT PRIMARY KEY, v BIGINT)")
    session.execute("INSERT INTO dim0 VALUES (0, 7)")
    session.execute("CREATE TABLE s0 (sk BIGINT, n BIGINT)")
    session.execute(
        "CREATE MATERIALIZED VIEW j0 AS "
        "SELECT n, v FROM s0 JOIN dim0 FOR SYSTEM_TIME AS OF PROCTIME() "
        "ON s0.sk = dim0.k"
    )
    session.execute("INSERT INTO s0 VALUES (NULL, 1), (0, 2)")
    out, _ = session.execute("SELECT n, v FROM j0")
    assert list(out["n"]) == [2]  # NULL-keyed row dropped, real 0 matches
    assert list(out["v"]) == [7]


def test_string_builtin_functions(session):
    session.execute("CREATE TABLE ev (name VARCHAR, n BIGINT)")
    session.execute("INSERT INTO ev VALUES ('Alice', 1), ('bob jr', 2)")
    out, _ = session.execute(
        "SELECT n, length(name) AS l, upper(name) AS u, "
        "substr(name, 1, 3) AS s3, replace(name, ' ', '_') AS r "
        "FROM ev ORDER BY n"
    )
    assert list(out["l"]) == [5, 6]
    assert list(out["u"]) == ["ALICE", "BOB JR"]
    assert list(out["s3"]) == ["Ali", "bob"]
    assert list(out["r"]) == ["Alice", "bob_jr"]
    # usable in streaming MVs too (pure_callback under jit)
    session.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT n, concat(name, name) AS dd FROM ev"
    )
    out, _ = session.execute("SELECT n, dd FROM m ORDER BY n")
    assert list(out["dd"]) == ["AliceAlice", "bob jrbob jr"]


def test_unaliased_string_builtin_decodes(session):
    session.execute("CREATE TABLE ev (name VARCHAR, n BIGINT)")
    session.execute("INSERT INTO ev VALUES ('abc', 1)")
    out, _ = session.execute("SELECT upper(name) FROM ev")
    assert list(out["upper_0"]) == ["ABC"]  # decoded, not raw codes


def test_string_builtins_protected(session):
    with pytest.raises(ValueError, match="builtin"):
        session.execute(
            "CREATE FUNCTION upper(s VARCHAR) RETURNS VARCHAR "
            "LANGUAGE python AS $$\ndef upper(s):\n    return s\n$$"
        )
    with pytest.raises(ValueError, match="builtin"):
        session.execute("DROP FUNCTION upper")
