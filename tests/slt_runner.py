"""Minimal sqllogictest runner (the reference's e2e tier format).

Reference: e2e_test/ *.slt files run by sqllogictest-rs against a
risedev cluster (SURVEY.md §4). Directives supported:

    statement ok
    <sql>

    statement error [substring]
    <sql>

    query <typestring> [rowsort]
    <sql>
    ----
    <expected rows, one per line, columns tab-or-space separated>

Blank lines separate records; ``#`` starts a comment. Values are
compared as rendered text (NULL for SQL NULL).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class Record:
    kind: str  # "ok" | "error" | "query"
    sql: str
    expected: Optional[List[str]] = None
    error_substr: str = ""
    rowsort: bool = False
    line: int = 0


def parse_slt(text: str) -> List[Record]:
    lines = text.splitlines()
    out: List[Record] = []
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if not line or line.startswith("#"):
            i += 1
            continue
        head = line.split()
        start = i + 1
        if head[0] == "statement":
            sql_lines = []
            i += 1
            in_dollar = False
            while i < len(lines):
                ln = lines[i]
                if not in_dollar and (
                    not ln.strip() or ln.startswith("#")
                ):
                    break
                # $$-quoted bodies (python UDFs) may hold blank lines
                if ln.count("$$") % 2 == 1:
                    in_dollar = not in_dollar
                sql_lines.append(ln)
                i += 1
            rec = Record(
                kind="ok" if head[1] == "ok" else "error",
                sql="\n".join(sql_lines),
                error_substr=" ".join(head[2:]) if head[1] == "error" else "",
                line=start,
            )
            out.append(rec)
        elif head[0] == "query":
            rowsort = "rowsort" in head[2:]
            sql_lines = []
            i += 1
            while i < len(lines) and lines[i].strip() != "----":
                sql_lines.append(lines[i])
                i += 1
            i += 1  # skip ----
            expected = []
            while i < len(lines) and lines[i].strip():
                expected.append(lines[i].rstrip())
                i += 1
            out.append(
                Record(
                    kind="query",
                    sql="\n".join(sql_lines),
                    expected=expected,
                    rowsort=rowsort,
                    line=start,
                )
            )
        else:
            raise SyntaxError(f"slt line {i + 1}: unknown directive {line!r}")
        i += 1
    return out


def _render(v) -> str:
    import numpy as np

    if v is None:
        return "NULL"
    if isinstance(v, (bool, np.bool_)):
        return "t" if bool(v) else "f"
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def run_slt(session, text: str, path: str = "<slt>") -> int:
    """Execute every record against a SqlSession; raises AssertionError
    with file:line context on the first mismatch. Returns #records."""
    records = parse_slt(text)
    for rec in records:
        where = f"{path}:{rec.line}"
        if rec.kind == "ok":
            session.execute(rec.sql)
            continue
        if rec.kind == "error":
            try:
                session.execute(rec.sql)
            except Exception as e:  # noqa: BLE001 — any SQL error counts
                if rec.error_substr and rec.error_substr.lower() not in str(
                    e
                ).lower():
                    raise AssertionError(
                        f"{where}: error {e!r} does not contain "
                        f"{rec.error_substr!r}"
                    ) from e
                continue
            raise AssertionError(f"{where}: expected an error, got success")
        out, _tag = session.execute(rec.sql)
        names = [n for n in out if not n.endswith("__null")]
        n = len(out[names[0]]) if names else 0
        got = []
        for r in range(n):
            cells = []
            for c in names:
                nl = out.get(c + "__null")
                cells.append(
                    "NULL" if nl is not None and nl[r] else _render(out[c][r])
                )
            got.append("\t".join(cells))
        # identical normalization on BOTH sides so spaced VARCHAR
        # values compare consistently
        got = [re.sub(r"\s+", "\t", g.strip()) for g in got]
        want = [re.sub(r"\s+", "\t", e.strip()) for e in rec.expected or []]
        norm = lambda rows: sorted(rows) if rec.rowsort else rows
        if norm(got) != norm(want):
            raise AssertionError(
                f"{where}: query mismatch\n  got:  {norm(got)}\n"
                f"  want: {norm(want)}\n  sql: {rec.sql}"
            )
    return len(records)
