"""Extended aggregates: avg / var_pop / var_samp / stddev_pop /
stddev_samp / bool_and / bool_or, lowered onto the base sum/count/
min/max machinery + a finishing projection (reference ships them as
first-class kernels, src/expr/impl/src/aggregate/; here the planner
decomposition keeps retraction/checkpoint/sharding free).

Covers: streaming GROUP BY MVs (incl. incremental updates), global
SimpleAgg MVs, batch SELECTs (grouped + global), and NULL semantics
(avg over zero rows, var_samp of one row).
"""

import numpy as np
import pytest

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.sql import Catalog

pytestmark = pytest.mark.smoke


def _sess():
    return SqlSession(Catalog({}), capacity=1 << 10)


def test_streaming_avg_grouped_incremental():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, avg(v) AS a, count(*) AS n FROM t GROUP BY k"
    )
    s.execute("INSERT INTO t VALUES (1, 10), (1, 20), (2, 5)")
    out, _ = s.execute("SELECT k, a, n FROM m ORDER BY k")
    assert list(out["k"]) == [1, 2]
    assert list(out["a"]) == pytest.approx([15.0, 5.0])
    # incremental: a second epoch shifts the running mean
    s.execute("INSERT INTO t VALUES (1, 30)")
    out, _ = s.execute("SELECT k, a FROM m ORDER BY k")
    assert list(out["a"]) == pytest.approx([20.0, 5.0])


def test_streaming_variance_family_matches_numpy():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS SELECT k, "
        "var_pop(v) AS vp, var_samp(v) AS vs, "
        "stddev_pop(v) AS sp, stddev_samp(v) AS ss "
        "FROM t GROUP BY k"
    )
    vals = [3, 7, 7, 19]
    s.execute(
        "INSERT INTO t VALUES " + ", ".join(f"(1, {v})" for v in vals)
    )
    out, _ = s.execute("SELECT vp, vs, sp, ss FROM m")
    a = np.asarray(vals, np.float64)
    assert out["vp"][0] == pytest.approx(a.var(ddof=0))
    assert out["vs"][0] == pytest.approx(a.var(ddof=1))
    assert out["sp"][0] == pytest.approx(a.std(ddof=0))
    assert out["ss"][0] == pytest.approx(a.std(ddof=1))


def test_streaming_var_samp_single_row_is_null():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, var_samp(v) AS vs, var_pop(v) AS vp FROM t GROUP BY k"
    )
    s.execute("INSERT INTO t VALUES (1, 42)")
    out, cols = s.execute("SELECT k, vs, vp FROM m")
    # var_samp of one row: NULL (n-1 = 0); var_pop of one row: 0
    assert out["vs"][0] is None or (
        isinstance(out["vs"][0], float) and np.isnan(out["vs"][0])
    )
    assert out["vp"][0] == pytest.approx(0.0)


def test_streaming_bool_and_or():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, b BOOLEAN)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS SELECT k, "
        "bool_and(b) AS ba, bool_or(b) AS bo FROM t GROUP BY k"
    )
    s.execute(
        "INSERT INTO t VALUES (1, true), (1, false), (2, true), (2, true)"
    )
    out, _ = s.execute("SELECT k, ba, bo FROM m ORDER BY k")
    assert [bool(x) for x in out["ba"]] == [False, True]
    assert [bool(x) for x in out["bo"]] == [True, True]


def test_streaming_global_avg_stddev():
    s = _sess()
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT avg(v) AS a, stddev_pop(v) AS sd, sum(v) AS s FROM t"
    )
    s.execute("INSERT INTO t VALUES (2), (4), (6)")
    out, _ = s.execute("SELECT a, sd, s FROM m")
    assert out["a"][0] == pytest.approx(4.0)
    assert out["sd"][0] == pytest.approx(np.std([2, 4, 6]))
    assert out["s"][0] == 12


def test_streaming_avg_retraction_via_cdc(tmp_path):
    """avg over a RETRACTING stream (Debezium CDC updates/deletes via
    CREATE SOURCE ... format='debezium') tracks the live mean exactly —
    the hidden sum/count decomposition retracts natively."""
    from risingwave_tpu.connectors.framework import FileLogSource

    d = str(tmp_path)
    s = _sess()
    s.execute(
        f"CREATE SOURCE c (g BIGINT, v BIGINT) "
        f"WITH (connector='filelog', path='{d}', format='debezium')"
    )
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT g, avg(v) AS a FROM c GROUP BY g"
    )
    FileLogSource.append(d, 0, [
        '{"op": "c", "after": {"g": 0, "v": 10}}',
        '{"op": "c", "after": {"g": 0, "v": 30}}',
        '{"op": "c", "after": {"g": 1, "v": 100}}',
    ])
    s.pump_sources()
    s.runtime.barrier()
    out, _ = s.execute("SELECT g, a FROM m ORDER BY g")
    assert list(out["a"]) == pytest.approx([20.0, 100.0])
    FileLogSource.append(d, 0, [
        # 10 -> 50 (update) and delete the 100 row entirely
        '{"op": "u", "before": {"g": 0, "v": 10}, '
        '"after": {"g": 0, "v": 50}}',
        '{"op": "d", "before": {"g": 1, "v": 100}}',
    ])
    s.pump_sources()
    s.runtime.barrier()
    out, _ = s.execute("SELECT g, a FROM m ORDER BY g")
    assert list(out["g"]) == [0]  # group 1 emptied by the delete
    assert list(out["a"]) == pytest.approx([40.0])


def test_batch_extended_aggs_grouped_and_global():
    s = _sess()
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT, b BOOLEAN)")
    s.execute(
        "INSERT INTO t VALUES (1, 2, true), (1, 4, true), "
        "(2, 10, false), (2, 30, true)"
    )
    out, _ = s.execute(
        "SELECT k, avg(v) AS a, var_samp(v) AS vs, "
        "bool_and(b) AS ba FROM t GROUP BY k ORDER BY k"
    )
    assert list(out["a"]) == pytest.approx([3.0, 20.0])
    assert list(out["vs"]) == pytest.approx([2.0, 200.0])
    assert [bool(x) for x in out["ba"]] == [True, False]
    out, _ = s.execute(
        "SELECT avg(v) AS a, stddev_samp(v) AS ss, bool_or(b) AS bo FROM t"
    )
    assert out["a"][0] == pytest.approx(11.5)
    assert out["ss"][0] == pytest.approx(np.std([2, 4, 10, 30], ddof=1))
    assert bool(out["bo"][0]) is True


def test_batch_var_samp_single_row_null():
    s = _sess()
    s.execute("CREATE TABLE t (v BIGINT)")
    s.execute("INSERT INTO t VALUES (7)")
    out, _ = s.execute("SELECT var_samp(v) AS vs FROM t")
    v = out["vs"][0]
    assert v is None or (isinstance(v, float) and np.isnan(v))
