"""Failure detection + self-healing (VERDICT r3 #9): a poisoned epoch
or dead actor thread triggers recovery INSIDE the runtime — no caller
ever calls recover().

Reference: meta failure detection + global recovery,
src/meta/src/barrier/mod.rs:676-710 + barrier/recovery.rs:353.
"""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.connectors.framework import (
    FileLogSource,
    GenericSourceExecutor,
    JsonParser,
)
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime import Pipeline
from risingwave_tpu.runtime.fragmenter import GraphPipeline
from risingwave_tpu.runtime.graph import FragmentSpec
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.types import DataType, Schema


import pytest as _pytest

pytestmark = _pytest.mark.smoke


class PoisonOnce(Executor):
    """Raises at the first armed barrier, then behaves forever after
    (the transient-fault model of the recovery suites)."""

    def __init__(self):
        self.armed = False
        self.fired = 0

    def apply(self, chunk):
        return [chunk]

    def on_barrier(self, b):
        if self.armed:
            self.armed = False
            self.fired += 1
            raise RuntimeError("poisoned epoch (injected)")
        return []


def _agg_chain(poison, table_id):
    agg = HashAggExecutor(
        group_keys=("k",),
        calls=(AggCall("sum", "v", "s"), AggCall("count_star", None, "c")),
        schema_dtypes={"k": jnp.int64, "v": jnp.int64},
        capacity=1 << 8,
        table_id=f"{table_id}.agg",
    )
    mview = MaterializeExecutor(
        pk=("k",), columns=("s", "c"), table_id=f"{table_id}.mview"
    )
    return [poison, agg, mview], mview


def test_poisoned_epoch_self_heals_with_source_replay(tmp_path):
    """Source-backed MV: the poisoned epoch's rows are NOT lost — the
    watchdog recovers, offsets roll back, and the pump's re-read
    replays them. No recover() call anywhere in this test."""
    d = str(tmp_path)
    schema = Schema([("k", DataType.INT64), ("v", DataType.INT64)])
    src = GenericSourceExecutor(
        FileLogSource(d), JsonParser(schema), table_id="src"
    )
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    poison = PoisonOnce()
    chain, mview = _agg_chain(poison, "mv")
    rt.register("mv", Pipeline(chain))
    rt.register_state(src)

    rng = np.random.default_rng(31)
    all_rows = []
    for epoch in range(6):
        rows = [
            {"k": int(rng.integers(0, 4)), "v": int(rng.integers(0, 50))}
            for _ in range(int(rng.integers(3, 10)))
        ]
        all_rows.extend(rows)
        FileLogSource.append(
            d, 0, [f'{{"k": {r["k"]}, "v": {r["v"]}}}' for r in rows]
        )
        if epoch == 3:
            poison.armed = True
        # the pump: poll + push + barrier until the epoch commits (a
        # recovered epoch rolls offsets back, so re-polling replays it)
        src.discover()  # partition-0 appears on the first append
        for _attempt in range(4):
            for c in src.poll(64, 16):
                rt.push("mv", c)
            before = rt.mgr.max_committed_epoch
            rt.barrier()
            if rt.mgr.max_committed_epoch > before:
                break
        else:
            raise AssertionError("epoch never committed")

    assert rt.auto_recoveries == 1 and poison.fired == 1
    want = {}
    for r in all_rows:
        s, c = want.get(r["k"], (0, 0))
        want[r["k"]] = (s + r["v"], c + 1)
    got = {k[0]: v for k, v in mview.snapshot().items()}
    assert got == want


def test_dead_actor_graph_self_heals(tmp_path):
    """Graph-backed fragment: the poisoned barrier kills the actor
    thread; the watchdog rebuilds the actor graph and restores state —
    the stream continues with exact results."""
    poison = PoisonOnce()
    chain, mview = _agg_chain(poison, "gmv")
    agg = chain[1]
    gp = GraphPipeline(
        [FragmentSpec("gmv", lambda i, ch=tuple(chain): list(ch))],
        {"single": "gmv"},
        "gmv",
        [agg, mview],
    )
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    rt.register("gmv", gp)

    rng = np.random.default_rng(7)
    want = {}

    def mk_chunk():
        n = int(rng.integers(3, 10))
        ks = rng.integers(0, 4, n).astype(np.int64)
        vs = rng.integers(0, 50, n).astype(np.int64)
        return ks, vs, StreamChunk.from_numpy({"k": ks, "v": vs}, 16)

    first_actor_graph = gp.graph
    for epoch in range(6):
        ks, vs, chunk = mk_chunk()
        if epoch == 3:
            poison.armed = True
        for _attempt in range(4):
            rt.push("gmv", chunk)
            before = rt.mgr.max_committed_epoch
            rt.barrier()
            if rt.mgr.max_committed_epoch > before:
                break
        else:
            raise AssertionError("epoch never committed")
        for k, v in zip(ks.tolist(), vs.tolist()):
            s, c = want.get(k, (0, 0))
            want[k] = (s + v, c + 1)

    assert rt.auto_recoveries == 1 and poison.fired == 1
    assert gp.graph is not first_actor_graph  # actors were rebuilt
    got = {k[0]: v for k, v in mview.snapshot().items()}
    assert got == want
    gp.close()
