"""Outer / semi / anti join types with degree state — oracle-backed
parity incl. retractions (VERDICT r2 #4; reference hash_join.rs:129 +
degree tables join/hash_join.rs:157).

Method: drive random insert/delete streams through HashJoinExecutor,
accumulate the emitted deltas into a row-multiset, and compare against
a from-scratch oracle join over the FINAL side multisets — exact for
every join type because deltas must net to the final join result.
"""

from collections import Counter

import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.hash_join import HashJoinExecutor, JOIN_TYPES
from risingwave_tpu.types import Op

CAP = 16  # chunk capacity

L_DT = {"lk": np.int64, "lv": np.int64}
R_DT = {"rk": np.int64, "rv": np.int64}


def _mk_chunk(rows, side):
    """rows: list of (key, val, op)."""
    k, v, ops = (
        np.array([r[0] for r in rows], np.int64),
        np.array([r[1] for r in rows], np.int64),
        np.array([r[2] for r in rows], np.int32),
    )
    names = ("lk", "lv") if side == "l" else ("rk", "rv")
    return StreamChunk.from_numpy(
        {names[0]: k, names[1]: v}, CAP, ops=ops
    )


def _drain(ex, chunks_out, acc):
    for c in chunks_out:
        d = c.to_numpy(with_ops=True)
        n = len(d["__op__"])
        for i in range(n):
            row = []
            for name in ex.out_names:
                isnull = d.get(name + "__null")
                if isnull is not None and isnull[i]:
                    row.append(None)
                else:
                    row.append(int(d[name][i]))
            sign = 1 if d["__op__"][i] in (Op.INSERT, Op.UPDATE_INSERT) else -1
            acc[tuple(row)] += sign


def _oracle(join_type, left_rows, right_rows):
    """Join of final multisets. Rows: Counter[(k, v)] per side."""
    out = Counter()
    lmatch = Counter()  # left rows with >=1 match
    rmatch = Counter()
    for (lk, lv), lc in left_rows.items():
        for (rk, rv), rc in right_rows.items():
            if lk == rk:
                if join_type in ("inner", "left", "right", "full"):
                    out[(lk, lv, rk, rv)] += lc * rc
                lmatch[(lk, lv)] = 1
                rmatch[(rk, rv)] = 1
    if join_type in ("left", "full"):
        for (lk, lv), lc in left_rows.items():
            if not lmatch.get((lk, lv)):
                out[(lk, lv, None, None)] += lc
    if join_type in ("right", "full"):
        for (rk, rv), rc in right_rows.items():
            if not rmatch.get((rk, rv)):
                out[(None, None, rk, rv)] += rc
    if join_type == "left_semi":
        for (lk, lv), lc in left_rows.items():
            if lmatch.get((lk, lv)):
                out[(lk, lv)] += lc
    if join_type == "left_anti":
        for (lk, lv), lc in left_rows.items():
            if not lmatch.get((lk, lv)):
                out[(lk, lv)] += lc
    if join_type == "right_semi":
        for (rk, rv), rc in right_rows.items():
            if rmatch.get((rk, rv)):
                out[(rk, rv)] += rc
    if join_type == "right_anti":
        for (rk, rv), rc in right_rows.items():
            if not rmatch.get((rk, rv)):
                out[(rk, rv)] += rc
    return out


def _project_oracle(join_type, oracle):
    """Oracle keys are (lk,lv,rk,rv) for pair types; executor output
    column order is sorted(left)+sorted(right) = (lk,lv,rk,rv)."""
    return {k: v for k, v in oracle.items() if v != 0}


def _run_stream(join_type, seed, n_steps=40):
    rng = np.random.default_rng(seed)
    ex = HashJoinExecutor(
        ["lk"], ["rk"], L_DT, R_DT,
        capacity=256, fanout=32, out_cap=1 << 12,
        join_type=join_type,
    )
    left_rows, right_rows = Counter(), Counter()
    acc = Counter()
    for _ in range(n_steps):
        side = "l" if rng.random() < 0.5 else "r"
        mult = left_rows if side == "l" else right_rows
        rows = []
        for _ in range(int(rng.integers(1, 6))):
            if mult and rng.random() < 0.35:
                k, v = list(mult.keys())[int(rng.integers(len(mult)))]
                rows.append((k, v, Op.DELETE))
                mult[(k, v)] -= 1
                if mult[(k, v)] == 0:
                    del mult[(k, v)]
            else:
                k = int(rng.integers(0, 6))
                v = int(rng.integers(0, 4))
                rows.append((k, v, Op.INSERT))
                mult[(k, v)] += 1
        chunk = _mk_chunk(rows, side)
        outs = (ex.apply_left if side == "l" else ex.apply_right)(chunk)
        _drain(ex, outs, acc)
    ex.on_barrier(None)  # raises on overflow/inconsistency
    got = {k: v for k, v in acc.items() if v != 0}
    want = _project_oracle(join_type, _oracle(join_type, left_rows, right_rows))
    return got, want


@pytest.mark.parametrize("join_type", JOIN_TYPES)
def test_join_type_stream_parity(join_type):
    for seed in (1, 2):
        got, want = _run_stream(join_type, seed)
        assert got == want, (
            f"{join_type} seed={seed}: {len(got)} vs {len(want)} rows; "
            f"extra={dict(list((Counter(got) - Counter(want)).items())[:5])} "
            f"missing={dict(list((Counter(want) - Counter(got)).items())[:5])}"
        )


def test_left_join_nullpad_transitions_minimal():
    """The canonical LEFT JOIN dance: unmatched -> NULL pad, match
    arrives -> pad retracted + pair emitted, match leaves -> pad back."""
    ex = HashJoinExecutor(
        ["lk"], ["rk"], L_DT, R_DT,
        capacity=64, fanout=4, out_cap=256, join_type="left",
    )
    acc = Counter()
    _drain(ex, ex.apply_left(_mk_chunk([(1, 10, Op.INSERT)], "l")), acc)
    assert dict(acc) == {(1, 10, None, None): 1}
    _drain(ex, ex.apply_right(_mk_chunk([(1, 77, Op.INSERT)], "r")), acc)
    acc = Counter({k: v for k, v in acc.items() if v != 0})
    assert dict(acc) == {(1, 10, 1, 77): 1}
    _drain(ex, ex.apply_right(_mk_chunk([(1, 77, Op.DELETE)], "r")), acc)
    acc = Counter({k: v for k, v in acc.items() if v != 0})
    assert dict(acc) == {(1, 10, None, None): 1}


def test_semi_anti_multiplicity():
    """Duplicate left rows each count once per stored copy; extra right
    matches do not multiply semi output."""
    ex = HashJoinExecutor(
        ["lk"], ["rk"], L_DT, R_DT,
        capacity=64, fanout=4, out_cap=256, join_type="left_semi",
    )
    acc = Counter()
    _drain(
        ex,
        ex.apply_left(
            _mk_chunk([(1, 10, Op.INSERT), (1, 10, Op.INSERT)], "l")
        ),
        acc,
    )
    assert not +acc  # no matches yet
    _drain(
        ex,
        ex.apply_right(
            _mk_chunk([(1, 1, Op.INSERT), (1, 2, Op.INSERT)], "r")
        ),
        acc,
    )
    acc = Counter({k: v for k, v in acc.items() if v != 0})
    assert dict(acc) == {(1, 10): 2}  # each stored left copy, once


def test_join_checkpoint_roundtrip_with_degrees():
    """Degrees survive checkpoint/recovery: transitions after restore
    behave as if uninterrupted."""
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import CheckpointManager

    def fresh():
        return HashJoinExecutor(
            ["lk"], ["rk"], L_DT, R_DT,
            capacity=64, fanout=4, out_cap=256, join_type="left",
            table_id="j1",
        )

    store = MemObjectStore()
    mgr = CheckpointManager(store)
    ex = fresh()
    acc = Counter()
    _drain(ex, ex.apply_left(_mk_chunk([(1, 10, Op.INSERT)], "l")), acc)
    _drain(ex, ex.apply_right(_mk_chunk([(1, 77, Op.INSERT)], "r")), acc)
    mgr.commit_epoch(1 << 16, [ex])

    ex2 = fresh()
    CheckpointManager(store).recover([ex2])
    # deleting the right row after recovery must revive the NULL pad —
    # only possible if the left row's degree was restored as 1
    _drain(ex2, ex2.apply_right(_mk_chunk([(1, 77, Op.DELETE)], "r")), acc)
    acc = Counter({k: v for k, v in acc.items() if v != 0})
    assert dict(acc) == {(1, 10, None, None): 1}
