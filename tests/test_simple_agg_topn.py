"""SimpleAgg (global agg, simple_agg.rs) + plain retractable TopN
(top_n_plain.rs) — oracle parity incl. retractions and recovery."""

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors import (
    MaterializeExecutor,
    SimpleAggExecutor,
    TopNExecutor,
)
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime import Pipeline
from risingwave_tpu.sql import Catalog, StreamPlanner
from risingwave_tpu.types import Op

CAP = 32
DT = {"k": jnp.int64, "v": jnp.int64}


def _chunk(rows):
    return StreamChunk.from_numpy(
        {
            "k": np.asarray([r[0] for r in rows], np.int64),
            "v": np.asarray([r[1] for r in rows], np.int64),
        },
        CAP,
        ops=np.asarray([r[2] for r in rows], np.int32),
    )


def test_simple_agg_initial_row_and_updates():
    agg = SimpleAggExecutor(
        (AggCall("count_star", None, "cnt"), AggCall("sum", "v", "s")), DT
    )
    mv = MaterializeExecutor(pk=(), columns=("cnt", "s"))
    pipe = Pipeline([agg, mv])
    pipe.barrier()
    assert mv.snapshot() == {(): (0, None)}  # row exists before any input

    pipe.push(_chunk([(1, 10, Op.INSERT), (2, 5, Op.INSERT)]))
    pipe.barrier()
    assert mv.snapshot() == {(): (2, 15)}

    pipe.push(_chunk([(1, 10, Op.DELETE)]))
    pipe.barrier()
    assert mv.snapshot() == {(): (1, 5)}

    pipe.push(_chunk([(2, 5, Op.DELETE)]))
    pipe.barrier()
    assert mv.snapshot() == {(): (0, None)}  # SUM of empty = NULL


def test_simple_agg_checkpoint_roundtrip():
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import CheckpointManager

    store = MemObjectStore()
    agg = SimpleAggExecutor(
        (AggCall("count_star", None, "cnt"), AggCall("sum", "v", "s")),
        DT, table_id="sa",
    )
    agg.apply(_chunk([(1, 7, Op.INSERT), (1, 3, Op.INSERT)]))
    agg.on_barrier(None)
    CheckpointManager(store).commit_epoch(1 << 16, [agg])

    agg2 = SimpleAggExecutor(
        (AggCall("count_star", None, "cnt"), AggCall("sum", "v", "s")),
        DT, table_id="sa",
    )
    CheckpointManager(store).recover([agg2])
    outs = agg2.apply(_chunk([(1, 7, Op.DELETE)]))
    outs = agg2.on_barrier(None)
    d = outs[0].to_numpy(with_ops=True)
    assert d["__op__"].tolist() == [Op.UPDATE_DELETE, Op.UPDATE_INSERT]
    assert d["cnt"].tolist() == [2, 1] and d["s"].tolist() == [10, 3]


def _topn_oracle(rows, n, desc):
    live = sorted(rows.items(), key=lambda kv: (kv[1], kv[0]), reverse=desc)
    return dict(live[:n])


@pytest.mark.parametrize("desc", [False, True])
def test_topn_stream_matches_oracle(desc):
    rng = np.random.default_rng(3)
    ex = TopNExecutor("v", 5, ("k",), DT, desc=desc, capacity=256)
    mv = MaterializeExecutor(pk=("k",), columns=("v",))
    pipe = Pipeline([ex, mv])
    rows = {}
    for _ in range(20):
        batch = []
        for _ in range(int(rng.integers(1, 8))):
            if rows and rng.random() < 0.35:
                k = list(rows)[int(rng.integers(len(rows)))]
                batch.append((k, rows.pop(k), Op.DELETE))
            else:
                k = int(rng.integers(0, 1000))
                v = int(rng.integers(0, 100))
                if k in rows:
                    batch.append((k, rows[k], Op.UPDATE_DELETE))
                    batch.append((k, v, Op.UPDATE_INSERT))
                else:
                    batch.append((k, v, Op.INSERT))
                rows[k] = v
        pipe.push(_chunk(batch))
        pipe.barrier()
        want = {
            (k,): (v,) for k, v in _topn_oracle(rows, 5, desc).items()
        }
        assert mv.snapshot() == want


def test_topn_recovery():
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.state_table import CheckpointManager

    store = MemObjectStore()
    ex = TopNExecutor("v", 3, ("k",), DT, capacity=64, table_id="tn")
    ex.apply(_chunk([(i, i * 10, Op.INSERT) for i in range(6)]))
    ex.on_barrier(None)
    CheckpointManager(store).commit_epoch(1 << 16, [ex])

    ex2 = TopNExecutor("v", 3, ("k",), DT, capacity=64, table_id="tn")
    CheckpointManager(store).recover([ex2])
    # deleting the current minimum must pull in the next row (40)
    outs = ex2.apply(_chunk([(0, 0, Op.DELETE)]))
    outs = ex2.on_barrier(None)
    snap = {}
    for c in outs:
        d = c.to_numpy(with_ops=True)
        for i in range(len(d["__op__"])):
            if d["__op__"][i] == Op.DELETE:
                snap.pop(int(d["k"][i]), None)
            else:
                snap[int(d["k"][i])] = int(d["v"][i])
    assert snap == {3: 30}  # 0 dropped out, 3 entered the top-3


def test_sql_simple_agg_and_topn():
    from risingwave_tpu.connectors.nexmark import (
        BID_SCHEMA, NexmarkConfig, NexmarkGenerator,
    )

    catalog = Catalog({"bid": BID_SCHEMA})
    planner = StreamPlanner(catalog, capacity=1 << 12)
    tot = planner.plan(
        "CREATE MATERIALIZED VIEW t AS SELECT count(*) AS n, "
        "sum(price) AS vol FROM bid"
    )
    top = planner.plan(
        "CREATE MATERIALIZED VIEW top AS SELECT auction, price "
        "FROM bid ORDER BY price DESC LIMIT 10"
    )
    gen = NexmarkGenerator(NexmarkConfig())
    prices = []
    for _ in range(3):
        bid = gen.next_chunks(1200, 2048)["bid"]
        d = bid.to_numpy(False)
        prices.extend(zip(d["auction"].tolist(), d["price"].tolist()))
        tot.pipeline.push(bid)
        top.pipeline.push(bid)
        tot.pipeline.barrier()
        top.pipeline.barrier()
    assert tot.mview.snapshot() == {
        (): (len(prices), sum(p for _, p in prices))
    }
    got = sorted(
        (v[1] if len(v) > 1 else v[0])
        for v in top.mview.snapshot().values()
    )
    want = sorted(sorted((p for _, p in prices), reverse=True)[:10])
    assert len(got) == 10
    assert got == want
