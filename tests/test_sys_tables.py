"""``rw_`` system tables + end-to-end freshness (the PR 16 surface).

The introspection contract: the runtime's own state — fragments,
arrangements, per-MV freshness, barrier latency + backpressure verdict,
channel depths, fusion status, recovery events — is queryable as plain
SQL relations through the SAME lock-free snapshot path shared MVs ride,
while streaming continues and across partial recovery. Plus the
freshness twin discipline: the fused and interpreted q5 twins must
agree not just on MV content but on the freshness frontier itself
(same epochs, same low-watermark values).
"""

import socket
import struct
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.frontend import PgServer, SqlSession
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime.fragmenter import GraphPipeline
from risingwave_tpu.runtime.graph import FragmentSpec
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.sim import CrashingExecutor
from risingwave_tpu.sql import Catalog
from risingwave_tpu.storage.object_store import MemObjectStore

pytestmark = pytest.mark.smoke

RW_TABLES = (
    "rw_fragments",
    "rw_arrangements",
    "rw_mv_freshness",
    "rw_barrier_latency",
    "rw_channel_depths",
    "rw_fusion_status",
    "rw_recovery_events",
    "rw_memory",
    "rw_overload_state",
)


# ---------------------------------------------------------------------------
# direct-session surface
# ---------------------------------------------------------------------------


def _session():
    s = SqlSession(Catalog({}), capacity=1 << 8)
    s.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    s.execute(
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k, sum(v) AS sv FROM t GROUP BY k"
    )
    s.execute("INSERT INTO t VALUES (1, 10), (2, 5), (1, 32)")
    return s


def test_every_rw_table_selectable():
    """All seven relations answer SELECT * (a failing builder degrades
    to empty rows, never an error)."""
    s = _session()
    for name in RW_TABLES:
        out, tag = s.execute(f"SELECT * FROM {name}")
        assert tag.startswith("SELECT"), name
        assert isinstance(out, dict) and out, name


def test_rw_fragments_and_fusion_status_describe_the_mv():
    s = _session()
    out, _ = s.execute("SELECT name, kind, executors FROM rw_fragments")
    names = [str(x) for x in out["name"]]
    assert "m" in names
    i = names.index("m")
    assert int(out["executors"][i]) >= 1
    out, _ = s.execute("SELECT fragment, executors FROM rw_fusion_status")
    assert "m" in [str(x) for x in out["fragment"]]


def test_rw_mv_freshness_tracks_barriers():
    """Every INSERT-driven barrier publishes a freshness row: the
    commit->visible wall is measured (>= 0), the epoch advances with
    further barriers, and barriers counts them."""
    s = _session()
    out, _ = s.execute(
        "SELECT mv, epoch, commit_to_visible_ms, barriers, staleness_ms "
        "FROM rw_mv_freshness"
    )
    mvs = [str(x) for x in out["mv"]]
    assert "m" in mvs
    i = mvs.index("m")
    e0 = int(out["epoch"][i])
    assert float(out["commit_to_visible_ms"][i]) >= 0.0
    assert float(out["staleness_ms"][i]) >= 0.0
    b0 = int(out["barriers"][i])
    s.execute("INSERT INTO t VALUES (3, 7)")
    out, _ = s.execute(
        "SELECT mv, epoch, barriers FROM rw_mv_freshness"
    )
    mvs = [str(x) for x in out["mv"]]
    i = mvs.index("m")
    assert int(out["epoch"][i]) > e0  # freshness is monotone in epoch
    assert int(out["barriers"][i]) == b0 + 1


def test_rw_barrier_latency_carries_backpressure_verdict():
    s = _session()
    out, _ = s.execute(
        "SELECT epoch, wall_ms, backpressure_fragment, backpressure_ms "
        "FROM rw_barrier_latency"
    )
    assert len(out["epoch"]) >= 1
    assert all(float(w) >= 0.0 for w in out["wall_ms"])
    # at least the latest barrier names its bottleneck fragment
    frags = [str(x) for x in out["backpressure_fragment"]]
    assert any(f for f in frags)


def test_rw_memory_carries_the_ledger_and_total_row():
    """rw_memory surfaces the governor's per-table device-state ledger
    plus a ``_total`` reconciliation row (ledger vs deviceprof modeled
    vs sampled memory_stats). The ledger is walked on the barrier
    clock while ARMED (dormant by default: tier-1 untouched)."""
    s = _session()
    gov = s.runtime.memory_governor
    assert gov.enabled is False  # dormant by default
    gov.budget_bytes = 1 << 30
    gov.enabled = True
    s.execute("INSERT INTO t VALUES (4, 1)")  # a governed barrier
    out, _ = s.execute(
        "SELECT table_id, executor, ledger_bytes, vetoes FROM rw_memory"
    )
    tids = [str(x) for x in out["table_id"]]
    assert "_total" in tids
    i = tids.index("_total")
    total = int(out["ledger_bytes"][i])
    per_table = [
        int(b) for t, b in zip(tids, out["ledger_bytes"]) if t != "_total"
    ]
    assert per_table, "no per-table ledger rows — executors unaccounted"
    assert total == sum(per_table) >= 0
    assert all(int(v) == 0 for v in out["vetoes"])  # ample budget


def test_rw_overload_state_tracks_the_ladder_and_credits():
    """rw_overload_state reflects the ladder rung and per-fragment
    credit windows; a raised ladder with derived credits produces one
    row per fragment."""
    from risingwave_tpu.runtime.memory_governor import THROTTLED

    s = _session()
    out, _ = s.execute(
        "SELECT fragment, credit, state, score, flaps FROM rw_overload_state"
    )
    assert [str(x) for x in out["state"]] == ["NORMAL"]
    assert float(out["credit"][0]) == 1.0

    gov = s.runtime.memory_governor
    gov.ladder.step(0.99)  # raise the ladder directly
    gov.admission.rederive(THROTTLED, 0.8, fragments=("m",))
    out, _ = s.execute(
        "SELECT fragment, credit, state, last_to FROM rw_overload_state"
    )
    frags = [str(x) for x in out["fragment"]]
    assert "m" in frags
    i = frags.index("m")
    assert 0.0 <= float(out["credit"][i]) <= 1.0
    assert str(out["state"][i]) == "DEGRADED"
    assert str(out["last_to"][i]) == "DEGRADED"


def test_rw_ddl_guard():
    """The rw_ namespace is reserved: DROP refuses, CREATE collides."""
    s = _session()
    with pytest.raises(ValueError, match="system table"):
        s.execute("DROP TABLE rw_fragments")
    with pytest.raises(ValueError, match="exists"):
        s.execute("CREATE TABLE rw_fragments (x BIGINT)")


def test_render_prometheus_exposed():
    """metrics.render_prometheus() is the module-level scrape surface
    the dashboard links to."""
    from risingwave_tpu import metrics

    metrics.REGISTRY.counter("sys_tables_probe_total").inc()
    text = metrics.render_prometheus()
    assert isinstance(text, str)
    assert "sys_tables_probe_total" in text
    assert metrics.REGISTRY.render_prometheus() == text


# ---------------------------------------------------------------------------
# pgwire: lock-free rw_ reads while streaming continues
# ---------------------------------------------------------------------------


class PgClient:
    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        params = b"user\0test\0database\0dev\0\0"
        body = struct.pack("!I", 196608) + params
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        self._drain_until_ready()

    def _recv_exact(self, n):
        buf = b""
        while len(buf) < n:
            got = self.sock.recv(n - len(buf))
            assert got, "server closed"
            buf += got
        return buf

    def _read_msg(self):
        head = self._recv_exact(5)
        tag = head[:1]
        (length,) = struct.unpack("!I", head[1:])
        return tag, self._recv_exact(length - 4)

    def _drain_until_ready(self):
        msgs = []
        while True:
            tag, body = self._read_msg()
            msgs.append((tag, body))
            if tag == b"Z":
                return msgs

    def query(self, sql):
        body = sql.encode() + b"\0"
        self.sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
        rows, names, tagline, err = [], [], None, None
        for tag, body in self._drain_until_ready():
            if tag == b"T":
                (ncols,) = struct.unpack("!h", body[:2])
                at = 2
                for _ in range(ncols):
                    end = body.index(b"\0", at)
                    names.append(body[at:end].decode())
                    at = end + 1 + 18
            elif tag == b"D":
                (ncols,) = struct.unpack("!h", body[:2])
                at = 2
                row = []
                for _ in range(ncols):
                    (ln,) = struct.unpack("!i", body[at : at + 4])
                    at += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(body[at : at + ln].decode())
                        at += ln
                rows.append(tuple(row))
            elif tag == b"C":
                tagline = body.rstrip(b"\0").decode()
            elif tag == b"E":
                err = body
        return names, rows, tagline, err

    def close(self):
        self.sock.sendall(b"X" + struct.pack("!I", 4))
        self.sock.close()


def test_pgwire_rw_selects_under_concurrent_streaming():
    """A reader connection hammers rw_mv_freshness / rw_barrier_latency
    while a writer connection streams INSERT-driven barriers: every
    read decodes cleanly (no torn rows off the lock-free path) and the
    MV's freshness epoch is MONOTONE across reads."""
    srv = PgServer(SqlSession(Catalog({}), capacity=1 << 8)).start()
    writer = reader = None
    try:
        writer = PgClient(srv.port)
        reader = PgClient(srv.port)
        _, _, _, err = writer.query("CREATE TABLE t (k BIGINT, v BIGINT)")
        assert err is None
        _, _, _, err = writer.query(
            "CREATE MATERIALIZED VIEW m AS "
            "SELECT k, sum(v) AS sv FROM t GROUP BY k"
        )
        assert err is None
        write_errs = []

        def feed():
            for i in range(30):
                _, _, _, e = writer.query(
                    f"INSERT INTO t VALUES ({i % 5}, {i})"
                )
                if e is not None:
                    write_errs.append(e)
                    return

        th = threading.Thread(target=feed)
        th.start()
        last_epoch, reads = -1, 0
        while th.is_alive() or reads == 0:
            names, rows, tag, err = reader.query(
                "SELECT mv, epoch, commit_to_visible_ms FROM rw_mv_freshness"
            )
            assert err is None, err
            for r in rows:
                if r[0] == "m":
                    e = int(r[1])
                    assert e >= last_epoch, "freshness epoch went BACK"
                    last_epoch = e
                    assert float(r[2]) >= 0.0
            reads += 1
            if reads > 500:  # safety valve, never spins forever
                break
        th.join(timeout=30)
        assert not th.is_alive() and write_errs == []
        assert reads > 0 and last_epoch > 0
        _, rows, _, err = reader.query(
            "SELECT epoch, wall_ms, backpressure_fragment "
            "FROM rw_barrier_latency"
        )
        assert err is None and len(rows) >= 1
        for r in rows:
            assert float(r[1]) >= 0.0
    finally:
        for c in (writer, reader):
            if c is not None:
                c.close()
        srv.shutdown()


# ---------------------------------------------------------------------------
# partial recovery: events land in rw_recovery_events, freshness survives
# ---------------------------------------------------------------------------


def _mk_singleton(name, crash=None):
    agg = HashAggExecutor(
        group_keys=("k",),
        calls=(AggCall("sum", "v", "s"), AggCall("count_star", None, "c")),
        schema_dtypes={"k": jnp.int64, "v": jnp.int64},
        capacity=1 << 8,
        table_id=f"{name}.agg",
    )
    mv = MaterializeExecutor(
        pk=("k",), columns=("s", "c"), table_id=f"{name}.mview"
    )
    chain = ([crash] if crash is not None else []) + [agg, mv]
    specs = [
        FragmentSpec("src", lambda i: []),
        FragmentSpec(
            "work", lambda i, c=tuple(chain): list(c), inputs=[("src", 0)]
        ),
    ]
    gp = GraphPipeline(
        specs, {"single": "src"}, "work", chain,
        ckpt_fragments=["work"] * len(chain),
    )
    return gp, mv


def test_recovery_events_and_freshness_across_partial_recovery():
    """Crash one MV's fragment mid-stream: the partial recovery lands
    in rw_recovery_events (partial + partial_done, seq-ordered), both
    MVs keep freshness rows, and the healthy MV's freshness epoch keeps
    advancing across the recovery window (monotone, never reset)."""
    rt = StreamingRuntime(
        MemObjectStore(), async_checkpoint=False, auto_recover=True
    )
    s = SqlSession(Catalog({}), rt, capacity=1 << 8)
    crash = CrashingExecutor("mv_b")
    gpa, _mva = _mk_singleton("mv_a")
    gpb, _mvb = _mk_singleton("mv_b", crash=crash)
    rt.register("mv_a", gpa)
    rt.register("mv_b", gpb)
    seq0 = max((e["seq"] for e in EVENT_LOG.events()), default=0)
    rng = np.random.default_rng(31)

    def feed():
        n = int(rng.integers(4, 10))
        c = StreamChunk.from_numpy(
            {"k": rng.integers(0, 4, n).astype(np.int64),
             "v": rng.integers(0, 40, n).astype(np.int64)}, 16,
        )
        rt.push("mv_a", c)
        rt.push("mv_b", c)

    epochs_a = []
    try:
        for i in range(5):
            if i == 3:
                crash.arm("apply", after=1)
            feed()
            before = rt.mgr.max_committed_epoch
            rt.barrier()
            if rt.mgr.max_committed_epoch == before:
                assert rt.last_recovery_mode == "partial"
                rt.barrier()
            out, _ = s.execute("SELECT mv, epoch FROM rw_mv_freshness")
            mvs = [str(x) for x in out["mv"]]
            assert "mv_a" in mvs and "mv_b" in mvs
            epochs_a.append(int(out["epoch"][mvs.index("mv_a")]))
        rt.wait_checkpoints()
    finally:
        gpa.close()
        gpb.close()
    assert crash.kills == 1 and rt.partial_recoveries == 1
    assert epochs_a == sorted(epochs_a)  # monotone ACROSS the recovery
    assert epochs_a[-1] > epochs_a[0]
    out, _ = s.execute("SELECT seq, mode, epoch FROM rw_recovery_events")
    new = [
        (int(q), str(m))
        for q, m in zip(out["seq"], out["mode"])
        if int(q) > seq0
    ]
    modes = [m for _q, m in new]
    assert "partial" in modes and "partial_done" in modes
    assert [q for q, _m in new] == sorted(q for q, _m in new)


# ---------------------------------------------------------------------------
# twin discipline: freshness frontier identical fused vs interpreted
# ---------------------------------------------------------------------------


def test_freshness_frontier_bit_identical_fused_vs_interpreted():
    """The fused q5 twin must agree with the interpreted twin on MV
    content AND the freshness surface itself: same epochs, same
    low-watermark frontier per barrier (commit->visible walls are wall
    clock and legitimately differ), with every barrier sampled."""
    from risingwave_tpu.connectors.nexmark import (
        NexmarkConfig,
        NexmarkGenerator,
    )
    from risingwave_tpu.queries.nexmark_q import build_q5_lite
    from risingwave_tpu.runtime.fused_step import fuse_pipeline

    def drive(fuse):
        q5 = build_q5_lite(capacity=1 << 10, state_cleaning=False)
        if fuse:
            wrappers = fuse_pipeline(q5.pipeline, label="q5")
            assert wrappers and wrappers[0].covers_whole_chain
        gen = NexmarkGenerator(NexmarkConfig(first_event_rate=5_000))
        mx = 0
        for _ in range(3):
            c = None
            while c is None:
                c = gen.next_chunks(400, 512)["bid"]
            q5.pipeline.push(c)
            mx = max(mx, int(c.to_numpy()["date_time"].max()))
            q5.pipeline.watermark("date_time", mx)
            q5.pipeline.barrier()
        return q5.mview.snapshot(), list(q5.pipeline.freshness_samples)

    snap_i, fr_i = drive(False)
    snap_f, fr_f = drive(True)
    assert snap_i == snap_f
    assert len(fr_i) == len(fr_f) == 3  # every barrier sampled
    # the low-watermark frontier is data-derived and must be bit-equal;
    # epochs are physical-time stamps — monotone within a twin, not
    # comparable across twins
    frontier = lambda fr: [x["low_watermark"] for x in fr]
    assert frontier(fr_i) == frontier(fr_f)
    for fr in (fr_i, fr_f):
        es = [x["epoch"] for x in fr]
        assert es == sorted(es) and len(set(es)) == len(es)
    for x in fr_f:
        assert x["commit_to_visible_ms"] >= 0.0
        assert x["source_to_visible_ms"] is not None
        assert x["low_watermark"] is not None
