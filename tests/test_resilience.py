"""Transient-fault resilience layer (reference: src/object_store/'s
retrying monitored wrapper + the madsim fault-injection tier):
RetryPolicy bounds, CircuitBreaker lifecycle, the RetryingObjectStore
durability boundary, degraded-mode checkpointing in the runtime, and
offset-anchored source-read retry."""

import time

import numpy as np
import pytest

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.metrics import REGISTRY
from risingwave_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    DeltaSpill,
    RetryBudgetExceeded,
    RetryingObjectStore,
    RetryPolicy,
    TransientStoreError,
)
from risingwave_tpu.runtime.pipeline import Pipeline
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.sim import FlakyStore
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager, StateDelta

pytestmark = pytest.mark.smoke


def _fast_policy(**kw):
    d = dict(
        max_attempts=4, base_backoff_s=1e-4, max_backoff_s=1e-3,
        deadline_s=5.0,
    )
    d.update(kw)
    return RetryPolicy(**d)


# -- RetryPolicy -----------------------------------------------------------
def test_retry_policy_retries_transient_then_succeeds():
    p = _fast_policy()
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise TransientStoreError("blip")
        return "ok"

    assert p.run(fn, op="t") == "ok"
    assert len(calls) == 3


def test_retry_policy_fatal_errors_propagate_immediately():
    p = _fast_policy()
    calls = []

    def fn():
        calls.append(1)
        raise FileNotFoundError("semantic miss, not transient")

    with pytest.raises(FileNotFoundError):
        p.run(fn, op="t")
    assert len(calls) == 1  # no retry burned on a fatal error


def test_retry_policy_attempt_budget_bounds():
    p = _fast_policy(max_attempts=3)
    calls = []

    def fn():
        calls.append(1)
        raise TransientStoreError("down")

    with pytest.raises(RetryBudgetExceeded) as ei:
        p.run(fn, op="t")
    assert len(calls) == 3
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last_error, TransientStoreError)


def test_retry_policy_deadline_bounds_with_fake_clock():
    """Provably deadline-bounded: with a fake clock, the loop must stop
    as soon as elapsed + next backoff crosses the deadline — no sleep
    may ever run past it."""
    p = RetryPolicy(
        max_attempts=1000, base_backoff_s=1.0, max_backoff_s=1.0,
        jitter_frac=0.0, deadline_s=3.5,
    )
    now = [0.0]
    sleeps = []

    def clock():
        return now[0]

    def sleep(s):
        sleeps.append(s)
        now[0] += s

    def fn():
        raise TransientStoreError("down forever")

    with pytest.raises(RetryBudgetExceeded) as ei:
        p.run(fn, op="t", clock=clock, sleep=sleep)
    assert sum(sleeps) < 3.5  # never slept past the deadline
    assert ei.value.attempts < 1000  # deadline, not attempts, stopped it


def test_retry_backoff_deterministic_for_seed():
    import random

    a = RetryPolicy(seed=9)
    b = RetryPolicy(seed=9)
    ra, rb = random.Random(9), random.Random(9)
    sched_a = [a.backoff_s(i, ra) for i in range(1, 6)]
    sched_b = [b.backoff_s(i, rb) for i in range(1, 6)]
    assert sched_a == sched_b  # seeded jitter replays exactly
    assert all(s <= a.max_backoff_s for s in sched_a)


def test_from_env_set_env_wins_over_caller_defaults(monkeypatch):
    """RW_RETRY_* is the operator's no-restart escape hatch: a SET env
    var must win even over a caller's pinned defaults; unset knobs fall
    back to those defaults."""
    monkeypatch.setenv("RW_RETRY_MAX_ATTEMPTS", "12")
    p = RetryPolicy.from_env(max_attempts=3, deadline_s=4.0)
    assert p.max_attempts == 12  # env wins
    assert p.deadline_s == 4.0  # unset knob: caller default holds
    monkeypatch.delenv("RW_RETRY_MAX_ATTEMPTS")
    assert RetryPolicy.from_env(max_attempts=3).max_attempts == 3
    monkeypatch.setenv("RW_BREAKER_THRESHOLD", "9")
    br = CircuitBreaker.from_env("t_env", failure_threshold=2)
    assert br.failure_threshold == 9


# -- CircuitBreaker --------------------------------------------------------
def test_breaker_lifecycle_and_events():
    now = [0.0]
    br = CircuitBreaker(
        "t_lifecycle", failure_threshold=2, cooldown_s=1.0,
        clock=lambda: now[0],
    )
    seq0 = len(EVENT_LOG.events(kind="breaker"))
    assert br.allow() and br.state == "closed"
    br.record_failure()
    assert br.state == "closed"  # below threshold
    br.record_failure()
    assert br.state == "open" and not br.allow()
    now[0] += 1.1  # cooldown elapses -> half-open probe allowed
    assert br.allow() and br.state == "half_open"
    br.record_failure()  # probe failed -> reopen
    assert br.state == "open"
    now[0] += 1.1
    assert br.allow()
    br.record_success()
    assert br.state == "closed"
    kinds = [
        (e["frm"], e["to"])
        for e in EVENT_LOG.events(kind="breaker")[seq0:]
        if e["name"] == "t_lifecycle"
    ]
    assert kinds == [
        ("closed", "open"),
        ("open", "half_open"),
        ("half_open", "open"),
        ("open", "half_open"),
        ("half_open", "closed"),
    ]
    assert (
        REGISTRY.counter("breaker_transitions_total").get(
            name="t_lifecycle", to="open"
        )
        >= 2
    )


# -- RetryingObjectStore ---------------------------------------------------
def test_retrying_store_absorbs_flaky_faults():
    disk = MemObjectStore()
    rs = RetryingObjectStore(
        FlakyStore(disk, rate=0.4, seed=11),
        _fast_policy(max_attempts=10),
    )
    for i in range(30):
        rs.put(f"k{i}", bytes([i]))
    assert [rs.read(f"k{i}") for i in range(30)] == [
        bytes([i]) for i in range(30)
    ]
    assert rs.inner.faults > 0  # the storm actually fired


def test_retrying_store_breaker_opens_and_fast_fails():
    class Down:
        def put(self, path, data):
            raise TransientStoreError("down")

    br = CircuitBreaker("t_store", failure_threshold=3, cooldown_s=60.0)
    rs = RetryingObjectStore(Down(), _fast_policy(max_attempts=3), br)
    with pytest.raises(RetryBudgetExceeded):
        rs.put("a", b"x")  # 3 attempts = 3 failures -> breaker opens
    assert br.state == "open"
    with pytest.raises(CircuitOpenError):
        rs.put("b", b"y")  # fast-fail: no attempt reaches the store


def test_retrying_store_never_catches_crashpoint():
    from risingwave_tpu.sim import CrashingStore, CrashPoint

    crashing = CrashingStore(MemObjectStore())
    crashing.arm(1)
    rs = RetryingObjectStore(crashing, _fast_policy())
    with pytest.raises(CrashPoint):
        rs.put("a", b"x")  # a process death is NOT retried


# -- DeltaSpill ------------------------------------------------------------
def test_delta_spill_roundtrip(tmp_path):
    spill = DeltaSpill(str(tmp_path))
    d = StateDelta(
        "t1",
        {"k": np.array([1, 2], np.int64)},
        {"v": np.array([1.5, 2.5], np.float64)},
        np.array([False, True]),
        ("k",),
    )
    spill.spill(7 << 16, [d])
    assert spill.epochs() == [7 << 16]
    (back,) = spill.load(7 << 16)
    assert back.table_id == "t1" and back.key_order == ("k",)
    np.testing.assert_array_equal(back.key_cols["k"], d.key_cols["k"])
    np.testing.assert_array_equal(back.value_cols["v"], d.value_cols["v"])
    np.testing.assert_array_equal(back.tombstone, d.tombstone)
    spill.remove(7 << 16)
    assert spill.epochs() == []


# -- degraded-mode runtime -------------------------------------------------
class ToggleStore(MemObjectStore):
    """MemObjectStore with a kill switch: while ``down``, every op is a
    transient fault (the hard-down blob store)."""

    def __init__(self):
        super().__init__()
        self.down = False

    def _gate(self):
        if self.down:
            raise TransientStoreError("store is down")

    def put(self, path, data):
        self._gate()
        super().put(path, data)

    def read(self, path):
        self._gate()
        return super().read(path)

    def read_range(self, path, off, length):
        self._gate()
        return super().read_range(path, off, length)

    def exists(self, path):
        self._gate()
        return super().exists(path)

    def list(self, prefix):
        self._gate()
        return super().list(prefix)

    def delete(self, path):
        self._gate()
        super().delete(path)


def _chunk(ids, vals, cap=8):
    return StreamChunk.from_numpy(
        {"id": np.asarray(ids, np.int64), "v": np.asarray(vals, np.int64)},
        cap,
    )


def test_runtime_degrades_spills_and_restores(tmp_path):
    """The acceptance path: breaker opens mid-epoch -> the runtime
    keeps serving queries from live state, spills checkpoint deltas
    locally, pauses compaction; when the store heals the spill replays
    in order, sinks release, and the manifest catches up — with
    degraded/restored/breaker transitions visible in the event log."""
    toggle = ToggleStore()
    breaker = CircuitBreaker(
        "t_degraded", failure_threshold=2, cooldown_s=0.2
    )
    store = RetryingObjectStore(
        toggle, _fast_policy(max_attempts=2, deadline_s=1.0), breaker
    )
    rt = StreamingRuntime(
        store,
        async_checkpoint=False,
        checkpoint_frequency=1,
        degraded_dir=str(tmp_path / "spill"),
    )
    assert rt.store_breaker is breaker  # pre-wrapped store adopts it
    mv = MaterializeExecutor(pk=["id"], columns=["v"], table_id="mv_dg")
    rt.register("f", Pipeline([mv]))

    seq0 = len(EVENT_LOG.events())
    rt.push("f", _chunk([1, 2], [10, 20]))
    rt.barrier()  # epoch 1: durable while healthy
    e1 = rt.mgr.max_committed_epoch
    assert e1 > 0 and not rt.degraded

    toggle.down = True
    rt.push("f", _chunk([3], [30]))
    rt.barrier()  # breaker opens mid-epoch -> degrade, no raise
    assert rt.degraded and breaker.state == "open"
    assert len(rt._spill.epochs()) == 1
    assert rt._compact_pause.is_set()  # compaction paused
    # queries still answer from live/HBM state (all three epochs' rows)
    assert mv.snapshot()[(3,)] == (30,)
    rt.push("f", _chunk([4], [40]))
    rt.barrier()  # still down: spills directly, no store touch
    assert len(rt._spill.epochs()) == 2
    assert rt.mgr.max_committed_epoch == e1  # manifest frozen at e1

    toggle.down = False
    time.sleep(0.25)  # let the breaker cooldown elapse
    rt.push("f", _chunk([5], [50]))
    rt.barrier()  # probe half-opens, replays the spill, commits live
    assert not rt.degraded and breaker.state == "closed"
    assert rt._spill.epochs() == []
    assert rt.mgr.max_committed_epoch > e1
    assert not rt._compact_pause.is_set()

    events = EVENT_LOG.events()[seq0:]
    kinds = [e["kind"] for e in events]
    assert "degraded" in kinds and "restored" in kinds
    restored = [e for e in events if e["kind"] == "restored"][-1]
    assert restored["epochs_replayed"] == 2
    opens = [
        e for e in events
        if e["kind"] == "breaker" and e.get("name") == "t_degraded"
    ]
    assert ("closed", "open") in [(e["frm"], e["to"]) for e in opens]
    assert ("half_open", "closed") in [(e["frm"], e["to"]) for e in opens]
    assert REGISTRY.counter("degraded_entries_total").get() >= 1

    # the replayed manifest is complete: a fresh recovery sees ALL rows
    mv2 = MaterializeExecutor(pk=["id"], columns=["v"], table_id="mv_dg")
    CheckpointManager(toggle).recover([mv2])
    assert mv2.snapshot() == mv.snapshot()
    assert sorted(mv2.snapshot()) == [(1,), (2,), (3,), (4,), (5,)]


def test_runtime_recover_discards_stale_spill(tmp_path):
    """recover() lands on the last DURABLE manifest; a degraded spill
    of rolled-back epochs must be discarded (sources replay), never
    replayed on top of the restored state."""
    toggle = ToggleStore()
    rt = StreamingRuntime(
        RetryingObjectStore(
            toggle,
            _fast_policy(max_attempts=2, deadline_s=1.0),
            CircuitBreaker("t_discard", failure_threshold=1, cooldown_s=99),
        ),
        async_checkpoint=False,
        degraded_dir=str(tmp_path / "spill"),
    )
    mv = MaterializeExecutor(pk=["id"], columns=["v"], table_id="mv_dc")
    rt.register("f", Pipeline([mv]))
    rt.push("f", _chunk([1], [10]))
    rt.barrier()
    toggle.down = True
    rt.push("f", _chunk([2], [20]))
    rt.barrier()
    assert rt.degraded and rt._spill.epochs()
    toggle.down = False
    rt.recover()
    assert not rt.degraded and rt._spill.epochs() == []
    assert mv.snapshot() == {(1,): (10,)}  # epoch 2 rolled back cleanly


# -- source read retry -----------------------------------------------------
def test_source_poll_retries_anchored_at_offset():
    """A transient read fault mid-poll retries from the SAME offset:
    output and committed offsets match an undisturbed twin exactly (no
    skipped or double-counted events)."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.connectors.source import NexmarkSourceExecutor

    calm = NexmarkSourceExecutor(NexmarkConfig(), split_num=2)
    flaky = NexmarkSourceExecutor(
        NexmarkConfig(), split_num=2, retry_policy=_fast_policy()
    )
    g = flaky.splits[0]
    orig = g.next_chunks
    fails = [2]

    def flaky_next(n, cap):
        if fails[0] > 0:
            fails[0] -= 1
            # fail AFTER consuming some events: the un-anchored retry
            # would skip them
            orig(max(1, n // 2), cap)
            raise TransientStoreError("connector blip")
        return orig(n, cap)

    g.next_chunks = flaky_next
    want = calm.poll(300, 512)
    got = flaky.poll(300, 512)
    assert fails[0] == 0  # the fault actually fired (twice)
    assert [s.offset for s in calm.splits] == [
        s.offset for s in flaky.splits
    ]
    for stream in ("person", "auction", "bid"):
        assert len(want[stream]) == len(got[stream])
        for cw, cg in zip(want[stream], got[stream]):
            for k, v in cw.to_numpy().items():
                np.testing.assert_array_equal(v, cg.to_numpy()[k])


# -- bounded manager read retry (satellite) --------------------------------
def test_manager_read_retry_is_deadline_bounded():
    """_read_retry must give up within the policy budget instead of
    spinning on a wedged manifest race, and expose attempts via the
    retry metrics."""
    mgr = CheckpointManager(
        MemObjectStore(),
        read_retry=RetryPolicy(
            max_attempts=3, base_backoff_s=1e-4, max_backoff_s=1e-3,
            deadline_s=2.0,
        ),
    )
    d = StateDelta(
        "t", {"k": np.array([1], np.int64)},
        {"v": np.array([2], np.int64)}, np.array([False]), ("k",),
    )
    mgr.commit_staged(1 << 16, [d])
    before = REGISTRY.counter("retries_total").get(op="storage.read")
    calls = []

    def wedged():
        calls.append(1)
        raise ValueError("decode race that never heals")

    with pytest.raises(RetryBudgetExceeded):
        mgr._read_retry(wedged)
    assert len(calls) == 3  # bounded, not an unbounded spin
    after = REGISTRY.counter("retries_total").get(op="storage.read")
    assert after - before == 3
    # and KeyError (user error) still surfaces immediately, unretried
    with pytest.raises(KeyError):
        mgr._read_retry(lambda: (_ for _ in ()).throw(KeyError("bad")))
