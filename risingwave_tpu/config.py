"""Layered configuration — the RwConfig analogue.

Reference: src/common/src/config.rs:138 (``RwConfig { server,
streaming, storage, ... }``, TOML + serde defaults + an
``unrecognized`` capture) and src/common/src/system_param/mod.rs:77
(cluster-wide MUTABLE system params: ``barrier_interval_ms``,
``checkpoint_frequency``).

Layering (config.rs order): dataclass defaults <- TOML file <-
explicit overrides. Unknown TOML keys are collected, not fatal —
matching the reference's forward-compatible `#[serde(default)]` +
unrecognized-capture pattern.
"""

from __future__ import annotations

try:
    import tomllib
except ModuleNotFoundError:  # python < 3.11
    import tomli as tomllib
from dataclasses import dataclass, field, fields
from typing import Any, Dict, Optional


@dataclass
class StreamingConfig:
    """config.rs:546 StreamingConfig (the knobs our runtime consumes)."""

    chunk_capacity: int = 4096  # fixed chunk shape (stream_chunk size)
    in_flight_checkpoints: int = 8  # async upload lane depth
    # rwlint at CREATE-MV time (analysis/): True turns error-severity
    # diagnostics into DDL-time failures instead of runtime corruption.
    # Env escape hatch: RW_STRICT_LINT=0 (SqlSession reads it when the
    # session is built without an explicit setting).
    strict_lint: bool = True


@dataclass
class StorageConfig:
    """config.rs:631 StorageConfig subset."""

    object_store_root: str = "./rw_state"
    compact_at: int = 8  # SSTs per table before full-merge compaction
    bloom_bits_per_key: int = 10


@dataclass
class SystemParams:
    """Mutable cluster params (system_param/mod.rs:77-78)."""

    barrier_interval_ms: int = 1000
    checkpoint_frequency: int = 1


@dataclass
class ResilienceConfig:
    """Transient-fault knobs at the durability boundary (reference:
    ObjectStoreConfig's retry/timeout block, src/object_store/). These
    feed ``resilience.RetryPolicy`` / ``CircuitBreaker`` as the
    baseline; a SET ``RW_RETRY_*`` / ``RW_BREAKER_*`` env knob wins
    over the config (the operator's no-restart/no-file escape hatch).
    Defaults mirror the env defaults."""

    retry_max_attempts: int = 8
    retry_base_backoff_ms: int = 50
    retry_max_backoff_ms: int = 2000
    retry_deadline_s: float = 30.0
    breaker_threshold: int = 5
    breaker_cooldown_s: float = 5.0


@dataclass
class ProfilerConfig:
    """Dispatch-wall profiler knobs (profiler.py). ``enabled`` turns on
    per-executor attribution + dispatch/transfer counting;
    ``slow_barrier_capture_ms`` auto-emits a PROFILE_* artifact (and a
    forensic stall dump) when a barrier exceeds it; ``jax_trace`` arms
    a real ``jax.profiler.trace`` window inside captures (heavy — the
    JSON artifact is always written regardless). Env knobs
    (RW_PROFILE, RW_PROFILE_SLOW_MS, RW_PROFILE_DIR,
    RW_PROFILE_JAX_TRACE, RW_PROFILE_FENCE) win over the file."""

    enabled: bool = False
    fence: bool = True
    slow_barrier_capture_ms: float = 0.0  # 0 = no auto-capture
    capture_dir: str = ""
    jax_trace: bool = False


@dataclass
class BlackboxConfig:
    """Black-box flight recorder + device-wedge sentinel knobs
    (blackbox.py). The in-memory ring is always on (``enabled``
    disables even that); ``dir`` arms the crash-surviving JSONL
    segment persistence with a bounded fsync cadence; ``sentinel``
    starts the heartbeat watchdog that converts a wedged device into a
    structured ``DeviceWedged`` + ``WEDGE_*.json`` forensic bundle.
    Env knobs (RW_BLACKBOX, RW_BLACKBOX_DIR, RW_BLACKBOX_RING,
    RW_BLACKBOX_FSYNC_S, RW_BLACKBOX_SEGMENT_MAX,
    RW_BLACKBOX_SENTINEL, RW_BLACKBOX_HEARTBEAT_S, RW_BLACKBOX_SLOW_MS,
    RW_BLACKBOX_DEADLINE_S) win over the file."""

    enabled: bool = True
    dir: str = ""  # "" = ring only, no disk persistence
    ring_barriers: int = 256
    fsync_interval_s: float = 2.0
    segment_max_bytes: int = 8_000_000
    sentinel: bool = False
    sentinel_interval_s: float = 5.0
    sentinel_slow_ms: float = 1000.0
    sentinel_deadline_s: float = 20.0


@dataclass
class RwConfig:
    streaming: StreamingConfig = field(default_factory=StreamingConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    system: SystemParams = field(default_factory=SystemParams)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    profiler: ProfilerConfig = field(default_factory=ProfilerConfig)
    blackbox: BlackboxConfig = field(default_factory=BlackboxConfig)
    unrecognized: Dict[str, Any] = field(default_factory=dict)


def _apply(section_obj, values: Dict[str, Any], unrecognized: Dict[str, Any], prefix: str):
    known = {f.name for f in fields(section_obj)}
    for k, v in values.items():
        if k in known:
            setattr(section_obj, k, v)
        else:
            unrecognized[f"{prefix}.{k}"] = v


def load_config(
    path: Optional[str] = None, overrides: Optional[Dict[str, Any]] = None
) -> RwConfig:
    """TOML file (optional) + dotted-path overrides, e.g.
    ``{"system.barrier_interval_ms": 250}``."""
    cfg = RwConfig()
    if path is not None:
        with open(path, "rb") as f:
            data = tomllib.load(f)
        for section in (
            "streaming", "storage", "system", "resilience", "profiler",
            "blackbox",
        ):
            if section in data:
                _apply(
                    getattr(cfg, section), data.pop(section),
                    cfg.unrecognized, section,
                )
        for k, v in data.items():
            cfg.unrecognized[k] = v
    for dotted, v in (overrides or {}).items():
        section, _, key = dotted.partition(".")
        obj = getattr(cfg, section, None)
        if obj is None or not hasattr(obj, key):
            cfg.unrecognized[dotted] = v
        else:
            setattr(obj, key, v)
    return cfg


def enable_compile_cache(cache_dir: str = None) -> str:
    """Point JAX's persistent XLA compilation cache at ``cache_dir``
    (default: ``<repo>/.jax_cache``) so identical compiles re-load
    across processes — bench children, watcher re-runs, and test runs
    all share it. Best-effort: returns the dir, or "" on refusal."""
    import os

    import jax

    base = (
        cache_dir
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache",
        )
    )
    # partition by platform context: XLA:CPU AOT results embed target-
    # machine features that vary with XLA_FLAGS/platform — loading a
    # bench-context artifact under pytest warns about feature
    # mismatches and risks SIGILL
    import hashlib

    ctx = "{}|{}".format(
        os.environ.get("JAX_PLATFORMS", ""),
        os.environ.get("XLA_FLAGS", ""),
    )
    d = os.path.join(base, hashlib.sha1(ctx.encode()).hexdigest()[:8])
    try:
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        # children inherit the BASE dir and derive their own context
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", base)
    except Exception:
        return ""
    return d
