"""Barrier-lifecycle observability: per-epoch stage attribution, device
telemetry (measured roofline fraction), and await-tree-style stall
dumps.

Reference: the reference threads ``tracing`` spans through every actor,
dumps await trees on stall (src/utils/runtime/), and attributes barrier
latency per stage in its grafana dashboards. Here every barrier gets an
``EpochTrace``: the runtime stamps each lifecycle stage (chunk ingest,
dispatch/flush, device step, checkpoint staging, SST upload, manifest
commit) into it, mirrors the stage durations into the
``barrier_stage_ms{stage=...}`` histogram (prometheus + chrome-trace via
trace.span), and derives per-barrier HBM telemetry: bytes touched =
device-state delta (utils_heap accounting) + chunk bytes moved, reported
as achieved bandwidth vs the configured chip peak so every bench JSON
carries a MEASURED roofline fraction (PROFILE.md "measured vs modeled").

``dump_stalls()`` is the q7-wedge forensic path: when a barrier exceeds
its deadline, snapshot every thread's open span stack, each actor's
input-channel depths and last-collected epoch, and the pending epochs,
to a JSON artifact BEFORE recovery tears the evidence down.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from risingwave_tpu.metrics import REGISTRY

# HBM peak per platform (GB/s): TPU v4 ≈ 1228, a generic GPU ≈ 2000,
# host DRAM ≈ 50. Override with RW_HBM_PEAK_GBPS for the actual chip —
# the roofline fraction is only as honest as this denominator.
_HBM_PEAK_GBPS = {"tpu": 1228.0, "gpu": 2000.0, "cpu": 50.0}


def hbm_peak_gbps(platform: Optional[str] = None) -> float:
    env = os.environ.get("RW_HBM_PEAK_GBPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if platform is None:
        try:
            import jax

            platform = jax.default_backend()
        except Exception:
            platform = "cpu"
    return _HBM_PEAK_GBPS.get(platform, _HBM_PEAK_GBPS["cpu"])


def roofline(bytes_touched: int, seconds: float, platform=None) -> Dict:
    """Measured achieved-bandwidth vs chip peak. ``bytes_touched`` is
    the accounted HBM traffic (state delta + chunks moved); ``seconds``
    the wall time it moved in. Model ceiling lives in PROFILE.md; this
    is the measured half."""
    peak = hbm_peak_gbps(platform)
    bw = (bytes_touched / seconds / 1e9) if seconds > 0 else 0.0
    return {
        "hbm_bytes_touched": int(bytes_touched),
        "achieved_bw_gbps": round(bw, 4),
        "hbm_peak_gbps": peak,
        "achieved_bw_frac": round(bw / peak, 6) if peak else 0.0,
    }


def chunk_nbytes(chunk) -> int:
    """Device bytes one StreamChunk occupies (column lanes + null lanes
    + valid + ops) — the per-push half of 'HBM bytes touched'."""
    total = 0
    for attr in ("columns", "nulls"):
        for arr in getattr(chunk, attr, {}).values():
            total += int(getattr(arr, "nbytes", 0))
    for attr in ("valid", "ops"):
        arr = getattr(chunk, attr, None)
        if arr is not None:
            total += int(getattr(arr, "nbytes", 0))
    return total


def record_stage(stage: str, ms: float, fragment: str = "-") -> None:
    """One stage observation -> the prometheus surface. Every label set
    keeps the same keys (stage, fragment) so exposition stays uniform."""
    REGISTRY.histogram("barrier_stage_ms").observe(
        ms, stage=stage, fragment=fragment
    )


@dataclass
class EpochTrace:
    """Everything one barrier did, attributed by lifecycle stage.

    ``stages_ms`` keys (the barrier lifecycle):
      ingest          — host time in push() since the previous barrier
      dispatch        — per-fragment barrier walk (flush + routing)
      device_step     — barrier-fence device wait (block_until_ready +
                        staged-scalar materialization; the ONLY forced
                        sync, at the barrier)
      checkpoint_stage— delta pull + mark flips (mgr.stage)
      upload          — SST build + object-store puts
      manifest_commit — version write (the durability point)
    """

    epoch: int
    seq: int
    checkpoint: bool
    t_start: float = field(default_factory=time.perf_counter)
    wall_ms: float = 0.0
    stages_ms: Dict[str, float] = field(default_factory=dict)
    chunk_bytes: int = 0
    state_bytes: int = 0
    state_delta_bytes: int = 0
    hbm_bytes_touched: int = 0
    # byte-accounting provenance (PR 11): the legacy host guess
    # (state-delta + chunk bytes — it never saw state-table READ
    # traffic), the compiled-executable model that replaces it when
    # deviceprof has analyzed the barrier's programs, and the modeled
    # traffic's padding/useful decomposition
    hbm_bytes_touched_legacy: int = 0
    modeled_bytes: int = 0
    padding_bytes_frac: float = 0.0
    useful_bytes: int = 0
    padding_bytes: int = 0
    # compact fused telemetry of the fragments that ran THIS barrier
    # (consumed from deviceprof at finalize; the flight recorder's
    # `tel` field — never a stale echo of an earlier barrier)
    telemetry: Dict = field(default_factory=dict)
    achieved_bw_gbps: float = 0.0
    achieved_bw_frac: float = 0.0
    useful_bw_frac: float = 0.0
    committed_at: Optional[float] = None
    # freshness + backpressure (ISSUE 16): wall clock when the barrier
    # opened (the commit->visible anchor), per-MV freshness deltas as
    # published, per-fragment dispatch walls, and the barrier's
    # bottleneck verdict — all host-side, stamped by runtime._end_trace
    barrier_open_wall: Optional[float] = None
    fragment_ms: Dict[str, float] = field(default_factory=dict)
    freshness: Dict = field(default_factory=dict)
    backpressure_fragment: Optional[str] = None
    backpressure_ms: float = 0.0
    backpressure: Dict = field(default_factory=dict)
    # mesh observability (ISSUE 18): per-shard barrier attribution +
    # exchange (src,dst) traffic matrix + hot-shard skew verdict for
    # the multi-chip path, folded by MESHPROF.observe_barrier. None on
    # serial barriers (the common case costs one attribute slot).
    mesh: Optional[Dict] = None

    def add_stage(self, stage: str, ms: float, fragment: str = "-") -> None:
        self.stages_ms[stage] = self.stages_ms.get(stage, 0.0) + ms
        if fragment != "-":
            self.fragment_ms[fragment] = (
                self.fragment_ms.get(fragment, 0.0) + ms
            )
        record_stage(stage, ms, fragment)

    def finalize(
        self,
        state_bytes: int,
        prev_state_bytes: int,
        platform: Optional[str] = None,
        modeled_bytes: Optional[int] = None,
        padding_frac: Optional[float] = None,
    ) -> None:
        """Close the trace: wall time + device telemetry. Called once
        the barrier's synchronous part is done (async commit stages may
        still land afterwards — they mutate stages_ms in place).

        Byte accounting: ``hbm_bytes_touched`` prefers the MODELED
        bytes of the barrier's compiled programs (deviceprof's XLA
        cost analysis — what the donated program actually reads and
        writes, state-table reads included) and falls back to the
        legacy state-delta + chunk sum, which is always kept as
        ``hbm_bytes_touched_legacy`` for artifact continuity. The
        modeled traffic decomposes into useful vs padding bytes using
        the telemetry lanes' live/capacity accounting, so
        ``achieved_bw_frac`` finally splits into "how busy was HBM"
        (achieved) vs "how much of that was masked-lane waste"
        (padding_bytes_frac -> useful_bw_frac)."""
        self.wall_ms = (time.perf_counter() - self.t_start) * 1e3
        self.state_bytes = int(state_bytes)
        self.state_delta_bytes = abs(int(state_bytes) - int(prev_state_bytes))
        self.hbm_bytes_touched_legacy = (
            self.state_delta_bytes + self.chunk_bytes
        )
        if modeled_bytes is None:
            try:
                from risingwave_tpu.deviceprof import DEVICEPROF

                # CONSUME the barrier's model: only fragments that
                # actually dispatched since the previous barrier count
                # (an idle barrier models zero traffic — no phantom
                # bandwidth), and their telemetry rides this trace
                # into the flight-recorder record
                tail = DEVICEPROF.consume_barrier()
                modeled_bytes = tail["modeled_bytes"]
                self.telemetry = tail["tel"]
                if padding_frac is None:
                    padding_frac = tail["padding_frac"]
            except Exception:  # noqa: BLE001 — accounting never faults
                modeled_bytes = 0
        self.modeled_bytes = int(modeled_bytes or 0)
        self.padding_bytes_frac = float(padding_frac or 0.0)
        self.hbm_bytes_touched = (
            self.modeled_bytes or self.hbm_bytes_touched_legacy
        )
        self.useful_bytes = int(
            self.hbm_bytes_touched * (1.0 - self.padding_bytes_frac)
        )
        self.padding_bytes = self.hbm_bytes_touched - self.useful_bytes
        rf = roofline(self.hbm_bytes_touched, self.wall_ms / 1e3, platform)
        self.achieved_bw_gbps = rf["achieved_bw_gbps"]
        self.achieved_bw_frac = rf["achieved_bw_frac"]
        self.useful_bw_frac = round(
            self.achieved_bw_frac * (1.0 - self.padding_bytes_frac), 6
        )
        REGISTRY.gauge("achieved_bw_frac").set(self.achieved_bw_frac)
        REGISTRY.gauge("useful_bw_frac").set(self.useful_bw_frac)
        REGISTRY.gauge("hbm_bytes_touched").set(float(self.hbm_bytes_touched))

    def to_dict(self) -> Dict:
        return {
            "epoch": self.epoch,
            "seq": self.seq,
            "checkpoint": self.checkpoint,
            "wall_ms": round(self.wall_ms, 3),
            "stages_ms": {k: round(v, 3) for k, v in self.stages_ms.items()},
            "chunk_bytes": self.chunk_bytes,
            "state_bytes": self.state_bytes,
            "state_delta_bytes": self.state_delta_bytes,
            "hbm_bytes_touched": self.hbm_bytes_touched,
            "hbm_bytes_touched_legacy": self.hbm_bytes_touched_legacy,
            "modeled_bytes": self.modeled_bytes,
            "padding_bytes_frac": self.padding_bytes_frac,
            "useful_bytes": self.useful_bytes,
            "padding_bytes": self.padding_bytes,
            "achieved_bw_gbps": self.achieved_bw_gbps,
            "achieved_bw_frac": self.achieved_bw_frac,
            "useful_bw_frac": self.useful_bw_frac,
            "fragment_ms": {
                k: round(v, 3) for k, v in self.fragment_ms.items()
            },
            "freshness": self.freshness,
            "backpressure_fragment": self.backpressure_fragment,
            "backpressure_ms": round(self.backpressure_ms, 3),
            "mesh": self.mesh,
        }


def stage_breakdown() -> Dict[str, Dict[str, float]]:
    """The registry's barrier_stage_ms summary — what bench.py embeds
    in every BENCH_*.json as ``barrier_stage_ms``."""
    h = REGISTRY.histograms.get("barrier_stage_ms")
    return h.summary() if h is not None else {}


# ---------------------------------------------------------------------------
# Stall dumps (await-tree analogue)
# ---------------------------------------------------------------------------

_DUMP_LOCK = threading.Lock()
_DUMP_SEQ = [0]  # same-second dumps must not overwrite each other


def dump_stalls(
    reason: str,
    runtime=None,
    graph=None,
    extra: Optional[Dict] = None,
    path: Optional[str] = None,
) -> str:
    """Snapshot what every thread/actor is doing into a JSON artifact.

    Captures: each thread's open span stack (trace.active_spans), each
    actor's liveness + input-channel depths + last-collected epoch,
    pending (uncollected) epochs with the stuck actors named, per-
    fragment epochs, and the recent event-log tail. Returns the artifact
    path. Never raises — a forensic dump must not worsen the stall."""
    from risingwave_tpu.trace import active_spans

    doc: Dict = {
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "spans": active_spans(),
    }
    try:
        if graph is not None:
            doc["graph"] = graph.stall_snapshot()
        if runtime is not None:
            doc["runtime"] = _runtime_snapshot(runtime)
        from risingwave_tpu.event_log import EVENT_LOG

        doc["recent_events"] = EVENT_LOG.events(limit=20)
        if extra:
            doc["extra"] = extra
        # freshness state + last bottleneck verdict: a stall dump says
        # how STALE every MV already is and which fragment was the
        # bottleneck on the barriers leading in
        from risingwave_tpu.freshness import FRESHNESS

        doc["freshness"] = FRESHNESS.snapshot()
        tr = getattr(runtime, "last_epoch_trace", None)
        if tr is not None and getattr(tr, "backpressure_fragment", None):
            doc["backpressure"] = {
                "fragment": tr.backpressure_fragment,
                "ms": round(tr.backpressure_ms, 3),
                "detail": tr.backpressure,
            }
        # mesh section: when a sharded runtime is active, a stall dump
        # names the hot shard — per-shard occupancy/state depths + the
        # last (src,dst) exchange matrix and skew verdict
        from risingwave_tpu.parallel.meshprof import MESHPROF

        if MESHPROF.enabled:
            msnap = MESHPROF.table_snapshot()
            if msnap.get("tables") or msnap.get("last_barrier"):
                doc["mesh"] = {
                    "tables": msnap.get("tables"),
                    "last_barrier": msnap.get("last_barrier"),
                    "exchange": msnap.get("exchange"),
                }
        if tr is not None and getattr(tr, "mesh", None):
            doc.setdefault("mesh", {})["trace"] = tr.mesh
    except Exception as e:  # partial dump beats no dump
        doc["snapshot_error"] = repr(e)
    try:
        # device-side evidence (q7 wedge forensics): HBM memory stats,
        # live-array census, accounted state tables, in-flight dispatch
        # counters — a wedged TPU leaves data, not just a dead tunnel
        from risingwave_tpu.profiler import device_forensics

        doc["device"] = device_forensics()
    except Exception as e:
        doc["device"] = repr(e)
    try:
        # black-box context: the last barriers BEFORE the stall (what
        # the flight recorder saw) + the sentinel's device classification
        from risingwave_tpu.blackbox import RECORDER, SENTINEL

        doc["blackbox"] = {
            "recorder_tail": RECORDER.snapshot_tail(32),
            "sentinel": SENTINEL.snapshot(),
        }
    except Exception as e:
        doc["blackbox"] = repr(e)
    fallback_err = None
    if path is None:
        d = os.environ.get("RW_STALL_DIR", ".")
        with _DUMP_LOCK:
            _DUMP_SEQ[0] += 1
            seq = _DUMP_SEQ[0]
        path = os.path.join(
            d, f"STALL_DUMP_{int(time.time())}_{seq}.json"
        )
    with _DUMP_LOCK:
        try:
            with open(path, "w") as f:
                json.dump(doc, f, indent=1, default=str)
        except OSError as e:
            # RW_STALL_DIR unwritable: the forensic artifact still must
            # land somewhere — fall back to the system temp dir and say
            # so in the event log (previously a silent "")
            fallback_err = repr(e)
            import tempfile

            path = os.path.join(
                tempfile.gettempdir(), os.path.basename(path)
            )
            try:
                with open(path, "w") as f:
                    json.dump(doc, f, indent=1, default=str)
            except OSError:
                return ""
    try:
        from risingwave_tpu.event_log import EVENT_LOG

        if fallback_err is not None:
            EVENT_LOG.record(
                "stall_dump_fallback", error=fallback_err, path=path
            )
        EVENT_LOG.record("stall_dump", reason=reason, path=path)
    except Exception:
        pass
    REGISTRY.counter("stall_dumps_total").inc()
    return path


def _runtime_snapshot(rt) -> Dict:
    """StreamingRuntime-side stall state: per-fragment epochs, the
    async-lane depth, and graph-backed fragments' actor snapshots."""
    pending = getattr(rt, "_pending_partial", None)
    snap: Dict = {
        "epoch": getattr(rt, "_epoch", None),
        "committed_epoch": rt.mgr.max_committed_epoch if rt.mgr else None,
        "inflight_commits": getattr(rt, "_inflight", 0),
        "closer_queue": len(getattr(rt, "_closer_q", ())),
        # partial-recovery provenance: which fragments are fenced for a
        # deferred scoped recovery, and how many partials have run —
        # a wedge mid-partial-recovery is debuggable from this alone
        "partial_recoveries": getattr(rt, "partial_recoveries", 0),
        "pending_partial": (
            sorted(pending["scope"]) if pending is not None else None
        ),
        "fragments": {},
    }
    # shape-stability forensics: a wedge-adjacent stall with pinned
    # executors or accumulated hazards names its own cause
    gov = getattr(rt, "shape_governor", None)
    if gov is not None:
        try:
            snap["shape_governor"] = gov.snapshot()
        except Exception as e:  # noqa: BLE001 — forensics never fault
            snap["shape_governor"] = repr(e)
    for name, p in getattr(rt, "fragments", {}).items():
        frag = {"epoch": getattr(p, "_epoch", None)}
        g = getattr(p, "graph", None)
        if g is not None:  # GraphPipeline: per-actor detail
            try:
                frag["actors"] = g.stall_snapshot()
            except Exception as e:
                frag["actors"] = repr(e)
        snap["fragments"][name] = frag
    return snap
