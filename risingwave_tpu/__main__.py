"""CLI — `python -m risingwave_tpu serve` starts a single-node cluster.

Reference roles: the `risingwave` all-in-one launcher + `risectl`
basics (src/cmd_all/, src/ctl/). One process hosts the frontend
(pgwire), the streaming runtime (barrier clock on a thread), and the
metrics endpoint; `CREATE TABLE` / `CREATE MATERIALIZED VIEW` /
`INSERT` / `SELECT` all work from any pg client.
"""

from __future__ import annotations

import argparse
import threading
import time


def serve(args) -> None:
    if args.device == "cpu":
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    from risingwave_tpu.config import load_config
    from risingwave_tpu.frontend import PgServer, SqlSession
    from risingwave_tpu.metrics import REGISTRY
    from risingwave_tpu.runtime import StreamingRuntime
    from risingwave_tpu.sql import Catalog
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    cfg = load_config(args.config) if args.config else None
    store = (
        LocalFsObjectStore(args.state_dir) if args.state_dir else None
    )
    runtime = (
        StreamingRuntime.from_config(cfg, store)
        if cfg is not None
        else StreamingRuntime(store)
    )
    session = SqlSession(Catalog({}), runtime)
    pg = PgServer(session, port=args.port).start()
    mport = REGISTRY.serve(args.metrics_port)
    print(
        f"risingwave-tpu serving: pgwire on 127.0.0.1:{pg.port}, "
        f"metrics on http://127.0.0.1:{mport}/metrics"
        + (f", state in {args.state_dir}" if args.state_dir else " (no store)")
    )

    stop = threading.Event()

    def clock():
        while not stop.is_set():
            try:
                session.pump_sources()
                runtime.tick()
            except Exception as e:  # noqa: BLE001 — keep serving
                print(f"barrier error: {e}")
            time.sleep(runtime.barrier_interval_ms / 1000 / 4)

    t = threading.Thread(target=clock, daemon=True)
    t.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        stop.set()
        pg.shutdown()


def main() -> None:
    ap = argparse.ArgumentParser(prog="risingwave_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("serve", help="start a single-node cluster")
    s.add_argument("--port", type=int, default=4566)
    s.add_argument("--metrics-port", type=int, default=0)
    s.add_argument("--state-dir", default=None, help="object store root")
    s.add_argument("--config", default=None, help="TOML config path")
    s.add_argument(
        "--device",
        choices=["auto", "cpu"],
        default="auto",
        help="auto = whatever jax finds (the TPU under axon); cpu forces "
        "the host backend",
    )
    s.set_defaults(fn=serve)
    args = ap.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()
