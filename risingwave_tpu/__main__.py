"""CLI — `python -m risingwave_tpu serve` starts a single-node cluster.

Reference roles: the `risingwave` all-in-one launcher + `risectl`
basics (src/cmd_all/, src/ctl/). One process hosts the frontend
(pgwire), the streaming runtime (barrier clock on a thread), and the
metrics endpoint; `CREATE TABLE` / `CREATE MATERIALIZED VIEW` /
`INSERT` / `SELECT` all work from any pg client.
"""

from __future__ import annotations

import argparse
import os
import threading
import time


def serve(args) -> None:
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    from risingwave_tpu.config import load_config
    from risingwave_tpu.frontend import PgServer, SqlSession
    from risingwave_tpu.metrics import REGISTRY
    from risingwave_tpu.runtime import StreamingRuntime
    from risingwave_tpu.sql import Catalog
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    cfg = load_config(args.config) if args.config else None
    store = (
        LocalFsObjectStore(args.state_dir) if args.state_dir else None
    )
    runtime = (
        StreamingRuntime.from_config(cfg, store)
        if cfg is not None
        else StreamingRuntime(store)
    )
    # a served cluster self-heals (barrier/mod.rs:676 failure recovery):
    # a poisoned epoch or dead actor recovers in place and the source
    # pump replays the lost epoch from committed offsets. Gate on the
    # runtime's ACTUAL persistence (from_config builds its own store)
    if runtime.mgr is not None:
        runtime.auto_recover = True
    from risingwave_tpu.storage.meta_backup import DDL_PATH

    # config sets the baseline; a SET RW_STRICT_LINT wins (the same
    # no-restart escape-hatch precedence as the [resilience] knobs) —
    # passing None lets SqlSession resolve the env default itself
    strict = (
        None
        if "RW_STRICT_LINT" in os.environ
        else (cfg.streaming.strict_lint if cfg is not None else None)
    )
    if store is not None and store.exists(DDL_PATH):
        # warm restart: replay the DDL log, recover state (meta_backup)
        session = SqlSession.restore(runtime, strict_lint=strict)
        print(f"restored {len(session.meta.ddl())} DDL statements")
    else:
        session = SqlSession(Catalog({}), runtime, strict_lint=strict)
    pg = PgServer(session, port=args.port).start()
    mport = REGISTRY.serve(args.metrics_port)
    print(
        f"risingwave-tpu serving: pgwire on 127.0.0.1:{pg.port}, "
        f"metrics on http://127.0.0.1:{mport}/metrics"
        + (f", state in {args.state_dir}" if args.state_dir else " (no store)")
    )

    stop = threading.Event()

    def clock():
        while not stop.is_set():
            try:
                session.pump_sources()
                runtime.tick()
            except Exception as e:  # noqa: BLE001 — keep serving
                print(f"barrier error: {e}")
            time.sleep(runtime.barrier_interval_ms / 1000 / 4)

    t = threading.Thread(target=clock, daemon=True)
    t.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        stop.set()
        pg.shutdown()


def ctl(args) -> None:
    """risectl analogue: backup management over a state dir."""
    from risingwave_tpu.storage.meta_backup import (
        create_backup,
        list_backups,
        restore_backup,
    )
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    store = LocalFsObjectStore(args.state_dir)
    if args.ctl_cmd == "backup-create":
        print(create_backup(store, args.backup_id))
    elif args.ctl_cmd == "backup-list":
        for b in list_backups(store):
            print(b)
    elif args.ctl_cmd == "backup-restore":
        dst = LocalFsObjectStore(args.dest)
        n = restore_backup(store, args.backup_id, dst)
        print(f"restored {n} blobs into {args.dest}")
    elif args.ctl_cmd == "scrub":
        from risingwave_tpu.storage.state_table import CheckpointManager

        rows = CheckpointManager(store).scrub(deep=args.deep)
        bad = 0
        for r in rows:
            line = (
                f"{r['status']:<12} {r['artifact']}  "
                f"table={r['table_id'] or '-'} "
                f"level={r['level']} epoch={r['epoch']}"
            )
            if r["detail"]:
                line += f"  {r['detail']}"
            print(line)
            bad += r["status"] == "corrupt"
        print(f"{len(rows)} artifacts, {bad} corrupt")
        if bad:
            raise SystemExit(1)


def main() -> None:
    ap = argparse.ArgumentParser(prog="risingwave_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("ctl", help="ops commands (risectl analogue)")
    csub = c.add_subparsers(dest="ctl_cmd", required=True)
    for name in ("backup-create", "backup-list", "backup-restore"):
        cc = csub.add_parser(name)
        cc.add_argument("--state-dir", required=True)
        if name != "backup-list":
            cc.add_argument("--backup-id", required=True)
        if name == "backup-restore":
            cc.add_argument("--dest", required=True)
    sc = csub.add_parser(
        "scrub", help="verify every checkpoint artifact (crc + digest)"
    )
    sc.add_argument("--state-dir", required=True)
    sc.add_argument(
        "--deep",
        action="store_true",
        help="also verify every per-block crc inside block SSTs",
    )
    c.set_defaults(fn=ctl)
    s = sub.add_parser("serve", help="start a single-node cluster")
    s.add_argument("--port", type=int, default=4566)
    s.add_argument("--metrics-port", type=int, default=0)
    s.add_argument("--state-dir", default=None, help="object store root")
    s.add_argument("--config", default=None, help="TOML config path")
    s.add_argument(
        "--device",
        choices=["auto", "cpu"],
        default="auto",
        help="auto = whatever jax finds (the TPU under axon); cpu forces "
        "the host backend",
    )
    s.set_defaults(fn=serve)
    ln = sub.add_parser(
        "lint",
        help="rwlint: static plan verifier + JAX compilation sanitizer "
        "over SQL files and/or the built-in Nexmark queries "
        "(analysis/; exit 0 = no errors)",
    )
    ln.add_argument(
        "paths", nargs="*", help="SQL files (DDL is executed in-memory)"
    )
    ln.add_argument(
        "--all-nexmark",
        action="store_true",
        help="lint every built-in Nexmark query pipeline (q5/q7/q8)",
    )
    ln.add_argument(
        "--deep",
        action="store_true",
        help="also trace jaxprs: dtype promotions, 64-bit hash "
        "arithmetic (no XLA compiles)",
    )
    ln.add_argument(
        "--fusion-report",
        action="store_true",
        dest="fusion_report",
        help="fusion-feasibility analysis per fragment: longest "
        "fusible executor prefix, RW-E8xx blockers with file:line "
        "provenance, estimated dispatch savings (implies "
        "--all-nexmark when no SQL files are given)",
    )
    ln.add_argument(
        "--sharing-report",
        action="store_true",
        dest="sharing_report",
        help="share-key fingerprints per keyed state table + the "
        "corpus' sharing opportunities (Shared Arrangements candidates; "
        "RW-E703 flags would-share tables split only by an incompatible "
        "bucket lattice). Analyzes the built-in corpus incl. the "
        "SQL-planned q5u twin",
    )
    ln.add_argument(
        "--mesh-report",
        action="store_true",
        dest="mesh_report",
        help="mesh-readiness analysis of the sharded corpus (q5/q7/q8 "
        "over the 8-virtual-device sim mesh): SPMD-fusibility proofs "
        "per sharded fragment, RW-E9xx blockers with file:line "
        "provenance, ranked by the committed multichip phase splits. "
        "Standalone: sets up its own mesh; exits 2 if jax was already "
        "initialized with fewer devices",
    )
    ln.add_argument("--json", action="store_true")
    ln.set_defaults(fn=_lint)
    bb = sub.add_parser(
        "blackbox",
        help="read a crash-surviving flight-recorder segment "
        "(BLACKBOX_*.jsonl, or a directory holding one): reconstruct "
        "the last-N-barrier timeline, optionally emit a Perfetto "
        "trace (exit 0 = parsed, 1 = timeline broken, 2 = unreadable)",
    )
    bb.add_argument(
        "path", help="segment file or the directory that holds it"
    )
    bb.add_argument(
        "--last", type=int, default=None, help="only the last N barriers"
    )
    bb.add_argument(
        "--trace",
        default=None,
        metavar="OUT.json",
        help="write a chrome://tracing / Perfetto trace of the timeline",
    )
    bb.add_argument(
        "--roofline",
        action="store_true",
        help="add a roofline summary column per barrier (modeled HBM "
        "bytes from the compiled executable, padding-bytes fraction, "
        "fused telemetry) and a timeline summary footer",
    )
    bb.add_argument("--json", action="store_true")
    bb.set_defaults(fn=_blackbox_read)
    cn = sub.add_parser(
        "compute-node",
        help="start a compute-node role behind a TCP wire "
        "(cluster/compute_node.py; compute_node_serve analogue)",
    )
    cn.add_argument("--port", type=int, default=0)
    cn.add_argument("--state-dir", required=True)
    cn.add_argument("--device", choices=["cpu", "tpu"], default="cpu")
    cn.set_defaults(fn=_compute_node)
    args = ap.parse_args()
    args.fn(args)


def _compute_node(args) -> None:
    from risingwave_tpu.cluster.compute_node import run

    run(args.port, args.state_dir, args.device)


def _blackbox_read(args) -> None:
    """Black-box reader: a post-mortem tool that must work when the
    process that wrote the segment is gone (SIGKILL, OOM, wedged
    device). Parses torn tails, merges a rotated .old sibling, prints
    the barrier timeline, and flags non-monotonic epochs."""
    import json as _json
    import os
    import sys

    # a post-mortem read must never touch the (possibly still-wedged)
    # device — same CPU pin as the lint CLI
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from risingwave_tpu.blackbox import read_segment, records_to_trace_events

    try:
        doc = read_segment(args.path, last=args.last)
    except (OSError, FileNotFoundError) as e:
        print(f"blackbox: cannot read {args.path!r}: {e}", file=sys.stderr)
        sys.exit(2)
    if args.trace:
        from risingwave_tpu.trace import render_chrome_trace

        with open(args.trace, "w") as f:
            f.write(
                render_chrome_trace(
                    records_to_trace_events(doc["records"]),
                    {1: "barrier", 2: "stages"},
                )
            )
    if args.json:
        print(_json.dumps(doc, default=str))
    else:
        recs = doc["records"]
        hdr = doc["header"] or {}
        print(
            f"blackbox: {len(recs)} barrier(s) from {doc['source']}"
            + (f" (pid {hdr.get('pid')})" if hdr else "")
            + (
                f", {doc['torn_lines']} torn line(s) tolerated"
                if doc["torn_lines"]
                else ""
            )
        )
        for r in recs:
            stages = " ".join(
                f"{k}={v:.1f}" for k, v in (r["stages_ms"] or {}).items()
            )
            extra = ""
            if "dispatches_delta" in r:
                extra += f" disp+{r['dispatches_delta']}"
            if r.get("sentinel"):
                extra += f" sen={r['sentinel']}"
            if "channel_depths" in r:
                extra += f" depths={r['channel_depths']}"
            if "mesh" in r:
                m = r["mesh"]
                extra += (
                    f" mesh[n={m.get('n_shards')}"
                    f" cov={m.get('coverage_frac', 0.0):.0%}"
                )
                sk = m.get("skew")
                if sk:
                    extra += (
                        f" SKEW shard{sk.get('shard')}"
                        f" x{sk.get('ratio', 0.0):.1f}"
                    )
                extra += "]"
            if args.roofline and "modeled_bytes" in r:
                extra += (
                    f" model={r['modeled_bytes'] / 1e6:.1f}MB"
                    f" pad={r.get('padding_bytes_frac', 0.0):.2%}"
                )
                tel = r.get("telemetry") or {}
                for frag, t in tel.items():
                    extra += f" {frag}[dirty={t.get('dirty', 0)}]"
            print(
                f"  epoch {r['epoch']} seq {r['seq']} "
                f"{'ckpt' if r['checkpoint'] else '    '} "
                f"wall {r['wall_ms']:.1f}ms  {stages}{extra}"
            )
        if args.roofline:
            # timeline summary: modeled traffic vs wall time — the
            # post-mortem roofline (what the fused programs moved, and
            # how much of it was masked-lane waste)
            modeled = [r for r in recs if r.get("modeled_bytes")]
            if modeled:
                total_b = sum(r["modeled_bytes"] for r in modeled)
                total_s = sum(r["wall_ms"] or 0.0 for r in modeled) / 1e3
                pad = sum(
                    r["modeled_bytes"] * r.get("padding_bytes_frac", 0.0)
                    for r in modeled
                )
                bw = total_b / total_s / 1e9 if total_s > 0 else 0.0
                print(
                    f"blackbox roofline: {len(modeled)} modeled "
                    f"barrier(s), {total_b / 1e6:.1f}MB modeled traffic "
                    f"({pad / max(total_b, 1):.1%} padding), "
                    f"~{bw:.2f} GB/s over barrier wall time"
                )
            else:
                print(
                    "blackbox roofline: no modeled-bytes records "
                    "(deviceprof was not armed in the writing process)"
                )
        meshed = [r for r in recs if r.get("mesh")]
        if meshed:
            # mesh footer: the last sharded barrier's per-shard locals
            # + (src,dst) exchange-row matrix — the post-mortem answer
            # to "which shard was hot when the segment ended"
            m = meshed[-1]["mesh"]
            loc = " ".join(
                f"s{i}={v:.1f}"
                for i, v in enumerate(m.get("shard_local_ms") or [])
            )
            print(
                f"blackbox mesh: {len(meshed)} sharded barrier(s), "
                f"last n={m.get('n_shards')} "
                f"cov={m.get('coverage_frac', 0.0):.0%}  {loc}"
            )
            xm = m.get("exchange_rows")
            if xm:
                for src, row in enumerate(xm):
                    cells = " ".join(f"{int(v):>7d}" for v in row)
                    print(f"  exchange src{src}: {cells}")
        if not doc["monotonic"]:
            print("blackbox: WARNING — epoch timeline is NOT monotonic")
        if args.trace:
            print(f"blackbox: Perfetto trace -> {args.trace}")
    sys.exit(0 if doc["monotonic"] else 1)


def _lint(args) -> None:
    # lint never touches the TPU: forcing CPU keeps a CI lint run from
    # grabbing the single-client tunnel (same dance as serve --device)
    import os
    import sys

    os.environ["JAX_PLATFORMS"] = "cpu"
    if getattr(args, "mesh_report", False):
        # the virtual-device flag only takes effect if it lands before
        # the first backend init — claim it here, before importing jax
        from risingwave_tpu.analysis.mesh_domain import (
            DEFAULT_MESH_SHARDS,
            MeshUnavailable,
            ensure_virtual_devices,
        )

        try:
            ensure_virtual_devices(DEFAULT_MESH_SHARDS)
        except MeshUnavailable as e:
            print(f"rwlint: {e}", file=sys.stderr)
            sys.exit(2)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from risingwave_tpu.analysis.lint import run_cli

    sys.exit(run_cli(args))


if __name__ == "__main__":
    main()
