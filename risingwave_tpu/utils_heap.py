"""Heap profiling — host allocation tracking + device-state accounting.

Reference: the compute node's jemalloc heap profiling + memory
dashboard (src/compute/src/memory/, risedev heap-profile tooling).
TPU re-design: host-side Python allocations are tracked with
``tracemalloc`` (grouped by source line, like jeprof's collapsed
stacks); DEVICE state — the dominant memory here — is accounted
exactly from each executor's ``state_nbytes()`` (slot arrays in HBM),
so one report covers both tiers.

Surface: ``start()`` / ``stop()`` + ``render()`` for programmatic use,
and the metrics server's ``/heap`` endpoint (set a runtime with
``attach_runtime`` — ``StreamingRuntime`` does this on construction).
"""

from __future__ import annotations

import tracemalloc
import weakref
from typing import List, Optional

_runtime_ref: Optional["weakref.ref"] = None


def attach_runtime(runtime) -> None:
    """Register the runtime whose executors the /heap report walks."""
    global _runtime_ref
    _runtime_ref = weakref.ref(runtime)


def start(nframes: int = 8) -> None:
    if not tracemalloc.is_tracing():
        tracemalloc.start(nframes)


def stop() -> None:
    if tracemalloc.is_tracing():
        tracemalloc.stop()


def is_running() -> bool:
    return tracemalloc.is_tracing()


def host_top(limit: int = 25) -> List[dict]:
    """Top host allocation sites since start(), by retained bytes."""
    if not tracemalloc.is_tracing():
        return []
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")
    return [
        {
            "site": str(s.traceback[0]) if s.traceback else "?",
            "bytes": int(s.size),
            "count": int(s.count),
        }
        for s in stats[:limit]
    ]


def device_state() -> List[dict]:
    """Per-executor device-state bytes (exact — the arrays ARE the
    state), newest runtime attached via attach_runtime."""
    rt = _runtime_ref() if _runtime_ref is not None else None
    if rt is None:
        return []
    out = []
    for ex in rt.executors():
        fn = getattr(ex, "state_nbytes", None)
        if fn is None:
            continue
        out.append(
            {
                "executor": type(ex).__name__,
                "table_id": getattr(ex, "table_id", "?"),
                "bytes": int(fn()),
            }
        )
    out.sort(key=lambda d: -d["bytes"])
    return out


def render(limit: int = 25) -> str:
    lines = ["# device state (exact, per executor)"]
    total = 0
    for d in device_state():
        total += d["bytes"]
        lines.append(
            f"{d['bytes']:>14,}  {d['executor']:<28} {d['table_id']}"
        )
    lines.append(f"{total:>14,}  TOTAL device state")
    lines.append("")
    if tracemalloc.is_tracing():
        lines.append(f"# host allocations (tracemalloc, top {limit})")
        for d in host_top(limit):
            lines.append(
                f"{d['bytes']:>14,}  n={d['count']:<8} {d['site']}"
            )
    else:
        lines.append(
            "# host tracking off — utils_heap.start() enables tracemalloc"
        )
    return "\n".join(lines) + "\n"
