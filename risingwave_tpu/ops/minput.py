"""Materialized-input MIN/MAX — retractable extremes.

Reference: src/stream/src/executor/aggregation/minput.rs — RisingWave
keeps EVERY input value of a MIN/MAX call in a sorted per-group state
table so a retraction of the current extreme can fall back to the next
value. Kyry/risingwave's hash_agg calls into that MaterializedInputState
whenever the input stream is not append-only.

TPU re-design: no per-group BTree. Each materialized call owns a
``(capacity, K)`` DISTINCT-VALUE multiset per group slot:

    vals[slot, lane]   value (floats as total-order keys)
    cnt[slot, lane]    multiplicity (0 = free lane)

One chunk (or whole epoch batch) updates it in a single fused pass:

1. sort rows by (slot, value) — equal (group, value) pairs cluster;
2. segment-reduce the net weight dw per distinct pair;
3. each surviving pair touches exactly ONE (slot, lane): its matching
   lane (cnt>0 & vals==v) or, for new values, the j-th free lane where
   j is the pair's rank among the group's new values this batch — so
   every scatter index is unique and the whole update is one
   scatter-add + one scatter-set, no loops;
4. re-reduce each touched group's lanes (min/max over cnt>0) and write
   the result into the ordinary accumulator lane — flush / NULL /
   emitted-retraction machinery is unchanged.

K bounds DISTINCT live values per group, not rows: exceeding it latches
``overflow`` (the capacity-growth contract shared with HashAgg /
join fanout). A delete of a value that was never stored latches
``inconsistent`` (reference: update_check wrapper).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from risingwave_tpu.ops.agg import (
    AggCall,
    _accum_dtype,
    _float_to_order_key,
    accum_init,
)


def create_minput(
    capacity: int, k: int, calls: Tuple[AggCall, ...], input_dtypes
) -> Dict[str, Tuple[jnp.ndarray, jnp.ndarray]]:
    """(vals, cnt) pair per materialized MIN/MAX call output."""
    out = {}
    for c in calls:
        if not getattr(c, "materialized", False):
            continue
        dt = _accum_dtype(c, input_dtypes[c.input])
        out[c.output] = (
            jnp.zeros((capacity, k), dt),
            jnp.zeros((capacity, k), jnp.int32),
        )
    return out


def minput_apply(
    vals: jnp.ndarray,  # (capacity, K)
    cnt: jnp.ndarray,  # (capacity, K) int32
    slots: jnp.ndarray,  # (n,) int32 group slot per row (-1 = skip)
    signs: jnp.ndarray,  # (n,) int in {-1,0,+1}
    v: jnp.ndarray,  # (n,) raw input values
    notnull: jnp.ndarray,  # (n,) bool
    kind: str,  # "min" | "max"
):
    """Fold one row batch into the multiset; returns
    ``(vals', cnt', rep_slots, extreme, total, overflow, inconsistent)``
    where ``rep_slots``/(n,) marks one representative row per TOUCHED
    group carrying its new ``extreme`` (accum dtype, sentinel when the
    group holds no values) and ``total`` live multiplicity."""
    n = v.shape[0]
    capacity, K = cnt.shape
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = _float_to_order_key(v)
    v = v.astype(vals.dtype)

    active = (slots >= 0) & (signs != 0) & notnull
    # inactive rows sort last (slot = capacity)
    s_key = jnp.where(active, slots, capacity).astype(jnp.int32)
    sorted_ops = jax.lax.sort(
        (s_key, v, signs.astype(jnp.int32), active), num_keys=2
    )
    sl, sv, sw, sa = sorted_ops

    def lane_change(lane):
        return jnp.concatenate([jnp.ones(1, jnp.bool_), lane[1:] != lane[:-1]])

    group_b = lane_change(sl)
    pair_b = group_b | lane_change(sv)
    pair_id = jnp.cumsum(pair_b.astype(jnp.int32)) - 1
    dw = jax.ops.segment_sum(
        jnp.where(sa, sw, 0), pair_id, num_segments=n
    )[pair_id]
    pair_rep = pair_b & sa

    # pre-state per pair: does the value already hold a lane?
    gslot = jnp.where(sa, sl, 0)
    row_cnt = cnt[gslot]  # (n, K)
    row_vals = vals[gslot]
    match = (row_cnt > 0) & (row_vals == sv[:, None])
    exists = jnp.any(match, axis=1)
    match_lane = jnp.argmax(match, axis=1)

    # j-th NEW pair of a group claims the j-th free lane (argsort of
    # occupied-flags ascending lists free lanes first, stable).
    # Segment-local 0-based rank among new pairs = global cumsum minus
    # the cumsum base at the group's first row.
    is_new = pair_rep & ~exists & (dw > 0)
    gid = jnp.cumsum(group_b.astype(jnp.int32)) - 1
    c = jnp.cumsum(is_new.astype(jnp.int32))
    base = jax.ops.segment_max(
        jnp.where(group_b, c - is_new.astype(jnp.int32), 0),
        gid,
        num_segments=n,
    )[gid]
    new_rank = c - 1 - base
    free_order = jnp.argsort(row_cnt > 0, axis=1, stable=True)  # (n, K)
    j = jnp.clip(new_rank, 0, K - 1)
    claim_lane = jnp.take_along_axis(free_order, j[:, None], axis=1)[:, 0]
    claim_free = (
        jnp.take_along_axis(row_cnt, claim_lane[:, None], axis=1)[:, 0] == 0
    )
    overflow = jnp.any(is_new & ((new_rank >= K) | ~claim_free))

    lane = jnp.where(exists, match_lane, claim_lane)
    touch = pair_rep & (dw != 0) & (exists | (is_new & claim_free))
    # a negative dw on a value with no lane, or driving cnt below zero,
    # is an inconsistent stream
    old_c = jnp.take_along_axis(row_cnt, lane[:, None], axis=1)[:, 0]
    new_c = jnp.where(exists, old_c, 0) + dw.astype(jnp.int32)
    inconsistent = jnp.any(pair_rep & (dw < 0) & ~exists) | jnp.any(
        touch & (new_c < 0)
    )
    new_c = jnp.maximum(new_c, 0)

    flat = jnp.where(touch, gslot * K + lane, capacity * K)
    cnt2 = (
        cnt.reshape(-1)
        .at[flat]
        .set(new_c, mode="drop")
        .reshape(capacity, K)
    )
    vals2 = (
        vals.reshape(-1)
        .at[flat]
        .set(sv, mode="drop")
        .reshape(capacity, K)
    )

    # re-reduce touched groups from the POST state
    grp_rep = group_b & sa
    g_cnt = cnt2[gslot]
    g_vals = vals2[gslot]
    sentinel = accum_init(kind, vals.dtype)
    masked = jnp.where(g_cnt > 0, g_vals, sentinel)
    extreme = (
        jnp.min(masked, axis=1) if kind == "min" else jnp.max(masked, axis=1)
    )
    total = jnp.sum(g_cnt, axis=1).astype(jnp.int64)
    rep_slots = jnp.where(grp_rep, sl, -1)
    return vals2, cnt2, rep_slots, extreme, total, overflow, inconsistent


def minput_clear(vals, cnt, slots):
    """Free whole groups (window expiry / delete_groups)."""
    capacity, K = cnt.shape
    idx = jnp.where(slots >= 0, slots, capacity)
    return vals, cnt.at[idx].set(0, mode="drop")


def minput_rescatter(vals, cnt, keep, new_slots, new_cap):
    """Rehash support: move rows to their new slots (2x growth)."""
    K = cnt.shape[1]
    idx = jnp.where(keep, new_slots, new_cap)
    nv = jnp.zeros((new_cap, K), vals.dtype).at[idx].set(vals, mode="drop")
    nc = jnp.zeros((new_cap, K), cnt.dtype).at[idx].set(cnt, mode="drop")
    return nv, nc
