"""Two-sided streaming join state kernel — the core of HashJoin.

Reference roles replaced:
- ``JoinHashMap`` — per-key row lists with cached entry state
  (src/stream/src/executor/join/hash_join.rs:157);
- the per-row probe/emit loop of ``hash_eq_match`` / ``execute_inner``
  (src/stream/src/executor/hash_join.rs:462-729).

The reference keeps, per join key, a heap ``Vec`` of rows (plus degree
counters) behind an LRU cache over a state table. On TPU the state must
be a flat array program, so a join side is TWO levels of static arrays:

    key table  : ops/hash_table.HashTable over the join-key lanes —
                 maps a key to a slot s in [0, capacity)
    row buckets: per payload column, a (capacity, fanout) array;
                 bucket s holds every live row whose key owns slot s,
                 with a (capacity, fanout) ``row_valid`` mask

Insert scatters each row into the first free bucket position; delete
finds the matching stored row (exact multi-column equality, NULL==NULL)
and clears it; probe gathers the *other* side's whole bucket per probe
row — a (chunk, fanout) gather — and emits one output pair per live
match. All three are batched over the chunk with no host round trips,
and intra-chunk collisions (two rows of one key in one chunk) are
resolved by an O(n log n) intra-chunk rank, not a serial loop.

Fanout is the static per-key row bound (the reference's Vec grows on
the heap; we latch ``overflow`` and the host executor rebuilds with a
doubled fanout — same contract as hash-table growth). Inner joins need
no degree state; degrees for outer joins ride the same bucket layout as
an extra int lane when those join types land.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from risingwave_tpu.ops.hash_table import (
    HashTable,
    lookup,
    lookup_or_insert,
    set_live,
)
from risingwave_tpu.ops.hashing import hash128


@jax.tree_util.register_pytree_node_class
@dataclass
class JoinSide:
    """One side's state: key table + row buckets (see module doc).

    ``rows``/``row_nulls`` map payload column name -> (capacity, fanout)
    arrays; ``row_valid`` marks occupied bucket entries. ``overflow``
    latches bucket exhaustion; ``inconsistent`` latches a delete that
    matched no stored row (the reference's consistency sanity check,
    src/stream/src/executor/mod.rs update_check wrapper).
    """

    table: HashTable
    rows: Dict[str, jnp.ndarray]
    row_nulls: Dict[str, jnp.ndarray]
    row_valid: jnp.ndarray
    overflow: jnp.ndarray  # () bool
    inconsistent: jnp.ndarray  # () bool
    sdirty: jnp.ndarray  # (capacity,) bool — changed since last checkpoint
    stored: jnp.ndarray  # (capacity,) bool — persisted in the object store
    degree: jnp.ndarray  # (capacity, fanout) int32 — matches on other side

    def tree_flatten(self):
        names = tuple(sorted(self.rows))
        null_names = tuple(sorted(self.row_nulls))
        children = (
            self.table,
            tuple(self.rows[n] for n in names),
            tuple(self.row_nulls[n] for n in null_names),
            self.row_valid,
            self.overflow,
            self.inconsistent,
            self.sdirty,
            self.stored,
            self.degree,
        )
        return children, (names, null_names)

    @classmethod
    def tree_unflatten(cls, aux, children):
        names, null_names = aux
        (table, rows, nulls, row_valid, overflow, inconsistent, sdirty,
         stored, degree) = children
        return cls(
            table=table,
            rows=dict(zip(names, rows)),
            row_nulls=dict(zip(null_names, nulls)),
            row_valid=row_valid,
            overflow=overflow,
            inconsistent=inconsistent,
            sdirty=sdirty,
            stored=stored,
            degree=degree,
        )

    @property
    def capacity(self) -> int:
        return self.row_valid.shape[0]

    @property
    def fanout(self) -> int:
        return self.row_valid.shape[1]

    @staticmethod
    def create(
        capacity: int,
        fanout: int,
        key_dtypes: Sequence[jnp.dtype],
        payload_dtypes: Dict[str, jnp.dtype],
        nullable: Sequence[str] = (),
    ) -> "JoinSide":
        return JoinSide(
            table=HashTable.create(capacity, key_dtypes),
            rows={
                n: jnp.zeros((capacity, fanout), d)
                for n, d in payload_dtypes.items()
            },
            row_nulls={
                n: jnp.zeros((capacity, fanout), jnp.bool_) for n in nullable
            },
            row_valid=jnp.zeros((capacity, fanout), jnp.bool_),
            overflow=jnp.zeros((), jnp.bool_),
            inconsistent=jnp.zeros((), jnp.bool_),
            sdirty=jnp.zeros(capacity, jnp.bool_),
            stored=jnp.zeros(capacity, jnp.bool_),
            degree=jnp.zeros((capacity, fanout), jnp.int32),
        )


def _intra_chunk_rank(
    slots: jnp.ndarray, h1: jnp.ndarray, h2: jnp.ndarray, m: jnp.ndarray
) -> jnp.ndarray:
    """rank[i] = #earlier masked rows with the same (slot, h1, h2).

    Insert ranking passes constant h1/h2 (group by SLOT alone: every
    insert into a bucket needs a distinct free position, whatever its
    content); delete ranking passes the row fingerprint (identical
    delete rows clear distinct matching entries, while distinct rows
    sharing a bucket rank independently against their own matches).
    Sort-based, shape-static; stable so ranks follow chunk order.
    """
    n = slots.shape[0]
    big = jnp.int64(1) << 62
    key = (
        slots.astype(jnp.int64) << jnp.int64(32)
        | h1.astype(jnp.int64)
    )
    key = jnp.where(m, key, big)
    # lexsort by (h2, composite) — h2 breaks 32-bit h1 ties
    order = jnp.lexsort((h2.astype(jnp.int64), key))
    k_sorted = key[order]
    h2_sorted = h2[order]
    seq = jnp.arange(n, dtype=jnp.int32)
    is_new = jnp.concatenate(
        [
            jnp.ones(1, jnp.bool_),
            (k_sorted[1:] != k_sorted[:-1]) | (h2_sorted[1:] != h2_sorted[:-1]),
        ]
    )
    # start index of each run, propagated forward (starts are increasing)
    start = jnp.where(is_new, seq, jnp.int32(0))
    start = jax.lax.associative_scan(jnp.maximum, start)
    rank_sorted = seq - start
    return jnp.zeros(n, jnp.int32).at[order].set(rank_sorted)


def _row_fingerprint(payload_cols, payload_nulls, names):
    """64 bits over all payload lanes (values canonicalized under NULL)
    — only used to RANK same-bucket rows; equality stays exact."""
    lanes = []
    for name in names:
        col = payload_cols[name]
        null = payload_nulls.get(name)
        if null is not None:
            col = jnp.where(null, jnp.zeros((), col.dtype), col)
            lanes.append(null)
        lanes.append(col)
    return hash128(tuple(lanes))


def _entry_matches(side: JoinSide, slots, payload_cols, payload_nulls, names):
    """(n, fanout) exact row equality against bucket entries (NULL==NULL)."""
    sl = jnp.maximum(slots, 0)
    ok = side.row_valid[sl]
    for name in names:
        stored = side.rows[name][sl]  # (n, fanout)
        val = payload_cols[name][:, None]
        eq = stored == val
        if jnp.issubdtype(stored.dtype, jnp.floating):
            eq |= jnp.isnan(stored) & jnp.isnan(val)
        snull = side.row_nulls.get(name)
        if snull is not None:
            stored_null = snull[sl]
            row_null = payload_nulls.get(name)
            if row_null is None:
                row_null = jnp.zeros(val.shape, jnp.bool_)
            else:
                row_null = row_null[:, None]
            eq = jnp.where(stored_null | row_null, stored_null == row_null, eq)
        ok &= eq
    return ok


def apply_side(
    side: JoinSide,
    key_cols: Tuple[jnp.ndarray, ...],
    payload_cols: Dict[str, jnp.ndarray],
    payload_nulls: Dict[str, jnp.ndarray],
    valid: jnp.ndarray,
    signs: jnp.ndarray,
    names: Tuple[str, ...],
    init_degree: Optional[jnp.ndarray] = None,
):
    """Apply one chunk to its own side: inserts then deletes.

    ``signs``: +1 insert / -1 delete per row (0 = skip). Rows are
    multiset entries; inserts fill the first free bucket positions,
    deletes clear the rank-th matching entry (so an insert+delete of
    the same row in one chunk nets out). ``init_degree`` (outer joins)
    seeds each inserted row's degree — its current match count on the
    other side (reference degree table, join/hash_join.rs:157).
    Returns the updated side.
    """
    ins = valid & (signs > 0)
    dele = valid & (signs < 0)
    touch = ins | dele

    # slot per row (deletes of absent keys fall through to inconsistent)
    table, slots, _, _ = lookup_or_insert(side.table, key_cols, touch)
    sdirty = side.sdirty.at[
        jnp.where(touch & (slots >= 0), slots, side.capacity)
    ].set(True, mode="drop")
    side = JoinSide(
        table, side.rows, side.row_nulls, side.row_valid,
        side.overflow | jnp.any(touch & (slots < 0)), side.inconsistent,
        sdirty, side.stored, side.degree,
    )

    h1, h2 = _row_fingerprint(payload_cols, payload_nulls, names)
    cap, fanout = side.capacity, side.fanout
    n = valid.shape[0]
    sl = jnp.maximum(slots, 0)

    # ---- inserts: rank-th free position in the bucket (rank by slot
    # only — ANY two inserts into one bucket need distinct positions) --
    zero = jnp.zeros_like(h1)
    rank_i = _intra_chunk_rank(slots, zero, zero, ins)
    bv = side.row_valid[sl]  # (n, fanout)
    free_rank = jnp.cumsum((~bv).astype(jnp.int32), axis=1)
    one_hot = (~bv) & (free_rank == (rank_i + 1)[:, None]) & ins[:, None]
    pos = jnp.argmax(one_hot, axis=1).astype(jnp.int32)
    placed = jnp.any(one_hot, axis=1) & ins & (slots >= 0)
    overflow = side.overflow | jnp.any(ins & (slots >= 0) & ~placed)

    flat_idx = jnp.where(placed, sl * fanout + pos, cap * fanout)
    rows = {
        name: side.rows[name]
        .reshape(-1)
        .at[flat_idx]
        .set(payload_cols[name], mode="drop")
        .reshape(cap, fanout)
        for name in names
    }
    row_nulls = {}
    for name, lane in side.row_nulls.items():
        src = payload_nulls.get(name)
        if src is None:
            src = jnp.zeros(n, jnp.bool_)
        row_nulls[name] = (
            lane.reshape(-1).at[flat_idx].set(src, mode="drop").reshape(cap, fanout)
        )
    row_valid = (
        side.row_valid.reshape(-1)
        .at[flat_idx]
        .set(True, mode="drop")
        .reshape(cap, fanout)
    )
    deg0 = (
        init_degree.astype(jnp.int32)
        if init_degree is not None
        else jnp.zeros(n, jnp.int32)
    )
    degree = (
        side.degree.reshape(-1)
        .at[flat_idx]
        .set(deg0, mode="drop")
        .reshape(cap, fanout)
    )
    side = JoinSide(
        side.table, rows, row_nulls, row_valid, overflow, side.inconsistent,
        side.sdirty, side.stored, degree,
    )

    # ---- deletes: rank-th matching entry -------------------------------
    rank_d = _intra_chunk_rank(slots, h1, h2, dele)
    match = _entry_matches(side, slots, payload_cols, payload_nulls, names)
    match = match & dele[:, None] & (slots >= 0)[:, None]
    mrank = jnp.cumsum(match.astype(jnp.int32), axis=1)
    one_hot_d = match & (mrank == (rank_d + 1)[:, None])
    dpos = jnp.argmax(one_hot_d, axis=1).astype(jnp.int32)
    hit = jnp.any(one_hot_d, axis=1)
    inconsistent = side.inconsistent | jnp.any(dele & (slots >= 0) & ~hit)

    dflat = jnp.where(hit, sl * fanout + dpos, cap * fanout)
    row_valid = (
        side.row_valid.reshape(-1)
        .at[dflat]
        .set(False, mode="drop")
        .reshape(cap, fanout)
    )
    degree = (
        side.degree.reshape(-1)
        .at[dflat]
        .set(jnp.int32(0), mode="drop")
        .reshape(cap, fanout)
    )

    # key liveness = bucket non-empty (drives rehash survival + probes)
    touched_slots = jnp.where(touch & (slots >= 0), slots, -1)
    any_live = jnp.any(row_valid[sl], axis=1)
    table = set_live(side.table, touched_slots, any_live)
    return JoinSide(
        table, side.rows, side.row_nulls, row_valid, side.overflow,
        inconsistent, side.sdirty, side.stored, degree,
    )


def degree_apply(
    other: JoinSide,
    match: jnp.ndarray,  # (n, fanout) live matches of this chunk's rows
    sl: jnp.ndarray,  # (n,) probed slots (clamped >= 0)
    signs: jnp.ndarray,  # (n,) ±1/0 per probe row
):
    """Bump the OTHER side's per-row degrees by this chunk's net effect
    and report transitions (reference: degree table updates inside
    hash_eq_match, join/hash_join.rs).

    Returns ``(other', trans_pid, went_pos, went_zero)``:
      trans_pid   (n*fanout,) int32 — flat (slot*fanout+pos) id of each
                  DISTINCT matched stored row, on representative lanes;
                  sentinel cap*fanout elsewhere
      went_pos    bool — degree crossed 0 -> >0 (matched for the first
                  time: outer joins retract the NULL-padded row)
      went_zero   bool — degree crossed >0 -> 0 (NULL-pad comes back)
    """
    cap, fanout = other.capacity, other.fanout
    n = match.shape[0]
    sent = jnp.int32(cap * fanout)
    pos_j = jnp.arange(fanout, dtype=jnp.int32)[None, :]
    pid = jnp.where(match, sl[:, None] * fanout + pos_j, sent).reshape(-1)
    delta = jnp.broadcast_to(signs[:, None], (n, fanout)).reshape(-1)
    delta = jnp.where(pid != sent, delta, 0).astype(jnp.int32)

    # distinct pids via sort + segment sum (multiple probe rows can hit
    # the same stored row in one chunk; the TRANSITION is per stored
    # row, over the chunk's net delta)
    spid, sdelta = jax.lax.sort((pid, delta), num_keys=1)
    boundary = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), spid[1:] != spid[:-1]]
    )
    seg_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    net = jax.ops.segment_sum(
        sdelta, seg_id, num_segments=spid.shape[0]
    )[seg_id]
    rep = boundary & (spid != sent)

    flat_deg = other.degree.reshape(-1)
    old = flat_deg[jnp.minimum(spid, sent - 1)]
    upd_idx = jnp.where(rep, spid, sent)
    new_flat = flat_deg.at[upd_idx].add(jnp.where(rep, net, 0), mode="drop")
    other = JoinSide(
        other.table, other.rows, other.row_nulls, other.row_valid,
        other.overflow, other.inconsistent, other.sdirty, other.stored,
        new_flat.reshape(cap, fanout),
    )
    new = old + net
    went_pos = rep & (old == 0) & (new > 0)
    went_zero = rep & (old > 0) & (new <= 0)
    trans_pid = jnp.where(rep, spid, sent)
    return other, trans_pid, went_pos, went_zero


def gather_flat(
    side: JoinSide, pid: jnp.ndarray, names: Sequence[str]
) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """Gather payload at flat (slot*fanout+pos) ids (sentinel-safe)."""
    cap, fanout = side.capacity, side.fanout
    safe = jnp.minimum(pid, cap * fanout - 1)
    cols = {n: side.rows[n].reshape(-1)[safe] for n in names}
    nulls = {
        n: lane.reshape(-1)[safe] for n, lane in side.row_nulls.items()
    }
    return cols, nulls


def probe_side(
    other: JoinSide,
    key_cols: Tuple[jnp.ndarray, ...],
    valid: jnp.ndarray,
):
    """Probe the other side: returns (slots, match) where match is the
    (n, fanout) mask of live stored rows joining each probe row."""
    slots, found = lookup(other.table, key_cols, valid)
    sl = jnp.maximum(slots, 0)
    match = other.row_valid[sl] & (found & valid)[:, None]
    return sl, match


def gather_matches(
    other: JoinSide, sl: jnp.ndarray, names: Sequence[str]
) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """Gather (n, fanout) bucket payloads for probed slots."""
    cols = {n: other.rows[n][sl] for n in names}
    nulls = {n: lane[sl] for n, lane in other.row_nulls.items()}
    return cols, nulls


def compact_pairs(
    flat_cols: Dict[str, jnp.ndarray],
    flat_nulls: Dict[str, jnp.ndarray],
    flat_ops: jnp.ndarray,
    flat_valid: jnp.ndarray,
    out_cap: int,
):
    """Compact sparse (n*fanout) join pairs into a fixed out_cap chunk.

    Returns (cols, nulls, ops, valid, overflow). Order-stable: pair i
    lands before pair j if i < j (cumsum positions), matching the
    reference's emission order per probe chunk.
    """
    pos = jnp.cumsum(flat_valid.astype(jnp.int32)) - 1
    overflow = jnp.any(flat_valid & (pos >= out_cap))
    idx = jnp.where(flat_valid & (pos < out_cap), pos, out_cap)

    def scatter(src, dtype=None):
        buf = jnp.zeros(out_cap, dtype or src.dtype)
        return buf.at[idx].set(src, mode="drop")

    cols = {n: scatter(a) for n, a in flat_cols.items()}
    nulls = {n: scatter(a) for n, a in flat_nulls.items()}
    ops = scatter(flat_ops)
    valid = jnp.zeros(out_cap, jnp.bool_).at[idx].set(flat_valid, mode="drop")
    return cols, nulls, ops, valid, overflow


@partial(jax.jit, static_argnames=("new_cap", "new_fanout"))
def regrow(side: JoinSide, new_cap: int, new_fanout: int) -> JoinSide:
    """Rebuild into a larger table and/or wider buckets, dropping
    tombstoned keys and compacting bucket holes (the heap-growth
    analogue; cf. executors/hash_agg._rehash)."""
    cap, fanout = side.capacity, side.fanout
    # live keys survive; sdirty dead keys survive too (the next
    # checkpoint needs their key lanes to write tombstones)
    keep = (side.table.live | side.sdirty) & (side.table.fp1 != jnp.uint32(0))

    new_table = HashTable.create(new_cap, tuple(k.dtype for k in side.table.keys))
    new_table, new_slots, _, _ = lookup_or_insert(new_table, side.table.keys, keep)
    new_table = set_live(
        new_table, jnp.where(keep, new_slots, -1), side.table.live
    )
    nidx = jnp.where(keep, new_slots, new_cap)
    new_sdirty = jnp.zeros(new_cap, jnp.bool_).at[nidx].set(
        side.sdirty, mode="drop"
    )
    new_stored = jnp.zeros(new_cap, jnp.bool_).at[nidx].set(
        side.stored, mode="drop"
    )

    # compact each bucket's live entries to the front of the new bucket
    entry_pos = jnp.cumsum(side.row_valid.astype(jnp.int32), axis=1) - 1
    entry_ok = side.row_valid & keep[:, None] & (entry_pos < new_fanout)
    dest_slot = jnp.broadcast_to(new_slots[:, None], (cap, fanout))
    flat_idx = jnp.where(
        entry_ok,
        dest_slot * new_fanout + entry_pos,
        new_cap * new_fanout,
    ).reshape(-1)

    def move(src, dtype):
        buf = jnp.zeros(new_cap * new_fanout, dtype)
        return (
            buf.at[flat_idx].set(src.reshape(-1), mode="drop")
            .reshape(new_cap, new_fanout)
        )

    rows = {n: move(a, a.dtype) for n, a in side.rows.items()}
    row_nulls = {n: move(a, jnp.bool_) for n, a in side.row_nulls.items()}
    row_valid = move(side.row_valid & entry_ok, jnp.bool_)
    degree = move(side.degree, jnp.int32)
    return JoinSide(
        new_table, rows, row_nulls, row_valid, side.overflow,
        side.inconsistent, new_sdirty, new_stored, degree,
    )


@partial(jax.jit, static_argnames=("key_index",))
def expire_keys(side: JoinSide, key_index: int, cutoff: jnp.ndarray) -> JoinSide:
    """Watermark state cleaning: drop every key whose key lane
    ``key_index`` < cutoff (reference: state cleaning via table
    watermarks, state_table.rs:1133 + skip_watermark.rs)."""
    lane = side.table.keys[key_index]
    expired = side.table.live & (lane < cutoff)
    slots = jnp.where(expired, jnp.arange(side.capacity, dtype=jnp.int32), -1)
    table = set_live(side.table, slots, False)
    row_valid = side.row_valid & ~expired[:, None]
    degree = jnp.where(expired[:, None], jnp.int32(0), side.degree)
    return JoinSide(
        table, side.rows, side.row_nulls, row_valid, side.overflow,
        side.inconsistent, side.sdirty | expired, side.stored, degree,
    )
