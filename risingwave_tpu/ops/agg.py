"""Grouped aggregation state kernel — the core of HashAgg.

Reference roles replaced:
- per-group agg state + apply_chunk
  (src/stream/src/executor/hash_agg.rs:326, executor/aggregation/
  {agg_group.rs, agg_state.rs})
- dirty-group tracking + per-barrier flush_data emitting one
  retraction/update row pair per changed group (hash_agg.rs:406).

TPU re-design: agg state is NOT a map of per-group objects — it is a
struct-of-arrays indexed by hash-table slot (ops/hash_table.py assigns
slots). Applying a chunk is a handful of masked segment-scatters:

    count[slot]  += sign                  (COUNT(*) / group liveness)
    sum[slot]    += sign * value          (SUM / COUNT(col))
    min[slot]     = min(min[slot], value) (append-only MIN/MAX)

so a whole chunk of any size updates all its groups in O(chunk) scatter
work with zero host round-trips, and the whole thing fuses under jit.

SQL NULL outputs: SUM/MIN/MAX over a group whose inputs are all NULL is
NULL (COUNT is 0). Each such call keeps a per-group non-null input
counter; flush emits a null lane from ``counter == 0`` (reference:
agg_state.rs null handling / Datum outputs).

Retraction: sum/count invert exactly via the sign. MIN/MAX cannot be
retracted without per-group materialized input (reference keeps a sorted
state table per extreme agg call, executor/aggregation/minput.rs); this
kernel maintains them append-only and *flags* any retraction touching a
MIN/MAX call in ``state.minmax_retracted`` so the host can reject or
escalate (windowed Nexmark plans delete whole groups, never individual
rows, so the append-only path covers q5/q7/q8).

Flush: per-barrier delta emission compacts dirty slots to the front
(static shapes) and emits, per dirty group:

    previously emitted & still live  -> (U-, old row) + (U+, new row)
    previously emitted & dead        -> (D,  old row)
    never emitted      & live        -> (I,  new row)

matching the reference's AggChangesEmitter semantics (hash_agg.rs:406).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from risingwave_tpu.types import Op

KINDS = ("count_star", "count", "sum", "min", "max")
# kinds whose SQL result is NULL when no non-NULL input exists
NULLABLE_KINDS = ("sum", "min", "max")


@dataclass(frozen=True)
class AggCall:
    """One aggregate call: kind + input column -> output column.

    Mirrors the reference's ``AggCall`` (src/expr/core/src/aggregate/)
    narrowed to the kernel-supported kinds. ``materialized`` selects the
    materialized-input MIN/MAX state (ops/minput.py, reference
    minput.rs) so row-level retractions are exact; append-only plans
    leave it False and pay no extra state.
    """

    kind: str
    input: Optional[str]  # None for count_star
    output: str
    materialized: bool = False

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unsupported agg kind {self.kind!r}")
        if (self.input is None) != (self.kind == "count_star"):
            raise ValueError(f"{self.kind} input mismatch")
        if self.materialized and self.kind not in ("min", "max"):
            raise ValueError("materialized only applies to min/max")


def _extreme_init(dtype, kind: str):
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if kind == "min" else info.min, dtype)


def accum_init(kind: str, dtype) -> jnp.ndarray:
    """The empty-group accumulator value for one agg kind (scalar)."""
    if kind in ("min", "max"):
        return _extreme_init(dtype, kind)
    return jnp.zeros((), dtype)


# -- ordered-float total-order encoding ---------------------------------
# Float MIN/MAX accumulators are stored as UNSIGNED total-order keys, not
# floats: scatter-min over raw floats lets one NaN poison a group forever
# (min(NaN, x) = NaN and append-only extremes can never retract it). The
# reference's ordered-float total ordering (src/common/src/types/, also
# used for the minput.rs sorted state) places NaN as the single largest
# value; the classic bit trick below realizes exactly that ordering on
# integer lanes, which the TPU scatters natively.

_FLOAT_ORDER = {
    jnp.dtype(jnp.float32): (jnp.uint32, jnp.uint32(1) << 31),
    jnp.dtype(jnp.float64): (jnp.uint64, jnp.uint64(1) << 63),
}


def _float_to_order_key(v: jnp.ndarray) -> jnp.ndarray:
    udtype, sign = _FLOAT_ORDER[jnp.dtype(v.dtype)]
    # canonicalize: one zero, one (positive quiet) NaN
    v = jnp.where(v == 0.0, jnp.zeros((), v.dtype), v)
    v = jnp.where(jnp.isnan(v), jnp.full((), jnp.nan, v.dtype), v)
    bits = jax.lax.bitcast_convert_type(v, udtype)
    neg = (bits & sign) != 0
    return jnp.where(neg, ~bits, bits | sign)


def _order_key_to_float(k: jnp.ndarray, float_dtype) -> jnp.ndarray:
    udtype, sign = _FLOAT_ORDER[jnp.dtype(float_dtype)]
    was_pos = (k & sign) != 0
    bits = jnp.where(was_pos, k & ~sign, ~k)
    return jax.lax.bitcast_convert_type(bits.astype(udtype), float_dtype)


@jax.tree_util.register_pytree_node_class
@dataclass
class AggState:
    """Slot-indexed aggregation state (all arrays length = capacity).

    ``row_count`` is the implicit COUNT(*) that determines group
    liveness (reference: AggGroup keeps row_count to decide emit vs
    delete, agg_group.rs). ``accums[name]`` holds one accumulator lane
    per AggCall output; ``nonnull[name]`` counts non-NULL inputs for
    NULLABLE_KINDS calls (0 -> SQL NULL output). ``emitted*`` snapshot
    what downstream has seen, so flush can produce exact U-/U+
    retractions. ``dirty`` marks slots touched since the last flush.
    ``minmax_retracted`` latches the unsupported-retraction condition
    for host-side checking.

    Storage lanes (the memtable-dirty analogue, mem_table.rs):
    ``sdirty`` marks slots changed since the last CHECKPOINT (cleared
    by StateTable commit); ``stored`` marks slots present in the object
    store (drives tombstone emission when a stored group dies).
    """

    row_count: jnp.ndarray  # int64
    accums: Dict[str, jnp.ndarray]
    nonnull: Dict[str, jnp.ndarray]  # int64, subset of accum names
    emitted: Dict[str, jnp.ndarray]
    emitted_isnull: Dict[str, jnp.ndarray]  # bool, same keys as nonnull
    emitted_valid: jnp.ndarray  # bool
    dirty: jnp.ndarray  # bool
    minmax_retracted: jnp.ndarray  # () bool
    sdirty: jnp.ndarray  # bool — changed since last checkpoint
    stored: jnp.ndarray  # bool — persisted in the object store

    def tree_flatten(self):
        anames = tuple(sorted(self.accums))
        nnames = tuple(sorted(self.nonnull))
        children = (
            self.row_count,
            tuple(self.accums[n] for n in anames),
            tuple(self.nonnull[n] for n in nnames),
            tuple(self.emitted[n] for n in anames),
            tuple(self.emitted_isnull[n] for n in nnames),
            self.emitted_valid,
            self.dirty,
            self.minmax_retracted,
            self.sdirty,
            self.stored,
        )
        return children, (anames, nnames)

    @classmethod
    def tree_unflatten(cls, aux, children):
        anames, nnames = aux
        (
            row_count,
            accums,
            nonnull,
            emitted,
            e_isnull,
            emitted_valid,
            dirty,
            mr,
            sdirty,
            stored,
        ) = children
        return cls(
            row_count=row_count,
            accums=dict(zip(anames, accums)),
            nonnull=dict(zip(nnames, nonnull)),
            emitted=dict(zip(anames, emitted)),
            emitted_isnull=dict(zip(nnames, e_isnull)),
            emitted_valid=emitted_valid,
            dirty=dirty,
            minmax_retracted=mr,
            sdirty=sdirty,
            stored=stored,
        )

    @property
    def capacity(self) -> int:
        return self.row_count.shape[0]


def _accum_dtype(call: AggCall, input_dtype) -> jnp.dtype:
    if call.kind in ("count_star", "count"):
        return jnp.int64
    if call.kind == "sum" and jnp.issubdtype(input_dtype, jnp.integer):
        return jnp.int64  # SQL SUM(int) widens to bigint
    if call.kind in ("min", "max") and jnp.issubdtype(input_dtype, jnp.floating):
        return _FLOAT_ORDER[jnp.dtype(input_dtype)][0]  # total-order key
    return input_dtype


def float_extreme_meta(calls: Sequence[AggCall], input_dtypes) -> tuple:
    """Static metadata for flush(): which outputs are float extremes and
    their original float dtype (needed to decode order keys back)."""
    out = []
    for c in calls:
        if c.kind in ("min", "max") and jnp.issubdtype(
            input_dtypes.get(c.input, jnp.int64), jnp.floating
        ):
            out.append((c.output, str(jnp.dtype(input_dtypes[c.input]))))
    return tuple(out)


def create_state(capacity: int, calls: Sequence[AggCall], input_dtypes) -> AggState:
    """``input_dtypes`` maps input column name -> jnp dtype."""
    accums, nonnull, emitted, e_isnull = {}, {}, {}, {}
    for c in calls:
        dt = _accum_dtype(c, None if c.input is None else input_dtypes[c.input])
        accums[c.output] = jnp.full(capacity, accum_init(c.kind, dt), dt)
        emitted[c.output] = jnp.zeros(capacity, dt)
        if c.kind in NULLABLE_KINDS:
            nonnull[c.output] = jnp.zeros(capacity, jnp.int64)
            e_isnull[c.output] = jnp.zeros(capacity, jnp.bool_)
    return AggState(
        row_count=jnp.zeros(capacity, jnp.int64),
        accums=accums,
        nonnull=nonnull,
        emitted=emitted,
        emitted_isnull=e_isnull,
        emitted_valid=jnp.zeros(capacity, jnp.bool_),
        dirty=jnp.zeros(capacity, jnp.bool_),
        minmax_retracted=jnp.zeros((), jnp.bool_),
        sdirty=jnp.zeros(capacity, jnp.bool_),
        stored=jnp.zeros(capacity, jnp.bool_),
    )


def apply(
    state: AggState,
    calls: Tuple[AggCall, ...],
    slots: jnp.ndarray,  # (n,) int32, -1 = skip
    signs: jnp.ndarray,  # (n,) int32 in {-1, 0, +1}; 0 for padding
    values: Dict[str, jnp.ndarray],
    nulls: Dict[str, jnp.ndarray],  # input-null lanes (may be absent)
) -> AggState:
    """Apply one chunk's rows to the state (pure; jit-composable).

    ``signs`` must already fold visibility (StreamChunk.effective_signs).
    NULL inputs contribute to nothing but COUNT(*) (SQL: aggregates skip
    NULLs; reference agg_state.rs null handling).
    """
    cap = state.capacity
    active = (slots >= 0) & (signs != 0)
    idx = jnp.where(active, slots, cap)  # cap = drop lane
    w = jnp.where(active, signs, 0).astype(jnp.int64)

    row_count = state.row_count.at[idx].add(w, mode="drop")
    dirty = state.dirty.at[idx].set(True, mode="drop")
    sdirty = state.sdirty.at[idx].set(True, mode="drop")

    accums = dict(state.accums)
    nonnull = dict(state.nonnull)
    mr = state.minmax_retracted
    for c in calls:
        acc = accums[c.output]
        if c.kind == "count_star":
            accums[c.output] = acc.at[idx].add(w, mode="drop")
            continue
        v = values[c.input]
        notnull = ~nulls.get(c.input, jnp.zeros(v.shape, jnp.bool_))
        wn = jnp.where(notnull, w, 0)
        if c.kind == "count":
            accums[c.output] = acc.at[idx].add(wn, mode="drop")
        elif c.kind == "sum":
            contrib = jnp.where(notnull, v.astype(acc.dtype) * w.astype(acc.dtype), 0)
            accums[c.output] = acc.at[idx].add(contrib, mode="drop")
            nonnull[c.output] = nonnull[c.output].at[idx].add(wn, mode="drop")
        elif c.materialized:
            # materialized-input MIN/MAX: the minput pass (ops/minput.py)
            # owns accum + nonnull maintenance; retraction is exact, so
            # no latch here
            continue
        else:  # min / max — append-only
            sentinel = accum_init(c.kind, acc.dtype)
            use = active & notnull & (w > 0)
            if jnp.issubdtype(v.dtype, jnp.floating):
                v = _float_to_order_key(v)  # NaN-safe total order
            vv = jnp.where(use, v.astype(acc.dtype), sentinel)
            uidx = jnp.where(use, slots, cap)
            if c.kind == "min":
                accums[c.output] = acc.at[uidx].min(vv, mode="drop")
            else:
                accums[c.output] = acc.at[uidx].max(vv, mode="drop")
            nonnull[c.output] = (
                nonnull[c.output]
                .at[uidx]
                .add(jnp.where(use, jnp.int64(1), jnp.int64(0)), mode="drop")
            )
            mr = mr | jnp.any(active & notnull & (w < 0))

    return AggState(
        row_count=row_count,
        accums=accums,
        nonnull=nonnull,
        emitted=state.emitted,
        emitted_isnull=state.emitted_isnull,
        emitted_valid=state.emitted_valid,
        dirty=dirty,
        minmax_retracted=mr,
        sdirty=sdirty,
        stored=state.stored,
    )


def reduce_by_key(
    key_lanes: Tuple[jnp.ndarray, ...],
    signs: jnp.ndarray,
    calls: Tuple[AggCall, ...],
    values: Dict[str, jnp.ndarray],
    nulls: Dict[str, jnp.ndarray],
):
    """Pre-reduce a row batch by group key (pure; jit-composable).

    The TPU-first answer to per-row hash probing: ``lax.sort`` (a
    vectorized compare-exchange network — no serialized gathers)
    clusters equal keys, segments split at any exact key change, and
    every aggregate contribution is segment-reduced, so the hash table
    downstream is probed and scattered once per DISTINCT key instead of
    once per row. This is the StatelessSimpleAgg-before-shuffle shape
    (src/stream/src/executor/stateless_simple_agg.rs) fused into the
    operator, applied per epoch rather than per actor.

    All agg kinds here are commutative across rows of one epoch batch
    (sum/count exactly; min/max append-only with the retraction latch),
    so reordering by sort is semantics-preserving.

    Returns ``(sorted_keys, rep_valid, w, reduced, minmax_ret)``:
      sorted_keys  key lanes in sort order (feed to lookup_or_insert)
      rep_valid    bool (n,) — True on each segment's first row
      w            int64 (n,) — Σ sign per segment, on rep rows
      reduced      dict of per-call reduced lanes (on rep rows):
                   'cnt_<out>' / 'sum_<out>' / 'nn_<out>' /
                   'ext_<out>' / 'nnp_<out>'
      minmax_ret   () bool — a retraction touched a MIN/MAX call
    """
    from risingwave_tpu.ops.hashing import hash128

    n = signs.shape[0]
    h1, h2 = hash128(key_lanes)
    vmask = signs != 0
    # invisible rows sort to the end (max fingerprint) and never become
    # segment representatives
    h1s = jnp.where(vmask, h1, jnp.uint32(0xFFFFFFFF))
    h2s = jnp.where(vmask, h2, jnp.uint32(0xFFFFFFFF))

    val_names = tuple(sorted(values))
    null_names = tuple(sorted(nulls))
    operands = (
        [h1s, h2s]
        + list(key_lanes)
        + [signs.astype(jnp.int32), vmask]
        + [values[nm] for nm in val_names]
        + [nulls[nm] for nm in null_names]
    )
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=2)
    h1s, h2s = sorted_ops[0], sorted_ops[1]
    nk = len(key_lanes)
    sorted_keys = tuple(sorted_ops[2 : 2 + nk])
    s_sign = sorted_ops[2 + nk].astype(jnp.int64)
    s_vmask = sorted_ops[3 + nk]
    s_vals = {
        nm: sorted_ops[4 + nk + i] for i, nm in enumerate(val_names)
    }
    s_nulls = {
        nm: sorted_ops[4 + nk + len(val_names) + i]
        for i, nm in enumerate(null_names)
    }

    # segment boundary: first row, or ANY exact lane change (fingerprint
    # collisions between different keys split correctly because the raw
    # key lanes participate)
    def lane_change(lane):
        return jnp.concatenate(
            [jnp.ones(1, jnp.bool_), lane[1:] != lane[:-1]]
        )

    boundary = lane_change(h1s) | lane_change(h2s) | lane_change(s_vmask)
    for lane in sorted_keys:
        ch = lane_change(lane)
        if jnp.issubdtype(lane.dtype, jnp.floating):
            both_nan = jnp.concatenate(
                [
                    jnp.zeros(1, jnp.bool_),
                    jnp.isnan(lane[1:]) & jnp.isnan(lane[:-1]),
                ]
            )
            ch = ch & ~both_nan  # NaN == NaN for grouping (total order)
        boundary = boundary | ch
    rep_valid = boundary & s_vmask
    seg_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1

    def segsum(x):
        return jax.ops.segment_sum(x, seg_id, num_segments=n)[seg_id]

    w = segsum(s_sign)
    reduced: Dict[str, jnp.ndarray] = {}
    minmax_ret = jnp.zeros((), jnp.bool_)
    for c in calls:
        if c.kind == "count_star":
            continue  # uses w directly
        v = s_vals[c.input]
        notnull = ~s_nulls.get(c.input, jnp.zeros(v.shape, jnp.bool_))
        wn = jnp.where(notnull, s_sign, 0)
        if c.kind == "count":
            reduced[f"cnt_{c.output}"] = segsum(wn)
        elif c.kind == "sum":
            acc_dt = _accum_dtype(c, v.dtype)
            contrib = jnp.where(
                notnull, v.astype(acc_dt) * s_sign.astype(acc_dt), 0
            )
            reduced[f"sum_{c.output}"] = segsum(contrib)
            reduced[f"nn_{c.output}"] = segsum(wn)
        elif c.materialized:
            continue  # minput pass maintains these (ops/minput.py)
        else:  # min / max (append-only)
            use = s_vmask & notnull & (s_sign > 0)
            if jnp.issubdtype(v.dtype, jnp.floating):
                v = _float_to_order_key(v)
            acc_dt = _accum_dtype(c, s_vals[c.input].dtype)
            sentinel = accum_init(c.kind, acc_dt)
            vv = jnp.where(use, v.astype(acc_dt), sentinel)
            seg_red = (
                jax.ops.segment_min
                if c.kind == "min"
                else jax.ops.segment_max
            )(vv, seg_id, num_segments=n)
            reduced[f"ext_{c.output}"] = seg_red[seg_id]
            reduced[f"nnp_{c.output}"] = segsum(
                jnp.where(use, jnp.int64(1), jnp.int64(0))
            )
            minmax_ret = minmax_ret | jnp.any(s_vmask & notnull & (s_sign < 0))
    return sorted_keys, rep_valid, w, reduced, minmax_ret


def apply_reduced(
    state: AggState,
    calls: Tuple[AggCall, ...],
    slots: jnp.ndarray,
    rep_valid: jnp.ndarray,
    w: jnp.ndarray,
    reduced: Dict[str, jnp.ndarray],
    minmax_ret: jnp.ndarray,
) -> AggState:
    """Apply ``reduce_by_key`` output to the state: one scatter per
    lane, indices touched once per distinct key."""
    cap = state.capacity
    active = rep_valid & (slots >= 0)
    idx = jnp.where(active, slots, cap)
    ww = jnp.where(active, w, 0)

    row_count = state.row_count.at[idx].add(ww, mode="drop")
    dirty = state.dirty.at[idx].set(True, mode="drop")
    sdirty = state.sdirty.at[idx].set(True, mode="drop")

    accums = dict(state.accums)
    nonnull = dict(state.nonnull)
    for c in calls:
        acc = accums[c.output]
        if c.kind == "count_star":
            accums[c.output] = acc.at[idx].add(ww, mode="drop")
        elif c.kind == "count":
            accums[c.output] = acc.at[idx].add(
                jnp.where(active, reduced[f"cnt_{c.output}"], 0), mode="drop"
            )
        elif c.kind == "sum":
            accums[c.output] = acc.at[idx].add(
                jnp.where(active, reduced[f"sum_{c.output}"], 0).astype(
                    acc.dtype
                ),
                mode="drop",
            )
            nonnull[c.output] = nonnull[c.output].at[idx].add(
                jnp.where(active, reduced[f"nn_{c.output}"], 0), mode="drop"
            )
        elif c.materialized:
            continue  # minput pass maintains these (ops/minput.py)
        else:  # min / max
            sentinel = accum_init(c.kind, acc.dtype)
            ext = jnp.where(
                active, reduced[f"ext_{c.output}"].astype(acc.dtype), sentinel
            )
            if c.kind == "min":
                accums[c.output] = acc.at[idx].min(ext, mode="drop")
            else:
                accums[c.output] = acc.at[idx].max(ext, mode="drop")
            nonnull[c.output] = nonnull[c.output].at[idx].add(
                jnp.where(active, reduced[f"nnp_{c.output}"], 0), mode="drop"
            )

    return AggState(
        row_count=row_count,
        accums=accums,
        nonnull=nonnull,
        emitted=state.emitted,
        emitted_isnull=state.emitted_isnull,
        emitted_valid=state.emitted_valid,
        dirty=dirty,
        minmax_retracted=state.minmax_retracted | minmax_ret,
        sdirty=sdirty,
        stored=state.stored,
    )


def _reset_groups(
    state: AggState,
    calls: Tuple[AggCall, ...],
    slots: jnp.ndarray,
    *,
    mark_dirty: bool,
) -> AggState:
    """Zero out groups' accumulators.

    ``mark_dirty=True`` (delete_groups): the next flush emits a Delete
    for each previously-emitted group — windowed retraction.
    ``mark_dirty=False`` (forget_groups): silent finalization — the
    flush emits nothing; downstream keeps the last emitted row as the
    window's final result while the operator frees the state (EOWC
    cleanup; reference hash_agg.rs emit-on-window-close mode +
    state_table.rs:1133 watermark cleaning). Callers must flush dirty
    groups FIRST or pending updates would be silently discarded.
    """
    cap = state.capacity
    idx = jnp.where(slots >= 0, slots, cap)
    row_count = state.row_count.at[idx].set(0, mode="drop")
    sdirty = state.sdirty.at[idx].set(True, mode="drop")
    if mark_dirty:
        dirty = state.dirty.at[idx].set(True, mode="drop")
        emitted_valid = state.emitted_valid
    else:
        dirty = state.dirty.at[idx].set(False, mode="drop")
        emitted_valid = state.emitted_valid.at[idx].set(False, mode="drop")
    kinds = {c.output: c.kind for c in calls}
    accums = {
        name: acc.at[idx].set(accum_init(kinds[name], acc.dtype), mode="drop")
        for name, acc in state.accums.items()
    }
    nonnull = {
        name: nn.at[idx].set(0, mode="drop") for name, nn in state.nonnull.items()
    }
    return AggState(
        row_count=row_count,
        accums=accums,
        nonnull=nonnull,
        emitted=state.emitted,
        emitted_isnull=state.emitted_isnull,
        emitted_valid=emitted_valid,
        dirty=dirty,
        minmax_retracted=state.minmax_retracted,
        sdirty=sdirty,
        stored=state.stored,
    )


def delete_groups(
    state: AggState, calls: Tuple[AggCall, ...], slots: jnp.ndarray
) -> AggState:
    """Drop whole groups (window expiry) WITH downstream retraction."""
    return _reset_groups(state, calls, slots, mark_dirty=True)


def forget_groups(
    state: AggState, calls: Tuple[AggCall, ...], slots: jnp.ndarray
) -> AggState:
    """Silently free groups (EOWC finalization). See _reset_groups."""
    return _reset_groups(state, calls, slots, mark_dirty=False)


@partial(
    jax.jit, static_argnames=("out_cap", "float_extremes"), donate_argnums=(0,)
)
def flush(
    state: AggState,
    table_keys: Tuple[jnp.ndarray, ...],
    out_cap: int,
    float_extremes: tuple = (),
):
    """Emit the per-barrier delta for dirty groups (hash_agg.rs:406).

    Returns ``(state', delta)`` where delta is a dict of fixed-capacity
    (2 * out_cap) arrays:
      ``ops``                int32 Op lane
      ``valid``              bool row-validity lane
      ``key<i>``             the i-th group-key lane (from table_keys)
      ``<output>``           one lane per agg output
      ``<output>__isnull``   bool SQL-NULL lane (NULLABLE_KINDS only)
      ``overflow``           () bool — True if more than out_cap dirty
                             groups existed; host must flush again.

    Old (U-/D) rows carry the previously-emitted accums; new (U+/I)
    rows carry the current ones. Rows interleave (old_i, new_i) so
    downstream sees retraction-before-insert per group, matching
    StreamChunk update-pair ordering (stream_chunk.rs:45).

    ``float_extremes`` (static, from ``float_extreme_meta``) lists agg
    outputs stored as float total-order keys; their lanes are decoded
    back to floats on emission.
    """
    cap = state.capacity
    # compact dirty slot ids to the front: sort puts False (0) last
    order = jnp.argsort(~state.dirty, stable=True)
    dirty_sorted = state.dirty[order]
    n_dirty = jnp.sum(state.dirty.astype(jnp.int32))
    take = dirty_sorted[:out_cap]
    slot_ids = order[:out_cap]
    overflow = n_dirty > out_cap

    live = take & (state.row_count[slot_ids] > 0)
    was = take & state.emitted_valid[slot_ids]

    minus_valid = was  # emit old row as U- or D
    plus_valid = live  # emit new row as U+ or I
    minus_op = jnp.where(live, jnp.int32(Op.UPDATE_DELETE), jnp.int32(Op.DELETE))
    plus_op = jnp.where(was, jnp.int32(Op.UPDATE_INSERT), jnp.int32(Op.INSERT))

    def interleave(a, b):
        return jnp.stack([a, b], axis=1).reshape(-1)

    delta = {
        "ops": interleave(minus_op, plus_op),
        "valid": interleave(minus_valid, plus_valid),
        "overflow": overflow,
        # [n dirty slots taken, overflow] — ONE host read serves both
        # the emit-size slice and the continue-flush check (each device
        # read is a full round-trip on a tunneled TPU)
        "status": jnp.stack(
            [jnp.sum(take.astype(jnp.int32)), overflow.astype(jnp.int32)]
        ),
    }
    for i, lane in enumerate(table_keys):
        kv = lane[slot_ids]
        delta[f"key{i}"] = interleave(kv, kv)
    decode = dict(float_extremes)
    for name, acc in state.accums.items():
        old = state.emitted[name][slot_ids]
        new = acc[slot_ids]
        if name in decode:
            old = _order_key_to_float(old, jnp.dtype(decode[name]))
            new = _order_key_to_float(new, jnp.dtype(decode[name]))
        delta[name] = interleave(old, new)
    for name, nn in state.nonnull.items():
        old_isnull = state.emitted_isnull[name][slot_ids]
        new_isnull = nn[slot_ids] == 0
        delta[name + "__isnull"] = interleave(old_isnull, new_isnull)

    # snapshot what we just emitted (only for flushed slots)
    fidx = jnp.where(take, slot_ids, cap)
    emitted = {
        name: state.emitted[name]
        .at[fidx]
        .set(state.accums[name][slot_ids], mode="drop")
        for name in state.accums
    }
    emitted_isnull = {
        name: state.emitted_isnull[name]
        .at[fidx]
        .set(state.nonnull[name][slot_ids] == 0, mode="drop")
        for name in state.nonnull
    }
    emitted_valid = state.emitted_valid.at[fidx].set(
        state.row_count[slot_ids] > 0, mode="drop"
    )
    dirty = state.dirty.at[fidx].set(False, mode="drop")

    state = AggState(
        row_count=state.row_count,
        accums=state.accums,
        nonnull=state.nonnull,
        emitted=emitted,
        emitted_isnull=emitted_isnull,
        emitted_valid=emitted_valid,
        dirty=dirty,
        minmax_retracted=state.minmax_retracted,
        sdirty=state.sdirty,
        stored=state.stored,
    )
    return state, delta
