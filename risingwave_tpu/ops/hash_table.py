"""Device-resident open-addressing hash table — the state substrate.

Reference roles replaced:
- ``JoinHashMap`` (src/stream/src/executor/join/hash_join.rs:157)
- HashAgg's dirty-group map / ``AggGroupCache``
  (src/stream/src/executor/hash_agg.rs:49-62)
- GroupTopN's per-group cache (src/stream/src/executor/top_n/group_top_n.rs:63)

Those are CPU pointer-chasing hash maps; on TPU the equivalent must be a
*flat array program*: a power-of-two slot table in HBM, linear probing,
and a batched insert that resolves intra-chunk collisions without locks.

Insert algorithm ("scatter-claim-verify"): all rows probe in lockstep.
At probe step t each unresolved row computes its candidate slot
``(h + t) & mask``. Rows whose candidate already holds their fingerprint
resolve to it. Rows pointing at an EMPTY slot *claim* it with one scatter
(XLA picks an arbitrary winner per slot among duplicates); re-reading the
slot tells each row whether it (or a same-key twin) won — losers advance
to the next probe step. The loop is a ``lax.fori_loop`` with a static
bound, so the whole thing jits into one fused program with no
data-dependent shapes.

Keys are stored as fingerprints (two independent 32-bit hashes, see
ops/hashing.hash128) plus the raw key lanes for exact verification —
fingerprint match alone would admit false merges at ~2^-64 rates, but
exact lanes make collisions impossible, matching the reference's exact
`HashKey` equality (src/common/src/hash/key.rs).

Deletion marks slots TOMBSTONE; tombstones are *not* reusable by insert
within an epoch (they still break probe chains only at rehash), and the
host-side StateTable rebuilds/rehashes the table when live+tombstone load
crosses the resize threshold — the TPU analogue of the reference growing
its hash maps on the heap.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from risingwave_tpu.ops.hashing import hash128

EMPTY = jnp.uint32(0)  # slot status: fingerprint 0 reserved for "empty"
TOMBSTONE_FLAG = 0x1  # bit in `status` lane

# Static probe bound. With load factor <= 0.5 the expected max probe
# length for linear probing is O(log n); 64 is comfortably beyond it for
# the table sizes we run (2^14..2^20) and keeps the fori_loop cheap.
MAX_PROBE = 64


@jax.tree_util.register_pytree_node_class
@dataclass
class HashTable:
    """A set of key slots; payload arrays live next to it, indexed by slot.

    Arrays (all length = capacity, power of two):
      fp1, fp2   uint32 fingerprints (fp1 == 0 means EMPTY slot)
      keys       (n_key_cols, capacity) raw key lanes for exact equality
      live       bool — True once inserted, False again when deleted
    """

    fp1: jnp.ndarray
    fp2: jnp.ndarray
    keys: Tuple[jnp.ndarray, ...]
    live: jnp.ndarray

    def tree_flatten(self):
        return ((self.fp1, self.fp2, self.keys, self.live), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def capacity(self) -> int:
        return self.fp1.shape[0]

    @staticmethod
    def create(capacity: int, key_dtypes: Sequence[jnp.dtype]) -> "HashTable":
        if capacity & (capacity - 1):
            raise ValueError("capacity must be a power of two")
        return HashTable(
            fp1=jnp.zeros(capacity, jnp.uint32),
            fp2=jnp.zeros(capacity, jnp.uint32),
            keys=tuple(jnp.zeros(capacity, d) for d in key_dtypes),
            live=jnp.zeros(capacity, jnp.bool_),
        )

    def occupancy(self) -> jnp.ndarray:
        """Slots ever claimed (live + tombstones) — drives host rehash."""
        return jnp.sum((self.fp1 != EMPTY).astype(jnp.int32))

    def num_live(self) -> jnp.ndarray:
        return jnp.sum(self.live.astype(jnp.int32))


def _keys_match(table: HashTable, slot: jnp.ndarray, key_cols) -> jnp.ndarray:
    ok = jnp.ones(slot.shape, jnp.bool_)
    for tk, k in zip(table.keys, key_cols):
        stored = tk[slot]
        eq = stored == k
        if jnp.issubdtype(tk.dtype, jnp.floating):
            # ordered-float total equality: NaN == NaN (reference treats
            # float keys via total ordering, src/common/src/types/). IEEE
            # `==` would make a NaN key unresolvable: it claims a slot,
            # fails its own verify, and re-claims forever — leaking
            # MAX_PROBE slots and returning -1 (a bogus rehash signal).
            eq |= jnp.isnan(stored) & jnp.isnan(k)
        ok &= eq
    return ok


@partial(jax.jit, static_argnames=("insert_missing",), donate_argnums=(0,))
def lookup_or_insert(
    table: HashTable,
    key_cols: Tuple[jnp.ndarray, ...],
    valid: jnp.ndarray,
    insert_missing: bool = True,
):
    """Batched find-or-insert. Returns (table', slots, found, inserted).

    slots[i] == -1 iff row i is invalid, or the key was absent and
    ``insert_missing`` is False, or the table overflowed MAX_PROBE
    (callers treat -1 slots of valid rows as an overflow signal and
    trigger a host-side rehash; see state/state_table.py).
    """
    cap = table.capacity
    mask = jnp.uint32(cap - 1)
    h1, h2 = hash128(key_cols)
    # fingerprint 0 is reserved for EMPTY: remap to 1
    fp1 = jnp.where(h1 == 0, jnp.uint32(1), h1)
    fp2 = h2

    n = valid.shape[0]
    slots = jnp.full(n, -1, jnp.int32)
    found = jnp.zeros(n, jnp.bool_)
    inserted = jnp.zeros(n, jnp.bool_)
    unresolved = valid
    # claim scratch is allocated ONCE and carried through the loop —
    # refilling O(capacity) per probe step would dominate the insert for
    # big tables. Entries are wiped after each election (O(n) scatter).
    claim = jnp.full(cap, n, jnp.int32)

    def body(t, carry):
        table, slots, found, inserted, unresolved, claim = carry
        cand = ((h1 + jnp.uint32(t)) & mask).astype(jnp.int32)

        slot_fp1 = table.fp1[cand]
        slot_fp2 = table.fp2[cand]
        is_empty = slot_fp1 == EMPTY
        fp_match = (slot_fp1 == fp1) & (slot_fp2 == fp2)
        exact = fp_match & _keys_match(table, cand, key_cols)

        # 1) resolve matches (live or tombstoned — caller reads `live`)
        hit = unresolved & exact
        slots = jnp.where(hit, cand, slots)
        found = found | (hit & table.live[cand])
        unresolved = unresolved & ~hit

        if insert_missing:
            # 2) elect ONE winner per contended empty slot with a single
            # scatter of the row index; the winner then writes fp + every
            # key lane uncontended. (Four independent scatters could pick
            # different winners per lane, leaving a torn chimera slot that
            # matches no key and leaks capacity — ADVICE.md r1, medium.)
            # Index lanes are EXPLICIT int32 (rwlint RW-E30x dtype
            # audit): weak python-int sentinels must never promote the
            # probe arithmetic under a different default-int regime.
            want = unresolved & is_empty
            idx = jnp.where(want, cand, jnp.int32(cap))  # cap = drop lane
            row_ids = jnp.arange(n, dtype=jnp.int32)
            claim = claim.at[idx].set(row_ids, mode="drop")
            won = want & (claim[cand] == row_ids)
            # wipe this round's entries so the scratch stays all-sentinel
            claim = claim.at[idx].set(n, mode="drop")
            widx = jnp.where(won, cand, jnp.int32(cap))
            new_fp1 = table.fp1.at[widx].set(fp1, mode="drop")
            new_fp2 = table.fp2.at[widx].set(fp2, mode="drop")
            new_keys = tuple(
                tk.at[widx].set(k, mode="drop")
                for tk, k in zip(table.keys, key_cols)
            )
            table = HashTable(new_fp1, new_fp2, new_keys, table.live)
            # 3) same-key twins of the winner resolve to the slot too
            landed = (
                want
                & (table.fp1[cand] == fp1)
                & (table.fp2[cand] == fp2)
                & _keys_match(table, cand, key_cols)
            )
            slots = jnp.where(landed, cand, slots)
            inserted = inserted | landed
            unresolved = unresolved & ~landed
            # NOTE: a winner and its same-key twins all get `inserted`;
            # dedup is by first-occurrence masks downstream, slot identity
            # is what matters for correctness.

        # rows that neither matched nor claimed advance to probe t+1
        return table, slots, found, inserted, unresolved, claim

    # while_loop with early exit: at load <= 0.5 nearly every row
    # resolves within a handful of probes, and each probe step costs
    # ~a dozen gathers/scatters — running the full static MAX_PROBE
    # bound (fori_loop) made every insert pay 64 steps regardless
    # (observed 20-50x slowdowns on real TPU, BENCH_r02 fault analysis)
    def cond(carry):
        t = carry[0]
        unresolved = carry[5]
        return (t < MAX_PROBE) & jnp.any(unresolved)

    def wbody(carry):
        t, table, slots, found, inserted, unresolved, claim = carry
        table, slots, found, inserted, unresolved, claim = body(
            t, (table, slots, found, inserted, unresolved, claim)
        )
        return (t + 1, table, slots, found, inserted, unresolved, claim)

    _, table, slots, found, inserted, _, _ = jax.lax.while_loop(
        cond,
        wbody,
        (jnp.int32(0), table, slots, found, inserted, unresolved, claim),
    )
    return table, slots, found, inserted


@jax.jit
def lookup(table: HashTable, key_cols, valid):
    """Read-only probe: returns (slots, found_live). slots -1 if absent."""
    cap = table.capacity
    mask = jnp.uint32(cap - 1)
    h1, h2 = hash128(key_cols)
    fp1 = jnp.where(h1 == 0, jnp.uint32(1), h1)
    fp2 = h2
    n = valid.shape[0]

    def body(t, carry):
        slots, found, unresolved = carry
        cand = ((h1 + jnp.uint32(t)) & mask).astype(jnp.int32)
        slot_fp1 = table.fp1[cand]
        exact = (
            (slot_fp1 == fp1)
            & (table.fp2[cand] == fp2)
            & _keys_match(table, cand, key_cols)
        )
        hit = unresolved & exact
        slots = jnp.where(hit, cand, slots)
        found = found | (hit & table.live[cand])
        # probe chain ends at a truly EMPTY slot -> key absent
        dead_end = unresolved & (slot_fp1 == EMPTY)
        unresolved = unresolved & ~hit & ~dead_end
        return slots, found, unresolved

    slots = jnp.full(n, -1, jnp.int32)
    found = jnp.zeros(n, jnp.bool_)

    def cond(carry):
        t, _, _, unresolved = carry
        return (t < MAX_PROBE) & jnp.any(unresolved)

    def wbody(carry):
        t, slots, found, unresolved = carry
        slots, found, unresolved = body(t, (slots, found, unresolved))
        return (t + 1, slots, found, unresolved)

    _, slots, found, _ = jax.lax.while_loop(
        cond, wbody, (jnp.int32(0), slots, found, valid)
    )
    return slots, found


def set_live(table: HashTable, slots: jnp.ndarray, live_value: jnp.ndarray) -> HashTable:
    """Mark slots live/dead (dead = logical delete, slot stays claimed)."""
    cap = table.capacity
    idx = jnp.where(slots >= 0, slots, jnp.int32(cap))
    new_live = table.live.at[idx].set(live_value, mode="drop")
    return HashTable(table.fp1, table.fp2, table.keys, new_live)


def stage_scalars(*xs):
    """Pack scalars into one device array and START its async D2H copy
    (finish with ``finish_scalars``). Lets every executor's barrier
    read overlap in flight instead of paying a round-trip each."""
    arr = jnp.stack([jnp.asarray(x).astype(jnp.int64) for x in xs])
    try:
        arr.copy_to_host_async()
    except AttributeError:  # backend without async copies
        pass
    return arr


def finish_scalars(arr) -> list:
    """Blocking counterpart: materialize a staged pack.

    Uses ``jax.device_get`` — an EXPLICIT transfer — because this runs
    inside the per-barrier device step, which tests arm with
    ``jax.transfer_guard("disallow")`` (RW_TRANSFER_GUARD): the one
    sanctioned D2H read per barrier must not trip the guard that
    exists to catch the unsanctioned ones."""
    return jax.device_get(arr).tolist()


def read_scalars(*xs) -> list:
    """ONE packed, blocking device->host read of several scalars
    (latches, occupancy counters) — stage + finish in one call."""
    return finish_scalars(stage_scalars(*xs))


def plan_rehash(
    cap: int, incoming: int, claimed: int, survivors: int, grow_at: float = 0.5
):
    """The shared growth policy behind every host-side ``_maybe_grow``
    (HashAgg / Dedup / HashJoin sides): given true occupancy, decide
    whether to rebuild and at what capacity.

    Returns None (no rebuild: the next chunk still fits under the load
    factor) or the new capacity — sized from ``survivors`` (what the
    rebuild will actually keep), NOT from pre-rebuild occupancy, so
    steady-state tombstone churn compacts in place instead of doubling
    forever. ``new_cap == cap`` is a pure tombstone compaction.
    """
    if claimed + incoming <= cap * grow_at:
        return None
    new_cap = cap
    while survivors + incoming > new_cap * grow_at:
        new_cap *= 2
    return new_cap


def last_occurrence_mask(slots: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """True for the LAST valid row of each distinct slot in the batch —
    pk-conflict "last write wins" (materialize.rs:192 Overwrite) needs a
    deterministic winner; XLA scatter picks an arbitrary one among
    duplicate indices."""
    return first_occurrence_mask(slots[::-1], valid[::-1])[::-1]


def first_occurrence_mask(slots: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """True for the first valid row of each distinct slot in the batch.

    Used to dedupe per-group work (e.g. one U-/U+ emission per group per
    chunk, mirroring the reference's per-barrier dirty-group flush,
    hash_agg.rs:406). Sort-based, shape-static.
    """
    n = slots.shape[0]
    order = jnp.argsort(
        jnp.where(valid & (slots >= 0), slots, jnp.int32(2**30)), stable=True
    )
    s_sorted = slots[order]
    v_sorted = (valid & (slots >= 0))[order]
    first_sorted = v_sorted & jnp.concatenate(
        [jnp.ones(1, jnp.bool_), s_sorted[1:] != s_sorted[:-1]]
    )
    mask = jnp.zeros(n, jnp.bool_).at[order].set(first_sorted)
    return mask
