"""Vectorized hashing: compound keys and vnode partitioning.

Reference:
- src/common/src/hash/consistent_hash/vnode.rs:34,54-56 — 256 virtual
  nodes (``VirtualNode::BITS = 8``); a row maps to a vnode by hashing its
  distribution key; vnode -> worker via a mapping owned by the control
  plane (docs/consistent-hash.md).
- src/common/src/hash/key.rs — pre-serialized compound hash keys.

TPU re-design: keys are never serialized to bytes on device. A compound
key is a tuple of typed lanes; 64-bit columns are bit-split into (lo, hi)
uint32 lane pairs up front so the mixing chain itself runs entirely in
uint32 vector ops (VPU-friendly) while every key bit still reaches every
mix. The 64-bit reference hash (XxHash64) is replaced by two
independently-seeded 32-bit mixes when a wider fingerprint is needed
(see ``hash128``).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

VNODE_COUNT = 256  # parity with VirtualNode::COUNT (vnode.rs:54-56)


def _mix32(h: jnp.ndarray) -> jnp.ndarray:
    """fmix32 from murmur3 — avalanche finalizer on uint32 lanes."""
    h = h.astype(jnp.uint32)
    h ^= h >> 16
    h = h * jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    h = h * jnp.uint32(0xC2B2AE35)
    h ^= h >> 16
    return h


def _split64(col: jnp.ndarray) -> list[jnp.ndarray]:
    """64-bit column -> (lo, hi) uint32 lanes via ONE bitcast.

    ``bitcast_convert_type`` to a narrower dtype appends a minor-most
    dim whose index 0 is the least-significant word — bit-identical to
    the old ``& 0xFFFFFFFF`` / ``>> 32`` split, but with ZERO 64-bit
    arithmetic: the hash chain stays valid under any ``jax_enable_x64``
    / platform promotion regime (rwlint RW-E302 guards this)."""
    bits = jax.lax.bitcast_convert_type(col, jnp.uint32)
    return [bits[..., 0], bits[..., 1]]


def _to_u32_lanes(col: jnp.ndarray) -> list[jnp.ndarray]:
    """Bit-cast any supported column dtype to one or more uint32 lane sets.

    64-bit columns yield BOTH halves as separate lanes (lo, hi) so the
    full 64 bits of the key flow into every downstream mix — folding to a
    single u32 would make the "independent" fingerprints of ``hash128``
    collide together for int64 ids, the most common key type in Nexmark
    (ADVICE.md r1 weak #6). Everything downstream of this function is
    EXPLICITLY uint32: no 64-bit op may appear in the mixing chain.
    """
    if col.dtype == jnp.bool_:
        return [col.astype(jnp.uint32)]
    if col.dtype == jnp.float32:
        # canonicalize -0.0 to +0.0 and all NaN payloads to one NaN so
        # equal-under-total-order SQL values hash equally (the reference
        # uses ordered-float total ordering, src/common/src/types/)
        col = jnp.where(col == 0.0, jnp.float32(0.0), col)
        col = jnp.where(jnp.isnan(col), jnp.float32(jnp.nan), col)
        return [jax.lax.bitcast_convert_type(col, jnp.uint32)]
    if col.dtype == jnp.float64:
        col = jnp.where(col == 0.0, jnp.float64(0.0), col)
        col = jnp.where(jnp.isnan(col), jnp.float64(jnp.nan), col)
        return _split64(col)
    if col.dtype in (jnp.int64, jnp.uint64):
        return _split64(col)
    return [col.astype(jnp.uint32)]


def hash_columns(cols: Sequence[jnp.ndarray], seed: int = 0) -> jnp.ndarray:
    """Hash a compound key column-set to uint32, row-wise.

    Equivalent role to ``HashKey::hash`` over the distribution/group key
    (reference: src/common/src/hash/key.rs); boost-style hash_combine
    chains the per-lane mixes.
    """
    h = jnp.full(cols[0].shape, jnp.uint32(0x811C9DC5 ^ seed), dtype=jnp.uint32)
    for c in cols:
        for lanes in _to_u32_lanes(c):
            h = h ^ (_mix32(lanes) + jnp.uint32(0x9E3779B9) + (h << 6) + (h >> 2))
    return _mix32(h)


def hash128(cols: Sequence[jnp.ndarray]) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Two independent 32-bit hashes (fingerprint + probe seed)."""
    return hash_columns(cols, seed=0), hash_columns(cols, seed=0x5BD1E995)


def group_key_lanes(chunk, names: Sequence[str]) -> tuple[jnp.ndarray, ...]:
    """Key lanes for GROUP BY / distribution with SQL NULL semantics.

    SQL GROUP BY puts all NULLs in ONE group, distinct from every real
    value (reference: hash keys serialize a null tag before the datum,
    src/common/src/hash/key.rs). We realize that as: canonicalize the
    value lane to its zero where NULL (so NULL rows agree bit-for-bit)
    and append the bool null lane itself as an extra key lane (so the
    NULL group never merges with the real zero-valued group).

    The returned tuple plugs directly into hash_columns / hash128 and
    into HashTable key columns — exact-compare over these lanes IS
    SQL group-key equality.

    NOTE: equi-JOIN keys have different semantics (NULL matches nothing);
    join operators must pre-filter null-keyed rows instead.
    """
    lanes = []
    for name in names:
        col = chunk.col(name)
        if chunk.is_nullable(name):
            null = chunk.nulls[name]
            zero = jnp.zeros((), dtype=col.dtype)
            lanes.append(jnp.where(null, zero, col))
            lanes.append(null)
        else:
            lanes.append(col)
    return tuple(lanes)


def vnode_of(cols: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Row -> virtual node in [0, 256) (reference: vnode.rs:34,

    TableDistribution::compute_vnode, src/common/src/hash/table_distribution.rs).
    """
    return (hash_columns(cols, seed=0xC0FFEE) % VNODE_COUNT).astype(jnp.int32)
