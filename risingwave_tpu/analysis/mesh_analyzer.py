"""Mesh-readiness analyzer — can a sharded fragment's barrier collapse
into ONE SPMD dispatch across the device mesh, proven statically.

ROADMAP item 3 (turn the host-routed exchange into on-device
collectives under ``shard_map``) has measurement — PR 18's meshprof
exchange matrix and phase splits (MULTICHIP.json) — but until now no
static tooling, exactly the state fusion was in before the PR 7
analyzer made the fused-step PRs safe to build.  This module answers,
per sharded fragment, per executor, with file:line provenance:

1. **What is SPMD-fusible today?**  A sharded executor earns a
   positive proof when its ``mesh_contract()`` declares the vnode
   dispatch honestly, its step abstractly traces under ``shard_map``
   over the N-device mesh at every bucket of the chunk lattice
   (``jax.make_jaxpr`` — no XLA, no allocation), and the AST scan of
   its barrier path finds no host-routed reads.  A fragment is
   SPMD-fusible when EVERY chain member proves — the shallow pass
   never mints a proof.
2. **What blocks it, and where?**  Stable RW-E9xx diagnostics:
   - RW-E901  host-routed exchange edge (stack/split/flatten
     boundary, device pulls or NumPy fallbacks on the barrier path)
   - RW-E902  hash-dispatch key not provably a pure function of the
     mesh axis (dispatch outside the consistent-hash ``dest_shard``
     path, axis mismatch, or no declared keys)
   - RW-E903  shard-local step not shard_map-traceable (trace raises,
     or the signature count across the bucket lattice exceeds the
     recompile budget: per-shard shape polymorphism)
   - RW-E904  replicated state mutated shard-locally
   - RW-E905  exchange/flush output shape data-dependent (a host
     recount loop gates the next step)
   - RW-E906  cross-shard reduction order not order-insensitive
   - RW-E907  per-destination dispatch fan-out (one host-driven
     device call per shard — the ×N dispatch wall the multichip
     dry-runs measured)
3. **What is it worth?**  With MULTICHIP.json's measured phase splits
   attached, blockers rank by measured exchange-boundary cost
   (``est_exchange_ms`` / ``est_dispatches_saved``) — the committed
   MESH_REPORT.json is the worklist the collective-exchange arc burns
   down, the way FUSION_REPORT.json drove the fused-step PRs.

The blocker phases group the host lanes the measured matrix exposes:
E901/E907 are the **exchange_route** phase (rows crossing shards
through host memory — MULTICHIP.json's host_split/host_flatten lanes),
E905 is **host_recount**, contract violations are **contract**, trace
failures are **compile**.  ``shard_local`` compute is on-device either
way and is NOT a blocker phase — which is why the static ranking
names the exchange route as the top reclaimable cost, reproducing the
measurement from source alone.
"""

from __future__ import annotations

import ast
import inspect
import os
import textwrap
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from risingwave_tpu.analysis.fusion_analyzer import (
    _BRANCH_CASTS,
    _NP_FALLBACK,
    _SYNC_ATTRS,
    _SYNC_CALLS,
    _lint_info,
    _thread_spec,
)
from risingwave_tpu.analysis.shape_domain import (
    ChunkSpec,
    recompile_budget,
)

# ---------------------------------------------------------------------------
# provenance helpers
# ---------------------------------------------------------------------------


def _rel(path: str) -> str:
    """Repo-relative provenance: committed MESH_REPORT.json must not
    embed the checkout prefix."""
    for marker in ("risingwave_tpu" + os.sep, "tests" + os.sep):
        i = path.find(marker)
        if i >= 0:
            return path[i:].replace(os.sep, "/")
    return os.path.basename(path)


def _class_site(cls) -> Tuple[str, int]:
    try:
        file = inspect.getsourcefile(cls) or "<unknown>"
        line = inspect.getsourcelines(cls)[1]
        return _rel(file), line
    except (OSError, TypeError):
        return "<unknown>", 0


def _method_site(cls, method: str) -> Tuple[str, int]:
    fn = getattr(cls, method, None)
    if fn is None:
        return _class_site(cls)
    try:
        file = inspect.getsourcefile(fn) or "<unknown>"
        line = inspect.getsourcelines(fn)[1]
        return _rel(file), line
    except (OSError, TypeError):
        return _class_site(cls)


# ---------------------------------------------------------------------------
# loop-aware host-routing scanner
# ---------------------------------------------------------------------------

# phase a blocker's cost lands in (the static twin of meshprof's
# measured phase split)
_PHASE_BY_CODE = {
    "RW-E901": "exchange_route",
    "RW-E907": "exchange_route",
    "RW-E905": "host_recount",
    "RW-E902": "contract",
    "RW-E904": "contract",
    "RW-E906": "contract",
    "RW-E903": "compile",
}


@dataclass(frozen=True)
class MeshSync:
    """One host-routing site on the sharded path, with its mechanism:
    ``host_read`` (E901), ``shard_fanout`` (E907 — inside a
    per-destination loop), ``recount`` (E905 — a device read gating a
    flush/drain loop)."""

    reason: str
    file: str
    line: int
    method: str
    kind: str = "host_read"

    def render(self) -> str:
        return f"{self.reason} at {self.file}:{self.line} (in {self.method})"


class _MeshScanner(ast.NodeVisitor):
    """One method's AST with LOOP CONTEXT: the same blocking-sync
    markers the fusion scanner uses, but classified by the loop that
    contains them — a device read inside a per-shard loop is the ×N
    dispatch wall (E907), one that gates a drain loop's exit is a
    host recount (E905), anything else is a host-routed edge (E901)."""

    def __init__(self, file: str, base_line: int, method: str):
        self.file = file
        self.base = base_line
        self.method = method
        self.out: List[MeshSync] = []
        self.self_calls: List[str] = []
        self._device_names: set = set()
        self._loops: List[bool] = []  # stack: is_shard_loop
        self._claimed_lines: set = set()

    # -- device-flavor heuristics (mirrors the fusion scanner) ----------
    def _mentions_device(self, node) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and isinstance(
                n.value, ast.Name
            ) and n.value.id == "self":
                return True
            if isinstance(n, ast.Name) and n.id in self._device_names:
                return True
            if isinstance(n, ast.Call):
                f = n.func
                name = (
                    f.id
                    if isinstance(f, ast.Name)
                    else f.attr
                    if isinstance(f, ast.Attribute)
                    else ""
                )
                if name.startswith("_") or name in ("col", "null_of"):
                    return True
                if isinstance(f, ast.Attribute) and isinstance(
                    f.value, ast.Name
                ) and f.value.id in ("jnp", "jax", "lax"):
                    return True
        return False

    def _in_shard_loop(self) -> bool:
        return any(self._loops)

    def _kind(self) -> str:
        return "shard_fanout" if self._in_shard_loop() else "host_read"

    def _add(self, node, reason: str, kind: Optional[str] = None) -> None:
        line = self.base + node.lineno - 1
        self.out.append(
            MeshSync(reason, self.file, line, self.method, kind or self._kind())
        )

    # -- assignments feed the device-name environment --------------------
    def visit_Assign(self, node):
        if self._mentions_device(node.value):
            for tgt in node.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        self._device_names.add(n.id)
        self.generic_visit(node)

    # -- loops -----------------------------------------------------------
    @staticmethod
    def _is_shard_iter(node: ast.For) -> bool:
        """Per-destination loops: ``for s in range(self.n_shards)``,
        ``for s in set(dest.tolist())`` and friends."""
        for n in ast.walk(node.iter):
            if isinstance(n, ast.Attribute) and n.attr in (
                "n_shards",
                "tolist",
            ):
                return True
            if isinstance(n, ast.Name) and n.id in ("n_shards", "dest"):
                return True
        return False

    def visit_For(self, node):
        shard = self._is_shard_iter(node)
        if shard and any(
            self._mentions_device(b) for b in node.body
        ):
            self._add(
                node,
                "per-destination dispatch fan-out: one host-driven "
                "device call per shard",
                kind="shard_fanout",
            )
        self._loops.append(shard)
        self.generic_visit(node)
        self._loops.pop()

    def visit_While(self, node):
        if self._device_cast_in(node.test):
            self._add(
                node,
                "drain loop gated by a device read (host recount)",
                kind="recount",
            )
            self._claim_casts(node.test)
        self._loops.append(False)
        self.generic_visit(node)
        self._loops.pop()

    def visit_If(self, node):
        # a device-cast test whose branch exits an enclosing loop =
        # the loop's iteration count is data-dependent (E905): the
        # received/flushed row count reaches the host before the next
        # round can run
        if self._loops and self._device_cast_in(node.test):
            exits = any(
                isinstance(n, (ast.Break, ast.Return, ast.Raise))
                for b in (node.body, node.orelse)
                for stmt in b
                for n in ast.walk(stmt)
            )
            if exits:
                self._add(
                    node,
                    "loop exit gated by a device read (host recount "
                    "of a data-dependent flush/exchange shape)",
                    kind="recount",
                )
                self._claim_casts(node.test)
        self.generic_visit(node)

    def _device_cast_in(self, test) -> bool:
        for n in ast.walk(test):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id in _BRANCH_CASTS
                and n.args
                and self._is_device_expr(n.args[0])
            ):
                return True
        return False

    def _claim_casts(self, test) -> None:
        """Casts consumed by a recount verdict are not re-reported as
        plain branch syncs."""
        for n in ast.walk(test):
            if isinstance(n, ast.Call):
                self._claimed_lines.add(self.base + n.lineno - 1)

    # -- sync markers ----------------------------------------------------
    def visit_Call(self, node):
        line = self.base + node.lineno - 1
        if line in self._claimed_lines:
            self.generic_visit(node)
            return
        f = node.func
        if isinstance(f, ast.Attribute):
            name = f.attr
            if name in _SYNC_ATTRS:
                self._add(node, _SYNC_ATTRS[name])
            elif name in _SYNC_CALLS:
                self._add(node, _SYNC_CALLS[name])
            elif name in _NP_FALLBACK and isinstance(f.value, ast.Name):
                if f.value.id in ("np", "numpy"):
                    self._add(
                        node,
                        f"NumPy fallback on a device value (np.{name})",
                    )
            elif isinstance(f.value, ast.Name) and f.value.id == "self":
                self.self_calls.append(name)
        elif isinstance(f, ast.Name):
            if f.id in _SYNC_CALLS:
                self._add(node, _SYNC_CALLS[f.id])
            elif f.id in _BRANCH_CASTS and node.args:
                if self._is_device_expr(node.args[0]):
                    self._add(
                        node,
                        f"Python branching on a traced value "
                        f"({f.id}() of a device scalar)",
                    )
        self.generic_visit(node)

    def _is_device_expr(self, node) -> bool:
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self._device_names
        return self._mentions_device(node)


# memoized per (class, method) — the DDL hook pays the parse once
_MESH_SCAN_MEMO: Dict[Tuple[type, str], Tuple[tuple, tuple]] = {}


def _parse_mesh_method(cls, method: str):
    memo = _MESH_SCAN_MEMO.get((cls, method))
    if memo is not None:
        return memo
    empty = ((), ())
    fn = getattr(cls, method, None)
    if fn is None or not callable(fn):
        _MESH_SCAN_MEMO[(cls, method)] = empty
        return empty
    from risingwave_tpu.executors.base import Executor

    base_fn = getattr(Executor, method, None)
    if base_fn is not None and getattr(fn, "__func__", fn) is getattr(
        base_fn, "__func__", base_fn
    ):
        _MESH_SCAN_MEMO[(cls, method)] = empty
        return empty
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        file = _rel(inspect.getsourcefile(fn) or "<unknown>")
        base_line = inspect.getsourcelines(fn)[1]
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        _MESH_SCAN_MEMO[(cls, method)] = empty
        return empty
    sc = _MeshScanner(file, base_line, f"{cls.__name__}.{method}")
    sc.visit(tree)
    out = (tuple(sc.out), tuple(sc.self_calls))
    _MESH_SCAN_MEMO[(cls, method)] = out
    return out


def _scan_mesh_method(
    cls, method: str, seen: set, depth: int = 0
) -> List[MeshSync]:
    if depth > 3 or (cls, method) in seen:
        return []
    seen.add((cls, method))
    syncs, helpers = _parse_mesh_method(cls, method)
    out = list(syncs)
    for helper in helpers:
        out.extend(_scan_mesh_method(cls, helper, seen, depth + 1))
    return out


def scan_mesh_syncs(ex, methods: Sequence[str]) -> List[MeshSync]:
    """All host-routing sites reachable from ``methods`` (plus the
    same-class helpers they call, bounded), loop-classified, with
    file:line provenance."""
    cls = type(ex)
    seen: set = set()
    out: List[MeshSync] = []
    for m in methods:
        out.extend(_scan_mesh_method(cls, m, seen))
    uniq: Dict[Tuple[str, int, str], MeshSync] = {}
    for s in out:
        uniq.setdefault((s.file, s.line, s.reason), s)
    return sorted(uniq.values(), key=lambda s: (s.file, s.line))


# ---------------------------------------------------------------------------
# per-executor classification
# ---------------------------------------------------------------------------


@dataclass
class MeshBlocker:
    """One E9xx finding with provenance + (once measurement attaches)
    its estimated reclaim."""

    code: str
    message: str
    executor: str
    method: str
    file: str
    line: int
    phase: str = ""
    est_exchange_ms: Optional[float] = None
    est_dispatches_saved: Optional[int] = None

    def __post_init__(self):
        if not self.phase:
            self.phase = _PHASE_BY_CODE.get(self.code, "contract")

    def to_json(self) -> dict:
        return {
            "code": self.code,
            "executor": self.executor,
            "method": self.method,
            "file": self.file,
            "line": self.line,
            "phase": self.phase,
            "est_exchange_ms": self.est_exchange_ms,
            "est_dispatches_saved": self.est_dispatches_saved,
            "message": self.message,
        }


@dataclass
class MeshExecutorClass:
    """One executor's SPMD verdict."""

    index: int
    name: str
    kind: str  # "mesh" | "boundary" | "outside"
    spmd_proven: bool = False
    traced: bool = False
    signatures: int = 0
    collectives: Tuple[str, ...] = ()
    blockers: List[MeshBlocker] = field(default_factory=list)
    sync_points: List[MeshSync] = field(default_factory=list)
    note: str = ""

    def to_json(self) -> dict:
        return {
            "index": self.index,
            "executor": self.name,
            "kind": self.kind,
            "spmd_proven": self.spmd_proven,
            "traced": self.traced,
            "signatures": self.signatures,
            "collectives": list(self.collectives),
            "blockers": [b.to_json() for b in self.blockers],
            "note": self.note or None,
        }


_SYNC_CODE = {
    "host_read": "RW-E901",
    "shard_fanout": "RW-E907",
    "recount": "RW-E905",
}


def classify_mesh_executor(
    ex,
    spec: Optional[ChunkSpec],
    fragment: str,
    index: int,
    deep: bool = True,
) -> MeshExecutorClass:
    """Classify ONE executor of a sharded chain: mesh-resident (proof
    or E9xx blockers), a host boundary adapter (E901 by construction),
    or outside the mesh (prefix ops — the fusion analyzer's problem,
    not a mesh blocker)."""
    from risingwave_tpu.parallel.exchange import DISPATCH_FN
    from risingwave_tpu.runtime.fragmenter import is_mesh_boundary

    name = type(ex).__name__
    prov = f"{index}:{name}"
    ec = MeshExecutorClass(index=index, name=name, kind="outside")

    def blocker(code, message, method="", file="", line=0):
        if not file:
            file, line = _class_site(type(ex))
        ec.blockers.append(
            MeshBlocker(code, message, prov, method, file, line)
        )

    if is_mesh_boundary(ex):
        ec.kind = "boundary"
        file, line = _method_site(type(ex), "apply")
        blocker(
            "RW-E901",
            f"host-routed exchange edge: {name} crosses rows between "
            "flat host chunks and the stacked mesh layout outside the "
            "sharded program",
            method=f"{name}.apply",
            file=file,
            line=line,
        )
        return ec

    getter = getattr(ex, "mesh_contract", None)
    if not callable(getter):
        return ec  # outside the mesh — not this analyzer's question
    try:
        contract = getter()
    except Exception as e:  # noqa: BLE001 — a broken contract is a finding
        ec.kind = "mesh"
        blocker(
            "RW-E001",
            f"mesh_contract() raised {type(e).__name__} — treated as "
            "opaque, nothing provable past this executor",
        )
        return ec
    ec.kind = "mesh"

    # -- E902: dispatch must be the consistent-hash vnode path ----------
    disp = contract.get("dispatch") or {}
    fn = disp.get("fn")
    if fn != DISPATCH_FN:
        blocker(
            "RW-E902",
            f"dispatch fn {fn!r} is not the consistent-hash "
            f"{DISPATCH_FN!r} path: the destination shard is not "
            "provably vnode(key) % n_shards",
        )
    axis = contract.get("axis")
    if disp.get("vnode_axis") != axis:
        blocker(
            "RW-E902",
            f"declared vnode axis {disp.get('vnode_axis')!r} does not "
            f"match the mesh axis {axis!r}: an all_to_all over the "
            "mesh would route rows to the wrong shard",
        )
    keys = disp.get("keys")
    flat_keys: tuple = ()
    if isinstance(keys, dict):
        flat_keys = tuple(k for side in keys.values() for k in side)
        if any(not tuple(side) for side in keys.values()):
            flat_keys = ()
    elif keys:
        flat_keys = tuple(keys)
    if not flat_keys:
        blocker(
            "RW-E902",
            "no dispatch keys declared for keyed sharded state: row "
            "ownership is undefined under the vnode mapping",
        )

    # -- E904: replicated leaves written by the per-shard step ----------
    updates = tuple(contract.get("updates", ()))
    for leaf, placement in (contract.get("state") or {}).items():
        if placement == "replicated" and leaf in updates:
            blocker(
                "RW-E904",
                f"state leaf {leaf!r} is declared replicated across "
                "the mesh but written by the per-shard step: silent "
                "cross-shard divergence",
            )

    # -- E906: merge order ----------------------------------------------
    if not contract.get("order_insensitive", False):
        blocker(
            "RW-E906",
            "cross-shard merge is not declared order-insensitive: the "
            "mesh result cannot be proven bit-identical to the serial "
            "twin",
        )

    # -- E901/E905/E907: the loop-classified host-routing scan ----------
    methods = (
        ("apply", "apply_left", "apply_right")
        + tuple(contract.get("barrier_methods", ()))
        + tuple(contract.get("fanout_methods", ()))
    )
    ec.sync_points = scan_mesh_syncs(ex, methods)
    for s in ec.sync_points:
        ec.blockers.append(
            MeshBlocker(
                _SYNC_CODE[s.kind],
                s.reason,
                prov,
                s.method,
                s.file,
                s.line,
            )
        )

    # -- E903 / positive proof: abstract shard_map trace over the
    #    bucket lattice ---------------------------------------------------
    trace_steps = contract.get("trace_steps")
    n = int(contract.get("n_shards") or 0)
    if spec is None:
        # schema threading lost (e.g. a join_tail section): trace with
        # a lane-free chunk — self-seeded contracts (the join builds
        # its own per-side abstract chunks) still prove; lane-reading
        # steps degrade to an honest note, never a silent skip
        spec = ChunkSpec((), (), 0)
    if deep and trace_steps is not None and n > 0:
        from risingwave_tpu.analysis.mesh_domain import (
            mesh_buckets,
            mesh_trace_signature,
            stacked_chunk,
        )

        sigs: Dict[str, set] = {}
        colls: List[str] = []
        failed = False
        for cap in mesh_buckets():
            abs_chunk = stacked_chunk(spec.with_capacity(cap), n)
            try:
                for label, step, args in trace_steps(abs_chunk):
                    sig = mesh_trace_signature(step, *args)
                    sigs.setdefault(label, set()).add(
                        (sig.in_avals, sig.out_avals)
                    )
                    colls.extend(sig.collectives)
                    for h in sig.host_calls:
                        file, line = _method_site(type(ex), "_build_step")
                        blocker(
                            "RW-E901",
                            f"host callback primitive {h!r} inside the "
                            "sharded program",
                            method=f"{name}._build_step",
                            file=file,
                            line=line,
                        )
            except Exception as e:  # noqa: BLE001
                kind = type(e).__name__
                file, line = _method_site(type(ex), "_build_step")
                if "Tracer" in kind or "Concretization" in kind:
                    blocker(
                        "RW-E903",
                        "shard-local step not shard_map-traceable at "
                        f"capacity {cap}: {kind} (Python branching on "
                        "per-shard values)",
                        method=f"{name}._build_step",
                        file=file,
                        line=line,
                    )
                else:
                    # untraceable with THIS schema: degrade honestly —
                    # no false blocker, no false proof
                    ec.note = (
                        f"abstract trace unavailable at capacity {cap}: "
                        f"{kind}"
                    )
                failed = True
                break
        if not failed and sigs:
            ec.traced = True
            ec.signatures = sum(len(v) for v in sigs.values())
            ec.collectives = tuple(sorted(set(colls)))
            budget = recompile_budget()
            per_label = max(len(v) for v in sigs.values())
            if per_label > budget:
                file, line = _method_site(type(ex), "_build_step")
                blocker(
                    "RW-E903",
                    f"{per_label} distinct shard_map signatures across "
                    f"the declared buckets > recompile budget {budget}: "
                    "per-shard shape polymorphism outside the lattice",
                    method=f"{name}._build_step",
                    file=file,
                    line=line,
                )

    # the positive proof: an honestly-declared mesh contract whose
    # step actually abstract-traced under shard_map over the lattice
    # with zero blockers. Shallow passes and failed traces are not
    # evidence.
    ec.spmd_proven = ec.traced and ec.signatures >= 1 and not ec.blockers
    return ec


# ---------------------------------------------------------------------------
# fragment / pipeline reports
# ---------------------------------------------------------------------------


@dataclass
class MeshFragmentReport:
    fragment: str
    executors: List[MeshExecutorClass] = field(default_factory=list)
    spmd_fusible: bool = False
    proof: Optional[dict] = None

    @property
    def blockers(self) -> List[MeshBlocker]:
        return [b for e in self.executors for b in e.blockers]

    @property
    def host_routed_edges(self) -> int:
        return sum(
            1
            for b in self.blockers
            if b.code in ("RW-E901", "RW-E907")
        )

    def to_json(self) -> dict:
        bl = self.blockers
        return {
            "fragment": self.fragment,
            "chain_len": len(self.executors),
            "mesh_executors": sum(
                1 for e in self.executors if e.kind == "mesh"
            ),
            "spmd_fusible": self.spmd_fusible,
            "proof": self.proof,
            "host_routed_edges": self.host_routed_edges,
            "executors": [e.to_json() for e in self.executors],
            "blockers": [b.to_json() for b in bl],
        }


def analyze_mesh_chain(
    chain: Sequence[object],
    spec: Optional[ChunkSpec],
    fragment: str,
    deep: bool = True,
) -> MeshFragmentReport:
    rep = MeshFragmentReport(fragment=fragment)
    for idx, ex in enumerate(chain):
        ec = classify_mesh_executor(ex, spec, fragment, idx, deep=deep)
        rep.executors.append(ec)
        spec = _thread_spec(spec, ex, _lint_info(ex))
    mesh = [e for e in rep.executors if e.kind == "mesh"]
    rep.spmd_fusible = (
        bool(mesh)
        and all(e.kind == "mesh" for e in rep.executors)
        and all(e.spmd_proven for e in mesh)
    )
    if rep.spmd_fusible:
        rep.proof = {
            "signatures": sum(e.signatures for e in mesh),
            "collectives": sorted(
                {c for e in mesh for c in e.collectives}
            ),
            "executors": [e.name for e in mesh],
        }
    return rep


def analyze_sharded_pipeline(
    pipeline,
    source_schemas: Optional[Dict[str, Dict[str, object]]] = None,
    name: str = "mv",
    deep: bool = True,
) -> List[MeshFragmentReport]:
    """Mesh reports for every SHARDED fragment of a pipeline (fragment
    extraction via runtime.fragmenter.sharded_chains — fragments with
    no mesh-resident executor are the fusion analyzer's territory)."""
    from risingwave_tpu.runtime.fragmenter import sharded_chains

    source_schemas = source_schemas or {}
    out: List[MeshFragmentReport] = []
    for frag, sections in sharded_chains(pipeline).items():
        for side, chain in sections.items():
            if not chain:
                continue
            schema = (
                source_schemas.get(side)
                if side in ("single", "left", "right")
                else None
            )
            spec = (
                ChunkSpec.from_schema(schema) if schema is not None else None
            )
            label = frag if side in ("single", "chain") else f"{frag}/{side}"
            out.append(
                analyze_mesh_chain(
                    chain, spec, f"{name}:{label}", deep=deep
                )
            )
    return out


# ---------------------------------------------------------------------------
# measured-cost ranking (MULTICHIP.json -> est_exchange_ms)
# ---------------------------------------------------------------------------

_HOST_LANES = ("host_split", "host_flatten", "host_other")


def attach_mesh_costs(
    reports: Sequence[MeshFragmentReport],
    mesh_block: Optional[dict],
    n_shards: int = 8,
) -> None:
    """Attach PR 18's measured exchange-boundary cost to the static
    blockers: the meshprof host lanes (host_split + host_flatten +
    host_other ms per barrier set) spread over this query's
    exchange_route blockers, and the ×N dispatch arithmetic on every
    fan-out site. Rank = highest measured reclaim first."""
    host_ms = 0.0
    if mesh_block:
        phases = mesh_block.get("phases_ms") or {}
        host_ms = sum(float(phases.get(k, 0.0)) for k in _HOST_LANES)
    route = [
        b
        for r in reports
        for b in r.blockers
        if b.phase == "exchange_route"
    ]
    share = round(host_ms / len(route), 3) if route and host_ms else None
    for b in route:
        b.est_exchange_ms = share
        if b.code == "RW-E907":
            b.est_dispatches_saved = max(0, n_shards - 1)
    for r in reports:
        for e in r.executors:
            e.blockers.sort(
                key=lambda b: (
                    -(b.est_exchange_ms or 0.0),
                    -(b.est_dispatches_saved or 0),
                    b.code,
                    b.line,
                )
            )


def report_to_json(reports: Sequence[MeshFragmentReport]) -> dict:
    frs = [r.to_json() for r in reports]
    codes: Dict[str, int] = {}
    for r in frs:
        for b in r["blockers"]:
            codes[b["code"]] = codes.get(b["code"], 0) + 1
    return {
        "fragments": frs,
        "summary": {
            "fragments": len(frs),
            "spmd_fusible_fragments": sum(
                1 for r in frs if r["spmd_fusible"]
            ),
            "host_routed_edges": sum(
                r["host_routed_edges"] for r in frs
            ),
            "blockers_by_code": dict(sorted(codes.items())),
        },
    }


def _ranking(per_query: Dict[str, List[MeshFragmentReport]]) -> List[dict]:
    rows = []
    for q, reports in per_query.items():
        for r in reports:
            for b in r.blockers:
                rows.append(
                    {
                        "query": q,
                        "fragment": r.fragment,
                        "executor": b.executor,
                        "code": b.code,
                        "phase": b.phase,
                        "file": b.file,
                        "line": b.line,
                        "est_exchange_ms": b.est_exchange_ms,
                        "est_dispatches_saved": b.est_dispatches_saved,
                        "message": b.message,
                    }
                )
    rows.sort(
        key=lambda r: (
            -(r["est_exchange_ms"] or 0.0),
            -(r["est_dispatches_saved"] or 0),
            r["code"],
            r["query"],
            r["line"],
        )
    )
    for i, r in enumerate(rows):
        r["rank"] = i + 1
    return rows


def _top_cost(rows: List[dict]) -> dict:
    by_phase: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    for r in rows:
        by_phase[r["phase"]] = by_phase.get(r["phase"], 0.0) + (
            r["est_exchange_ms"] or 0.0
        )
        counts[r["phase"]] = counts.get(r["phase"], 0) + 1
    top = (
        max(by_phase, key=lambda p: (by_phase[p], counts[p]))
        if by_phase
        else None
    )
    return {
        "phase": top,
        "est_ms": round(by_phase.get(top, 0.0), 3) if top else 0.0,
        "blockers": counts.get(top, 0) if top else 0,
        "phases_est_ms": {
            k: round(v, 3) for k, v in sorted(by_phase.items())
        },
        "source": "MULTICHIP.json phases_ms (host_split + host_flatten "
        "+ host_other per query)",
    }


def analyze_sharded_nexmark(
    deep: bool = True,
    multichip: Optional[dict] = None,
    n_shards: int = 8,
) -> Dict[str, object]:
    """Mesh reports for the sharded Nexmark corpus (q5/q7/q8 on the
    N-virtual-device sim mesh) — the committed MESH_REPORT.json shape.
    ``multichip``: the committed MULTICHIP.json dict; its per-query
    measured phase splits rank the blockers."""
    from risingwave_tpu.analysis.lint import (
        NEXMARK_SOURCE_SCHEMAS,
        build_sharded_nexmark_corpus,
    )

    per_query: Dict[str, List[MeshFragmentReport]] = {}
    out: Dict[str, object] = {}
    mdata = (multichip or {}).get("queries", {})
    for qname, q in build_sharded_nexmark_corpus(n_shards).items():
        try:
            reports = analyze_sharded_pipeline(
                q.pipeline,
                NEXMARK_SOURCE_SCHEMAS[qname],
                qname,
                deep=deep,
            )
            attach_mesh_costs(
                reports,
                (mdata.get(qname) or {}).get("mesh"),
                n_shards=n_shards,
            )
            per_query[qname] = reports
            out[qname] = report_to_json(reports)
        finally:
            close = getattr(q.pipeline, "close", None)
            if close is not None:
                try:
                    close()
                except BaseException:  # noqa: BLE001
                    pass
    rows = _ranking(per_query)
    out["ranking"] = rows
    out["top_cost"] = _top_cost(rows)
    try:
        from risingwave_tpu.provenance import stamp

        out["_provenance"] = stamp()
    except Exception:  # noqa: BLE001 — provenance is best effort
        pass
    return out
