"""Fusion-feasibility analyzer — what blocks the one-jitted-step-per-
barrier refactor, proven statically.

PR 6's profiler showed the north-star gap is a host dispatch wall
(~319ms/barrier of host python vs 0.24ms of device compute). ROADMAP
item 1's fix is fusing each fragment's executor chain into one jitted
``device_step(state, chunk)``. Before that multi-PR refactor starts,
this module answers — per fragment, per executor, with file:line
provenance — three questions:

1. **What is fusible today?** An executor is device-fusible when its
   trace contract (executors/base.py ``trace_contract``) exposes a
   pure step over (state, chunk), the step abstractly traces over the
   declared chunk-size bucket lattice (analysis/shape_domain.py), and
   the AST scan of its hot methods finds no blocking host
   synchronization. The longest fusible executor PREFIX of a chain is
   what the fusion refactor can collapse first.
2. **What blocks fusion, and where?** Every blocker is a stable
   diagnostic with executor + file:line provenance:
   - RW-E801  blocking host sync inside the hot path (device_get /
     .item() / NumPy fallback / blocking scalar reads / Python
     branching on traced values)
   - RW-E802  dynamic (data-dependent) output shape
   - RW-E803  unbucketed shape-polymorphic window (the q7 wedge
     class): a window-keyed executor with no declared bucket lattice
     for its per-window shape domain
   - RW-E804  state not donation-safe for a fused step
   - RW-E805  jaxpr signature count over the bucket lattice exceeds
     the recompile budget
3. **What is it worth?** With PR 6's measured ``executor_ms`` /
   ``device_dispatches_total`` attached, blockers rank by measured
   dispatch cost — the committed FUSION_REPORT.json is the worklist
   the fusion refactor burns down PR by PR.

The same role Shared Arrangements' static dataflow invariants play for
sharing (PAPERS.md), applied to compilability: the TiLT direction
(compile whole time-centric queries) needs a proof of WHERE whole-query
compilation is possible before anyone rewrites executors around it.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from risingwave_tpu.analysis.diagnostics import Diagnostic
from risingwave_tpu.analysis.shape_domain import (
    ChunkSpec,
    bucket_lattice,
    recompile_budget,
    trace_signature,
)

# ---------------------------------------------------------------------------
# host-sync scanner: AST over an executor's hot methods
# ---------------------------------------------------------------------------

# call markers that BLOCK on a host<->device round-trip. stage_scalars
# is deliberately absent: staging is async — the overlapped
# stage/finish protocol (base.finish_barrier) is the sanctioned read
# and is counted separately, not flagged.
_SYNC_CALLS = {
    "device_get": "jax.device_get (blocking device->host transfer)",
    "device_put": "jax.device_put (blocking host->device transfer)",
    "read_scalars": "blocking packed scalar read (read_scalars)",
    "pull_rows": "blocking device row pull (pull_rows)",
    "finish_scalars": "blocking staged-scalar materialization outside "
    "finish_barrier",
    "to_numpy": "chunk.to_numpy() device pull",
    "snapshot": "host snapshot materialization",
}
_SYNC_ATTRS = {
    "item": ".item() device scalar read",
}
# numpy entry points that silently materialize device arrays
_NP_FALLBACK = {"asarray", "flatnonzero", "array", "concatenate"}
_BRANCH_CASTS = {"int", "bool", "float"}

_HOT_METHODS = (
    "apply",
    "apply_left",
    "apply_right",
    "on_barrier",
    "on_watermark",
)


@dataclass(frozen=True)
class SyncPoint:
    reason: str
    file: str
    line: int
    method: str

    def render(self) -> str:
        return f"{self.reason} at {self.file}:{self.line} (in {self.method})"


class _MethodScanner(ast.NodeVisitor):
    """One method's AST: collect blocking-sync call sites and the local
    names assigned from device-flavored expressions (self.* attributes
    or calls to underscore-prefixed kernels), so ``int(n_closed)``-style
    Python branching on traced values is caught without flagging
    ``int(watermark.value)``-style host arithmetic."""

    def __init__(self, file: str, base_line: int, method: str):
        self.file = file
        self.base = base_line
        self.method = method
        self.out: List[SyncPoint] = []
        self.self_calls: List[str] = []  # self._helper() names for recursion
        self.attr_calls: List[Tuple[str, str]] = []  # (self attr, method)
        self._device_names: set = set()

    def _add(self, node, reason: str) -> None:
        self.out.append(
            SyncPoint(
                reason, self.file, self.base + node.lineno - 1, self.method
            )
        )

    @staticmethod
    def _mentions_device(node) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and isinstance(
                n.value, ast.Name
            ) and n.value.id == "self":
                return True
            if isinstance(n, ast.Call):
                f = n.func
                name = (
                    f.id
                    if isinstance(f, ast.Name)
                    else f.attr
                    if isinstance(f, ast.Attribute)
                    else ""
                )
                if name.startswith("_") or name in ("col", "null_of"):
                    return True
                # jnp./jax./lax. results are device arrays by
                # construction
                if isinstance(f, ast.Attribute) and isinstance(
                    f.value, ast.Name
                ) and f.value.id in ("jnp", "jax", "lax"):
                    return True
        return False

    def visit_Assign(self, node):
        if self._mentions_device(node.value):
            for tgt in node.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        self._device_names.add(n.id)
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute):
            name = f.attr
            if name in _SYNC_ATTRS:
                self._add(node, _SYNC_ATTRS[name])
            elif name in _SYNC_CALLS:
                self._add(node, _SYNC_CALLS[name])
            elif name in _NP_FALLBACK and isinstance(f.value, ast.Name):
                if f.value.id in ("np", "numpy"):
                    self._add(
                        node,
                        f"NumPy fallback on a device value (np.{name})",
                    )
            elif (
                isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"
            ):
                # self.<attr>.<method>(...): one-level delegation (the
                # epoch-batch wrapper's self.agg.apply_stacked)
                self.attr_calls.append((f.value.attr, name))
            elif isinstance(f.value, ast.Name) and f.value.id == "self":
                self.self_calls.append(name)
        elif isinstance(f, ast.Name):
            if f.id in _SYNC_CALLS:
                self._add(node, _SYNC_CALLS[f.id])
            elif f.id in _BRANCH_CASTS and node.args:
                arg = node.args[0]
                if self._is_device_expr(arg):
                    self._add(
                        node,
                        f"Python branching on a traced value "
                        f"({f.id}() of a device scalar)",
                    )
        self.generic_visit(node)

    def _is_device_expr(self, node) -> bool:
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self._device_names
        return self._mentions_device(node)


# class source never changes within a process: memoize the per-method
# parse so the DDL hook's scan cost is paid once, not per CREATE MV
_SCAN_MEMO: Dict[Tuple[type, str], Tuple[tuple, tuple, tuple]] = {}


def _parse_method(cls, method: str):
    """(own sync points, same-class helper names, delegated attr
    calls) of one method — memoized per (class, method)."""
    memo = _SCAN_MEMO.get((cls, method))
    if memo is not None:
        return memo
    empty = ((), (), ())
    fn = getattr(cls, method, None)
    if fn is None or not callable(fn):
        _SCAN_MEMO[(cls, method)] = empty
        return empty
    # skip framework defaults: nothing executor-specific to report
    from risingwave_tpu.executors.base import Executor

    base_fn = getattr(Executor, method, None)
    if base_fn is not None and getattr(fn, "__func__", fn) is getattr(
        base_fn, "__func__", base_fn
    ):
        _SCAN_MEMO[(cls, method)] = empty
        return empty
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        file = inspect.getsourcefile(fn) or "<unknown>"
        base_line = inspect.getsourcelines(fn)[1]
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        _SCAN_MEMO[(cls, method)] = empty
        return empty
    sc = _MethodScanner(file, base_line, f"{cls.__name__}.{method}")
    sc.visit(tree)
    out = (
        tuple(sc.out),
        tuple(sc.self_calls),
        tuple(sc.attr_calls),
    )
    _SCAN_MEMO[(cls, method)] = out
    return out


def _scan_method(
    cls,
    method: str,
    seen: set,
    depth: int = 0,
    exclude: Tuple[str, ...] = (),
) -> List[SyncPoint]:
    """Scan one method (and, bounded, the same-class helpers it calls)
    for blocking host syncs, with exact file:line provenance.
    ``exclude`` names helpers the contract declares statically dead on
    this instance's configuration (e.g. a host fallback branch the
    constructor ruled out)."""
    if depth > 3 or (cls, method) in seen or method in exclude:
        return []
    seen.add((cls, method))
    syncs, helpers, _delegated = _parse_method(cls, method)
    out = list(syncs)
    for helper in helpers:
        out.extend(_scan_method(cls, helper, seen, depth + 1, exclude))
    return out


def scan_host_syncs(
    ex,
    extra_methods: Sequence[str] = (),
    exclude: Sequence[str] = (),
) -> List[SyncPoint]:
    """All blocking host-sync points on an executor's hot path (apply
    + barrier/watermark flush + contract-declared extras), found by
    scanning the class source. The finish_barrier staged-scalar
    protocol is exempt by design (the one sanctioned overlapped read
    per barrier)."""
    cls = type(ex)
    seen: set = set()
    out: List[SyncPoint] = []
    delegated: List[Tuple[str, str]] = []
    for m in tuple(_HOT_METHODS) + tuple(extra_methods):
        _syncs, _helpers, attr_calls = _parse_method(cls, m)
        delegated.extend(attr_calls)
        out.extend(_scan_method(cls, m, seen, exclude=tuple(exclude)))
    # one-level delegation through instance attributes (wrapper
    # executors): scan the wrapped object's method too
    for attr, meth in delegated:
        inner = getattr(ex, attr, None)
        if inner is not None and isinstance(inner, object):
            icls = type(inner)
            if hasattr(icls, meth):
                out.extend(_scan_method(icls, meth, seen))
    # de-dup (helpers reachable from several hot methods)
    uniq: Dict[Tuple[str, int, str], SyncPoint] = {}
    for s in out:
        uniq.setdefault((s.file, s.line, s.reason), s)
    return sorted(
        uniq.values(), key=lambda s: (s.file, s.line)
    )


def staged_reads(ex) -> int:
    """1 when the executor participates in the sanctioned overlapped
    stage_scalars/finish_barrier protocol (one concurrent device
    round-trip per barrier — a fused step would keep this read)."""
    from risingwave_tpu.executors.base import Executor

    return int(
        type(ex)._on_barrier_scalars is not Executor._on_barrier_scalars
    )


# ---------------------------------------------------------------------------
# per-executor classification
# ---------------------------------------------------------------------------


@dataclass
class ExecutorClass:
    """One executor's fusion verdict."""

    index: int
    name: str
    kind: str  # "device" | "host" | "opaque"
    fusible: bool
    blockers: List[Diagnostic] = field(default_factory=list)
    sync_points: List[SyncPoint] = field(default_factory=list)
    signatures: int = 0  # distinct jaxpr signatures over the lattice
    staged_reads: int = 0
    # host syncs inside contract-declared ``fallback_syncs`` methods:
    # the fused per-barrier step compiles a device-side replacement
    # for those methods, so their reads exist only on the interpreted
    # fallback path — reported, never a fusibility blocker
    fallback_sync_points: List[SyncPoint] = field(default_factory=list)
    est_cost_ms: Optional[float] = None  # measured, when profile given
    est_dispatches: Optional[float] = None  # measured device dispatches

    def to_json(self) -> dict:
        return {
            "index": self.index,
            "executor": self.name,
            "kind": self.kind,
            "fusible": self.fusible,
            "signatures": self.signatures,
            "staged_reads": self.staged_reads,
            "fallback_sync_points": [
                s.render() for s in self.fallback_sync_points
            ],
            "est_cost_ms": self.est_cost_ms,
            "est_dispatches": self.est_dispatches,
            "blockers": [
                {
                    "code": d.code,
                    "severity": d.severity,
                    "message": d.message,
                }
                for d in self.blockers
            ],
        }


def _prov(idx: int, ex) -> str:
    return f"{idx}:{type(ex).__name__}"


def _lint_info(ex) -> Optional[dict]:
    fn = getattr(ex, "lint_info", None)
    if fn is None:
        return None
    try:
        return fn()
    except Exception:  # noqa: BLE001 — analysis must never crash
        return None


def _contract(ex) -> Optional[dict]:
    fn = getattr(ex, "trace_contract", None)
    if fn is None:
        return None
    try:
        return fn()
    except Exception:  # noqa: BLE001 — analysis must never crash
        return None


def _is_window_keyed(ex, info: Optional[dict]) -> bool:
    if info:
        if info.get("window_key") is not None:
            return True
        if info.get("window_cols"):
            return True
    return getattr(ex, "window_key", None) is not None or bool(
        getattr(ex, "window_cols", None)
    )


def classify_executor(
    ex,
    spec: Optional[ChunkSpec],
    fragment: str,
    index: int,
    deep: bool = True,
) -> ExecutorClass:
    """Classify ONE executor: device-fusible, host-bound (with named
    blockers), or opaque. ``spec`` is the abstract input chunk (None =
    schema unknown upstream — tracing is skipped, contracts + the AST
    scan still apply). ``deep`` enables abstract jaxpr tracing over
    the bucket lattice (CLI/CI); the DDL hook runs shallow."""
    name = type(ex).__name__
    prov = _prov(index, ex)
    info = _lint_info(ex)
    contract = _contract(ex)
    ec = ExecutorClass(index=index, name=name, kind="opaque", fusible=False)
    if spec is None and contract is not None:
        # a contract-declared input schema seeds tracing when nothing
        # threads one in (two-input joins heading a join_tail fragment
        # declare their probe-side schema: the executor knows its own
        # input exactly, the fragment extractor does not)
        decl = contract.get("input_schema")
        if decl:
            spec = ChunkSpec.from_schema(
                decl, nulls=tuple(contract.get("input_nulls", ()))
            )

    def blocker(code: str, message: str, severity: str = "warning"):
        ec.blockers.append(
            Diagnostic(
                code,
                message,
                fragment=fragment,
                executor=prov,
                severity=severity,
            )
        )

    if contract is None:
        # no trace contract: nothing provable — hard-stops the prefix
        return ec

    ec.kind = contract.get("kind", "opaque")
    ec.staged_reads = staged_reads(ex)

    # -- host-sync scan (both kinds: a "device" claim is verified) ----
    # ``fallback_syncs`` methods are scanned SEPARATELY: the fused
    # per-barrier step compiles a device-resident replacement for them
    # (e.g. HashAgg's flush -> fused_step's in-program delta
    # extraction, proven equivalent by the fused-vs-interpreted twin
    # suite), so the fusibility verdict excludes them. NOTE the
    # verdict is a CAPABILITY claim — "this chain can compile into
    # one step" — not a promise the runtime fuses it: fuse_chain may
    # still pick the interpreted/epoch-batched fallback (e.g. an agg
    # feeding an interpreted join), where these reads DO run per
    # barrier. They stay visible as ``fallback_sync_points`` and
    # perf_gate ratchets them (must never grow vs the baseline).
    fallback = tuple(contract.get("fallback_syncs", ()))
    ec.sync_points = scan_host_syncs(
        ex,
        contract.get("hot_methods", ()),
        tuple(contract.get("scan_exclude", ())) + fallback,
    )
    for m in fallback:
        ec.fallback_sync_points.extend(
            _scan_method(type(ex), m, set())
        )
    for s in ec.sync_points:
        blocker("RW-E801", s.render())
    if ec.kind == "host":
        reason = contract.get("host_reason", "host-bound data path")
        if not ec.sync_points:
            blocker("RW-E801", reason)

    # -- emission shape --------------------------------------------------
    emission = contract.get("emission", "passthrough")
    if emission == "data_dependent":
        blocker(
            "RW-E802",
            "emission capacity derives from live-row counts — every "
            "distinct size compiles a fresh downstream program",
        )

    # -- window bucket lattice (RW-E803/E806, the q7 wedge class) --------
    if _is_window_keyed(ex, info):
        wb = contract.get("window_buckets")
        if wb is None:
            blocker(
                "RW-E803",
                "window-keyed shape domain has no declared bucket "
                "lattice: state rebuilds/emissions under window churn "
                "re-trace the fused step without bound",
            )
        else:
            from risingwave_tpu.runtime.bucketing import validate_lattice

            why = validate_lattice(wb)
            if why is not None:
                blocker(
                    "RW-E806",
                    "declared window_buckets lattice is unsatisfiable "
                    f"by the bucketing layer ({why}): the shape-"
                    "stability proof is vacuous",
                )

    # -- donation (RW-E804) ----------------------------------------------
    if contract.get("state") is not None and not contract.get(
        "donate", False
    ):
        blocker(
            "RW-E804",
            "state buffers are not donated by the step kernel — a "
            "fused per-barrier step would hold two live copies in HBM",
        )

    # -- abstract tracing over the bucket lattice ------------------------
    step = contract.get("trace_step")
    if deep and ec.kind == "device" and step is not None and spec is not None:
        sigs = set()
        for bucket in bucket_lattice(spec):
            try:
                sig = trace_signature(step, bucket)
            except Exception as e:  # noqa: BLE001
                kind = type(e).__name__
                if "Tracer" in kind or "Concretization" in kind:
                    blocker(
                        "RW-E801",
                        f"Python branching on traced values: abstract "
                        f"tracing at capacity {bucket.capacity} raised "
                        f"{kind}",
                    )
                else:
                    # untraceable with THIS schema (builder-shaped
                    # input the spec cannot express): degrade to
                    # opaque — no false blocker, no false proof
                    ec.kind = "opaque"
                break
            sigs.add((sig.in_avals, sig.out_avals))
            for h in sig.host_calls:
                blocker(
                    "RW-E801",
                    f"host callback primitive {h!r} inside the traced "
                    "step",
                )
            for t in sig.transfers:
                blocker(
                    "RW-E802",
                    f"transfer primitive {t!r} inside the traced step",
                )
        ec.signatures = len(sigs)
        budget = recompile_budget()
        if ec.signatures > budget:
            blocker(
                "RW-E805",
                f"{ec.signatures} distinct jaxpr signatures across the "
                f"declared buckets > recompile budget {budget}",
            )

    # fusible = a POSITIVE proof: a device contract whose step was
    # actually abstract-traced over the lattice (signatures >= 1) with
    # zero blockers. A device claim that could NOT be traced — no
    # step, no input spec to trace with, or a shallow (DDL) pass that
    # skips tracing — is not evidence and never mints a fusible proof;
    # those passes only surface contract-level hazards (E803 et al).
    ec.fusible = (
        ec.kind == "device"
        and step is not None
        and not ec.blockers
        and ec.signatures >= 1
    )
    return ec


# ---------------------------------------------------------------------------
# schema threading (the abstract interpreter's environment)
# ---------------------------------------------------------------------------


def _thread_spec(
    spec: Optional[ChunkSpec], ex, info: Optional[dict]
) -> Optional[ChunkSpec]:
    """Push a ChunkSpec through one executor using its lint_info
    schema transitions (the same rules plan_verifier applies) — None
    when tracking is lost (opaque / unknown dtypes). An ``emits``
    executor REBUILDS the spec even when the input spec is unknown
    (joins with fully-declared output dtypes re-anchor tracing for
    their tail)."""
    if info is None:
        return None
    emits = info.get("emits")
    if emits is not None:
        schema = {n: dt for n, dt in spec.columns} if spec else {}
        renames = info.get("renames") or {}
        out = {}
        for k, v in emits.items():
            if v is None:
                src = renames.get(k)
                v = schema.get(src) if src is not None else None
            out[k] = v
        from risingwave_tpu.analysis.shape_domain import DEFAULT_BUCKETS

        cap = spec.capacity if spec else DEFAULT_BUCKETS[0]
        # null lanes thread through rename-passthrough outputs only:
        # computed outputs are non-nullable by the chunk contract
        # (with_columns drops stale lanes). Executors minting NEW
        # nullable lanes (outer joins) are not expressible in
        # lint_info — their tail traces the non-nullable variant,
        # which is why `fusible` demands the trace itself, not just
        # this spec, to succeed.
        in_nulls = set(spec.nulls) if spec else set()
        nulls = tuple(
            sorted(
                k
                for k, src in renames.items()
                if k in out and src is not None and src in in_nulls
            )
        )
        return ChunkSpec.from_schema(out, cap, nulls)
    if spec is None:
        return None
    schema = {n: dt for n, dt in spec.columns}
    adds = info.get("adds") or {}
    if adds:
        out = dict(schema)
        for k, v in adds.items():
            out[k] = v
        nulls = tuple(spec.nulls)
        return ChunkSpec.from_schema(out, spec.capacity, nulls)
    return spec


# ---------------------------------------------------------------------------
# fragment / pipeline reports
# ---------------------------------------------------------------------------


@dataclass
class FragmentReport:
    fragment: str
    executors: List[ExecutorClass] = field(default_factory=list)
    fusible_prefix: int = 0
    whole_chain_fusible: bool = False
    host_sync_points: int = 0
    diagnostics: List[Diagnostic] = field(default_factory=list)

    def to_json(self) -> dict:
        # what fusing this fragment reclaims: the measured host-python
        # ms of every blocked executor (None without profile data)
        blocked = [
            e.est_cost_ms
            for e in self.executors
            if not e.fusible and e.est_cost_ms is not None
        ]
        return {
            "fragment": self.fragment,
            "fusible_prefix": self.fusible_prefix,
            "chain_len": len(self.executors),
            "whole_chain_fusible": self.whole_chain_fusible,
            "host_sync_points": self.host_sync_points,
            "fallback_sync_points": sum(
                len(e.fallback_sync_points) for e in self.executors
            ),
            "est_savings_ms": (
                round(sum(blocked), 3) if blocked else None
            ),
            "executors": [e.to_json() for e in self.executors],
            "blockers": [
                {
                    "code": d.code,
                    "executor": d.executor,
                    "severity": d.severity,
                    "message": d.message,
                }
                for d in self.diagnostics
            ],
        }


def analyze_chain(
    chain: Sequence[object],
    spec: Optional[ChunkSpec],
    fragment: str,
    deep: bool = True,
) -> FragmentReport:
    rep = FragmentReport(fragment=fragment)
    prefix_intact = True
    for idx, ex in enumerate(chain):
        ec = classify_executor(ex, spec, fragment, idx, deep=deep)
        rep.executors.append(ec)
        rep.diagnostics.extend(ec.blockers)
        rep.host_sync_points += len(ec.sync_points)
        if prefix_intact and ec.fusible:
            rep.fusible_prefix += 1
        else:
            prefix_intact = False
        spec = _thread_spec(spec, ex, _lint_info(ex))
    rep.whole_chain_fusible = rep.fusible_prefix == len(rep.executors) and (
        len(rep.executors) > 0
    )
    return rep


def _spec_from_schema(
    schema: Optional[Dict[str, object]]
) -> Optional[ChunkSpec]:
    if schema is None:
        return None
    return ChunkSpec.from_schema(schema)


def analyze_pipeline(
    pipeline,
    source_schemas: Optional[Dict[str, Dict[str, object]]] = None,
    name: str = "mv",
    deep: bool = True,
) -> List[FragmentReport]:
    """Per-fragment fusion reports for any pipeline shape (serial
    Pipeline, TwoInputPipeline, GraphPipeline) — fragment extraction
    via runtime.fragmenter.fragment_chains."""
    from risingwave_tpu.runtime.fragmenter import fragment_chains

    source_schemas = source_schemas or {}
    out: List[FragmentReport] = []
    for frag, sections in fragment_chains(pipeline).items():
        for side, chain in sections.items():
            if not chain:
                continue
            # only source-fed sections seed an abstract schema; graph
            # fragments fed by other fragments (side "chain") and the
            # join+tail section re-anchor through lint_info emits
            schema = (
                source_schemas.get(side)
                if side in ("single", "left", "right")
                else None
            )
            label = frag if side in ("single", "chain") else f"{frag}/{side}"
            out.append(
                analyze_chain(
                    chain,
                    _spec_from_schema(schema),
                    f"{name}:{label}",
                    deep=deep,
                )
            )
    return out


def analyze_planned(planned, deep: bool = False) -> List[FragmentReport]:
    """The DDL-time surface: shallow by default (contracts + AST scan,
    no tracing — keeps CREATE MV inside the lint budget)."""
    pipeline = getattr(planned, "pipeline", planned)
    return analyze_pipeline(
        pipeline, None, getattr(planned, "name", "mv"), deep=deep
    )


# ---------------------------------------------------------------------------
# measured-cost ranking + report assembly
# ---------------------------------------------------------------------------


def _executor_cost_ms(profile: dict, name: str) -> Optional[float]:
    """Sum of executor_ms across phases for one executor label in a
    PR 6 profile block ({'executor_ms': {label: {...,'sum': s}}})."""
    total, seen = 0.0, False
    for hist in ("executor_ms", "executor_device_wait_ms"):
        for lbl, row in (profile.get(hist) or {}).items():
            if f"executor={name}" in lbl and isinstance(row, dict):
                total += float(row.get("sum", 0.0))
                seen = True
    return total if seen else None


def attach_costs(
    reports: Sequence[FragmentReport],
    profile: Optional[dict],
    dispatches: Optional[dict] = None,
) -> None:
    """Annotate executor classes with measured dispatch-wall cost
    (``executor_ms``) and device-dispatch counts
    (``device_dispatches_total``) from a PR 6 profiler capture —
    turning the static blocker list into a RANKED worklist (highest
    measured cost first): fusing a fragment reclaims the summed
    host-python ms of its blocked executors and collapses their
    dispatches into one program launch."""
    if not profile:
        return
    for rep in reports:
        for ec in rep.executors:
            ec.est_cost_ms = _executor_cost_ms(profile, ec.name)
            if dispatches:
                for lbl, n in dispatches.items():
                    # the profiler emits bare executor names; labeled
                    # histograms use executor=NAME
                    if lbl == ec.name or f"executor={ec.name}" in lbl:
                        ec.est_dispatches = (
                            ec.est_dispatches or 0.0
                        ) + float(n)
        rep.diagnostics.sort(
            key=lambda d: -(
                next(
                    (
                        e.est_cost_ms
                        for e in rep.executors
                        if d.executor == f"{e.index}:{e.name}"
                        and e.est_cost_ms is not None
                    ),
                    0.0,
                )
            )
        )


def report_to_json(reports: Sequence[FragmentReport]) -> dict:
    frs = [r.to_json() for r in reports]
    return {
        "fragments": frs,
        "summary": {
            "fragments": len(frs),
            "fusible_fragments": sum(
                1 for r in frs if r["whole_chain_fusible"]
            ),
            "host_sync_points": sum(r["host_sync_points"] for r in frs),
            "fusible_prefix_total": sum(r["fusible_prefix"] for r in frs),
            "chain_len_total": sum(r["chain_len"] for r in frs),
            "blockers_by_code": _count_codes(frs),
        },
    }


def _count_codes(frs: Sequence[dict]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for r in frs:
        for b in r["blockers"]:
            out[b["code"]] = out.get(b["code"], 0) + 1
    return dict(sorted(out.items()))


def analyze_nexmark(
    deep: bool = True, profile_bench: Optional[dict] = None
) -> Dict[str, dict]:
    """Fusion reports for the built-in Nexmark corpus (the committed
    FUSION_REPORT.json shape). ``profile_bench``: a BENCH JSON dict —
    each query's ``{q}_executor_ms`` block ranks its blockers."""
    from risingwave_tpu.analysis.lint import (
        NEXMARK_SOURCE_SCHEMAS,
        build_nexmark_corpus,
    )

    out: Dict[str, dict] = {}
    for qname, q in build_nexmark_corpus().items():
        reports = analyze_pipeline(
            q.pipeline, NEXMARK_SOURCE_SCHEMAS[qname], qname, deep=deep
        )
        prof, disp = None, None
        if profile_bench:
            key = qname
            if qname == "q5" and f"{qname}_executor_ms" not in (
                profile_bench or {}
            ):
                key = "q5u"  # the unified-path capture covers q5
            prof = profile_bench.get(f"{key}_executor_ms")
            disp = profile_bench.get(f"{key}_device_dispatches")
        attach_costs(reports, prof, disp)
        out[qname] = report_to_json(reports)
    # provenance rides every regenerated FUSION report ("_"-prefixed:
    # the perf_gate ratchet skips it; the generation check reads it)
    try:
        from risingwave_tpu.provenance import stamp

        out["_provenance"] = stamp()
    except Exception:  # noqa: BLE001 — provenance is best effort
        pass
    return out
