"""Sharing analysis — per-table share-key fingerprints + the corpus'
sharing opportunities (``lint --sharing-report``).

The Shared Arrangements insight (PAPERS.md, arxiv 1812.02639) is that
maintained keyed indexes are REUSABLE across queries; the runtime half
lives in ``runtime/arrangements.py`` (whole-plan attach at CREATE-MV
time). This module is the STATIC half: walk every plan's stateful
executors, fingerprint each keyed state table (index key columns,
dtypes, window spec, bucket lattice, upstream chain signature), and
report:

- **exact** duplicates — same everything including the upstream step
  chain: physically shareable today (the DDL registry would attach);
- **index** opportunities — same (class, keys, dtypes, window spec)
  reached through different chains: the classic shared-arrangement
  candidate set (Nexmark q5 and the unified q5u report the same
  window-agg index here);
- **RW-E703** — a would-share pair that differs ONLY by an
  incompatible bucket lattice: the one knob (capacity) stands between
  the plans and one shared device index.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

from risingwave_tpu.analysis.diagnostics import Diagnostic

__all__ = [
    "run_sharing_report",
    "sharing_report",
    "table_share_keys",
]


def _stable_hash(value) -> str:
    return hashlib.sha1(repr(value).encode()).hexdigest()[:12]


def _dtype_name(v) -> str:
    """Normalize the zoo of dtype spellings (np.dtype, jnp scalar
    classes, strings) to one canonical name — fingerprints must not
    split on representation."""
    try:
        import numpy as np

        return str(np.dtype(v))
    except Exception:  # noqa: BLE001 — exotic dtype object
        return str(v)


def _step_key(ex) -> Tuple:
    """A stable identity for one upstream executor in the chain prefix
    (the data transformation feeding the state table)."""
    fn = getattr(ex, "pure_step", None)
    step = fn() if fn is not None else None
    if step is not None:
        try:
            return (
                step.func.__name__,
                tuple(repr(a) for a in step.args),
                tuple(
                    (k, repr(v)) for k, v in sorted(step.keywords.items())
                ),
            )
        except Exception:  # noqa: BLE001 — fall through to class identity
            pass
    info = None
    fn = getattr(ex, "lint_info", None)
    if fn is not None:
        try:
            info = fn()
        except Exception:  # noqa: BLE001 — opaque
            info = None
    return (type(ex).__name__, repr(sorted((info or {}).items())))


def _window_buckets(ex) -> Optional[Tuple[int, ...]]:
    """The declared bucket lattice backing the executor's window-keyed
    shapes (the PR 9 pow2 lattice), read from the trace contract; the
    live allocator snapshot is the fallback."""
    fn = getattr(ex, "trace_contract", None)
    if fn is not None:
        try:
            wb = (fn() or {}).get("window_buckets")
            if wb:
                return tuple(int(b) for b in wb)
        except Exception:  # noqa: BLE001 — contract is best-effort here
            pass
    alloc = getattr(ex, "_buckets", None)
    lat = getattr(alloc, "lattice", None)
    if lat:
        return tuple(int(b) for b in lat)
    return None


def table_share_keys(pipeline, name: str = "mv") -> List[Dict]:
    """One record per keyed state table in the plan: the share-key
    fingerprint components plus the derived exact/index hashes."""
    from risingwave_tpu.runtime.fragmenter import fragment_chains
    from risingwave_tpu.runtime.fused_step import expand_fused

    out: List[Dict] = []
    for frag, sections in fragment_chains(pipeline).items():
        for section, chain in sections.items():
            chain = expand_fused(chain)
            prefix: List[Tuple] = []
            for ex in chain:
                info = None
                fn = getattr(ex, "lint_info", None)
                if fn is not None:
                    try:
                        info = fn()
                    except Exception:  # noqa: BLE001
                        info = None
                table_ids = (info or {}).get("table_ids", ())
                if not table_ids and not hasattr(ex, "table_id"):
                    prefix.append(_step_key(ex))
                    continue
                table_ids = table_ids or (ex.table_id,)
                keys = tuple(
                    (info or {}).get("state_pk")
                    or (info or {}).get("keys")
                    or ()
                )
                dtypes = (info or {}).get("expects") or {}
                key_dtypes = tuple(
                    (k, _dtype_name(dtypes[k])) for k in keys if k in dtypes
                )
                window_key = (info or {}).get("window_key")
                lattice = _window_buckets(ex)
                # index identity = WHAT the index is keyed by; the
                # window_key is a state-CLEANING knob (watermark wiring
                # differs between a hand-built plan and the SQL-planned
                # twin without changing the maintained index) so it is
                # reported but not part of the identity
                index_key = (
                    type(ex).__name__,
                    keys,
                    key_dtypes,
                )
                for tid in table_ids:
                    out.append(
                        {
                            "plan": name,
                            "fragment": f"{frag}/{section}",
                            "table_id": tid,
                            "executor": type(ex).__name__,
                            "keys": list(keys),
                            "key_dtypes": dict(key_dtypes),
                            "window_key": window_key,
                            "lattice": list(lattice) if lattice else None,
                            # the classic shared-arrangement candidate
                            # identity: WHAT the index is keyed by
                            "index_fingerprint": _stable_hash(index_key),
                            # physical-share identity: index + lattice
                            # + the exact upstream transformation chain
                            "share_fingerprint": _stable_hash(
                                (index_key, lattice, tuple(prefix))
                            ),
                        }
                    )
                prefix.append(_step_key(ex))
    return out


def sharing_report(corpus: Dict[str, object]) -> Dict:
    """``{plan_name: pipeline}`` -> the full sharing report: per-plan
    table fingerprints, cross-plan opportunities, E703 diagnostics."""
    tables: List[Dict] = []
    for name, pipeline in corpus.items():
        tables.extend(table_share_keys(pipeline, name))

    by_exact: Dict[str, List[Dict]] = {}
    by_index: Dict[str, List[Dict]] = {}
    for t in tables:
        by_exact.setdefault(t["share_fingerprint"], []).append(t)
        by_index.setdefault(t["index_fingerprint"], []).append(t)

    exact = [
        {
            "fingerprint": fp,
            "tables": [f"{t['plan']}:{t['table_id']}" for t in ts],
        }
        for fp, ts in sorted(by_exact.items())
        if len(ts) > 1
    ]
    opportunities = []
    diags: List[Diagnostic] = []
    for fp, ts in sorted(by_index.items()):
        plans = sorted({t["plan"] for t in ts})
        if len(ts) < 2 or not ts[0]["keys"]:
            continue  # keyless state: nothing to share an index ON
        opportunities.append(
            {
                "index_fingerprint": fp,
                "keys": ts[0]["keys"],
                "window_key": ts[0]["window_key"],
                "plans": plans,
                "tables": sorted(
                    f"{t['plan']}:{t['table_id']}" for t in ts
                ),
            }
        )
        # would-share pairs broken by the lattice: same index identity
        # AND the same window spec (the CODES contract — a pair that
        # also differs in window wiring would not share even with
        # aligned capacities, so flagging it would send the operator
        # on a false errand), but incompatible declared lattices
        by_window: Dict[object, List[Dict]] = {}
        for t in ts:
            by_window.setdefault(t["window_key"], []).append(t)
        for wts in by_window.values():
            lattices = {tuple(t["lattice"] or ()) for t in wts}
            if len(wts) < 2 or len(lattices) < 2:
                continue
            members = sorted(
                f"{t['plan']}:{t['table_id']}"
                f"[lattice={t['lattice'] and t['lattice'][:1]}"
                f"..{t['lattice'] and t['lattice'][-1:]}]"
                for t in wts
            )
            diags.append(
                Diagnostic(
                    code="RW-E703",
                    message=(
                        "would-share index "
                        f"(keys={wts[0]['keys']}, window_key="
                        f"{wts[0]['window_key']}) split by incompatible "
                        f"bucket lattices across {members} — align "
                        "capacities to share one arrangement"
                    ),
                    fragment=wts[0]["fragment"],
                    executor=wts[0]["executor"],
                    severity="warning",
                )
            )
    return {
        "tables": tables,
        "exact_duplicates": exact,
        "opportunities": opportunities,
        "diagnostics": diags,
        "summary": {
            "plans": len(corpus),
            "state_tables": len(tables),
            "exact_shareable_groups": len(exact),
            "index_opportunities": len(opportunities),
            "lattice_mismatches": sum(
                1 for d in diags if d.code == "RW-E703"
            ),
        },
    }


def _q5u_pipeline(capacity: int = 1 << 10):
    """The unified q5 twin — the SAME Nexmark q5 query built through
    the SQL planner's graph path (what bench's q5u tier runs). Its
    window-agg index must fingerprint onto q5's (the ISSUE's shared
    window-agg evidence). Shadow-built on the host device."""
    from risingwave_tpu.analysis.plan_verifier import _host_device
    from risingwave_tpu.connectors.nexmark import BID_SCHEMA
    from risingwave_tpu.runtime.fragmenter import graph_planned_mv
    from risingwave_tpu.sql import Catalog, StreamPlanner

    sql = (
        "CREATE MATERIALIZED VIEW q5u AS "
        "SELECT auction, window_start, count(*) AS num "
        "FROM HOP(bid, date_time, INTERVAL '2' SECOND, "
        "INTERVAL '10' SECOND) "
        "GROUP BY auction, window_start"
    )
    catalog = Catalog({"bid": BID_SCHEMA})
    factory = lambda: StreamPlanner(catalog, capacity=capacity)
    with _host_device():
        planned = graph_planned_mv(factory, sql, parallelism=1)
    return planned


def run_sharing_report() -> Dict:
    """``lint --sharing-report``: the built-in corpus (q5/q7/q8 twins
    + the SQL-planned q5u) through ``sharing_report``, JSON-ready."""
    from risingwave_tpu.analysis.lint import build_nexmark_corpus
    from risingwave_tpu.provenance import stamp

    built = build_nexmark_corpus()
    corpus = {name: q.pipeline for name, q in built.items()}
    q5u = _q5u_pipeline()
    corpus["q5u"] = q5u.pipeline
    try:
        rep = sharing_report(corpus)
    finally:
        close = getattr(q5u.pipeline, "close", None)
        if close is not None:
            try:
                close()
            except BaseException:
                pass
    rep["diagnostics"] = [
        {
            "code": d.code,
            "severity": d.severity,
            "fragment": d.fragment,
            "executor": d.executor,
            "message": d.message,
        }
        for d in rep["diagnostics"]
    ]
    rep["_provenance"] = stamp()
    return rep
