"""Abstract mesh domain for the mesh-readiness analyzer.

The SPMD-fusibility question (ROADMAP item 3) is a PLACEMENT question
layered on the shape question `shape_domain.py` already answers: a
sharded fragment's barrier collapses into ONE dispatch iff its step is
a single ``shard_map``-ed program over the mesh — state stacked along
the shard axis, rows crossing shards only through in-program
collectives (``lax.all_to_all``), and nothing about the program
depending on which shard runs it.  This module is the static twin of
that contract:

- ``ensure_virtual_devices()``: the lint CLI's mesh bootstrap.  The
  analyzer traces against a REAL ``Mesh`` of N virtual host devices
  (``xla_force_host_platform_device_count``) because the sharded
  executors build their stacked state against one; the flag only
  applies before the JAX backend initializes, so this either installs
  it in time or raises ``MeshUnavailable`` LOUDLY (exit 2 in the CLI)
  instead of tracing a 1-device mesh and proving nothing.
- stacked abstraction helpers: the executors' live state already
  carries the leading ``(n_shards, ...)`` axis, so its abstract twin
  is just ``ShapeDtypeStruct`` leaves of the same shape — no
  allocation, the `shape_domain.py` discipline.  Chunks get the
  leading axis added (``stacked_chunk``).
- ``mesh_trace_signature()``: the jaxpr fingerprint of one shard_map-
  ed step — in/out avals + primitives, with the COLLECTIVE primitives
  (the on-device exchange evidence) and host/transfer primitives (the
  anti-evidence) pulled out.  A positive SPMD proof requires at least
  the tracing to succeed and the program to be collective-clean or
  collective-only — host callbacks inside the mesh program are an
  immediate E901.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import Optional, Tuple

# NOTE: jax is imported lazily inside functions wherever the import
# could race the backend-init check (ensure_virtual_devices must run
# BEFORE anything touches jax.devices()).

DEFAULT_MESH_SHARDS = 8
MESH_AXIS = "shard"

_FLAG = "xla_force_host_platform_device_count"

# primitives that prove rows cross shards ON DEVICE (the collective
# exchange the scale-out arc wants); their presence inside a sharded
# step is positive evidence, not a blocker
COLLECTIVE_PRIMITIVES = frozenset(
    {
        "all_to_all",
        "all_gather",
        "psum",
        "psum2",  # shard_map's check_rep rewrite of psum
        "pmax",
        "pmin",
        "ppermute",
        "reduce_scatter",
        "axis_index",
    }
)


class MeshUnavailable(RuntimeError):
    """The N-virtual-device mesh cannot be set up in this process
    (JAX backend already initialized without the device-count flag).
    The lint CLI maps this to exit code 2 — loud, never a silent
    1-device "proof"."""


def _jax_initialized() -> bool:
    """True iff a JAX backend has already been instantiated in this
    process — past that point ``xla_force_host_platform_device_count``
    is inert."""
    mod = sys.modules.get("jax._src.xla_bridge")
    if mod is None:
        return False
    backends = getattr(mod, "_backends", None)
    return bool(backends)


def ensure_virtual_devices(n: int = DEFAULT_MESH_SHARDS) -> None:
    """Make >= ``n`` host devices available, or raise MeshUnavailable.

    Idempotent: if the flag is already in XLA_FLAGS (conftest.py sets
    it for the test suite) or the initialized backend already exposes
    enough devices, this is a no-op check."""
    flags = os.environ.get("XLA_FLAGS", "")
    if _FLAG not in flags:
        if _jax_initialized():
            import jax

            have = len(jax.devices())
            if have >= n:
                return
            raise MeshUnavailable(
                f"JAX backend already initialized with {have} device(s); "
                f"--{_FLAG}={n} cannot apply anymore. Run "
                "`lint --mesh-report` in a fresh process (it sets the "
                "flag itself before touching JAX)."
            )
        os.environ["XLA_FLAGS"] = (flags + f" --{_FLAG}={n}").strip()
    import jax

    have = len(jax.devices())
    if have < n:
        raise MeshUnavailable(
            f"requested {n} virtual host devices but the backend "
            f"initialized with {have} — --{_FLAG} was present too late "
            "or another platform won. Run `lint --mesh-report` in a "
            "fresh process."
        )


def virtual_mesh(n: int = DEFAULT_MESH_SHARDS, axis: str = MESH_AXIS):
    """A real N-device mesh over the virtual host devices (the "sim
    mesh" the sharded Nexmark corpus builds against)."""
    ensure_virtual_devices(n)
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n]), (axis,))


def abstract_tree(tree):
    """A pytree's abstract twin: every array leaf becomes a
    ``ShapeDtypeStruct`` of the same shape/dtype (state is already
    stacked ``(n_shards, ...)`` in the sharded executors, so no axis
    surgery). Non-array leaves (ints, None) pass through."""
    import jax

    def leaf(a):
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is None or dtype is None:
            return a
        return jax.ShapeDtypeStruct(tuple(shape), dtype)

    return jax.tree.map(leaf, tree)


def stacked_chunk(spec, n: int):
    """A ``shape_domain.ChunkSpec`` as a stacked abstract StreamChunk:
    ``(n, capacity)`` ShapeDtypeStruct lanes — what a shard_map-ed
    step's chunk argument looks like from outside the mesh."""
    import jax
    import jax.numpy as jnp

    from risingwave_tpu.array.chunk import StreamChunk

    cap = spec.capacity
    sds = lambda dt: jax.ShapeDtypeStruct((n, cap), jnp.dtype(dt))
    return StreamChunk(
        columns={name: sds(dt) for name, dt in spec.columns},
        valid=sds(jnp.bool_),
        nulls={name: sds(jnp.bool_) for name in spec.nulls},
        ops=sds(jnp.int32),
    )


def stacked_schema_chunk(dtypes, nullable, cap: int, n: int):
    """A stacked abstract StreamChunk straight from a declared
    ``{name: dtype}`` schema — for executors whose input lanes are
    self-declared (e.g. a join side's arrival chunk) rather than
    threaded from the source spec."""
    import jax
    import jax.numpy as jnp

    from risingwave_tpu.array.chunk import StreamChunk

    sds = lambda dt: jax.ShapeDtypeStruct((n, cap), jnp.dtype(dt))
    return StreamChunk(
        columns={k: sds(dt) for k, dt in dtypes.items()},
        valid=sds(jnp.bool_),
        nulls={k: sds(jnp.bool_) for k in nullable},
        ops=sds(jnp.int32),
    )


@dataclass(frozen=True)
class MeshSignature:
    """Fingerprint of one abstract shard_map trace: jit-cache identity
    (in/out avals) + primitive census with the mesh-relevant classes
    pulled out."""

    in_avals: Tuple[str, ...]
    out_avals: Tuple[str, ...]
    primitives: Tuple[str, ...] = field(hash=False, default=())
    collectives: Tuple[str, ...] = ()
    host_calls: Tuple[str, ...] = ()
    transfers: Tuple[str, ...] = ()


def _fmt_aval(v) -> str:
    aval = getattr(v, "aval", v)
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", "?")
    return f"{dtype}[{','.join(map(str, shape))}]"


def mesh_trace_signature(step, *abstract_args) -> MeshSignature:
    """Abstractly trace ``step(*abstract_args)`` (a shard_map-ed
    callable over ShapeDtypeStruct pytrees — no XLA, no allocation).
    Raises whatever tracing raises; TracerBoolConversionError &
    friends are the analyzer's E903 evidence."""
    import jax

    from risingwave_tpu.analysis.shape_domain import (
        HOST_PRIMITIVES,
        TRANSFER_PRIMITIVES,
    )

    jaxpr = jax.make_jaxpr(step)(*abstract_args)
    core = jaxpr.jaxpr
    prims: list = []
    colls: list = []
    hosts: list = []
    transfers: list = []

    def visit(j):
        for eqn in j.eqns:
            name = eqn.primitive.name
            prims.append(name)
            if name in COLLECTIVE_PRIMITIVES:
                colls.append("psum" if name == "psum2" else name)
            if name in HOST_PRIMITIVES:
                hosts.append(name)
            if name in TRANSFER_PRIMITIVES:
                transfers.append(name)
            for p in eqn.params.values():
                for q in p if isinstance(p, (tuple, list)) else (p,):
                    if hasattr(q, "eqns"):
                        visit(q)  # open Jaxpr (shard_map, while, scan)
                    elif hasattr(q, "jaxpr"):
                        visit(q.jaxpr)  # ClosedJaxpr (pjit, cond)

    visit(core)
    return MeshSignature(
        in_avals=tuple(_fmt_aval(v) for v in core.invars),
        out_avals=tuple(_fmt_aval(v) for v in core.outvars),
        primitives=tuple(prims),
        collectives=tuple(colls),
        host_calls=tuple(hosts),
        transfers=tuple(transfers),
    )


def mesh_buckets(chunk_caps: Optional[Tuple[int, ...]] = None):
    """The chunk-capacity lattice the mesh proof sweeps — the shared
    fusion lattice unless overridden (``RW_MESH_BUCKETS``)."""
    env = os.environ.get("RW_MESH_BUCKETS", "").strip()
    if env:
        try:
            caps = tuple(sorted({int(x) for x in env.split(",") if x.strip()}))
            if caps:
                return caps
        except ValueError:
            pass
    if chunk_caps:
        return tuple(chunk_caps)
    from risingwave_tpu.analysis.shape_domain import declared_buckets

    return declared_buckets()
