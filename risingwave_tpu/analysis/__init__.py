"""rwlint — static plan-graph verifier + JAX compilation sanitizer.

The invariants the planner -> fragmenter -> executor pipeline ASSUMES
but (before this package) never verified become DDL-time checks:

- ``plan_verifier``: walks the fragment DAG / executor chains and
  checks per-channel schema + dtype agreement, distribution-key <->
  downstream keyed-state alignment across every hash exchange,
  state-table pk coverage, watermark-column reachability for
  window-keyed state cleaning, channel wiring, and barrier-DAG
  acyclicity — emitting ``RW-E###`` diagnostics with fragment/executor
  provenance instead of runtime corruption (TiLT, arxiv 2301.12030:
  typed-IR stream plans make these statically checkable; Shared
  Arrangements, arxiv 1812.02639: key alignment IS the soundness
  invariant of shared keyed state).
- ``jax_sanitizer``: inspects the jaxprs of compiled step functions
  (64-bit promotion / non-32-bit hash arithmetic / missing buffer
  donation), guards the per-barrier device step against implicit
  host<->device transfers, and fingerprints per-executor abstract
  input signatures across epochs to catch recompile storms.
- ``lint``: the entry points — ``lint_planned`` (the CREATE-MV hook),
  ``lint_pipeline`` (hand-built pipelines: bench, tests), SQL-file
  and all-Nexmark linting behind ``python -m risingwave_tpu lint``.

The package ``__init__`` is LAZY: runtime modules (pipeline/graph)
import ``analysis.jax_sanitizer`` on their hot paths, and an eager
re-export here would cycle through plan_verifier -> executors ->
pipeline.
"""

from __future__ import annotations

_EXPORTS = {
    "Diagnostic": "diagnostics",
    "PlanLintError": "diagnostics",
    "CODES": "diagnostics",
    "verify_planned": "plan_verifier",
    "verify_graph_specs": "plan_verifier",
    "lint_planned": "lint",
    "lint_pipeline": "lint",
    "lint_sql_file": "lint",
    "lint_all_nexmark": "lint",
    "transfer_guard": "jax_sanitizer",
    "RecompileWatch": "jax_sanitizer",
    "SignatureWatch": "jax_sanitizer",
    "SIGNATURES": "jax_sanitizer",
    "check_promotions": "jax_sanitizer",
    "check_hash_path_32bit": "jax_sanitizer",
    "check_donation": "jax_sanitizer",
    "analyze_pipeline": "fusion_analyzer",
    "analyze_planned": "fusion_analyzer",
    "analyze_nexmark": "fusion_analyzer",
    "classify_executor": "fusion_analyzer",
    "scan_host_syncs": "fusion_analyzer",
    "ChunkSpec": "shape_domain",
    "capacity_bucket": "shape_domain",
}


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(f"{__name__}.{mod}"), name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
