"""Abstract shape domain for the fusion analyzer.

The device-fusibility question is at heart a SHAPE question: XLA
compiles one program per abstract input signature (shapes + dtypes),
so a fragment chain fuses into one per-barrier step iff every
executor's step is traceable AND its signature set over the chunk
sizes it will actually see is small and closed (array/chunk.py:
fixed-capacity chunks are the whole design).  This module is the
static twin of that contract:

- ``ChunkSpec``: an abstract StreamChunk — columns/dtypes/null lanes/
  capacity, no data.  ``abstract()`` materializes it as a pytree of
  ``jax.ShapeDtypeStruct`` leaves, which is what ``jax.eval_shape`` /
  ``jax.make_jaxpr`` need to trace an executor's step WITHOUT running
  it (and without allocating device memory).
- ``bucket_lattice()``: the declared chunk-size buckets.  The runtime
  quantizes chunk capacities (epoch batching pads the stacked axis to
  a power of two; hash_agg's flush emits exactly two capacities), so
  compiled-program counts are bounded by the lattice size — an
  executor is shape-stable iff tracing it at every bucket yields one
  jaxpr signature per bucket (RW-E803's proof obligation).
- ``trace_signature()``: the jaxpr fingerprint of one (step, spec)
  pair — primitive sequence + in/out avals.  Two buckets that
  fingerprint identically share a compiled program; the number of
  DISTINCT fingerprints across the lattice is the recompile bill a
  fused step would pay (RW-E805's budget).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk

# default chunk-size bucket lattice: two pow2 capacities are enough to
# PROVE per-bucket signature stability (a data-dependent shape shows up
# as extra signatures at either bucket); override for wider sweeps
DEFAULT_BUCKETS = (1 << 8, 1 << 10)

# distinct jaxpr signatures one executor may contribute to a fused
# per-barrier step across the whole lattice before the analyzer calls
# it a recompile bill (RW-E805). A recompile is ~30-40s on the
# tunneled TPU, so the budget is deliberately tight.
DEFAULT_RECOMPILE_BUDGET = 8


def declared_buckets() -> Tuple[int, ...]:
    """The lattice under analysis: ``RW_FUSION_BUCKETS`` (comma-
    separated capacities) or the default two-bucket pow2 probe."""
    env = os.environ.get("RW_FUSION_BUCKETS", "").strip()
    if not env:
        return DEFAULT_BUCKETS
    try:
        caps = tuple(
            sorted({int(x) for x in env.split(",") if x.strip()})
        )
    except ValueError:
        return DEFAULT_BUCKETS
    return caps or DEFAULT_BUCKETS


def recompile_budget() -> int:
    try:
        return int(
            os.environ.get(
                "RW_FUSION_RECOMPILE_BUDGET", DEFAULT_RECOMPILE_BUDGET
            )
        )
    except ValueError:
        return DEFAULT_RECOMPILE_BUDGET


@dataclass(frozen=True)
class ChunkSpec:
    """Abstract StreamChunk: (column name -> dtype), null-lane names,
    capacity. Dtypes are stored as strings so specs hash/compare."""

    columns: Tuple[Tuple[str, str], ...]
    nulls: Tuple[str, ...] = ()
    capacity: int = DEFAULT_BUCKETS[0]

    @staticmethod
    def from_schema(
        schema: Dict[str, object],
        capacity: int = DEFAULT_BUCKETS[0],
        nulls: Sequence[str] = (),
    ) -> Optional["ChunkSpec"]:
        """None when any dtype is unknown — the analyzer never guesses
        a lane width (a wrong dtype would trace a DIFFERENT program
        than the runtime compiles, proving nothing)."""
        cols = []
        for name in sorted(schema):
            dt = schema[name]
            if dt is None:
                return None
            try:
                cols.append((name, str(jnp.dtype(dt))))
            except TypeError:
                return None
        return ChunkSpec(tuple(cols), tuple(sorted(nulls)), capacity)

    def with_capacity(self, capacity: int) -> "ChunkSpec":
        return ChunkSpec(self.columns, self.nulls, capacity)

    def names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.columns)

    def abstract(self) -> StreamChunk:
        """The spec as a StreamChunk of ``ShapeDtypeStruct`` leaves —
        a valid pytree for eval_shape/make_jaxpr (StreamChunk's
        flatten/unflatten never looks at leaf values)."""
        cap = self.capacity
        sds = lambda dt: jax.ShapeDtypeStruct((cap,), jnp.dtype(dt))
        return StreamChunk(
            columns={n: sds(dt) for n, dt in self.columns},
            valid=sds(jnp.bool_),
            nulls={n: sds(jnp.bool_) for n in self.nulls},
            ops=sds(jnp.int32),
        )


def bucket_lattice(
    spec: ChunkSpec, buckets: Optional[Sequence[int]] = None
) -> Tuple[ChunkSpec, ...]:
    """The spec at every declared capacity bucket."""
    caps = tuple(buckets) if buckets is not None else declared_buckets()
    return tuple(spec.with_capacity(c) for c in caps)


def capacity_bucket(capacity: int) -> int:
    """Pow2 bucket of a concrete chunk capacity — the dynamic twin
    (SignatureWatch records this per hazard so runtime events
    cross-reference static RW-E803 findings)."""
    if capacity <= 1:
        return 1
    return 1 << (int(capacity) - 1).bit_length()


# primitives whose presence inside a traced step proves the step is
# NOT device-resident: the fused program would bounce through the host
# every barrier
HOST_PRIMITIVES = frozenset(
    {"pure_callback", "io_callback", "callback", "debug_callback"}
)
TRANSFER_PRIMITIVES = frozenset({"device_put"})


@dataclass(frozen=True)
class TraceSignature:
    """Fingerprint of one abstract trace: what the jit cache would key
    on (in/out avals) plus the primitive sequence (program identity)."""

    in_avals: Tuple[str, ...]
    out_avals: Tuple[str, ...]
    primitives: Tuple[str, ...] = field(hash=False, default=())
    host_calls: Tuple[str, ...] = ()
    transfers: Tuple[str, ...] = ()


def _fmt_aval(v) -> str:
    aval = getattr(v, "aval", v)
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", "?")
    return f"{dtype}[{','.join(map(str, shape))}]"


def trace_signature(step, spec: ChunkSpec) -> TraceSignature:
    """Abstractly trace ``step(chunk)`` at one bucket. Raises whatever
    tracing raises (TracerBoolConversionError & friends are the
    analyzer's evidence of Python branching on traced values)."""
    jaxpr = jax.make_jaxpr(step)(spec.abstract())
    core = jaxpr.jaxpr
    prims: list = []
    hosts: list = []
    transfers: list = []

    def visit(j):
        for eqn in j.eqns:
            name = eqn.primitive.name
            prims.append(name)
            if name in HOST_PRIMITIVES:
                hosts.append(name)
            if name in TRANSFER_PRIMITIVES:
                transfers.append(name)
            for p in eqn.params.values():
                sub = getattr(p, "jaxpr", None)
                if sub is not None:
                    visit(sub)
                elif isinstance(p, (tuple, list)):
                    for q in p:
                        if hasattr(q, "jaxpr"):
                            visit(q.jaxpr)

    visit(core)
    return TraceSignature(
        in_avals=tuple(_fmt_aval(v) for v in core.invars),
        out_avals=tuple(_fmt_aval(v) for v in core.outvars),
        primitives=tuple(prims),
        host_calls=tuple(hosts),
        transfers=tuple(transfers),
    )


def out_chunk_capacities(step, spec: ChunkSpec) -> Tuple[int, ...]:
    """Capacities of the StreamChunk outputs of ``step`` at one bucket
    (eval_shape only — the cheap query when the full jaxpr is not
    needed). Non-chunk outputs are ignored."""
    out = jax.eval_shape(step, spec.abstract())
    caps = []

    def walk(x):
        if isinstance(x, StreamChunk):
            caps.append(int(x.valid.shape[-1]))
        elif isinstance(x, (tuple, list)):
            for y in x:
                walk(y)

    walk(out)
    return tuple(caps)
