"""Part A — the static plan-graph verifier.

Walks planner/fragmenter output BEFORE actors spawn and checks the
invariants the runtime otherwise assumes:

- per-channel schema agreement: every column an executor reads exists
  on its input channel with the dtype the executor declared (RW-E101 /
  RW-E102);
- exchange soundness: hash-dispatch keys exist upstream (RW-E201) and
  cover the downstream parallel fragment's keyed state (RW-E202) — the
  Shared-Arrangements alignment invariant; unkeyed dispatch kinds never
  feed parallel keyed state (RW-E203);
- join key dtype agreement across sides (RW-E204);
- watermark reachability: window-keyed state cleaning is only sound
  when a watermark can actually reach the window column — i.e. the
  column traces to a source column or a watermark-producing executor
  through the chain's watermark-translation maps (RW-E501);
- wiring: channels reference real fragments, no duplicate edges, the
  barrier DAG is acyclic, every fragment's output is consumed
  (RW-E6xx);
- state tables: materialize pk coverage (RW-E701), unique table_ids
  within a plan (RW-E702).

Metadata comes from ``Executor.lint_info()`` (executors/base.py).
Executors that expose none are OPAQUE: schema/watermark tracking stops
at them and downstream value-level checks are skipped — the verifier
never guesses, so a diagnostic is always a provable defect.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax.numpy as jnp

from risingwave_tpu.analysis.diagnostics import Diagnostic, LintReport

# schema: col -> dtype (None = present, dtype unknown); whole-schema
# None = opaque (tracking lost)
Schema = Optional[Dict[str, object]]


def _host_device():
    """``jax.default_device(cpu)`` context, or a no-op when the CPU
    backend is unavailable (e.g. JAX_PLATFORMS pinned elsewhere)."""
    import contextlib

    import jax

    try:
        return jax.default_device(jax.devices("cpu")[0])
    except Exception:  # noqa: BLE001
        return contextlib.nullcontext()


def _dt(x) -> Optional[object]:
    if x is None:
        return None
    try:
        return jnp.dtype(x)
    except TypeError:
        return None


def _info_of(
    ex, rep: Optional[LintReport] = None, fragment: str = "", prov: str = ""
) -> Optional[dict]:
    fn = getattr(ex, "lint_info", None)
    if fn is None:
        return None  # legitimately opaque: no metadata advertised
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — lint must never crash DDL
        # a BROKEN lint_info is not silent opacity: without a signal,
        # every check downstream of this executor quietly regresses
        # while the suite keeps reporting clean (warning, not error —
        # degraded verification must not refuse an honest DDL)
        if rep is not None:
            rep.add(
                "RW-E001",
                f"lint_info() raised {type(e).__name__}: {e}",
                fragment=fragment,
                executor=prov,
                severity="warning",
            )
        return None


def _prov(idx: int, ex) -> str:
    return f"{idx}:{type(ex).__name__}"


def _e708_severity() -> str:
    """RW-E708 is report-only by DEFAULT even though sessions default
    strict (RW_STRICT_LINT unset = strict): promoting it to an error
    for every pre-existing DDL would refuse plans that were legal
    yesterday. Only an EXPLICITLY-set truthy RW_STRICT_LINT (the
    __main__.py opt-in convention) makes unaccounted state a refusal."""
    import os

    v = os.environ.get("RW_STRICT_LINT")
    if v is not None and v.strip().lower() not in ("", "0", "off", "false"):
        return "error"
    return "warning"


def _check_ledger_visible(ex, info, fragment, prov, rep) -> None:
    """RW-E708: an executor that registers state table_ids with the
    runtime but is invisible to the memory governor's ledger — no
    ``state_nbytes()``/``state_bytes()`` accounting contract and no
    allocator-backed capacity note (``_buckets``). Unaccounted device
    state cannot be budgeted, vetoed or spilled: under overload it is
    exactly the state that OOMs the device while the governor reports
    headroom."""
    if not (info.get("table_ids") or ()):
        return
    if (
        hasattr(ex, "state_nbytes")
        or hasattr(ex, "state_bytes")
        or getattr(ex, "_buckets", None) is not None
    ):
        return
    rep.add(
        "RW-E708",
        f"{type(ex).__name__} registers state table(s) "
        f"{tuple(info.get('table_ids') or ())!r} but exposes neither "
        "state_nbytes()/state_bytes() nor an allocator capacity note — "
        "its device state is invisible to the HBM memory ledger",
        fragment=fragment,
        executor=prov,
        severity=_e708_severity(),
    )


def _check_digest_coverage(ex, info, fragment, prov, rep) -> None:
    """RW-E709: an executor that registers state table_ids but has no
    working ``state_digest()`` — its device state sits OUTSIDE the
    integrity layer's corruption checks (no fused-vs-interpreted
    cross-check, no checkpoint digest, no scrub coverage), so a silent
    in-HBM bit-flip there is undetectable by construction. Severity
    follows the E708 convention: report-only unless RW_STRICT_LINT is
    explicitly set truthy."""
    if not (info.get("table_ids") or ()):
        return
    from risingwave_tpu.storage.state_table import Checkpointable

    fn = getattr(type(ex), "state_digest", None)
    if fn is None or fn is Checkpointable.state_digest:
        rep.add(
            "RW-E709",
            f"{type(ex).__name__} registers state table(s) "
            f"{tuple(info.get('table_ids') or ())!r} but implements no "
            "state_digest() — silent corruption of its device state is "
            "invisible to the integrity layer",
            fragment=fragment,
            executor=prov,
            severity=_e708_severity(),
        )
        return
    lanes_fn = getattr(ex, "digest_lanes", None)
    if callable(lanes_fn):
        try:
            from risingwave_tpu.integrity import foldable_dtypes

            bad = list(foldable_dtypes(lanes_fn()[0]))
        except Exception:  # noqa: BLE001 — lanes need built state;
            return  # runtime digest paths still exercise them
        if bad:
            rep.add(
                "RW-E709",
                f"{type(ex).__name__} digest_lanes() exposes lanes the "
                f"fold cannot cover: {bad!r}",
                fragment=fragment,
                executor=prov,
                severity=_e708_severity(),
            )


class _TableIds:
    """Plan-wide table_id uniqueness (RW-E702). Parallel instances of
    one logical fragment share table_ids BY DESIGN (disjoint vnode
    partitions of the same logical table), so collection is keyed by
    (instance, table_id) and duplicates only flag within an instance."""

    def __init__(self, rep: LintReport):
        self.rep = rep
        self.seen: Dict[Tuple[int, str], Tuple[str, str]] = {}

    def add(self, instance: int, tids, fragment: str, executor: str) -> None:
        for tid in tids or ():
            key = (instance, tid)
            if key in self.seen:
                f0, e0 = self.seen[key]
                self.rep.add(
                    "RW-E702",
                    f"state table_id {tid!r} already used by "
                    f"[frag={f0} ex={e0}]",
                    fragment=fragment,
                    executor=executor,
                )
            else:
                self.seen[key] = (fragment, executor)


def _walk_chain(
    chain: Sequence[object],
    schema: Schema,
    wm: Optional[Set[str]],
    fragment: str,
    rep: LintReport,
    tids: _TableIds,
    instance: int = 0,
) -> Tuple[Schema, Optional[Set[str]]]:
    """Push a schema + watermark-capability set through one executor
    chain, checking each executor's declared metadata on the way."""
    for idx, ex in enumerate(chain):
        prov = _prov(idx, ex)
        info = _info_of(ex, rep, fragment, prov)
        tid = getattr(ex, "table_id", None)
        if info is None:
            # opaque executor: record its table id, stop tracking
            tids.add(instance, (tid,) if tid else (), fragment, prov)
            schema, wm = None, None
            continue
        tids.add(instance, info.get("table_ids", ()), fragment, prov)
        _check_ledger_visible(ex, info, fragment, prov, rep)
        _check_digest_coverage(ex, info, fragment, prov, rep)

        expects = {k: _dt(v) for k, v in (info.get("expects") or {}).items()}
        requires = set(info.get("requires") or ()) | set(expects)
        if schema is not None:
            for col in sorted(requires):
                if col not in schema:
                    rep.add(
                        "RW-E101",
                        f"column {col!r} is not produced upstream "
                        f"(channel carries {sorted(schema)})",
                        fragment=fragment,
                        executor=prov,
                    )
                else:
                    want = expects.get(col)
                    have = _dt(schema[col])
                    if want is not None and have is not None and want != have:
                        rep.add(
                            "RW-E102",
                            f"column {col!r} arrives as {have} but the "
                            f"executor declared {want}",
                            fragment=fragment,
                            executor=prov,
                        )
            for col in info.get("state_pk") or ():
                if col not in schema:
                    rep.add(
                        "RW-E701",
                        f"state-table pk column {col!r} is not in the "
                        f"input schema (channel carries {sorted(schema)})",
                        fragment=fragment,
                        executor=prov,
                    )

        wcol = info.get("window_key")
        if wcol is not None and wm is not None and wcol not in wm:
            rep.add(
                "RW-E501",
                f"window-keyed state cleaning on {wcol!r}, but no "
                "watermark can reach it (not a source column, not a "
                "hop-window output, not watermark-filter generated) — "
                "state would grow without bound",
                fragment=fragment,
                executor=prov,
            )

        # schema transition
        emits = info.get("emits")
        if emits is not None:
            prev = schema
            schema = {k: _dt(v) for k, v in emits.items()}
            if prev is not None:
                # rename-only outputs inherit the source column's dtype
                for out, src in (info.get("renames") or {}).items():
                    if (
                        out in schema
                        and schema[out] is None
                        and src is not None
                    ):
                        schema[out] = _dt(prev.get(src))
        elif schema is not None:
            adds = info.get("adds") or {}
            if adds:
                schema = dict(schema)
                for k, v in adds.items():
                    schema[k] = _dt(v)

        # watermark-capability transition
        if wm is not None:
            if emits is not None:
                renames = info.get("renames") or {}
                wm = {
                    out
                    for out, src in renames.items()
                    if src is not None and src in wm
                }
            else:
                for in_col, out_col in (info.get("watermark_map") or {}).items():
                    if in_col in wm:
                        wm = set(wm) | {out_col}
            src = info.get("watermark_src")
            if src is not None:
                wm = set(wm) | {src}
    return schema, wm


def _trace_back(chain_prefix: Sequence[object], name: str) -> Optional[str]:
    """The input-channel column ``name`` is an unmodified copy of, or
    None if computed/renamed-over/opaque (the verifier's twin of the
    fragmenter's ``_trace_source_col``, driven by lint_info)."""
    cur = name
    for ex in reversed(list(chain_prefix)):
        info = _info_of(ex)
        if info is None:
            return None
        emits = info.get("emits")
        if emits is not None:
            src = (info.get("renames") or {}).get(cur)
            if src is None:
                return None
            cur = src
            continue
        if cur in (info.get("adds") or {}):
            return None  # computed in this executor
    return cur


def _join_info(
    join, rep: Optional[LintReport] = None, fragment: str = ""
) -> Optional[dict]:
    return _info_of(
        join, rep, fragment, f"join:{type(join).__name__}"
    )


def _verify_join(
    join,
    lschema: Schema,
    rschema: Schema,
    lwm: Optional[Set[str]],
    rwm: Optional[Set[str]],
    fragment: str,
    rep: LintReport,
    tids: _TableIds,
    instance: int = 0,
) -> Tuple[Schema, Optional[Set[str]]]:
    info = _join_info(join, rep, fragment)
    prov = f"join:{type(join).__name__}"
    if info is None:
        tid = getattr(join, "table_id", None)
        tids.add(instance, (tid,) if tid else (), fragment, prov)
        return None, None
    tids.add(instance, info.get("table_ids", ()), fragment, prov)
    _check_ledger_visible(join, info, fragment, prov, rep)
    _check_digest_coverage(join, info, fragment, prov, rep)
    lkeys = tuple(info.get("left_keys") or ())
    rkeys = tuple(info.get("right_keys") or ())
    for side, schema, expects in (
        ("left", lschema, info.get("expects_left") or {}),
        ("right", rschema, info.get("expects_right") or {}),
    ):
        if schema is None:
            continue
        for col, want in expects.items():
            if col not in schema:
                rep.add(
                    "RW-E101",
                    f"join {side} input lacks column {col!r} "
                    f"(channel carries {sorted(schema)})",
                    fragment=fragment,
                    executor=prov,
                )
            else:
                want, have = _dt(want), _dt(schema[col])
                if want is not None and have is not None and want != have:
                    rep.add(
                        "RW-E102",
                        f"join {side} column {col!r} arrives as {have} "
                        f"but the join declared {want}",
                        fragment=fragment,
                        executor=prov,
                    )
    # per-position key dtype agreement across sides (RW-E204)
    el = info.get("expects_left") or {}
    er = info.get("expects_right") or {}
    for pos, (lk, rk) in enumerate(zip(lkeys, rkeys)):
        ld, rd = _dt(el.get(lk)), _dt(er.get(rk))
        if ld is not None and rd is not None and ld != rd:
            rep.add(
                "RW-E204",
                f"join key position {pos}: left {lk!r} is {ld} but "
                f"right {rk!r} is {rd} — equal keys would hash apart",
                fragment=fragment,
                executor=prov,
            )
    # window-column watermark reachability per side (RW-E501)
    wcols = info.get("window_cols")
    if wcols:
        for col, wm, side in ((wcols[0], lwm, "left"), (wcols[1], rwm, "right")):
            if wm is not None and col not in wm:
                rep.add(
                    "RW-E501",
                    f"join {side} window column {col!r} is not "
                    "watermark-reachable — join state would grow "
                    "without bound",
                    fragment=fragment,
                    executor=prov,
                )
    emits = info.get("emits")
    schema = {k: _dt(v) for k, v in emits.items()} if emits is not None else None
    wm_out: Optional[Set[str]] = None
    if schema is not None and lwm is not None and rwm is not None:
        wm_out = (set(lwm) | set(rwm)) & set(schema)
    return schema, wm_out


# ---------------------------------------------------------------------------
# pipeline-level entry points
# ---------------------------------------------------------------------------


def _first_keyed(chain: Sequence[object]):
    """(index, keys) of the first executor exposing state partition
    keys, or None."""
    for j, ex in enumerate(chain):
        info = _info_of(ex)
        if info is None:
            return None
        if info.get("keys"):
            return j, tuple(info["keys"])
    return None


def verify_serial_pipeline(
    pipeline, source_schemas: Dict[str, Schema], name: str, rep: LintReport
) -> None:
    tids = _TableIds(rep)
    if hasattr(pipeline, "join") and hasattr(pipeline, "left"):
        ls = source_schemas.get("left")
        rs = source_schemas.get("right")
        lschema, lwm = _walk_chain(
            pipeline.left, ls, set(ls) if ls else None, name, rep, tids
        )
        rschema, rwm = _walk_chain(
            pipeline.right, rs, set(rs) if rs else None, name, rep, tids
        )
        schema, wm = _verify_join(
            pipeline.join, lschema, rschema, lwm, rwm, name, rep, tids
        )
        _walk_chain(pipeline.tail, schema, wm, name, rep, tids)
        return
    if hasattr(pipeline, "executors"):
        ss = source_schemas.get("single")
        _walk_chain(
            pipeline.executors, ss, set(ss) if ss else None, name, rep, tids
        )


def verify_graph_specs(
    specs: Sequence[object],
    out_fragment: str,
    source_fragments: Dict[str, str],  # side -> fragment name
    source_schemas: Dict[str, Schema],  # side -> schema
    rep: LintReport,
    ckpt_executors: Optional[Sequence[object]] = None,
) -> None:
    """Fragment-DAG verification: wiring, acyclicity, exchange key
    alignment, then per-fragment chain walks in topological order.
    With ``ckpt_executors`` (the pipeline's checkpoint registry), also
    checks every fragment's rebuildable boundary (RW-E606)."""
    by_name: Dict[str, object] = {}
    for s in specs:
        if s.name in by_name:
            rep.add(
                "RW-E602",
                f"fragment name {s.name!r} declared twice",
                fragment=s.name,
            )
        by_name[s.name] = s

    # -- wiring ----------------------------------------------------------
    ok_edges: Dict[str, List[Tuple[str, int]]] = {s.name: [] for s in specs}
    consumed: Set[str] = set()
    for s in specs:
        seen: Set[Tuple[str, int]] = set()
        for up, port in s.inputs:
            if up not in by_name:
                rep.add(
                    "RW-E601",
                    f"input channel references unknown fragment {up!r}",
                    fragment=s.name,
                )
                continue
            if (up, port) in seen:
                rep.add(
                    "RW-E602",
                    f"duplicate channel from {up!r} port {port} — the "
                    "consumer would collect every barrier twice",
                    fragment=s.name,
                )
                continue
            seen.add((up, port))
            ok_edges[s.name].append((up, port))
            consumed.add(up)
    for side, frag in source_fragments.items():
        if frag not in by_name:
            rep.add(
                "RW-E605",
                f"declared source fragment {frag!r} (side {side!r}) "
                "does not exist",
                fragment=frag,
            )
    if out_fragment not in by_name:
        rep.add(
            "RW-E605",
            f"declared output fragment {out_fragment!r} does not exist",
            fragment=out_fragment,
        )
    for s in specs:
        if s.name != out_fragment and s.name not in consumed:
            rep.add(
                "RW-E604",
                f"fragment {s.name!r} output is never consumed "
                "(not an input of any fragment, not the output fragment)",
                fragment=s.name,
            )

    # -- acyclicity (Kahn) ----------------------------------------------
    indeg = {s.name: len(ok_edges[s.name]) for s in specs}
    downstream: Dict[str, List[str]] = {s.name: [] for s in specs}
    for s in specs:
        for up, _port in ok_edges[s.name]:
            downstream[up].append(s.name)
    order: List[str] = [n for n, d in indeg.items() if d == 0]
    topo: List[str] = []
    while order:
        n = order.pop()
        topo.append(n)
        for d in downstream[n]:
            indeg[d] -= 1
            if indeg[d] == 0:
                order.append(d)
    if len(topo) < len(by_name):
        cyc = sorted(set(by_name) - set(topo))
        rep.add(
            "RW-E603",
            f"fragment graph contains a cycle through {cyc} — a barrier "
            "injected at the sources can never align",
            fragment=",".join(cyc),
        )
        return  # schema walk needs a topological order

    # -- per-fragment builds + schema walk in topo order -----------------
    tids = _TableIds(rep)
    frag_side = {frag: side for side, frag in source_fragments.items()}
    out_schema: Dict[str, Schema] = {}
    out_wm: Dict[str, Optional[Set[str]]] = {}
    builds: Dict[str, object] = {}
    for name in topo:
        s = by_name[name]
        try:
            # shadow build ONLY to read lint_info (the live actors hold
            # their own, possibly epoch-batch-fused, executors) — pin
            # its state allocations to host CPU so DDL-time lint never
            # transiently doubles HBM state on a device session
            with _host_device():
                built = s.build(0)
        except Exception:  # noqa: BLE001 — builder needs live inputs
            built = None
        builds[name] = built
        # input schema per port: merge upstream outputs (dtype conflicts
        # degrade to unknown rather than guessing)
        port_schema: Dict[int, Schema] = {}
        port_wm: Dict[int, Optional[Set[str]]] = {}
        if not s.inputs:
            side = frag_side.get(name)
            sch = source_schemas.get(side) if side is not None else None
            port_schema[0] = dict(sch) if sch is not None else None
            port_wm[0] = set(sch) if sch is not None else None
        for up, port in ok_edges[name]:
            upsch = out_schema.get(up)
            upwm = out_wm.get(up)
            if port not in port_schema:
                port_schema[port] = (
                    dict(upsch) if upsch is not None else None
                )
                port_wm[port] = set(upwm) if upwm is not None else None
            else:
                cur = port_schema[port]
                if cur is None or upsch is None:
                    port_schema[port] = None
                    port_wm[port] = None
                else:
                    for k, v in upsch.items():
                        if k in cur and _dt(cur[k]) != _dt(v):
                            cur[k] = None
                        else:
                            cur.setdefault(k, v)
                    if port_wm[port] is not None and upwm is not None:
                        port_wm[port] = port_wm[port] & upwm
                    else:
                        port_wm[port] = None
        if isinstance(built, dict):
            lschema, lwm = _walk_chain(
                built.get("left", []),
                port_schema.get(0),
                port_wm.get(0),
                name,
                rep,
                tids,
            )
            rschema, rwm = _walk_chain(
                built.get("right", []),
                port_schema.get(1),
                port_wm.get(1),
                name,
                rep,
                tids,
            )
            schema, wm = _verify_join(
                built["join"], lschema, rschema, lwm, rwm, name, rep, tids
            )
            schema, wm = _walk_chain(
                built.get("tail", []), schema, wm, name, rep, tids
            )
        elif isinstance(built, (list, tuple)):
            schema, wm = _walk_chain(
                list(built),
                port_schema.get(0),
                port_wm.get(0),
                name,
                rep,
                tids,
            )
        else:
            schema, wm = None, None
        out_schema[name] = schema
        out_wm[name] = wm

    # -- exchange key alignment ------------------------------------------
    for name in topo:
        s = by_name[name]
        kind = s.dispatch
        keys: Sequence[str] = ()
        if isinstance(kind, tuple):
            kind, keys = kind[0], tuple(kind[1] or ())
        upsch = out_schema.get(name)
        if kind == "hash" and upsch is not None:
            for k in keys:
                if k not in upsch:
                    rep.add(
                        "RW-E201",
                        f"hash-dispatch key {k!r} is not in the "
                        f"fragment's output (carries {sorted(upsch)})",
                        fragment=name,
                    )
        for down in downstream[name]:
            d = by_name[down]
            if d.parallelism <= 1:
                continue
            built = builds.get(down)
            port_of = dict((up, p) for up, p in ok_edges[down])
            port = port_of.get(name, 0)
            if isinstance(built, dict):
                chain = built.get("left" if port == 0 else "right", [])
                jinfo = _join_info(built.get("join"))
                state_keys = (
                    tuple(
                        (jinfo.get("left_keys") if port == 0 else jinfo.get("right_keys"))
                        or ()
                    )
                    if jinfo is not None
                    else None
                )
                prefix = chain
                prov = f"join:{type(built.get('join')).__name__}"
            elif isinstance(built, (list, tuple)):
                fk = _first_keyed(list(built))
                if fk is None:
                    state_keys = None
                    prefix, prov = [], ""
                else:
                    j, state_keys = fk
                    prefix = list(built)[:j]
                    prov = _prov(j, list(built)[j])
            else:
                continue
            if state_keys is None:
                continue  # no keyed state visible — nothing to misroute
            if kind in ("round_robin", "broadcast"):
                rep.add(
                    "RW-E203",
                    f"{kind} dispatch feeds parallel fragment {down!r} "
                    "which holds keyed state — rows of one key would "
                    "land on several instances",
                    fragment=name,
                    executor=prov,
                )
                continue
            if kind != "hash":
                continue
            traced = {}
            for k in state_keys:
                src = _trace_back(prefix, k)
                if src is not None:
                    traced[src] = k
            for dcol in keys:
                if dcol not in traced:
                    rep.add(
                        "RW-E202",
                        f"dispatch key {dcol!r} does not map to any "
                        f"state key of parallel fragment {down!r} "
                        f"(state keys {list(state_keys)}) — equal-key "
                        "rows could land on different instances",
                        fragment=name,
                        executor=prov,
                    )

    # -- rebuildable boundary per fragment (RW-E606) ----------------------
    if ckpt_executors is not None:
        _check_rebuildable(topo, builds, ckpt_executors, rep)


def _check_rebuildable(
    topo: Sequence[str],
    builds: Dict[str, object],
    ckpt_executors: Sequence[object],
    rep: LintReport,
) -> None:
    """RW-E606: every stateful executor a fragment builds must be
    restorable through the pipeline's checkpoint registry (same
    table_id, with a real ``restore_state``), or a partial recovery of
    that fragment cannot rebuild its state — the plan would only ever
    recover stop-the-world, silently. Flagged at DDL time."""
    from risingwave_tpu.storage.state_table import Checkpointable

    def _tids(ex) -> Tuple[str, ...]:
        fn = getattr(ex, "checkpoint_table_ids", None)
        if fn is None:
            return ()
        try:
            return tuple(fn())
        except Exception:  # noqa: BLE001 — lint must never crash DDL
            return ()

    restorable: Set[str] = set()
    for ex in ckpt_executors:
        if not isinstance(ex, Checkpointable):
            continue
        if type(ex).restore_state is Checkpointable.restore_state:
            rep.add(
                "RW-E606",
                f"checkpoint registry entry {type(ex).__name__} "
                f"(tables {list(_tids(ex))}) does not implement "
                "restore_state — its state checkpoints but can never "
                "be restored",
                executor=type(ex).__name__,
            )
            continue
        restorable |= set(_tids(ex))

    for name in topo:
        built = builds.get(name)
        if built is None:
            continue  # builder needs live inputs: nothing provable
        if isinstance(built, dict):
            chains = (
                list(built.get("left", ()))
                + list(built.get("right", ()))
                + ([built["join"]] if built.get("join") is not None else [])
                + list(built.get("tail", ()))
            )
        else:
            chains = list(built)
        for idx, ex in enumerate(chains):
            if not isinstance(ex, Checkpointable):
                continue
            missing = [t for t in _tids(ex) if t not in restorable]
            if missing:
                rep.add(
                    "RW-E606",
                    f"stateful executor's tables {missing} are not "
                    "covered by the pipeline's checkpoint registry — "
                    f"fragment {name!r} has no rebuildable boundary "
                    "(partial recovery cannot restore it)",
                    fragment=name,
                    executor=_prov(idx, ex),
                )


def verify_planned(
    planned,
    catalog=None,
    source_schemas: Optional[Dict[str, Schema]] = None,
) -> List[Diagnostic]:
    """Verify one PlannedMV (serial or graph pipeline). Source schemas
    come from the catalog via ``planned.inputs`` unless given."""
    rep = LintReport()
    name = getattr(planned, "name", "mv")
    pipeline = getattr(planned, "pipeline", planned)
    if source_schemas is None:
        source_schemas = {}
        if catalog is not None:
            for src, side in (getattr(planned, "inputs", None) or {}).items():
                if src not in getattr(catalog, "tables", {}):
                    continue
                sch = catalog.schema_dtypes(src)
                sides = ("left", "right") if side == "both" else (side,)
                for s in sides:
                    source_schemas[s] = dict(sch)
    if hasattr(pipeline, "_specs") and hasattr(pipeline, "graph"):
        verify_graph_specs(
            pipeline._specs,
            pipeline._out,
            dict(pipeline._sources),
            {
                side: source_schemas.get(side)
                for side in pipeline._sources
            },
            rep,
            # the checkpoint registry, when the pipeline exposes one
            # (GraphPipeline does; spec-level stubs don't) — drives the
            # RW-E606 rebuildable-boundary check
            ckpt_executors=getattr(pipeline, "_executors", None),
        )
    else:
        verify_serial_pipeline(pipeline, source_schemas, name, rep)
    return rep.diagnostics
