"""rwlint entry points: the CREATE-MV hook, pipeline linting for
hand-built plans (bench / tests), SQL-file linting, and the CLI driver
behind ``python -m risingwave_tpu lint``.

Cost contract: ``lint_planned`` is pure host-side metadata walking —
no tracing, no XLA — so the DDL path stays O(plan size), well under
the 50ms/query budget (PROFILE.md has measured numbers). The deep
sanitizer (``--deep``) traces jaxprs and is CLI/test-only.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from risingwave_tpu.analysis.diagnostics import Diagnostic, PlanLintError
from risingwave_tpu.analysis.plan_verifier import verify_planned


def _record(name: str, diags: List[Diagnostic], elapsed_ms: float) -> None:
    from risingwave_tpu.metrics import REGISTRY

    REGISTRY.histogram("lint_ms").observe(elapsed_ms)
    for d in diags:
        REGISTRY.counter("lint_diagnostics_total").inc(code=d.code)
    errors = [d for d in diags if d.severity == "error"]
    if errors:
        from risingwave_tpu.event_log import EVENT_LOG

        EVENT_LOG.record(
            "lint",
            relation=name,
            errors=len(errors),
            codes=",".join(sorted({d.code for d in errors})),
        )


def lint_planned(
    planned,
    catalog=None,
    source_schemas: Optional[Dict[str, dict]] = None,
    strict: bool = True,
) -> List[Diagnostic]:
    """Verify one PlannedMV; with ``strict``, error findings refuse the
    DDL via PlanLintError. Always records metrics + event log."""
    t0 = time.perf_counter()
    diags = verify_planned(planned, catalog=catalog, source_schemas=source_schemas)
    name = getattr(planned, "name", "mv")
    _record(name, diags, (time.perf_counter() - t0) * 1e3)
    errors = [d for d in diags if d.severity == "error"]
    if strict and errors:
        raise PlanLintError(errors, name=name)
    return diags


def lint_pipeline(
    pipeline,
    source_schemas: Optional[Dict[str, dict]] = None,
    name: str = "mv",
    strict: bool = True,
) -> List[Diagnostic]:
    """Lint a hand-built Pipeline / TwoInputPipeline / GraphPipeline
    (the bench and Python-API surface). ``source_schemas`` maps input
    side ("single"/"left"/"right") -> {col: dtype}."""

    class _Shim:
        pass

    shim = _Shim()
    shim.name = name
    shim.pipeline = pipeline
    shim.inputs = {}
    return lint_planned(
        shim, source_schemas=source_schemas or {}, strict=strict
    )


# ---------------------------------------------------------------------------
# built-in Nexmark query corpus
# ---------------------------------------------------------------------------

_I64 = "int64"
_I32 = "int32"

NEXMARK_SOURCE_SCHEMAS = {
    "q5": {"single": {"auction": _I64, "date_time": _I64}},
    "q7": {
        side: {
            "auction": _I64,
            "bidder": _I64,
            "price": _I64,
            "date_time": _I64,
        }
        for side in ("left", "right")
    },
    "q8": {
        "left": {"id": _I64, "name": _I32, "date_time": _I64},
        "right": {"seller": _I64, "date_time": _I64},
    },
}


def build_nexmark_corpus(capacity: int = 1 << 10, only: str = None):
    """Small-capacity twins of the built-in Nexmark plans — the lint
    corpus shared by ``lint --all-nexmark``, bench's pre-run gate, and
    the test suite (the verifier is static: plan shape is all that
    matters, so tiny capacities keep it fast). ``only`` selects one
    query; unknown names yield {}."""
    from risingwave_tpu.queries.nexmark_q import (
        build_q5_lite,
        build_q7,
        build_q8,
    )

    from risingwave_tpu.analysis.plan_verifier import _host_device

    builders = {
        "q5": lambda: build_q5_lite(capacity=capacity),
        "q7": lambda: build_q7(
            capacity=capacity,
            agg_capacity=capacity,
            filter_capacity=capacity,
            out_cap=capacity,
        ),
        "q8": lambda: build_q8(capacity=capacity, out_cap=capacity),
    }
    names = (only,) if only is not None else tuple(builders)
    # lint-only twins: pin their state allocations to host CPU so a
    # pre-bench gate on a TPU session never transiently touches HBM
    with _host_device():
        return {n: builders[n]() for n in names if n in builders}


def lint_all_nexmark(
    deep: bool = False, strict: bool = False
) -> Dict[str, List[Diagnostic]]:
    """Lint every built-in Nexmark query pipeline. With ``deep``, also
    run the jaxpr sanitizer over each pipeline's executors and the
    shared hash kernels."""
    out: Dict[str, List[Diagnostic]] = {}
    built = build_nexmark_corpus()
    for qname, q in built.items():
        out[qname] = lint_pipeline(
            q.pipeline,
            NEXMARK_SOURCE_SCHEMAS[qname],
            name=qname,
            strict=strict,
        )
    if deep:
        from risingwave_tpu.analysis.jax_sanitizer import (
            sanitize_executors,
            sanitize_hash_kernels,
            sanitize_state_kernels,
        )

        for qname, q in built.items():
            out[qname] = out[qname] + sanitize_executors(
                q.pipeline.executors
            )
        out["hash_kernels"] = sanitize_hash_kernels()
        out["state_kernels"] = sanitize_state_kernels()
    return out


def lint_sql_file(path: str) -> Dict[str, List[Diagnostic]]:
    """Execute a SQL file's DDL through an in-memory session (no object
    store, serial mode) and collect the lint findings of every CREATE
    MATERIALIZED VIEW. Statements split on ';' with `--` comment LINES
    stripped — this is not a SQL lexer: dollar-quoted UDF bodies with
    semicolons, and string literals spanning lines where a continuation
    line starts with `--`, are not supported here."""
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.runtime import StreamingRuntime
    from risingwave_tpu.sql import Catalog

    session = SqlSession(
        Catalog({}), StreamingRuntime(store=None), strict_lint=False
    )
    with open(path) as f:
        text = f.read()
    # strip whole `--` comment LINES before splitting on ';' (not
    # trailing comments: `--` may legally appear inside a string
    # literal): a comment must neither swallow the statement sharing
    # its segment nor split one at a ';' inside the comment text
    text = "\n".join(
        ln
        for ln in text.splitlines()
        if not ln.lstrip().startswith("--")
    )
    findings: Dict[str, List[Diagnostic]] = {}
    for raw in text.split(";"):
        # re-strip per segment: a trailing same-line comment
        # ("stmt; -- note") survives the pre-strip and becomes a
        # comment-only residual segment after the split
        stmt = "\n".join(
            ln
            for ln in raw.splitlines()
            if not ln.lstrip().startswith("--")
        ).strip()
        if not stmt:
            continue
        # lint runs DDL only: catalog-shaping statements feed the
        # verifier; DML/queries (bulk INSERT seeds, smoke SELECTs)
        # would do real work and abort the lint on unrelated failures
        if stmt.split(None, 1)[0].upper() not in (
            "CREATE",
            "DROP",
            "ALTER",
            "SET",
        ):
            continue
        before = len(session.lint_findings)
        session.execute(stmt)
        for name, d in session.lint_findings[before:]:
            findings.setdefault(name, []).append(d)
    return findings


# ---------------------------------------------------------------------------
# fusion-feasibility surface (analysis/fusion_analyzer.py)
# ---------------------------------------------------------------------------


def fusion_findings_for_ddl(planned) -> List[Diagnostic]:
    """The CREATE-MV fusion hook: SHALLOW analysis (trace contracts +
    host-sync AST scan, no jaxpr tracing — stays inside the DDL lint
    budget) filtered to the strict-relevant hazard classes: RW-E803
    (unbucketed shape-polymorphic window — the class that wedges real
    TPUs; ROADMAP item 2) and RW-E806 (a declared window_buckets
    lattice the bucketing layer cannot satisfy — the proof is
    vacuous). Full reports are a CLI/CI surface
    (``lint --fusion-report``).

    Graph pipelines are analyzed through their LIVE checkpoint
    registry (every stateful — hence every window-keyed — executor is
    in it) instead of re-shadow-building each fragment spec: the plan
    verifier already paid for one shadow build this DDL; a second one
    per CREATE MV would double the lint cost for nothing E803 needs."""
    from risingwave_tpu.analysis.fusion_analyzer import (
        analyze_chain,
        analyze_planned,
    )

    pipeline = getattr(planned, "pipeline", planned)
    name = getattr(planned, "name", "mv")
    if hasattr(pipeline, "_specs") and hasattr(pipeline, "graph"):
        # a parallel plan's registry holds PartitionedStateViews —
        # analyze one underlying instance (identical plan shape across
        # instances, so one carries the whole contract)
        chain = [
            getattr(e, "_instances", [e])[0]
            for e in getattr(pipeline, "_executors", ())
        ]
        reports = [
            analyze_chain(chain, None, f"{name}:ckpt", deep=False)
        ]
    else:
        reports = analyze_planned(planned, deep=False)
    out: List[Diagnostic] = []
    for rep in reports:
        out.extend(
            d
            for d in rep.diagnostics
            if d.code in ("RW-E803", "RW-E806")
        )
    return out


def _committed_profile() -> Optional[dict]:
    """The committed BENCH artifact's profiler blocks, when present —
    ranks fusion blockers by measured dispatch-wall cost."""
    import json
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        "BENCH_partial.json",
    )
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def run_fusion_report() -> dict:
    """``lint --fusion-report --all-nexmark``: per-query fusion
    reports, blockers ranked by the committed profile when one
    exists."""
    from risingwave_tpu.analysis.fusion_analyzer import analyze_nexmark

    return analyze_nexmark(deep=True, profile_bench=_committed_profile())


# ---------------------------------------------------------------------------
# mesh-readiness surface (analysis/mesh_analyzer.py)
# ---------------------------------------------------------------------------

# the sharded corpus plans REAL SQL through the planner and shards it
# (runtime.fragmenter.sharded_planned_mv) — the same q5/q7/q8 shapes
# the sharded-equivalence tests and the multichip dry-runs exercise
NEXMARK_SHARDED_SQL = {
    "q5": (
        "CREATE MATERIALIZED VIEW q5 AS "
        "SELECT auction, window_start, count(*) AS num "
        "FROM HOP(bid, date_time, INTERVAL '2' SECOND, "
        "INTERVAL '10' SECOND) "
        "GROUP BY auction, window_start"
    ),
    "q7": (
        "CREATE MATERIALIZED VIEW q7 AS "
        "SELECT b.auction, b.bidder, b.price, b.wstart "
        "FROM (SELECT auction, bidder, price, window_start AS wstart "
        "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)) AS b "
        "JOIN (SELECT max(price) AS maxprice, window_start AS mwstart "
        "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
        "GROUP BY window_start) AS m "
        "ON b.wstart = m.mwstart AND b.price = m.maxprice"
    ),
    "q8": (
        "CREATE MATERIALIZED VIEW q8 AS "
        "SELECT p.id, p.name, p.starttime "
        "FROM (SELECT id, name, window_start AS starttime "
        "FROM TUMBLE(person, date_time, INTERVAL '10' SECOND) "
        "GROUP BY id, name, window_start) AS p "
        "JOIN (SELECT seller, window_start AS astarttime "
        "FROM TUMBLE(auction, date_time, INTERVAL '10' SECOND) "
        "GROUP BY seller, window_start) AS a "
        "ON p.id = a.seller AND p.starttime = a.astarttime"
    ),
}


def build_sharded_nexmark_corpus(
    n_shards: int = 8, capacity: int = 1 << 11, only: str = None
):
    """The SHARDED Nexmark corpus: q5/q7/q8 planned from SQL and run
    through the mesh sharding pass over an ``n_shards``-device mesh —
    the mesh analyzer's acceptance corpus. Requires that many devices
    (the CLI path arranges the 8-virtual-device sim mesh before any
    backend init; tests get it from conftest's XLA_FLAGS). Small
    capacities: the analysis is static, plan shape is all that
    matters. Callers own ``pipeline.close()`` (graph actors spawn at
    plan time)."""
    from risingwave_tpu.connectors.nexmark import (
        AUCTION_SCHEMA,
        BID_SCHEMA,
        PERSON_SCHEMA,
    )
    from risingwave_tpu.runtime.fragmenter import sharded_planned_mv
    from risingwave_tpu.sql import Catalog
    from risingwave_tpu.sql.planner import StreamPlanner

    catalog = Catalog(
        {
            "bid": BID_SCHEMA,
            "person": PERSON_SCHEMA,
            "auction": AUCTION_SCHEMA,
        }
    )

    def factory():
        return StreamPlanner(catalog, capacity=capacity)

    names = (only,) if only is not None else tuple(NEXMARK_SHARDED_SQL)
    return {
        n: sharded_planned_mv(factory, NEXMARK_SHARDED_SQL[n], n_shards)
        for n in names
        if n in NEXMARK_SHARDED_SQL
    }


def _committed_multichip() -> Optional[dict]:
    """The committed multichip dry-run artifact (PR 18's meshprof
    matrix + phase splits), when present — ranks mesh blockers by
    measured exchange-boundary cost."""
    import json
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        "MULTICHIP.json",
    )
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def run_mesh_report(n_shards: int = 8) -> dict:
    """``lint --mesh-report``: per-query mesh-readiness reports over
    the sharded corpus, blockers ranked by MULTICHIP.json's measured
    phase splits. The caller must have arranged >= n_shards devices
    (``mesh_domain.ensure_virtual_devices``)."""
    from risingwave_tpu.analysis.mesh_analyzer import (
        analyze_sharded_nexmark,
    )

    return analyze_sharded_nexmark(
        deep=True, multichip=_committed_multichip(), n_shards=n_shards
    )


def mesh_findings_for_ddl(planned) -> List[Diagnostic]:
    """The CREATE-MV mesh hook: SHALLOW analysis (mesh contracts + the
    memoized loop-classified host-routing scan — no tracing, no mesh,
    no devices) of any plan that actually contains mesh-resident
    executors. Plans with none (every serial/graph plan a session
    builds today) cost one O(executors) scan and return [] — the DDL
    budget is untouched. Findings are report-only by default
    (warnings); RW_STRICT_MESH=1 upgrades them to refusals in the
    session hook."""
    from risingwave_tpu.analysis.mesh_analyzer import (
        analyze_sharded_pipeline,
    )
    from risingwave_tpu.runtime.fragmenter import (
        is_mesh_boundary,
        is_mesh_executor,
    )

    pipeline = getattr(planned, "pipeline", planned)
    name = getattr(planned, "name", "mv")
    exs = list(getattr(pipeline, "executors", ()) or ())
    # cheap gate BEFORE the fragment shadow-build: a plan with no mesh
    # executor anywhere cannot have sharded fragments
    if not any(
        is_mesh_executor(e) or is_mesh_boundary(e) for e in exs
    ):
        return []
    out: List[Diagnostic] = []
    for rep in analyze_sharded_pipeline(pipeline, name=name, deep=False):
        for b in rep.blockers:
            out.append(
                Diagnostic(
                    code=b.code,
                    message=f"{b.message} at {b.file}:{b.line}",
                    fragment=rep.fragment,
                    executor=b.executor,
                    severity="warning",
                )
            )
    return out


# ---------------------------------------------------------------------------
# CLI driver (python -m risingwave_tpu lint ...)
# ---------------------------------------------------------------------------


def run_cli(args) -> int:
    """Returns the process exit code: 0 = no error findings."""
    import json as _json

    if getattr(args, "sharing_report", False):
        # the sharing report is its own corpus analysis (it builds the
        # SQL-planned q5u twin next to the hand-built queries) — run it
        # standalone so CI can consume one clean JSON document
        from risingwave_tpu.analysis.sharing import run_sharing_report

        rep = run_sharing_report()
        if args.json:
            print(_json.dumps(rep, default=str))
        else:
            s = rep["summary"]
            print(
                f"sharing: {s['plans']} plan(s), {s['state_tables']} "
                f"keyed state table(s), {s['exact_shareable_groups']} "
                f"exact-shareable group(s), {s['index_opportunities']} "
                f"index opportunity(ies), {s['lattice_mismatches']} "
                "lattice mismatch(es)"
            )
            for t in rep["tables"]:
                print(
                    f"  {t['plan']}:{t['table_id']} [{t['executor']}] "
                    f"keys={t['keys']} index={t['index_fingerprint']} "
                    f"share={t['share_fingerprint']}"
                )
            for o in rep["opportunities"]:
                print(
                    f"  OPPORTUNITY keys={o['keys']}: "
                    f"{', '.join(o['tables'])}"
                )
            for d in rep["diagnostics"]:
                print(f"  {d['code']} [{d['severity']}] {d['message']}")
        # lattice mismatches are warnings (advisory), never exit-fatal
        return 0

    if getattr(args, "mesh_report", False):
        # the mesh report owns its mesh: it sets up the 8-virtual-
        # device sim mesh itself, BEFORE any jax backend init — and
        # refuses loudly (exit 2, the usage/input code) when some
        # earlier import already initialized jax with fewer devices,
        # because silently analyzing a 1-device "mesh" would mint
        # worthless proofs
        from risingwave_tpu.analysis.mesh_domain import (
            DEFAULT_MESH_SHARDS,
            MeshUnavailable,
            ensure_virtual_devices,
        )

        try:
            ensure_virtual_devices(DEFAULT_MESH_SHARDS)
        except MeshUnavailable as e:
            msg = str(e)
            print(
                _json.dumps({"error": msg})
                if args.json
                else f"rwlint: {msg}"
            )
            return 2
        rep = run_mesh_report(n_shards=DEFAULT_MESH_SHARDS)
        if args.json:
            print(_json.dumps(rep, default=str))
        else:
            for q in sorted(rep):
                if q.startswith("_") or q in ("ranking", "top_cost"):
                    continue
                s = rep[q]["summary"]
                print(
                    f"{q} mesh: {s['spmd_fusible_fragments']}/"
                    f"{s['fragments']} fragments SPMD-fusible, "
                    f"{s['host_routed_edges']} host-routed edge(s), "
                    f"blockers {s['blockers_by_code']}"
                )
            top = rep.get("top_cost") or {}
            print(
                f"top cost: phase={top.get('phase')} "
                f"est_ms={top.get('est_ms')} over "
                f"{top.get('blockers')} blocker(s)"
            )
            for r in (rep.get("ranking") or [])[:8]:
                est = r["est_exchange_ms"]
                print(
                    f"  #{r['rank']} {r['code']} [{r['query']} "
                    f"{r['fragment']} {r['executor']}] "
                    f"est={est if est is not None else '-'}ms "
                    f"{r['file']}:{r['line']}"
                )
        # the report is an inventory, not a gate: blockers are the
        # expected state until the collective-exchange arc lands —
        # perf_gate --mesh-static owns the ratchet
        return 0

    fusion_report = getattr(args, "fusion_report", False)
    if fusion_report and not (args.all_nexmark or args.paths):
        # a bare --fusion-report means "the built-in corpus"
        args.all_nexmark = True
    if fusion_report and not args.all_nexmark:
        # never silently drop the flag: SQL-file fusion analysis is
        # not a surface (the DDL hook covers planned MVs) — exit 2 so
        # CI cannot mistake "no fusion section" for "no blockers"
        msg = (
            "--fusion-report analyzes the built-in corpus: add "
            "--all-nexmark (SQL files get fusion findings through "
            "the CREATE-MV lint hook, not this flag)"
        )
        print(_json.dumps({"error": msg}) if args.json else f"rwlint: {msg}")
        return 2
    if not args.all_nexmark and not args.paths:
        # exit-code contract: 2 = usage/input (CI tells this apart
        # from 1 = lint errors), never an interpreter traceback — and
        # --json consumers get JSON on EVERY exit path
        msg = "nothing to lint: pass SQL files and/or --all-nexmark"
        print(_json.dumps({"error": msg}) if args.json else f"rwlint: {msg}")
        return 2

    findings: Dict[str, List[Diagnostic]] = {}
    usage_errors: List[str] = []
    if args.all_nexmark:
        for name, diags in lint_all_nexmark(deep=args.deep).items():
            findings.setdefault(name, []).extend(diags)
    for path in args.paths:
        try:
            per_file = lint_sql_file(path)
        except OSError as e:
            # keep going: findings already collected for other targets
            # must still be reported, not dropped on a later bad path
            usage_errors.append(f"cannot read {path}: {e}")
            continue
        except Exception as e:  # noqa: BLE001 — bad SQL in the file
            usage_errors.append(f"{path}: {type(e).__name__}: {e}")
            continue
        for name, diags in per_file.items():
            findings.setdefault(f"{path}:{name}", []).extend(diags)
    fusion: Optional[Dict[str, dict]] = None
    if fusion_report and args.all_nexmark:
        fusion = run_fusion_report()
    n_err = 0
    if args.json:
        out = {
            name: [
                {
                    "code": d.code,
                    "severity": d.severity,
                    "fragment": d.fragment,
                    "executor": d.executor,
                    "message": d.message,
                }
                for d in diags
            ]
            for name, diags in findings.items()
        }
        if usage_errors:
            out["__errors__"] = usage_errors
        if fusion is not None:
            out["__fusion__"] = fusion
        print(_json.dumps(out))
        n_err = sum(
            1
            for diags in findings.values()
            for d in diags
            if d.severity == "error"
        )
    else:
        for name in sorted(findings):
            diags = findings[name]
            errs = [d for d in diags if d.severity == "error"]
            n_err += len(errs)
            status = "FAIL" if errs else ("warn" if diags else "ok")
            print(f"{name}: {status}")
            for d in diags:
                print(f"  {d.render()}")
        if fusion is not None:
            for q in sorted(fusion):
                if q.startswith("_"):
                    continue  # _provenance and friends: not a query
                s = fusion[q]["summary"]
                print(
                    f"{q} fusion: {s['fusible_fragments']}/"
                    f"{s['fragments']} fragments fusible, prefix "
                    f"{s['fusible_prefix_total']}/{s['chain_len_total']}"
                    f" executors, {s['host_sync_points']} host-sync "
                    f"point(s), blockers {s['blockers_by_code']}"
                )
                for fr in fusion[q]["fragments"]:
                    for b in fr["blockers"]:
                        print(
                            f"  {b['code']} [frag={fr['fragment']} "
                            f"ex={b['executor']}] {b['message']}"
                        )
        total = len(findings)
        for msg in usage_errors:
            print(f"rwlint: {msg}")
        print(
            f"rwlint: {total} target(s), {n_err} error(s), "
            f"{sum(len(v) for v in findings.values()) - n_err} warning(s)"
        )
    # usage/input problems dominate lint findings in the exit code so
    # CI never mistakes a half-linted run for a clean (or merely
    # finding-bearing) one
    if usage_errors:
        return 2
    return 1 if n_err else 0
