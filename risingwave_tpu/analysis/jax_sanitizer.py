"""Part B — the JAX compilation sanitizer.

Four independent hygiene checks over the compiled/compilable surface of
a plan (none of them run XLA — tracing and lowering only):

- ``check_promotions``: trace a step function and flag implicit
  32->64-bit ``convert_element_type`` eqns (RW-E301). On TPU an
  accidental f64/i64 lane doubles HBM traffic and can silently fall
  off the fast paths.
- ``check_hash_path_32bit``: the hash chain must be pure 32-bit
  arithmetic — any 64-bit add/mul/shift/bitand inside it means the
  result depends on ``jax_enable_x64`` / platform promotion rules
  (RW-E302). 64-bit inputs may only enter via ``bitcast_convert_type``
  into uint32 lanes.
- ``check_donation``: a state-carrying kernel lowered WITHOUT buffer
  donation holds two copies of its state alive per step (RW-E401).
- ``transfer_guard``: context manager arming ``jax.transfer_guard``
  around the per-barrier device step (RW_TRANSFER_GUARD env, default
  off; tests arm it) so implicit host<->device transfers raise at the
  exact step that issued them (RW-E402 is the lint-side code).

Plus the recompile instrumentation:

- ``RecompileWatch``: snapshots the jit-cache sizes of the registered
  step kernels; a steady-state delta is a recompile storm in the
  making. Deltas feed ``recompiles_total{fn=...}`` (metrics.py).
- ``SignatureWatch`` / ``SIGNATURES``: fingerprints each executor's
  abstract input signature (shapes+dtypes, the jit cache key's data
  part) per chunk; a NEW fingerprint after ``mark_stable()`` is a
  shape-unstable executor (RW-E403) — reported to metrics + event log.
"""

from __future__ import annotations

import contextlib
import os
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import jax

from risingwave_tpu.analysis.diagnostics import Diagnostic

_64BIT = ("int64", "uint64", "float64")
_32BIT = ("int32", "uint32", "float32")
# arithmetic primitives whose 64-bit output makes a hash value depend
# on jax_enable_x64 / platform promotion. bitcast_convert_type — the
# sanctioned way to split a 64-bit key into uint32 lanes — is not
# arithmetic, so it is never flagged.
_ARITH = {
    "add", "sub", "mul", "xor", "or", "and", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "rem", "div",
}


def _aval_dtype(v) -> Optional[str]:
    aval = getattr(v, "aval", None)
    dt = getattr(aval, "dtype", None)
    return str(dt) if dt is not None else None


def _scan_eqns(jaxpr, fn):
    """Depth-first over a (closed) jaxpr including sub-jaxprs (scan /
    while / cond bodies), calling ``fn(eqn)`` for every equation."""
    core = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in core.eqns:
        fn(eqn)
        for p in eqn.params.values():
            sub = getattr(p, "jaxpr", None)
            if sub is not None:
                _scan_eqns(p, fn)
            elif isinstance(p, (tuple, list)):
                for q in p:
                    if hasattr(q, "jaxpr"):
                        _scan_eqns(q, fn)


def check_promotions(
    fn: Callable, *example_args, name: str = "", **example_kwargs
) -> List[Diagnostic]:
    """RW-E301: implicit 32->64-bit widening inside a traced step."""
    name = name or getattr(fn, "__name__", repr(fn))
    jaxpr = jax.make_jaxpr(fn)(*example_args, **example_kwargs)
    out: List[Diagnostic] = []

    def visit(eqn):
        if eqn.primitive.name != "convert_element_type":
            return
        new = str(eqn.params.get("new_dtype", ""))
        if new not in _64BIT:
            return
        src = _aval_dtype(eqn.invars[0])
        if src in _32BIT:
            out.append(
                Diagnostic(
                    "RW-E301",
                    f"{name}: {src} -> {new} promotion inside the "
                    "compiled step (doubles lane width on device)",
                    executor=name,
                )
            )

    _scan_eqns(jaxpr, visit)
    return out


def check_hash_path_32bit(
    fn: Callable, *example_args, name: str = "", **example_kwargs
) -> List[Diagnostic]:
    """RW-E302: 64-bit arithmetic anywhere in a hash function's jaxpr.

    The contract (ops/hashing.py): 64-bit key columns are bit-split
    into uint32 lanes up front via bitcast; every mix/combine after
    that is uint32. Any 64-bit add/mul/shift/mask op means the hash
    value depends on the x64 flag / platform promotion — the exact
    class of bug where vnode routing diverges between hosts."""
    name = name or getattr(fn, "__name__", repr(fn))
    jaxpr = jax.make_jaxpr(fn)(*example_args, **example_kwargs)
    seen: Set[str] = set()
    out: List[Diagnostic] = []

    def visit(eqn):
        prim = eqn.primitive.name
        if prim not in _ARITH or prim in seen:
            return
        for v in eqn.outvars:
            if _aval_dtype(v) in _64BIT:
                seen.add(prim)
                out.append(
                    Diagnostic(
                        "RW-E302",
                        f"{name}: 64-bit {prim} in the hash path — "
                        "result depends on jax_enable_x64 / platform "
                        "promotion (split keys into uint32 lanes via "
                        "bitcast instead)",
                        executor=name,
                    )
                )
                return

    _scan_eqns(jaxpr, visit)
    return out


def check_donation(
    fn: Callable, *example_args, name: str = "", **example_kwargs
) -> List[Diagnostic]:
    """RW-E401: a jitted state kernel lowered without any donated
    buffer. ``example_args`` may be ``jax.ShapeDtypeStruct``s — the
    check lowers (no XLA compile, no allocation)."""
    name = name or getattr(fn, "__name__", repr(fn))
    lowered = fn.lower(*example_args, **example_kwargs)
    txt = lowered.as_text()
    if "jax.buffer_donor" in txt or "tf.aliasing_output" in txt:
        return []
    return [
        Diagnostic(
            "RW-E401",
            f"{name}: no donated buffers — every step holds two live "
            "copies of the carried state in HBM",
            executor=name,
        )
    ]


# ---------------------------------------------------------------------------
# transfer guard (RW-E402 at runtime)
# ---------------------------------------------------------------------------


def transfer_guard():
    """Context manager for the per-barrier device step: when
    ``RW_TRANSFER_GUARD`` is armed (tests set it to 1; opt out with 0),
    implicit host<->device transfers raise AT the offending step
    instead of silently serializing the pipeline. Explicit transfers
    (``jax.device_get`` — e.g. ops/hash_table.finish_scalars) stay
    legal. Off (no-op) unless armed: production serving may stream
    through host-map executors by design."""
    mode = os.environ.get("RW_TRANSFER_GUARD", "0").strip().lower()
    if mode in ("", "0", "off", "false", "allow"):
        return contextlib.nullcontext()
    if mode in ("1", "on", "true"):
        mode = "disallow"
    return jax.transfer_guard(mode)


# ---------------------------------------------------------------------------
# recompile instrumentation
# ---------------------------------------------------------------------------


def _default_kernels() -> List[Tuple[str, object]]:
    """The fused step kernels whose jit caches define 'the pipeline
    compiled once'. Missing attributes are skipped (refactor-proof)."""
    out: List[Tuple[str, object]] = []

    def grab(modname: str, attr: str) -> None:
        import importlib

        try:
            mod = importlib.import_module(modname)
        except ImportError:
            return
        fn = getattr(mod, attr, None)
        if fn is not None and hasattr(fn, "_cache_size"):
            out.append((attr.lstrip("_"), fn))

    grab("risingwave_tpu.executors.hash_agg", "_agg_step")
    grab("risingwave_tpu.executors.hash_agg", "_agg_step_mi")
    grab("risingwave_tpu.executors.hop_window", "_hop_step")
    grab("risingwave_tpu.executors.project", "_project_step")
    grab("risingwave_tpu.executors.filter", "_filter_step")
    grab("risingwave_tpu.executors.dedup", "_dedup_step")
    grab("risingwave_tpu.executors.materialize", "_mv_step")
    grab("risingwave_tpu.ops.hash_table", "lookup_or_insert")
    grab("risingwave_tpu.ops.hash_table", "lookup")
    return out


class RecompileWatch:
    """Per-kernel jit-cache miss tracking across a steady-state window.

    ``snapshot()`` after warmup; ``deltas()`` at the end returns
    {kernel: new-compile count} and feeds ``recompiles_total`` — the
    regression gate for 'steady-state epochs trigger zero recompiles'.
    """

    def __init__(self, kernels: Optional[Sequence[Tuple[str, object]]] = None):
        self.kernels = list(kernels) if kernels is not None else _default_kernels()
        self._base: Dict[str, int] = {}

    def snapshot(self) -> Dict[str, int]:
        self._base = {n: f._cache_size() for n, f in self.kernels}
        return dict(self._base)

    def deltas(self, record: bool = True) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for n, f in self.kernels:
            d = f._cache_size() - self._base.get(n, 0)
            if d > 0:
                out[n] = d
        if record and out:
            from risingwave_tpu.metrics import record_recompiles

            record_recompiles(out)
            # recording CONSUMES the window: advance the base so a
            # second deltas()/total() never double-counts the same
            # misses into recompiles_total
            for n, d in out.items():
                self._base[n] = self._base.get(n, 0) + d
        return out

    def total(self, record: bool = True) -> int:
        return sum(self.deltas(record=record).values())


class SignatureWatch:
    """Abstract-input-signature fingerprinting per executor.

    ``start()`` begins observation (runtime/pipeline.walk_chain feeds
    every (executor, chunk) pair when enabled); ``mark_stable()`` ends
    the warmup window; any NEW signature after that is a recompile
    hazard: the executor's inputs are shape-unstable, so its fused step
    re-traces. Hazards go to ``recompile_hazard_total{executor=...}``,
    the meta event log, and ``report()`` as RW-E403.

    Novelty is judged per executor CLASS, not per instance: the XLA
    jit cache keys on (function, abstract signature), so a shape one
    instance legitimized during warmup costs nothing on a fresh
    instance of the same class (bench protocol: measure on a freshly
    built pipeline after a warmup twin compiled everything; recovery:
    rebuilt actors re-present their old shapes). Only shapes NO
    instance ever presented before stability are hazards."""

    def __init__(self):
        import threading

        self.enabled = False
        self._stable = False
        self._sigs: Dict[int, Set[tuple]] = {}
        self._names: Dict[int, str] = {}
        self._class_sigs: Dict[str, Set[tuple]] = {}
        self._hazards: Dict[str, List[tuple]] = {}
        self._taken: Dict[str, int] = {}
        # hazards are appended from actor/closer threads while the
        # barrier thread reads deltas (ShapeGovernor): guard the
        # hazard dict — the no-hazard hot path never takes the lock
        self._haz_lock = threading.Lock()

    def start(self) -> "SignatureWatch":
        self.enabled = True
        self._stable = False
        self._sigs.clear()
        self._names.clear()
        self._class_sigs.clear()
        self._hazards.clear()
        self._taken.clear()
        return self

    def mark_stable(self) -> None:
        self._stable = True

    def stop(self) -> None:
        self.enabled = False

    @staticmethod
    def _fingerprint(chunk) -> tuple:
        cols = tuple(
            (k, v.shape, str(v.dtype))
            for k, v in sorted(chunk.columns.items())
        )
        nulls = tuple(sorted(chunk.nulls))
        return (cols, nulls, chunk.valid.shape)

    def observe(self, ex, chunk) -> None:
        try:
            sig = self._fingerprint(chunk)
        except AttributeError:
            return  # not a StreamChunk (defensive)
        key = id(ex)
        seen = self._sigs.setdefault(key, set())
        if sig in seen:
            return
        seen.add(sig)
        name = type(ex).__name__
        self._names[key] = name
        cls_seen = self._class_sigs.setdefault(name, set())
        known_to_class = sig in cls_seen
        cls_seen.add(sig)
        if self._stable and not known_to_class:
            from risingwave_tpu.analysis.shape_domain import (
                capacity_bucket,
            )
            from risingwave_tpu.event_log import EVENT_LOG
            from risingwave_tpu.metrics import REGISTRY

            # the shape BUCKET that produced the hazard: the dynamic
            # twin of the fusion analyzer's chunk-size bucket lattice —
            # a runtime hazard whose executor also carries a static
            # RW-E803 finding names the same bucket in both reports
            bucket = capacity_bucket(int(chunk.valid.shape[-1]))
            with self._haz_lock:
                self._hazards.setdefault(name, []).append((bucket, sig))
            REGISTRY.counter("recompile_hazard_total").inc(executor=name)
            REGISTRY.counter("recompile_hazard_bucket_total").inc(
                executor=name, bucket=str(bucket)
            )
            EVENT_LOG.record(
                "recompile_hazard",
                executor=name,
                bucket=bucket,
                code="RW-E803",
                signature=repr(sig)[:200],
            )

    def take_hazard_deltas(self) -> Dict[str, int]:
        """Post-warmup hazards per executor class since the last take —
        the runtime ShapeGovernor's per-barrier feed (consuming: a
        second call within the same barrier returns {})."""
        out: Dict[str, int] = {}
        with self._haz_lock:
            for name, sigs in self._hazards.items():
                n = len(sigs)
                d = n - self._taken.get(name, 0)
                if d > 0:
                    out[name] = d
                    self._taken[name] = n
        return out

    def hazard_total(self) -> int:
        """Cumulative post-warmup hazards (NON-consuming — bench/test
        assertion surface; take_hazard_deltas() is the governor's)."""
        with self._haz_lock:
            return sum(len(s) for s in self._hazards.values())

    def report(self) -> List[Diagnostic]:
        return [
            Diagnostic(
                "RW-E403",
                f"executor saw {len(sigs)} new abstract input "
                "signature(s) after warmup in capacity bucket(s) "
                f"{sorted({b for b, _ in sigs})} — every one re-traces "
                "its fused step (recompile storm on TPU); cross-check "
                "the static RW-E803 findings for this executor "
                "(lint --fusion-report)",
                executor=name,
                severity="warning",
            )
            for name, sigs in sorted(self._hazards.items())
        ]


# the process singleton walk_chain consults (off unless start()ed)
SIGNATURES = SignatureWatch()


# ---------------------------------------------------------------------------
# pipeline-level sanitize (deep lint)
# ---------------------------------------------------------------------------


def sanitize_executors(executors: Sequence[object]) -> List[Diagnostic]:
    """Trace every executor's pure step (when it exposes one) with a
    synthetic fixed-capacity chunk and scan for promotions. Cheap: no
    XLA compiles, tracing only."""
    import jax.numpy as jnp
    import numpy as np

    from risingwave_tpu.array.chunk import StreamChunk

    out: List[Diagnostic] = []
    for ex in executors:
        step = getattr(ex, "pure_step", lambda: None)()
        if step is None:
            continue
        info = getattr(ex, "lint_info", lambda: None)() or {}
        dtypes = {
            k: v
            for k, v in (info.get("expects") or {}).items()
            if v is not None
        }
        if not dtypes:
            continue
        cols = {
            k: np.zeros(8, dtype=np.dtype(jnp.dtype(v)))
            for k, v in dtypes.items()
        }
        chunk = StreamChunk.from_numpy(cols, 8)
        try:
            out.extend(
                check_promotions(step, chunk, name=type(ex).__name__)
            )
        except Exception:  # noqa: BLE001 — sanitizer is best-effort
            continue
    return out


def sanitize_state_kernels() -> List[Diagnostic]:
    """RW-E401 over the shared state kernels: the hash-table
    probe/insert step must donate its table buffers, or every barrier
    holds two live copies of the state in HBM. Lower-only — no XLA
    compile, no device allocation beyond the tiny example table."""
    import jax.numpy as jnp

    from risingwave_tpu.ops.hash_table import HashTable, lookup_or_insert

    t = HashTable.create(64, (jnp.dtype(jnp.int64),))
    keys = (jnp.zeros(8, jnp.int64),)
    valid = jnp.ones(8, jnp.bool_)
    return check_donation(
        lookup_or_insert, t, keys, valid, name="lookup_or_insert"
    )


def sanitize_hash_kernels() -> List[Diagnostic]:
    """The shared hash path itself (ops/hashing): must be pure 32-bit
    for int64 compound keys — the dtype-audit regression gate."""
    import jax.numpy as jnp

    from risingwave_tpu.ops import hashing

    keys = (
        jnp.zeros(8, jnp.int64),
        jnp.zeros(8, jnp.int32),
        jnp.zeros(8, jnp.float64),
    )
    out = check_hash_path_32bit(
        lambda ks: hashing.hash_columns(ks, seed=0xC0FFEE),
        keys,
        name="hash_columns",
    )
    out.extend(
        check_hash_path_32bit(
            lambda ks: hashing.hash128(ks), keys, name="hash128"
        )
    )
    out.extend(
        check_hash_path_32bit(
            lambda ks: hashing.vnode_of(ks), keys, name="vnode_of"
        )
    )
    return out
