"""Structured lint diagnostics — the ``RW-E###`` vocabulary.

One code = one invariant. Codes are STABLE API (tests assert them, the
README tables them); add new ones, never renumber. Families:

- RW-E1xx  per-channel schema / dtype agreement
- RW-E2xx  distribution-key / join-key alignment (exchange soundness)
- RW-E3xx  dtype promotion & hash-path width (x64-portability)
- RW-E4xx  compilation hygiene (donation, transfers, recompiles)
- RW-E5xx  watermark propagation / state-cleaning reachability
- RW-E6xx  fragment-graph wiring (channels, cycles, reachability)
- RW-E7xx  state tables (pk coverage, table-id uniqueness)
- RW-E8xx  fusion feasibility (host-sync blockers, shape stability)
- RW-E9xx  mesh / SPMD-collective readiness (analysis/mesh_analyzer.py)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Sequence

CODES = {
    # verifier self-diagnostics
    "RW-E001": "executor lint_info() raised — treated as opaque, "
    "verification degraded past this executor",
    # schema / dtype agreement
    "RW-E101": "executor reads a column its input channel does not carry",
    "RW-E102": "column dtype disagrees with the executor's declared dtype",
    # key alignment across exchanges / joins
    "RW-E201": "hash-dispatch key missing from the upstream fragment's output",
    "RW-E202": "dispatch keys do not cover the parallel fragment's state keys",
    "RW-E203": "non-hash dispatch feeds a parallel fragment with keyed state",
    "RW-E204": "join key dtypes disagree between the left and right sides",
    # dtype promotion / hashing width
    "RW-E301": "implicit 32->64-bit promotion inside a compiled step",
    "RW-E302": "hash path performs 64-bit arithmetic (x64/platform dependent)",
    # compilation hygiene
    "RW-E401": "state-carrying kernel compiled without buffer donation",
    "RW-E402": "implicit host<->device transfer inside the device step",
    "RW-E403": "shape-unstable executor: abstract input signature changed "
    "after warmup (recompile hazard)",
    # watermark propagation
    "RW-E501": "window-keyed state cleaning on a column no watermark can reach",
    # fragment-graph wiring
    "RW-E601": "channel references an unknown upstream fragment",
    "RW-E602": "duplicate channel between the same fragment pair and port",
    "RW-E603": "fragment graph contains a cycle (barriers can never align)",
    "RW-E604": "fragment output is never consumed and is not the sink",
    "RW-E605": "declared output/source fragment does not exist",
    "RW-E606": "stateful fragment has no rebuildable boundary (state not "
    "covered by the pipeline's restorable checkpoint registry — partial "
    "recovery cannot restore it)",
    # state tables
    "RW-E701": "state-table primary key not covered by the input schema",
    "RW-E702": "duplicate state table_id within one plan",
    "RW-E703": "would-share state tables differ ONLY by an incompatible "
    "bucket lattice: same index key columns, dtypes and window spec, but "
    "the declared capacity lattices disagree — aligning capacities would "
    "let one shared arrangement serve both (runtime/arrangements.py)",
    "RW-E708": "stateful executor invisible to the memory ledger: it "
    "registers state table_ids but exposes neither a state_nbytes()/"
    "state_bytes() accounting contract nor an allocator-backed capacity "
    "note (_buckets) — its device state dodges the HBM budget the "
    "memory governor enforces (runtime/memory_governor.py). Report-only "
    "by default; refused when RW_STRICT_LINT is explicitly set",
    "RW-E709": "stateful executor without state-digest coverage: it "
    "registers state table_ids but implements no state_digest() "
    "contract (or its digest_lanes() expose lanes the fold cannot "
    "cover) — silent device-state corruption in this executor is "
    "undetectable to the integrity layer (integrity.py): no fused-vs-"
    "interpreted cross-check, no checkpoint digest, no scrub coverage. "
    "Report-only by default; refused when RW_STRICT_LINT is explicitly "
    "set",
    # fusion feasibility (analysis/fusion_analyzer.py): what blocks
    # fusing a fragment's executor chain into ONE jitted per-barrier
    # device step (ROADMAP item 1), proven statically
    "RW-E801": "host synchronization inside the hot path — a fused "
    "per-barrier device step would stall on this blocking host<->device "
    "round-trip",
    "RW-E802": "dynamic / data-dependent output shape — every distinct "
    "emission size compiles a fresh program downstream",
    "RW-E803": "unbucketed shape-polymorphic window: the executor's "
    "window-keyed shape domain has no declared bucket lattice, so "
    "window churn re-traces its fused step without bound (the q7 wedge "
    "class)",
    "RW-E804": "state buffer not donation-safe for a fused step — the "
    "fused program would hold two live copies of the carried state in "
    "HBM",
    "RW-E805": "fused-step jaxpr count exceeds the recompile budget "
    "across the declared chunk-size buckets",
    "RW-E806": "window-keyed executor declares a window_buckets lattice "
    "the bucketing layer cannot satisfy (not pow2 / not increasing / "
    "out of allocator bounds / empty) — the shape-stability proof is "
    "vacuous",
    "RW-E807": "fusion refused with provenance (runtime/fused_step "
    "fusion_refusals): a chain or two-input pipeline the planner left "
    "interpreted — lattice-incompatible member, unbucketed join side, "
    "unsupported shape, or a join-fed MV tail whose feeder's emission "
    "shape family is not closed. Policy decisions are recorded, never "
    "silent",
    # mesh / SPMD-collective readiness (analysis/mesh_analyzer.py):
    # what blocks fusing a sharded fragment's barrier into ONE SPMD
    # dispatch across the device mesh (ROADMAP item 3), proven
    # statically against the executors' mesh_contract() declarations
    "RW-E901": "host-routed exchange edge: rows cross shards through "
    "host memory (stack/split/flatten or per-shard device_get) instead "
    "of an on-device collective inside the sharded program",
    "RW-E902": "hash-dispatch key is not provably a pure function of "
    "the mesh axis: dest_shard disagrees with the declared vnode axis "
    "or the dispatch key is computed outside the consistent-hash path, "
    "so an all_to_all would route rows to the wrong shard",
    "RW-E903": "shard-local step not shard_map-traceable: per-shard "
    "shape polymorphism outside the declared bucket lattice (each "
    "shard would compile its own program family, defeating SPMD)",
    "RW-E904": "replicated state mutated shard-locally: a leaf the "
    "contract declares replicated across the mesh is written inside "
    "the per-shard step (silent cross-shard divergence hazard)",
    "RW-E905": "exchange output shape is data-dependent: the received "
    "row count reaches the host before the next step can run, so the "
    "collective cannot fuse into the donated program without a host "
    "recount",
    "RW-E906": "cross-shard reduction order is nondeterministic: the "
    "merge of per-shard partials is not order-insensitive, so the "
    "mesh result cannot be bit-identical to the serial twin",
    "RW-E907": "per-destination dispatch fan-out: the executor issues "
    "one host-driven device call per destination shard (the "
    "dispatch-wall x N mechanism the multichip dry-runs measured) "
    "instead of one program over the stacked mesh axis",
}


@dataclass(frozen=True)
class Diagnostic:
    """One finding, with fragment/executor provenance."""

    code: str
    message: str
    fragment: str = ""
    executor: str = ""
    severity: str = "error"  # "error" | "warning"

    def __post_init__(self):
        if self.code not in CODES:
            raise ValueError(f"unknown diagnostic code {self.code!r}")

    def render(self) -> str:
        where = []
        if self.fragment:
            where.append(f"frag={self.fragment}")
        if self.executor:
            where.append(f"ex={self.executor}")
        loc = f" [{' '.join(where)}]" if where else ""
        return f"{self.code}{loc} {self.message}"


@dataclass
class LintReport:
    """Collector threaded through the verifier passes."""

    diagnostics: List[Diagnostic] = field(default_factory=list)

    def add(
        self,
        code: str,
        message: str,
        fragment: str = "",
        executor: str = "",
        severity: str = "error",
    ) -> None:
        self.diagnostics.append(
            Diagnostic(code, message, fragment, executor, severity)
        )

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    def render(self) -> str:
        return "\n".join(d.render() for d in self.diagnostics)


class PlanLintError(ValueError):
    """strict_lint promotion: DDL is refused with every finding listed."""

    def __init__(self, diagnostics: Sequence[Diagnostic], name: str = ""):
        self.diagnostics = list(diagnostics)
        what = f" for {name!r}" if name else ""
        lines = "\n  ".join(d.render() for d in self.diagnostics)
        super().__init__(
            f"plan verification failed{what} "
            f"({len(self.diagnostics)} finding(s)):\n  {lines}"
        )
