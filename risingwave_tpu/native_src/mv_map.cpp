// Native MV row map — the MaterializeExecutor's hot host path.
//
// Reference role: the reference's MaterializeExecutor applies chunk
// deltas to its StateTable via native Rust row maps
// (src/stream/src/executor/mview/materialize.rs:44 + MaterializeCache
// :551). The TPU build's compute plane is JAX, but the per-barrier MV
// delta apply is host-side row work — a Python dict of tuples pays
// interpreter cost per row, this map pays ~ns per row.
//
// C ABI on purpose: loaded via ctypes (no pybind11 in the image); all
// data crosses as raw int64 buffers from numpy. Keys/values are fixed
// arity int64 lanes (dictionary codes included); the Python wrapper
// falls back to the dict path for any other layout.

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct KeyHash {
    size_t operator()(const std::string& s) const {
        // FNV-1a over the raw key bytes
        uint64_t h = 1469598103934665603ull;
        for (unsigned char c : s) {
            h ^= c;
            h *= 1099511628211ull;
        }
        return static_cast<size_t>(h);
    }
};

struct MvMap {
    int64_t k_arity;
    int64_t v_arity;
    std::unordered_map<std::string, std::string, KeyHash> rows;
};

}  // namespace

extern "C" {

void* mv_new(int64_t k_arity, int64_t v_arity) {
    auto* m = new MvMap{k_arity, v_arity, {}};
    m->rows.reserve(1 << 16);
    return m;
}

void mv_free(void* h) { delete static_cast<MvMap*>(h); }

// Apply n rows in order: is_del[i] ? erase : upsert (last op per pk
// wins by construction — sequential apply).
void mv_apply(void* h, const int64_t* keys, const int64_t* vals,
              const uint8_t* is_del, int64_t n) {
    auto* m = static_cast<MvMap*>(h);
    const size_t kb = m->k_arity * sizeof(int64_t);
    const size_t vb = m->v_arity * sizeof(int64_t);
    std::string key;
    for (int64_t i = 0; i < n; i++) {
        key.assign(reinterpret_cast<const char*>(keys + i * m->k_arity), kb);
        if (is_del[i]) {
            m->rows.erase(key);  // overwrite-conflict: missing ok
        } else {
            std::string& slot = m->rows[key];
            slot.assign(reinterpret_cast<const char*>(vals + i * m->v_arity),
                        vb);
        }
    }
}

int64_t mv_len(void* h) {
    return static_cast<int64_t>(static_cast<MvMap*>(h)->rows.size());
}

// Dump every row into caller-allocated buffers (len()*arity each).
void mv_dump(void* h, int64_t* keys_out, int64_t* vals_out) {
    auto* m = static_cast<MvMap*>(h);
    const size_t kb = m->k_arity * sizeof(int64_t);
    const size_t vb = m->v_arity * sizeof(int64_t);
    int64_t i = 0;
    for (const auto& kv : m->rows) {
        std::memcpy(keys_out + i * m->k_arity, kv.first.data(), kb);
        std::memcpy(vals_out + i * m->v_arity, kv.second.data(), vb);
        i++;
    }
}

// Point lookup: returns 1 and fills vals_out if present.
int32_t mv_get(void* h, const int64_t* key, int64_t* vals_out) {
    auto* m = static_cast<MvMap*>(h);
    std::string k(reinterpret_cast<const char*>(key),
                  m->k_arity * sizeof(int64_t));
    auto it = m->rows.find(k);
    if (it == m->rows.end()) return 0;
    std::memcpy(vals_out, it->second.data(),
                m->v_arity * sizeof(int64_t));
    return 1;
}
}
