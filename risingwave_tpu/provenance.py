"""Artifact provenance — which engine produced this JSON?

The re-anchor before PR 11 cost a round of confusion because every
committed BENCH_TPU artifact silently predated the engine it was being
compared against (PRs 9-10 changed the shape layer and the whole
dispatch model; the artifacts did not say so). Every bench / fusion /
profile artifact now carries three fields:

- ``git_sha``   — the commit the writing process ran from (best
  effort: ``git rev-parse HEAD``; RW_GIT_SHA overrides for detached
  bench children; "unknown" when neither resolves);
- ``pr_tag``    — a human-readable tag for the writing engine
  (RW_PR_TAG, default ``genN``);
- ``engine_generation`` — a MONOTONIC integer bumped whenever a PR
  changes what the numbers MEAN (dispatch model, shape layer, byte
  accounting). ``perf_gate`` warns when it ratchets against an
  artifact from an older generation — stale-artifact confusion becomes
  mechanically detectable instead of a forensic exercise.

No jax import, ever: the pure-JSON perf_gate mode and the blackbox
reader CLI stamp/compare provenance from plain processes.
"""

from __future__ import annotations

import os
import subprocess
from typing import Dict, Optional

__all__ = ["ENGINE_GENERATION", "git_sha", "pr_tag", "stamp"]

# Bump when a PR changes what artifact numbers mean. History:
#   9  = bucketed padded shapes (padding overhead enters every metric)
#   10 = fused device-resident barrier step (dispatch counts collapse)
#   11 = modeled-bytes roofline (hbm_bytes_touched semantics change:
#        compiled-executable model, not the host byte guess)
ENGINE_GENERATION = 11

_CACHED_SHA: Optional[str] = None


def git_sha() -> str:
    """The writing process's commit (cached; never raises)."""
    global _CACHED_SHA
    env = os.environ.get("RW_GIT_SHA")
    if env:
        return env
    if _CACHED_SHA is None:
        try:
            _CACHED_SHA = (
                subprocess.run(
                    ["git", "rev-parse", "HEAD"],
                    capture_output=True,
                    text=True,
                    timeout=5,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                ).stdout.strip()
                or "unknown"
            )
        except Exception:  # noqa: BLE001 — provenance is best effort
            _CACHED_SHA = "unknown"
    return _CACHED_SHA


def pr_tag() -> str:
    return os.environ.get("RW_PR_TAG", f"gen{ENGINE_GENERATION}")


def stamp() -> Dict:
    """The three provenance fields, ready to merge into an artifact."""
    return {
        "git_sha": git_sha(),
        "pr_tag": pr_tag(),
        "engine_generation": ENGINE_GENERATION,
    }
