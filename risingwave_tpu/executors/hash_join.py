"""HashJoin executor — streaming two-sided equi-join with retraction.

Reference: src/stream/src/executor/hash_join.rs:129 (3,252 LoC) +
executor/join/hash_join.rs:157 (JoinHashMap + degree table). Semantics
matched for INNER / LEFT / RIGHT / FULL OUTER / LEFT|RIGHT SEMI /
LEFT|RIGHT ANTI:
- each arriving chunk updates its own side's multiset state and probes
  the other side, emitting one output row per (probe row, stored match)
  with the probe row's sign (execute_inner / hash_eq_match,
  hash_join.rs:462-729);
- outer/semi/anti variants ride per-stored-row DEGREE state: a row's
  degree is its current match count on the other side; zero-crossings
  drive NULL-pad retraction/revival (outer) or bare-row emission
  (semi/anti) — the reference's degree table semantics
  (join/hash_join.rs:157) realized as one extra (capacity, fanout)
  int32 lane updated by batched scatter (ops/join.degree_apply);
- barrier-aligned two-input operator: the runtime feeds chunks in
  arrival order via ``apply_left`` / ``apply_right`` and calls
  ``on_barrier`` once both inputs hit the barrier (barrier_align.rs);
- watermark on the window column cleans closed-window state on both
  sides (state cleaning via table watermarks, state_table.rs:1133).

TPU re-design: no per-key Vec + LRU cache — each side is a JoinSide
(ops/join.py): a device hash table over the join key plus fixed-fanout
row buckets, so one chunk's insert+delete+probe+emit runs as one fused
jitted program per side. Output pairs are compacted into fixed
``out_cap`` chunks (static shapes; overflow latches and raises at the
barrier, the capacity-growth contract shared with HashAgg).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.ops.hash_table import read_scalars, stage_scalars
from risingwave_tpu.ops.hash_table import lookup_or_insert, set_live
from risingwave_tpu.runtime.bucketing import (
    BucketAllocator,
    BucketPolicy,
    needs_plan,
    plan_capacity,
)
from risingwave_tpu.storage.state_table import (
    host_key_view,
    lanes_from_host_keys,
    Checkpointable,
    StateDelta,
    grow_pow2,
    pull_rows,
    stage_marks,
)
from risingwave_tpu.ops.join import (
    JoinSide,
    apply_side,
    compact_pairs,
    degree_apply,
    expire_keys,
    gather_flat,
    gather_matches,
    probe_side,
    regrow,
)
from risingwave_tpu.types import Op

GROW_AT = 0.5
# mid-epoch rebuild only when the HOST insert bound nears the table
# itself (MAX_PROBE overflow risk); ordinary growth resolves at the
# barrier from the true occupancy note (HashAgg's twin constant)
HARD_GROW_AT = 0.75


JOIN_TYPES = (
    "inner",
    "left",
    "right",
    "full",
    "left_semi",
    "left_anti",
    "right_semi",
    "right_anti",
)


def join_step_fn(
    own: JoinSide,
    other: JoinSide,
    chunk: StreamChunk,
    own_keys: Tuple[str, ...],
    other_keys: Tuple[str, ...],
    own_names: Tuple[str, ...],
    other_names: Tuple[str, ...],
    out_cap: int,
    join_type: str = "inner",
    arrival: str = "l",
    out_names: Tuple[str, ...] = (),
):
    """One chunk through its own side + probe of the other side, with
    the full join-type matrix (reference hash_join.rs:129 inner/outer/
    semi/anti variants + degree tables join/hash_join.rs:157).

    Emission groups (all static-shape, compacted together):
    1. PAIRS (inner/outer): one row per (probe row, stored match),
       probe row's sign.
    2. OWN NULL-PAD / SEMI / ANTI on arrival: probe rows judged by
       their CURRENT match count mc (outer: mc==0 -> row + NULLs; semi:
       mc>0 -> row; anti: mc==0 -> row), probe row's sign.
    3. TRANSITIONS on the other side's stored rows whose degree crossed
       zero (degree_apply): outer -> retract/revive the NULL-padded
       row; semi/anti -> emit/retract the bare row.

    Returns (own', other', out_cols, out_nulls, out_ops, out_valid,
    overflow).
    """
    semi_anti = join_type.endswith("semi") or join_type.endswith("anti")
    drive = "l" if join_type.startswith("left") else "r"
    pairs_on = not semi_anti
    own_outer = join_type == "full" or (
        (join_type == "left" and arrival == "l")
        or (join_type == "right" and arrival == "r")
    )
    other_outer = join_type == "full" or (
        (join_type == "left" and arrival == "r")
        or (join_type == "right" and arrival == "l")
    )
    need_degree = join_type != "inner"

    key_cols = tuple(chunk.col(k) for k in own_keys)
    # SQL equi-join: NULL keys match nothing and need no state
    key_ok = jnp.ones(chunk.capacity, jnp.bool_)
    for k in own_keys:
        lane = chunk.nulls.get(k)
        if lane is not None:
            key_ok &= ~lane
    valid = chunk.valid & key_ok
    signs = chunk.effective_signs()
    active = valid & (signs != 0)

    # probe the other side (read-only) and stage the emissions
    sl, match = probe_side(other, key_cols, active)
    o_cols, o_nulls = gather_matches(other, sl, other_names)
    mc = jnp.sum(match.astype(jnp.int32), axis=1)

    n, fanout = match.shape
    flatm = lambda a: a.reshape(n * fanout)
    bcast = lambda a: jnp.broadcast_to(a[:, None], (n, fanout))

    groups = []  # (cols, nulls, ops, valid) of flat lanes

    if pairs_on:
        g_cols = {name: flatm(bcast(chunk.col(name))) for name in own_names}
        g_cols.update({name: flatm(o_cols[name]) for name in other_names})
        g_nulls = {
            name: flatm(bcast(lane))
            for name, lane in chunk.nulls.items()
            if name in own_names
        }
        g_nulls.update({name: flatm(lane) for name, lane in o_nulls.items()})
        g_ops = flatm(
            bcast(
                jnp.where(
                    signs > 0, jnp.int32(Op.INSERT), jnp.int32(Op.DELETE)
                )
            )
        )
        groups.append((g_cols, g_nulls, g_ops, flatm(match)))

    # group 2: judged by current match count, on arrival rows
    if own_outer or (semi_anti and arrival == drive):
        if own_outer:
            cond = active & (mc == 0)
        elif join_type.endswith("semi"):
            cond = active & (mc > 0)
        else:  # anti
            cond = active & (mc == 0)
        g_cols = {name: chunk.col(name) for name in own_names}
        g_nulls = {
            name: lane
            for name, lane in chunk.nulls.items()
            if name in own_names
        }
        if own_outer:  # NULL-pad the other side
            for name in other_names:
                g_cols[name] = jnp.zeros(n, other.rows[name].dtype)
                g_nulls[name] = jnp.ones(n, jnp.bool_)
        g_ops = jnp.where(
            signs > 0, jnp.int32(Op.INSERT), jnp.int32(Op.DELETE)
        )
        groups.append((g_cols, g_nulls, g_ops, cond))

    # degree maintenance + group 3: zero-crossing transitions
    if need_degree:
        other, trans_pid, went_pos, went_zero = degree_apply(
            other, match, sl, jnp.where(active, signs, 0)
        )
        emit_trans = other_outer or (semi_anti and arrival != drive)
        if emit_trans:
            t_cols, t_nulls = gather_flat(other, trans_pid, other_names)
            g_cols = dict(t_cols)
            g_nulls = dict(t_nulls)
            if other_outer:  # NULL-pad the arrival side
                for name in own_names:
                    g_cols[name] = jnp.zeros(
                        trans_pid.shape[0], chunk.col(name).dtype
                    )
                    g_nulls[name] = jnp.ones(trans_pid.shape[0], jnp.bool_)
            if other_outer or join_type.endswith("anti"):
                # matched for the first time -> retract pad/bare row;
                # unmatched again -> emit it
                g_ops = jnp.where(
                    went_pos, jnp.int32(Op.DELETE), jnp.int32(Op.INSERT)
                )
            else:  # semi: matched -> emit; unmatched -> retract
                g_ops = jnp.where(
                    went_pos, jnp.int32(Op.INSERT), jnp.int32(Op.DELETE)
                )
            groups.append((g_cols, g_nulls, g_ops, went_pos | went_zero))

    # concatenate groups into one flat emission (schema = out_names)
    flat_cols: Dict[str, jnp.ndarray] = {}
    flat_nulls: Dict[str, jnp.ndarray] = {}
    col_dtype = {}
    for g_cols, _, _, _ in groups:
        for name, a in g_cols.items():
            col_dtype.setdefault(name, a.dtype)
    null_names = set()
    for _, g_nulls, _, _ in groups:
        null_names.update(g_nulls)
    for name in out_names:
        parts, nparts = [], []
        for g_cols, g_nulls, _, _ in groups:
            m = next(iter(g_cols.values())).shape[0]
            if name in g_cols:
                parts.append(g_cols[name])
            else:
                parts.append(jnp.zeros(m, col_dtype[name]))
            if name in null_names:
                nparts.append(g_nulls.get(name, jnp.zeros(m, jnp.bool_)))
        flat_cols[name] = jnp.concatenate(parts)
        if nparts:
            flat_nulls[name] = jnp.concatenate(nparts)
    flat_ops = jnp.concatenate([g[2] for g in groups])
    flat_valid = jnp.concatenate([g[3] for g in groups])

    out_cols, out_nulls, out_ops, out_valid, em_overflow = compact_pairs(
        flat_cols, flat_nulls, flat_ops, flat_valid, out_cap
    )

    # then fold the chunk into our own state (seeding degrees with the
    # current match count for outer/semi/anti)
    payload = {name: chunk.col(name) for name in own_names}
    pnulls = {
        name: lane for name, lane in chunk.nulls.items() if name in own_names
    }
    own = apply_side(
        own,
        key_cols,
        payload,
        pnulls,
        valid,
        signs,
        own_names,
        init_degree=mc if need_degree else None,
    )
    return own, other, out_cols, out_nulls, out_ops, out_valid, em_overflow


_join_step = partial(
    jax.jit,
    static_argnames=(
        "own_keys",
        "other_keys",
        "own_names",
        "other_names",
        "out_cap",
        "join_type",
        "arrival",
        "out_names",
    ),
    donate_argnums=(0, 1),
)(join_step_fn)


class HashJoinExecutor(Executor, Checkpointable):
    """Streaming INNER equi-join.

    Args:
      left_keys / right_keys: equi-join column names, positionally
        paired; dtypes of each pair must match (the hash is computed on
        raw lanes).
      left_dtypes / right_dtypes: column name -> dtype per side; ALL
        listed columns are stored as state and emitted. Names across the
        two sides must be disjoint (rename upstream).
      capacity: per-side key-table capacity (grows 2x at 50% load).
      fanout: per-key stored-row bound (grows 2x when exceeded... at
        the next barrier's raise; size for the workload's key skew).
      out_cap: per-chunk emission capacity.
      left_nullable / right_nullable: nullable payload columns.
      window_cols: optional (left_col, right_col) event-window lanes —
        a watermark on either clears state of both sides below it.
    """

    def __init__(
        self,
        left_keys: Sequence[str],
        right_keys: Sequence[str],
        left_dtypes: Dict[str, object],
        right_dtypes: Dict[str, object],
        capacity: int = 1 << 15,
        fanout: int = 16,
        out_cap: int = 1 << 14,
        left_nullable: Sequence[str] = (),
        right_nullable: Sequence[str] = (),
        window_cols: Optional[Tuple[str, str]] = None,
        join_type: str = "inner",
        table_id: str = "hash_join",
        bucket_policy: Optional[BucketPolicy] = None,
        bucketed: bool = True,
    ):
        self.table_id = table_id
        if join_type not in JOIN_TYPES:
            raise ValueError(f"unknown join type {join_type!r}")
        self.join_type = join_type
        if set(left_dtypes) & set(right_dtypes):
            raise ValueError(
                f"overlapping output columns: {set(left_dtypes) & set(right_dtypes)}"
            )
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)
        self.left_names = tuple(sorted(left_dtypes))
        self.right_names = tuple(sorted(right_dtypes))
        if join_type.endswith("semi") or join_type.endswith("anti"):
            self.out_names = (
                self.left_names
                if join_type.startswith("left")
                else self.right_names
            )
        else:
            self.out_names = self.left_names + self.right_names
        self.out_cap = out_cap
        self.window_cols = window_cols
        self.left_nullable = tuple(left_nullable)
        self.right_nullable = tuple(right_nullable)

        lk_dtypes = tuple(jnp.dtype(left_dtypes[k]) for k in self.left_keys)
        rk_dtypes = tuple(jnp.dtype(right_dtypes[k]) for k in self.right_keys)
        if lk_dtypes != rk_dtypes:
            raise ValueError(f"join key dtype mismatch: {lk_dtypes} vs {rk_dtypes}")
        # declared per-side input dtypes, kept for the plan verifier
        self._lint_left_dtypes = {
            n: jnp.dtype(d) for n, d in left_dtypes.items()
        }
        self._lint_right_dtypes = {
            n: jnp.dtype(d) for n, d in right_dtypes.items()
        }

        self.left = JoinSide.create(
            capacity,
            fanout,
            lk_dtypes,
            {n: jnp.dtype(left_dtypes[n]) for n in self.left_names},
            nullable=left_nullable,
        )
        self.right = JoinSide.create(
            capacity,
            fanout,
            rk_dtypes,
            {n: jnp.dtype(right_dtypes[n]) for n in self.right_names},
            nullable=right_nullable,
        )
        # shape-stability: each side's key table walks a declared pow2
        # bucket lattice (one allocator per side — the sides churn
        # independently); bucketed=False keeps the legacy unbounded-
        # rehash twin (the RW-E803 wedge class under window churn)
        if bucketed:
            policy = bucket_policy or BucketPolicy.from_capacity(capacity, grow_at=GROW_AT)
            self._buckets = {
                "l": BucketAllocator(policy),
                "r": BucketAllocator(policy),
            }
        else:
            self._buckets = None
        self._bound = {"l": 0, "r": 0}
        self._occ_note = {"l": 0, "r": 0}  # true claimed at last barrier
        self._grew_midepoch = {"l": False, "r": False}  # one bump/epoch
        self._em_overflow = jnp.zeros((), jnp.bool_)
        self._wm = {"l": None, "r": None, "out": None}
        # cold tier (state >> HBM): the runtime wires cold_get_rows to
        # CheckpointManager.get_rows; evicted durable keys are recorded
        # host-side per side and fault back in when touched. The
        # property setter binds the host-side fault-in/expire HOOKS —
        # while unarmed (None) the hot path is provably host-sync free
        # (the NumPy helpers are unreachable), the HashAgg discipline.
        self._evicted = {"left": set(), "right": set()}
        self._cold_tombstones: Dict[str, list] = {}
        self._cold_apply_hook = None  # _fault_in when armed
        self._cold_expire_hook = None  # _expire_evicted when armed
        self.cold_get_rows = None

    @property
    def cold_get_rows(self):
        return self._cold_get_rows

    @cold_get_rows.setter
    def cold_get_rows(self, fn) -> None:
        self._cold_get_rows = fn
        armed = fn is not None
        self._cold_apply_hook = self._fault_in if armed else None
        self._cold_expire_hook = self._expire_evicted if armed else None

    def lint_info(self):
        dtypes = dict(self._lint_left_dtypes)
        dtypes.update(self._lint_right_dtypes)
        return {
            "left_keys": self.left_keys,
            "right_keys": self.right_keys,
            "expects_left": dict(self._lint_left_dtypes),
            "expects_right": dict(self._lint_right_dtypes),
            "emits": {n: dtypes.get(n) for n in self.out_names},
            "table_ids": (self.table_id,),
            "window_cols": self.window_cols,
        }

    def trace_contract(self):
        contract = {
            "kind": "device",
            "trace_step": lambda c: _join_step(
                self.left,
                self.right,
                c,
                self.left_keys,
                self.right_keys,
                self.left_names,
                self.right_names,
                self.out_cap,
                self.join_type,
                "l",
                self.out_names,
            ),
            "state": (self.left, self.right),
            "donate": True,
            "emission": "fixed",
            "emission_caps": (self.out_cap,),
            # the trace_step probes as a LEFT arrival: its input schema
            # is the declared left side — the analyzer seeds tracing
            # from this when the join heads a fragment (join_tail
            # sections have no source schema to thread)
            "input_schema": dict(self._lint_left_dtypes),
            "input_nulls": self.left_nullable,
            # two-input fusibility: the fused two-input program
            # (runtime/fused_step) can absorb this join — per-side
            # probe/build kernels are mask-aware (padded rows provably
            # inert, proven by the masked-lane twin tests), so bucket-
            # padded flush lanes cost one masked device op. Requires
            # the bucket lattice on both sides (the unbucketed twin is
            # the RW-E803 wedge class and stays interpreted).
            "two_input": True,
            "two_input_fusible": self._buckets is not None,
            # both JoinSides draw their capacities from the declared
            # pow2 lattice: the window-churn expiry/growth cycle costs
            # at most one trace per bucket per side (None only on the
            # legacy unbucketed twin — the RW-E803 wedge class)
            "window_buckets": (
                self._buckets["l"].lattice
                if self._buckets is not None
                else None
            ),
        }
        if self._buckets is not None:
            # the interpreted growth path's packed read exists only
            # where interpretation runs (the fused wrapper plans from
            # barrier notes instead) — fallback-only, not a blocker
            contract["fallback_syncs"] = ("_maybe_grow",)
        if self._cold_get_rows is not None:
            # an ARMED cold tier splices host fault-in/expire back into
            # the data path — scan it honestly (the corpus twins the
            # analyzer proves are never armed)
            contract["hot_methods"] = ("_fault_in", "_expire_evicted")
        return contract

    def pin_max_bucket(self):
        """ShapeGovernor hook: freeze BOTH sides at their high-water
        buckets (shrink disabled; regrow applied on the next apply)."""
        if self._buckets is None:
            return {"pinned": False}
        return {
            "table_id": self.table_id,
            "pinned_cap_left": self._buckets["l"].pin(),
            "pinned_cap_right": self._buckets["r"].pin(),
        }

    def padding_stats(self):
        return {
            "capacity": self.left.capacity + self.right.capacity,
            "live": int(self.left.table.num_live())
            + int(self.right.table.num_live()),
        }

    # -- data ------------------------------------------------------------
    def apply_left(self, chunk: StreamChunk) -> List[StreamChunk]:
        return self._apply("l", chunk)

    def apply_right(self, chunk: StreamChunk) -> List[StreamChunk]:
        return self._apply("r", chunk)

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        raise TypeError("HashJoin is two-input: use apply_left/apply_right")

    def _apply(self, side: str, chunk: StreamChunk) -> List[StreamChunk]:
        if self._cold_apply_hook is not None:
            # merge-on-return BEFORE the step: an arriving chunk probes
            # the other side and appends to its own — both sides' cold
            # buckets for its keys must be resident or matches are lost
            self._cold_apply_hook(side, chunk)
        own = self.left if side == "l" else self.right
        own = self._maybe_grow(side, own, chunk.capacity)
        other = self.right if side == "l" else self.left
        own_keys = self.left_keys if side == "l" else self.right_keys
        other_keys = self.right_keys if side == "l" else self.left_keys
        own_names = self.left_names if side == "l" else self.right_names
        other_names = self.right_names if side == "l" else self.left_names

        own, other, cols, nulls, ops, valid, em_overflow = _join_step(
            own,
            other,
            chunk,
            own_keys,
            other_keys,
            own_names,
            other_names,
            self.out_cap,
            self.join_type,
            side,
            self.out_names,
        )
        if side == "l":
            self.left, self.right = own, other
        else:
            self.right, self.left = own, other
        self._bound[side] += chunk.capacity
        # latch on device; checked once per barrier (a bool() here would
        # force a host sync on every chunk and stall the pipeline)
        self._em_overflow = self._em_overflow | em_overflow
        return [StreamChunk(columns=cols, valid=valid, nulls=nulls, ops=ops)]

    def _grow_hint(self, side: str, own: JoinSide, incoming: int) -> JoinSide:
        """The FUSED wrapper's pre-dispatch growth bookkeeping: ZERO
        device reads — one emergency bucket bump per side per epoch at
        most (BucketAllocator.bump; the host bound counts padded
        chunk capacities, so exact sizing from it over-grows);
        ordinary growth/shrink resolves at the barrier from the
        staged true occupancy+survivor notes."""
        if self._buckets is None:
            return self._maybe_grow(side, own, incoming)
        cap = own.capacity
        bound = min(self._bound[side], cap)
        self._bound[side] = bound
        if self._grew_midepoch[side] or (
            bound + incoming <= cap * HARD_GROW_AT
        ):
            return own
        new_cap = self._buckets[side].bump(cap)
        if new_cap is not None:
            own = regrow(own, new_cap, own.fanout)
            self._bound[side] = min(bound, new_cap)
        self._grew_midepoch[side] = True
        return own

    def _maybe_grow(self, side: str, own: JoinSide, incoming: int) -> JoinSide:
        """INTERPRETED-path growth: the exact legacy policy (one
        packed blocking read when the trigger trips). Declared under
        ``fallback_syncs`` on bucketed instances — the fused program
        replaces it with _grow_hint + barrier-note planning, so the
        read runs only where interpretation runs."""
        cap = own.capacity
        alloc = self._buckets[side] if self._buckets is not None else None
        if not needs_plan(alloc, cap, self._bound[side], incoming, GROW_AT):
            return own
        # ONE packed read: tunneled-TPU round-trips dominate
        claimed, survivors = read_scalars(
            own.table.occupancy(),
            jnp.sum((own.table.live | own.sdirty).astype(jnp.int32)),
        )
        new_cap = plan_capacity(
            alloc, cap, incoming, claimed, survivors, GROW_AT
        )
        if new_cap is not None:
            own = regrow(own, new_cap, own.fanout)
            claimed = int(own.table.occupancy())
        self._bound[side] = claimed
        return own

    # -- cold tier (state >> HBM; join/hash_join.rs:157 LRU-over-
    # Hummock analogue: durable buckets leave HBM, fault back on touch)
    def state_nbytes(self) -> int:
        return sum(
            leaf.nbytes
            for leaf in jax.tree.leaves((self.left, self.right))
        )

    def evict_cold(self) -> int:
        """Free every fully-durable key's bucket from HBM, shrinking
        each side to its hot set. Returns keys evicted."""
        if self.cold_get_rows is None:
            raise RuntimeError("evict_cold needs cold_get_rows (runtime)")
        # non-integer key lanes ride the host-side evicted set as exact
        # bit patterns (host_key_view) — VARCHAR keys are dictionary
        # codes (integers) and float keys bit-cast losslessly
        return self._evict_side("left") + self._evict_side("right")

    def _evict_side(self, name: str) -> int:
        import dataclasses

        side = getattr(self, name)
        claimed = side.table.fp1 != jnp.uint32(0)
        durable = claimed & side.stored & ~side.sdirty
        n_evict = int(jnp.sum(durable.astype(jnp.int32)))
        if n_evict == 0:
            return 0
        # record evicted keys host-side: the membership check is what
        # lets the hot path skip cold lookups for genuinely-new keys
        sel = np.flatnonzero(np.asarray(durable))
        keys = pull_rows(
            {f"k{i}": l for i, l in enumerate(side.table.keys)}, sel
        )
        lanes = [
            host_key_view(np.asarray(keys[f"k{i}"]))
            for i in range(len(side.table.keys))
        ]
        ev = self._evicted[name]
        for j in range(len(sel)):
            ev.add(tuple(int(a[j]) for a in lanes))
        # rebuild the side holding only the hot keys (eviction must
        # actually free HBM, not just slots)
        hot = claimed & ~durable
        hsel = np.flatnonzero(np.asarray(hot))
        n_hot = len(hsel)
        new_cap = grow_pow2(n_hot, 1 << 10, GROW_AT)
        fresh = JoinSide.create(
            new_cap,
            side.fanout,
            tuple(k.dtype for k in side.table.keys),
            {nm: a.dtype for nm, a in side.rows.items()},
            nullable=tuple(side.row_nulls),
        )
        if n_hot:
            pull = {f"k{i}": l for i, l in enumerate(side.table.keys)}
            pull["rv"] = side.row_valid
            pull["deg"] = side.degree
            pull["live"] = side.table.live
            pull["sd"] = side.sdirty
            pull["st"] = side.stored
            for nm, a in side.rows.items():
                pull[f"r_{nm}"] = a
            for nm, a in side.row_nulls.items():
                pull[f"n_{nm}"] = a
            rows = pull_rows(pull, hsel)
            jl = tuple(
                jnp.asarray(rows[f"k{i}"])
                for i in range(len(side.table.keys))
            )
            table, slots, _, _ = lookup_or_insert(
                fresh.table, jl, jnp.ones(n_hot, jnp.bool_)
            )
            table = set_live(table, slots, jnp.asarray(rows["live"]))
            fresh = dataclasses.replace(
                fresh,
                table=table,
                rows={
                    nm: a.at[slots].set(jnp.asarray(rows[f"r_{nm}"]))
                    for nm, a in fresh.rows.items()
                },
                row_nulls={
                    nm: a.at[slots].set(jnp.asarray(rows[f"n_{nm}"]))
                    for nm, a in fresh.row_nulls.items()
                },
                row_valid=fresh.row_valid.at[slots].set(
                    jnp.asarray(rows["rv"])
                ),
                degree=fresh.degree.at[slots].set(
                    jnp.asarray(rows["deg"])
                ),
                sdirty=fresh.sdirty.at[slots].set(jnp.asarray(rows["sd"])),
                stored=fresh.stored.at[slots].set(jnp.asarray(rows["st"])),
                overflow=side.overflow,
                inconsistent=side.inconsistent,
            )
        setattr(self, name, fresh)
        self._bound["l" if name == "left" else "r"] = int(
            fresh.table.occupancy()
        )
        return n_evict

    def _expire_evicted(self, name: str, pos: int, cutoff: int) -> None:
        """Watermark closes EVICTED keys too: they leave the evicted
        set (never fault back) and their store rows tombstone at the
        next checkpoint — recovery must not resurrect closed windows
        (expire_keys only reaches resident slots)."""
        side = getattr(self, name)
        dt = np.dtype(side.table.keys[pos].dtype)
        if dt.kind == "f":
            # evicted tuples hold bit patterns (host_key_view): convert
            # back to the numeric domain for the watermark comparison
            itype = np.int32 if dt.itemsize == 4 else np.int64
            conv = lambda x: float(np.array(x, itype).view(dt))
        else:
            conv = lambda x: x
        ev = self._evicted[name]
        closed = {t for t in ev if conv(t[pos]) < cutoff}
        if closed:
            ev.difference_update(closed)
            self._cold_tombstones.setdefault(name, []).extend(closed)

    def _fault_in(self, side: str, chunk: StreamChunk) -> None:
        if not (self._evicted["left"] or self._evicted["right"]):
            return  # armed but nothing evicted: never pull the chunk
        own_keys = self.left_keys if side == "l" else self.right_keys
        cols = [
            host_key_view(np.asarray(chunk.col(k))) for k in own_keys
        ]
        valid = np.asarray(chunk.valid)
        touched = {
            tuple(int(c[i]) for c in cols) for i in np.flatnonzero(valid)
        }
        for name in ("left", "right"):
            hits = touched & self._evicted[name]
            if hits:
                self._restore_cold_keys(name, sorted(hits))

    def _restore_cold_keys(self, name: str, key_tuples) -> None:
        import dataclasses

        letter = "l" if name == "left" else "r"
        side = getattr(self, name)
        n = len(key_tuples)
        side = self._maybe_grow(letter, side, n)
        lanes_np = lanes_from_host_keys(
            key_tuples, [k.dtype for k in side.table.keys]
        )
        found, vals = self.cold_get_rows(
            f"{self.table_id}.{name}", dict(lanes_np)
        )
        nt = int(found.sum())
        if nt:
            jl = tuple(
                jnp.asarray(lanes_np[f"k{i}"][found])
                for i in range(len(side.table.keys))
            )
            table, slots, _, _ = lookup_or_insert(
                side.table, jl, jnp.ones(nt, jnp.bool_)
            )
            table = set_live(table, slots, True)
            side = dataclasses.replace(
                side,
                table=table,
                rows={
                    nm: a.at[slots].set(
                        jnp.asarray(
                            vals[f"r_{nm}"][found].astype(a.dtype)
                        )
                    )
                    for nm, a in side.rows.items()
                },
                row_nulls={
                    nm: a.at[slots].set(
                        jnp.asarray(vals[f"n_{nm}"][found].astype(bool))
                    )
                    for nm, a in side.row_nulls.items()
                },
                row_valid=side.row_valid.at[slots].set(
                    jnp.asarray(vals["rv"][found].astype(bool))
                ),
                degree=(
                    side.degree.at[slots].set(
                        jnp.asarray(vals["deg"][found].astype(np.int32))
                    )
                    if "deg" in vals  # legacy pre-degree checkpoints
                    else side.degree
                ),
                stored=side.stored.at[slots].set(True),
            )
        setattr(self, name, side)
        self._bound[letter] += nt
        self._evicted[name].difference_update(key_tuples)

    # -- control ---------------------------------------------------------
    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        self._staged_scalars = stage_scalars(
            self._em_overflow,
            self.left.overflow,
            self.left.inconsistent,
            self.right.overflow,
            self.right.inconsistent,
            self.left.table.occupancy(),
            self.right.table.occupancy(),
            jnp.sum((self.left.table.live | self.left.sdirty).astype(jnp.int32)),
            jnp.sum((self.right.table.live | self.right.sdirty).astype(jnp.int32)),
        )
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return []

    def _plan_side_at_barrier(
        self, side: str, claimed: int, survivors: int
    ) -> None:
        """Barrier-boundary capacity planning from the TRUE occupancy
        note (grow past the load factor, apply pending lazy shrink,
        honor a governor pin) — zero mid-epoch device reads."""
        own = self.left if side == "l" else self.right
        cap = own.capacity
        epoch_inc = max(self._bound[side] - self._occ_note[side], 0)
        self._occ_note[side] = claimed
        self._bound[side] = claimed
        alloc = self._buckets[side]
        alloc.note_barrier(cap, claimed)
        new_cap = alloc.plan(
            cap, 0, claimed, survivors, margin=max(claimed, epoch_inc)
        )
        if new_cap is not None and new_cap != cap:
            own = regrow(own, new_cap, own.fanout)
            if side == "l":
                self.left = own
            else:
                self.right = own

    def _on_barrier_scalars(self, vals) -> None:
        em, lo, li, ro, ri, cl, cr, sl, sr = vals
        self._grew_midepoch = {"l": False, "r": False}
        if self._buckets is not None:
            self._plan_side_at_barrier("l", int(cl), int(sl))
            self._plan_side_at_barrier("r", int(cr), int(sr))
        else:
            self._bound["l"] = int(cl)
            self._bound["r"] = int(cr)
        if em:
            raise RuntimeError(
                "join emission overflowed out_cap within one chunk; "
                "raise out_cap or shrink source chunks"
            )
        for name, ovf, inc in (("left", lo, li), ("right", ro, ri)):
            if ovf:
                raise RuntimeError(
                    f"{name} join side overflowed (bucket fanout or probe "
                    "chain); grow fanout/capacity"
                )
            if inc:
                raise RuntimeError(
                    f"{name} join side saw a DELETE matching no stored row "
                    "(inconsistent input stream)"
                )

    def on_watermark(self, watermark: Watermark):
        """Expire the matching side's closed windows; emit a downstream
        watermark on the LEFT window column once BOTH sides passed a new
        minimum (the reference's per-input watermark alignment on
        joins: output wm = min over inputs)."""
        if self.window_cols is None or watermark.column not in self.window_cols:
            return watermark, []
        cutoff = jnp.asarray(watermark.value, jnp.int64)
        if watermark.column == self.window_cols[0]:
            pos = self._key_index("l", self.window_cols[0])
            self.left = expire_keys(self.left, pos, cutoff)
            if self._cold_expire_hook is not None:
                self._cold_expire_hook("left", pos, int(watermark.value))
            self._wm["l"] = watermark.value
        else:
            pos = self._key_index("r", self.window_cols[1])
            self.right = expire_keys(self.right, pos, cutoff)
            if self._cold_expire_hook is not None:
                self._cold_expire_hook("right", pos, int(watermark.value))
            self._wm["r"] = watermark.value
        if self._wm["l"] is None or self._wm["r"] is None:
            return None, []
        aligned = min(self._wm["l"], self._wm["r"])
        if self._wm["out"] is not None and aligned <= self._wm["out"]:
            return None, []
        self._wm["out"] = aligned
        return Watermark(self.window_cols[0], aligned), []

    def _key_index(self, side: str, name: str) -> int:
        keys = self.left_keys if side == "l" else self.right_keys
        return keys.index(name)


# -- checkpoint/restore (StateTable integration) -------------------------
@jax.jit
def _side_mark_checkpointed(side: JoinSide, upsert, tomb) -> JoinSide:
    return JoinSide(
        side.table,
        side.rows,
        side.row_nulls,
        side.row_valid,
        side.overflow,
        side.inconsistent,
        jnp.zeros_like(side.sdirty),
        (side.stored | upsert) & ~tomb,
        side.degree,
    )


def _side_delta(side: JoinSide, table_id: str):
    """Stage one side's changed keys: the whole bucket rides as 2D
    value lanes (rows re-land at the same in-bucket positions on
    restore, so emitted pair identity is stable). Marks flip eagerly
    (see StateDelta's durability contract). Returns (delta, new_side)
    or None."""
    import numpy as np

    sdirty = np.asarray(side.sdirty)
    if not sdirty.any():
        return None
    upsert, tomb, sel = stage_marks(
        sdirty, np.asarray(side.table.live), np.asarray(side.stored)
    )
    lanes = {
        f"k{i}": lane for i, lane in enumerate(side.table.keys)
    }
    key_names = tuple(lanes)
    lanes["rv"] = side.row_valid
    lanes["deg"] = side.degree
    for n, a in side.rows.items():
        lanes[f"r_{n}"] = a
    for n, a in side.row_nulls.items():
        lanes[f"n_{n}"] = a
    pulled = pull_rows(lanes, sel)
    keys = {k: pulled[k] for k in key_names}
    vals = {k: v for k, v in pulled.items() if k not in key_names}
    new_side = _side_mark_checkpointed(
        side, jnp.asarray(upsert), jnp.asarray(tomb)
    )
    return StateDelta(table_id, keys, vals, tomb[sel], key_names), new_side


def _side_restore(side: JoinSide, key_cols, value_cols) -> JoinSide:
    """Rebuild a JoinSide from recovered rows (fresh table, same
    capacity/fanout unless growth is needed)."""
    import numpy as np

    n = len(next(iter(key_cols.values()))) if key_cols else 0
    fanout = side.fanout
    if n and "rv" in value_cols and value_cols["rv"].shape[1] != fanout:
        raise ValueError(
            f"checkpoint bucket fanout {value_cols['rv'].shape[1]} != "
            f"executor fanout {fanout}: restore lands rows at their "
            "stored in-bucket positions — configure the same fanout"
        )
    cap = grow_pow2(n, side.capacity, GROW_AT)
    fresh = JoinSide.create(
        cap,
        fanout,
        tuple(k.dtype for k in side.table.keys),
        {name: a.dtype for name, a in side.rows.items()},
        nullable=tuple(side.row_nulls),
    )
    if not n:
        return fresh
    lanes = tuple(
        jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d.dtype))
        for i, d in enumerate(side.table.keys)
    )
    table, slots, _, _ = lookup_or_insert(
        fresh.table, lanes, jnp.ones(n, jnp.bool_)
    )
    table = set_live(table, slots, True)

    def put2d(dst, src):
        return dst.at[slots].set(jnp.asarray(src))

    rows = {
        name: put2d(a, value_cols[f"r_{name}"].astype(a.dtype))
        for name, a in fresh.rows.items()
    }
    row_nulls = {
        name: put2d(a, value_cols[f"n_{name}"])
        for name, a in fresh.row_nulls.items()
    }
    row_valid = put2d(fresh.row_valid, value_cols["rv"])
    # older checkpoints predate the degree lane; default to zeros
    degree = (
        put2d(fresh.degree, value_cols["deg"].astype(jnp.int32))
        if "deg" in value_cols
        else fresh.degree
    )
    stored = fresh.stored.at[slots].set(True)
    return JoinSide(
        table,
        rows,
        row_nulls,
        row_valid,
        jnp.zeros((), jnp.bool_),
        jnp.zeros((), jnp.bool_),
        jnp.zeros(cap, jnp.bool_),
        stored,
        degree,
    )


def _join_checkpoint_table_ids(self):
    return [f"{self.table_id}.left", f"{self.table_id}.right"]


def _join_checkpoint_delta(self):
    out = []
    got = _side_delta(self.left, f"{self.table_id}.left")
    if got is not None:
        out.append(got[0])
        self.left = got[1]
    got = _side_delta(self.right, f"{self.table_id}.right")
    if got is not None:
        out.append(got[0])
        self.right = got[1]
    # watermark-closed EVICTED keys: their buckets live only in the
    # store — stage explicit tombstones so recovery cannot resurrect
    # closed windows (resident expiry tombstones ride _side_delta)
    pending = getattr(self, "_cold_tombstones", None)
    if pending:
        from risingwave_tpu.ops.hash_table import lookup as _ht_lookup

        by_tid = {d.table_id: d for d in out}
        for name, tuples in pending.items():
            if not tuples:
                continue
            side = getattr(self, name)
            # a key re-created AFTER its window closed (late arrival) is
            # RESIDENT again: its upsert (or its own tombstone) stages
            # via _side_delta — a cold tombstone in the same delta would
            # make point reads and merge reads disagree on the key
            lanes_np = lanes_from_host_keys(
                tuples, [k.dtype for k in side.table.keys]
            )
            lanes_j = tuple(
                jnp.asarray(lanes_np[f"k{i}"])
                for i in range(len(side.table.keys))
            )
            slots, _found = _ht_lookup(
                side.table, lanes_j, jnp.ones(len(tuples), jnp.bool_)
            )
            resident = np.asarray(slots) >= 0
            tuples = [t for t, r in zip(tuples, resident) if not r]
            if not tuples:
                continue
            tid = f"{self.table_id}.{name}"
            keys = lanes_from_host_keys(
                tuples, [k.dtype for k in side.table.keys]
            )
            nvals = {}
            nrows = len(tuples)
            nvals["rv"] = np.zeros(
                (nrows, side.fanout), side.row_valid.dtype
            )
            nvals["deg"] = np.zeros((nrows, side.fanout), np.int32)
            for nm, a in side.rows.items():
                nvals[f"r_{nm}"] = np.zeros((nrows,) + a.shape[1:], a.dtype)
            for nm, a in side.row_nulls.items():
                nvals[f"n_{nm}"] = np.zeros((nrows,) + a.shape[1:], a.dtype)
            tomb = np.ones(nrows, bool)
            prev = by_tid.get(tid)
            if prev is None:
                out.append(
                    StateDelta(
                        tid, keys, nvals, tomb, tuple(keys)
                    )
                )
            else:
                merged_keys = {
                    k: np.concatenate([prev.key_cols[k], keys[k]])
                    for k in prev.key_cols
                }
                merged_vals = {
                    k: np.concatenate([prev.value_cols[k], nvals[k]])
                    for k in prev.value_cols
                }
                out[out.index(prev)] = StateDelta(
                    tid,
                    merged_keys,
                    merged_vals,
                    np.concatenate([prev.tombstone, tomb]),
                    prev.key_order,
                )
        self._cold_tombstones = {}
    return out


def _join_restore_state(self, table_id, key_cols, value_cols):
    if table_id.endswith(".left"):
        self.left = _side_restore(self.left, key_cols, value_cols)
        self._bound["l"] = int(self.left.table.occupancy())
    else:
        self.right = _side_restore(self.right, key_cols, value_cols)
        self._bound["r"] = int(self.right.table.occupancy())
    # a full restore materializes EVERYTHING the store holds — no key
    # is cold anymore
    self._evicted = {"left": set(), "right": set()}


def _join_digest_lanes(self):
    """Both sides folded as one lane set (``l_``/``r_`` prefixes keep
    the seeds distinct); bucket lanes are pre-masked by row_valid
    inside integrity.join_side_lanes."""
    from risingwave_tpu.integrity import join_side_lanes

    ll, llive = join_side_lanes(self.left, jnp.where)
    rl, rlive = join_side_lanes(self.right, jnp.where)
    lanes = {f"l_{k}": v for k, v in ll.items()}
    lanes.update({f"r_{k}": v for k, v in rl.items()})
    return lanes, llive, rlive


def _join_state_digest(self) -> int:
    """Host twin of the fused per-side digest lanes: the two sides'
    digests XOR together (each side digest is what the fused program
    stages, so cross-checks stay per-side)."""
    from risingwave_tpu.integrity import host_digest, join_side_lanes

    import numpy as np

    ld = host_digest(*join_side_lanes(self.left, np.where))
    rd = host_digest(*join_side_lanes(self.right, np.where))
    return ld ^ rd


HashJoinExecutor.checkpoint_table_ids = _join_checkpoint_table_ids
HashJoinExecutor.checkpoint_delta = _join_checkpoint_delta
HashJoinExecutor.restore_state = _join_restore_state
HashJoinExecutor.digest_lanes = _join_digest_lanes
HashJoinExecutor.state_digest = _join_state_digest
