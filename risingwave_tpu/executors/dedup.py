"""Append-only dedup executor — streaming DISTINCT on a key.

Reference: src/stream/src/executor/dedup/append_only_dedup.rs — emits
each pk's FIRST row and drops later duplicates; state is the set of
seen pks, cleaned by watermark.

TPU re-design: the seen-set is ops/hash_table.HashTable; one jitted
step does batched lookup-or-insert and emits rows that claimed a new
slot (intra-chunk twins dedupe via first_occurrence_mask). Append-only
by contract: a DELETE in the input latches ``inconsistent`` and raises
at the barrier, like the reference's append-only executors.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.ops.hash_table import HashTable, first_occurrence_mask, lookup_or_insert, read_scalars, stage_scalars, set_live
from risingwave_tpu.runtime.bucketing import (
    BucketAllocator,
    BucketPolicy,
    needs_plan,
    plan_capacity,
)
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    grow_pow2,
    pull_rows,
    stage_marks,
)

GROW_AT = 0.5
# mid-epoch rebuild only when the HOST insert bound nears the table
# itself (MAX_PROBE overflow risk); ordinary growth resolves at the
# barrier from the true occupancy note (HashAgg's twin constant)
HARD_GROW_AT = 0.75


def dedup_step_fn(
    table: HashTable, sdirty, chunk: StreamChunk, keys: Tuple[str, ...]
):
    key_cols = tuple(chunk.col(k) for k in keys)
    signs = chunk.effective_signs()
    saw_delete = jnp.any(chunk.valid & (signs < 0))
    valid = chunk.valid & (signs > 0)
    table, slots, _, inserted = lookup_or_insert(table, key_cols, valid)
    table = set_live(table, jnp.where(inserted, slots, -1), True)
    sdirty = sdirty.at[
        jnp.where(inserted, slots, table.capacity)
    ].set(True, mode="drop")
    dropped = jnp.any(valid & (slots < 0))
    # `inserted` marks a claim's winner AND its same-key twins; keep one
    emit = inserted & first_occurrence_mask(slots, inserted)
    return table, sdirty, chunk.mask(emit), saw_delete, dropped


_dedup_step = partial(
    jax.jit, static_argnames=("keys",), donate_argnums=(0, 1)
)(dedup_step_fn)


@partial(jax.jit, static_argnames=("new_cap",))
def _rebuild(table: HashTable, sdirty, stored, new_cap: int):
    keep = table.live | sdirty  # sdirty dead keys carry pending tombstones
    new = HashTable.create(new_cap, tuple(k.dtype for k in table.keys))
    new, slots, _, _ = lookup_or_insert(new, table.keys, keep)
    new = set_live(new, jnp.where(keep, slots, -1), table.live)
    idx = jnp.where(keep, slots, new_cap)
    new_sdirty = jnp.zeros(new_cap, jnp.bool_).at[idx].set(sdirty, mode="drop")
    new_stored = jnp.zeros(new_cap, jnp.bool_).at[idx].set(stored, mode="drop")
    return new, new_sdirty, new_stored


class AppendOnlyDedupExecutor(Executor, Checkpointable):
    """DISTINCT ON (keys): first row per key passes, duplicates drop.

    ``window_key``: optional (column, retention_ms) — a watermark on
    that key column marks seen-set entries below ``wm - retention``
    dead; the next table rebuild reclaims them (until then late
    duplicates stay suppressed — strictly more exact than the
    reference's cache eviction, never less).
    """

    def __init__(
        self,
        keys: Sequence[str],
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 16,
        window_key: Optional[Tuple[str, int]] = None,
        table_id: str = "dedup",
        bucket_policy: Optional[BucketPolicy] = None,
        bucketed: bool = True,
    ):
        self.keys = tuple(keys)
        self.table_id = table_id
        self.table = HashTable.create(
            capacity, tuple(jnp.dtype(schema_dtypes[k]) for k in self.keys)
        )
        self.sdirty = jnp.zeros(capacity, jnp.bool_)
        self.stored = jnp.zeros(capacity, jnp.bool_)
        self.window_key = window_key
        # shape-stability: capacities drawn from a declared pow2
        # lattice (runtime/bucketing) — ``bucketed=False`` is the
        # legacy unbounded-rehash twin (tests, soak baselines)
        self._buckets = (
            BucketAllocator(
                bucket_policy or BucketPolicy.from_capacity(capacity, grow_at=GROW_AT)
            )
            if bucketed
            else None
        )
        self._bound = 0
        self._occ_note = 0  # true claimed at the last barrier (staged)
        self._grew_midepoch = False  # one overflow-guard bump per epoch
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)

    def lint_info(self):
        expects = {
            k: lane.dtype for k, lane in zip(self.keys, self.table.keys)
        }
        return {
            "expects": expects,
            "keys": self.keys,
            "table_ids": (self.table_id,),
            "window_key": self.window_key[0] if self.window_key else None,
        }

    def trace_contract(self):
        contract = {
            "kind": "device",
            "trace_step": lambda c: _dedup_step(
                self.table, self.sdirty, c, self.keys
            ),
            "state": (self.table, self.sdirty),
            "donate": True,
            "emission": "passthrough",
            # the seen-set's capacities are drawn from the allocator's
            # declared pow2 lattice: window churn is bounded to one
            # trace per bucket (None only on the legacy unbucketed twin)
            "window_buckets": (
                self._buckets.lattice if self._buckets is not None else None
            ),
        }
        if self._buckets is not None:
            # the interpreted growth path's packed read exists only
            # where interpretation runs: the fused program's wrapper
            # plans from barrier notes instead (_grow_hint) — the
            # analyzer scores it as fallback-only, not a blocker
            contract["fallback_syncs"] = ("_maybe_grow",)
        return contract

    def pin_max_bucket(self):
        """ShapeGovernor hook: freeze the seen-set at its high-water
        bucket (shrink disabled; applied by the next apply)."""
        if self._buckets is None:
            return {"pinned": False}
        return {
            "table_id": self.table_id,
            "pinned_cap": self._buckets.pin(),
        }

    def padding_stats(self):
        return {
            "capacity": self.table.capacity,
            "live": int(self.table.num_live()),
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        for k in self.keys:
            if k in chunk.nulls:
                raise ValueError(
                    f"dedup key {k!r} carries a null lane (unsupported)"
                )
        self._maybe_grow(chunk.capacity)
        self._bound += chunk.capacity
        self.table, self.sdirty, out, saw_delete, dropped = _dedup_step(
            self.table, self.sdirty, chunk, self.keys
        )
        self._saw_delete = self._saw_delete | saw_delete
        self._dropped = self._dropped | dropped
        return [out]

    def _grow_hint(self, incoming: int):
        """The FUSED wrapper's pre-dispatch growth bookkeeping: ZERO
        device reads. The host bound counts padded chunk capacities —
        letting the exact planner size from it over-grows by buckets —
        so the fused path bumps ONE bucket, at most once per epoch,
        purely as MAX_PROBE headroom (BucketAllocator.bump); ordinary
        growth/shrink resolves at the barrier from the staged true
        occupancy note (_on_barrier_scalars). A genuinely faster
        blow-up still trips the overflow latch, the existing
        contract."""
        if self._buckets is None:
            return self._maybe_grow(incoming)
        cap = self.table.capacity
        self._bound = min(self._bound, cap)
        if self._grew_midepoch or (
            self._bound + incoming <= cap * HARD_GROW_AT
        ):
            return
        new_cap = self._buckets.bump(cap)
        if new_cap is not None:
            self.table, self.sdirty, self.stored = _rebuild(
                self.table, self.sdirty, self.stored, new_cap
            )
            self._bound = min(self._bound, new_cap)
        self._grew_midepoch = True

    def _maybe_grow(self, incoming: int):
        """INTERPRETED-path growth: the exact legacy policy — when the
        load-factor trigger (or a pending shrink / governor-pin
        wakeup) trips, ONE packed blocking read learns the true
        occupancy and plans from it. Declared under the contract's
        ``fallback_syncs`` on bucketed instances: the fused per-
        barrier program never calls this method (the wrapper's
        _grow_hint + barrier-note planning are its replacement), so
        the read runs only where interpretation runs — the analyzer
        scores it as fallback_sync_points, outside the fusibility
        verdict (the HashAgg _flush_all discipline)."""
        cap = self.table.capacity
        if not needs_plan(self._buckets, cap, self._bound, incoming, GROW_AT):
            return
        # ONE packed read: tunneled-TPU round-trips dominate
        claimed, survivors = read_scalars(
            self.table.occupancy(),
            jnp.sum((self.table.live | self.sdirty).astype(jnp.int32)),
        )
        new_cap = plan_capacity(
            self._buckets, cap, incoming, claimed, survivors, GROW_AT
        )
        if new_cap is not None:
            self.table, self.sdirty, self.stored = _rebuild(
                self.table, self.sdirty, self.stored, new_cap
            )
            claimed = int(self.table.occupancy())
        self._bound = claimed

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        # staged read; finish_barrier materializes after the walk
        self._staged_scalars = stage_scalars(
            self._saw_delete,
            self._dropped,
            self.table.occupancy(),
            jnp.sum((self.table.live | self.sdirty).astype(jnp.int32)),
        )
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return []

    def _on_barrier_scalars(self, vals) -> None:
        saw_delete, dropped, claimed, survivors = vals
        self._grew_midepoch = False
        epoch_inc = max(self._bound - self._occ_note, 0)
        self._occ_note = int(claimed)
        self._bound = int(claimed)
        if self._buckets is not None:
            cap = self.table.capacity
            self._buckets.note_barrier(cap, int(claimed))
            new_cap = self._buckets.plan(
                cap,
                0,
                int(claimed),
                int(survivors),
                margin=max(int(claimed), epoch_inc),
            )
            if new_cap is not None and new_cap != cap:
                self.table, self.sdirty, self.stored = _rebuild(
                    self.table, self.sdirty, self.stored, new_cap
                )
        if saw_delete:
            raise RuntimeError("append-only dedup received a DELETE")
        if dropped:
            raise RuntimeError("dedup table overflowed MAX_PROBE; grow capacity")

    def on_watermark(self, watermark: Watermark):
        if self.window_key is None or watermark.column != self.window_key[0]:
            return watermark, []
        cutoff = jnp.asarray(
            watermark.value - self.window_key[1], jnp.int64
        )
        lane = self.table.keys[self.keys.index(self.window_key[0])]
        expired = self.table.live & (lane < cutoff)
        slots = jnp.where(
            expired, jnp.arange(self.table.capacity, dtype=jnp.int32), -1
        )
        self.table = set_live(self.table, slots, False)
        self.sdirty = self.sdirty | expired
        return watermark, []

    # -- integrity --------------------------------------------------------
    def digest_lanes(self):
        from risingwave_tpu.integrity import dedup_lanes

        return dedup_lanes(self.table)

    def state_digest(self) -> int:
        """Host twin of the fused digest lane (integrity.dedup_lanes)."""
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self):
        import numpy as np

        sdirty = np.asarray(self.sdirty)
        if not sdirty.any():
            return []
        upsert, tomb, sel = stage_marks(
            sdirty, np.asarray(self.table.live), np.asarray(self.stored)
        )
        lanes = {f"k{i}": l for i, l in enumerate(self.table.keys)}
        keys = pull_rows(lanes, sel)
        self.stored = (self.stored | jnp.asarray(upsert)) & ~jnp.asarray(tomb)
        self.sdirty = jnp.zeros_like(self.sdirty)
        return [
            StateDelta(
                self.table_id,
                keys,
                {},
                tomb[sel],
                tuple(f"k{i}" for i in range(len(self.table.keys))),
            )
        ]

    def restore_state(self, table_id, key_cols, value_cols):
        import numpy as np

        n = len(next(iter(key_cols.values()))) if key_cols else 0
        key_dtypes = tuple(k.dtype for k in self.table.keys)
        cap = grow_pow2(n, self.table.capacity, GROW_AT)
        table = HashTable.create(cap, key_dtypes)
        self.sdirty = jnp.zeros(cap, jnp.bool_)
        self.stored = jnp.zeros(cap, jnp.bool_)
        if n:
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d))
                for i, d in enumerate(key_dtypes)
            )
            table, slots, _, _ = lookup_or_insert(
                table, lanes, jnp.ones(n, jnp.bool_)
            )
            table = set_live(table, slots, True)
            self.stored = self.stored.at[slots].set(True)
        self.table = table
        self._bound = int(n)
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)
