"""Per-epoch chunk batching — fuse a stateless prefix into HashAgg's
one-device-program-per-epoch path.

The reference's benched executor IS its production executor (the
criterion harness drives the real HashAggExecutor,
src/stream/src/executor/hash_agg.rs:62 + src/stream/benches/). This
wrapper gives the planner-built actor graph the same property on TPU:
instead of one device dispatch per chunk (per-chunk Python dispatch
dominates on a tunneled TPU), the fragment accumulates the epoch's
chunks and applies them in ONE fused XLA program — the stateless prefix
(filter/project/hop) traced into the same program through
``HashAggExecutor.apply_stacked``'s ``pre`` hook.

Emission semantics are unchanged: HashAgg emits only at barriers /
watermarks, and the wrapper flushes its buffer before delegating either,
so downstream executors observe byte-identical streams.

Since the fused per-barrier step landed (runtime/fused_step.py), this
wrapper is the designated FALLBACK for agg runs the fused program
cannot absorb whole: an agg whose flush EXITS to an interpreted
consumer (a join) keeps its exact-sliced interpreted flush but still
gets the one-device-program-per-epoch apply path through this
wrapper. ``ComposedSteps`` and ``_compose_lint_infos`` below are
shared with the fused step (same value-hashing compile discipline,
same composed-metadata rules).

Compile discipline (see docs in array/chunk.py): the stacked leading
axis is padded to a power of two, so at most log2(max chunks/epoch)
distinct programs exist per chunk signature.
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.parallel.sharded_agg import stack_chunks


class ComposedSteps:
    """A chunk->chunk composition of ``functools.partial`` steps with
    VALUE hashing: two compositions of the same (function, static args)
    sequence are equal, so the fused epoch program — which takes the
    composition as a STATIC jit argument — compiles once per plan
    shape, not once per wrapper instance (graph rebuilds and fresh
    planner passes hit the cache; a recompile is ~30-40s on the
    tunneled TPU)."""

    __slots__ = ("steps", "_key", "_hash", "__weakref__")

    def __init__(self, steps):
        self.steps = tuple(steps)
        self._key = tuple(
            (s.func, s.args, tuple(sorted(s.keywords.items())))
            for s in self.steps
        )
        # the composition is a STATIC jit argument hashed on every
        # fused dispatch: pay the partial-tuple hash once, not per
        # barrier (tuples do not cache their hash)
        self._hash = hash(self._key)

    def __call__(self, chunk):
        # Under an ACTIVE lifted-literal param scope, inline the
        # UNJITTED step bodies: a nested pjit call caches its jaxpr
        # keyed by (statics, avals) ONLY, so an ambient value read
        # during tracing (expr.LiftedLit -> param_scope) would be
        # baked into that cached jaxpr as a leaked tracer const and
        # poison the next trace. Inlining makes the ambient read an
        # ordinary intermediate of the outer trace. Without params the
        # nested-jit jaxpr cache is safe AND cheaper (baked plans
        # re-trace the cached jaxpr instead of the step bodies).
        from risingwave_tpu.expr.expr import params_active

        if params_active():
            for f in self.steps:
                inner = getattr(f.func, "__wrapped__", None)
                chunk = (
                    inner(chunk, *f.args, **f.keywords)
                    if inner is not None
                    else f(chunk)
                )
            return chunk
        for f in self.steps:
            chunk = f(chunk)
        return chunk

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return (
            isinstance(other, ComposedSteps) and self._key == other._key
        )


class EpochBatchedAggExecutor(Executor):
    """[stateless-pure*, HashAgg] fused into a per-epoch batched op.

    The wrapped ``agg`` object is SHARED with the pipeline's checkpoint
    registry (GraphPipeline holds the original executor objects), so
    checkpoint/restore, cold-tier eviction and state introspection all
    keep working through the original reference — only the actor's data
    path goes through this wrapper.
    """

    def __init__(
        self,
        prefix: Sequence[Executor],
        agg: HashAggExecutor,
        mode: str = "reduce",
    ):
        self.prefix = list(prefix)
        self.agg = agg
        self.mode = mode
        pures = tuple(p.pure_step() for p in self.prefix)
        if any(f is None for f in pures):
            raise ValueError("prefix executors must expose pure_step()")
        self._pre = ComposedSteps(pures) if pures else None
        self._buf: List[StreamChunk] = []
        self._sig = None

    # -- static metadata --------------------------------------------------
    def lint_info(self):
        """The composition of the members' metadata: the wrapper IS
        ``prefix... ; agg`` to the verifier. Opacity propagates — if
        any member exposes nothing, the wrapper exposes nothing (the
        verifier never guesses)."""
        infos = []
        for m in list(self.prefix) + [self.agg]:
            fn = getattr(m, "lint_info", None)
            info = fn() if fn is not None else None
            if info is None:
                return None
            infos.append(info)
        return _compose_lint_infos(infos)

    def state_nbytes(self) -> int:
        """Memory-ledger contract: all state lives in the wrapped agg
        (the prefix is stateless-pure by construction)."""
        fn = getattr(self.agg, "state_nbytes", None)
        return int(fn()) if fn is not None else 0

    def trace_contract(self):
        inner = self.agg.trace_contract()
        if inner is None:
            return None
        contract = dict(inner)
        # the fused epoch program IS apply_stacked: prefix pure steps
        # trace into the agg's program; the per-chunk trace_step stays
        # the agg's (same kernels, same state)
        contract["hot_methods"] = tuple(
            contract.get("hot_methods", ())
        ) + ("flush",)
        return contract

    # -- data path --------------------------------------------------------
    @staticmethod
    def _signature(c: StreamChunk):
        """Chunks must agree on capacity/columns/null lanes/dtypes to
        stack; a signature change flushes the current buffer."""
        return (
            c.capacity,
            tuple(sorted((k, str(v.dtype)) for k, v in c.columns.items())),
            tuple(sorted(c.nulls)),
        )

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        sig = self._signature(chunk)
        if self._sig is not None and sig != self._sig:
            self.flush()
        self._sig = sig
        self._buf.append(chunk)
        return []

    def flush(self) -> None:
        """Apply everything buffered in one device dispatch."""
        buf, self._buf = self._buf, []
        self._sig = None
        if not buf:
            return
        n = len(buf)
        target = 1 << (n - 1).bit_length() if n > 1 else 1
        if target > n:
            c0 = buf[0]
            empty = StreamChunk(
                c0.columns, jnp.zeros_like(c0.valid), c0.nulls, c0.ops
            )
            buf = buf + [empty] * (target - n)
        self.agg.apply_stacked(
            stack_chunks(buf), pre=self._pre, mode=self.mode
        )

    # -- control path -----------------------------------------------------
    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        self.flush()
        return self.agg.on_barrier(barrier)

    def on_watermark(self, watermark: Watermark):
        # buffered rows precede the watermark in stream order: apply
        # them before any state cleaning the watermark triggers
        self.flush()
        outs: List[StreamChunk] = []
        wm = watermark
        for p in self.prefix:
            wm, o = p.on_watermark(wm)
            outs.extend(o)
            if wm is None:
                return None, outs
        wm, o = self.agg.on_watermark(wm)
        outs.extend(o)
        return wm, outs

    def emit_watermark(self):
        # fused prefix members never generate watermarks (enforced by
        # fuse_epoch_batch); only the agg can (EOWC)
        return self.agg.emit_watermark()

    def finish_barrier(self) -> None:
        for p in self.prefix:
            p.finish_barrier()
        self.agg.finish_barrier()

    def capture_checkpoint(self) -> None:
        # pipelined barriers: the actor seals the wrapped agg's delta
        # (the agg object is the one the checkpoint registry holds)
        self.agg.capture_checkpoint()


def _compose_lint_infos(infos):
    """Fold a member sequence's lint_info dicts into ONE equivalent
    dict (the wrapper's view). Conservative by construction: anything
    that cannot be traced back to the wrapper's input column space is
    dropped rather than guessed, so a composed plan can only LOSE
    checks relative to walking the members individually, never gain
    false positives."""
    rmap = {}  # current-schema col -> wrapper-input col (None=computed)

    def back(col):
        return rmap.get(col, col)

    requires, expects = set(), {}
    table_ids: List[str] = []
    wmap = {}
    window_key = None
    emits_final, renames_final, keys_final = None, None, None
    for pos, info in enumerate(infos):
        reqs = set(info.get("requires") or ()) | set(
            info.get("expects") or {}
        )
        for r in sorted(reqs):
            src = back(r)
            if src is not None:
                requires.add(src)
                dt = (info.get("expects") or {}).get(r)
                if dt is not None and src not in expects:
                    expects[src] = dt
        table_ids.extend(info.get("table_ids") or ())
        wk = info.get("window_key")
        if wk is not None and window_key is None and pos == 0:
            # only a first-member window key is expressible at the
            # wrapper boundary (later members see internally-derived
            # watermark columns the boundary cannot name)
            window_key = wk
        for in_col, out_col in (info.get("watermark_map") or {}).items():
            src = back(in_col)
            if src is not None:
                wmap[src] = out_col
        emits = info.get("emits")
        if emits is not None:
            renames = info.get("renames") or {}
            new_rmap = {}
            for out in emits:
                src = renames.get(out)
                new_rmap[out] = back(src) if src is not None else None
            rmap = new_rmap
            emits_final = dict(emits)
            renames_final = dict(rmap)
            ks = info.get("keys")
            if ks:
                mapped = tuple(back(k) for k in ks)
                keys_final = (
                    mapped if all(m is not None for m in mapped) else None
                )
        else:
            for col in info.get("adds") or {}:
                rmap = dict(rmap)
                rmap[col] = None  # computed mid-composition
    out = {
        "requires": tuple(sorted(requires)),
        "expects": expects,
        "table_ids": tuple(table_ids),
    }
    if emits_final is not None:
        out["emits"] = emits_final
        out["renames"] = renames_final or {}
    if keys_final:
        out["keys"] = keys_final
    if window_key is not None:
        out["window_key"] = window_key
    if wmap:
        out["watermark_map"] = wmap
    return out


def fuse_epoch_batch(chain: Sequence[Executor]) -> List[Executor]:
    """Rewrite every ``[stateless-pure*, HashAgg]`` run in an actor
    chain into an EpochBatchedAggExecutor. Anything that breaks the
    run (stateful op, watermark generator, no pure_step) passes through
    untouched, as does a HashAgg with no preceding run (still batched:
    the wrapper works with an empty prefix)."""
    out: List[Executor] = []
    run: List[Executor] = []
    for ex in chain:
        if type(ex) is HashAggExecutor:
            out.append(EpochBatchedAggExecutor(run, ex))
            run = []
        elif (
            ex.pure_step() is not None
            and type(ex).emit_watermark is Executor.emit_watermark
        ):
            run.append(ex)
        else:
            out.extend(run)
            run = []
            out.append(ex)
    out.extend(run)
    return out
