"""Expand executor — row duplication for GROUPING SETS.

Reference: src/stream/src/executor/expand.rs — each input row is
emitted once per column subset with the columns OUTSIDE the subset
replaced by NULL and a ``flag`` column identifying the subset; a
downstream HashAgg grouping on (keys..., flag) then computes every
grouping set in one pass.

TPU re-design (the hop-window recipe): K = len(subsets) is static, so
a chunk of capacity C becomes one chunk of capacity C*K — copy k forms
a contiguous block (U-/U+ adjacency preserved), with copy k's
out-of-subset columns carrying an all-True null lane. Pure tiling +
masks; no loops, no dynamic shapes.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor


@partial(jax.jit, static_argnames=("subsets", "names", "flag_col"))
def _expand_step(chunk: StreamChunk, subsets, names, flag_col: str):
    cap = chunk.capacity
    k = len(subsets)
    tile = lambda a: jnp.tile(a, k)
    cols = {n: tile(a) for n, a in chunk.columns.items()}
    cols[flag_col] = jnp.repeat(jnp.arange(k, dtype=jnp.int64), cap)
    nulls = {}
    for n in names:
        base = chunk.nulls.get(n)
        lanes = []
        for subset in subsets:
            if n in subset:
                lanes.append(
                    base
                    if base is not None
                    else jnp.zeros(cap, jnp.bool_)
                )
            else:  # outside the subset: NULL in this copy
                lanes.append(jnp.ones(cap, jnp.bool_))
        nulls[n] = jnp.concatenate(lanes)
    # columns not mentioned in any subset keep their own null lanes
    for n, lane in chunk.nulls.items():
        if n not in nulls:
            nulls[n] = tile(lane)
    return StreamChunk(cols, tile(chunk.valid), nulls, tile(chunk.ops))


class ExpandExecutor(Executor):
    """GROUPING SETS expansion: ``subsets`` lists, per output copy, the
    columns that KEEP their values (the grouping set); all other listed
    columns become NULL in that copy; ``flag_col`` carries the subset
    ordinal (group on (cols..., flag) downstream)."""

    def __init__(
        self,
        subsets: Sequence[Sequence[str]],
        flag_col: str = "flag",
    ):
        if not subsets:
            raise ValueError("expand needs at least one subset")
        self.subsets = tuple(tuple(s) for s in subsets)
        # the union of all subset columns is what expansion touches
        self.names = tuple(
            sorted({c for s in self.subsets for c in s})
        )
        self.flag_col = flag_col

    def lint_info(self):
        import jax.numpy as _jnp

        return {
            "requires": self.names,
            "adds": {self.flag_col: _jnp.int64},
            "table_ids": (),
        }

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: _expand_step(
                c, self.subsets, self.names, self.flag_col
            ),
            "state": None,
            "donate": True,
            # output capacity is input capacity x len(subsets): a pure
            # function of the input bucket
            "emission": "passthrough",
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        missing = [n for n in self.names if n not in chunk.columns]
        if missing:
            raise KeyError(f"expand subset columns not in chunk: {missing}")
        if self.flag_col in chunk.columns:
            raise ValueError(
                f"flag column {self.flag_col!r} collides with an input "
                "column; pass a different flag_col"
            )
        return [
            _expand_step(chunk, self.subsets, self.names, self.flag_col)
        ]
