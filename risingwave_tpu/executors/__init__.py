"""Streaming executors — the dataflow operators.

Reference: src/stream/src/executor/ — each operator is an async stream
transformer over Message::{Chunk, Barrier, Watermark}
(src/stream/src/executor/mod.rs:180,871).

TPU re-design: an executor is a thin host object owning device state
(pytrees) and calling pure jit-compiled step kernels. The host drives
epochs; barriers are plain step boundaries, not async events. Chains of
stateless executors fuse into single XLA programs.
"""

from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.executors.filter import FilterExecutor
from risingwave_tpu.executors.project import ProjectExecutor
from risingwave_tpu.executors.hop_window import HopWindowExecutor
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.executors.dedup import AppendOnlyDedupExecutor
from risingwave_tpu.executors.dynamic_filter import DynamicMaxFilterExecutor
from risingwave_tpu.executors.hash_join import HashJoinExecutor
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.executors.generators import NowExecutor, ValuesExecutor
from risingwave_tpu.executors.row_id_gen import RowIdGenExecutor
from risingwave_tpu.executors.simple_agg import SimpleAggExecutor
from risingwave_tpu.executors.sort import SortExecutor
from risingwave_tpu.executors.top_n import GroupTopNExecutor
from risingwave_tpu.executors.top_n_plain import (
    RetractableGroupTopNExecutor,
    TopNExecutor,
)
from risingwave_tpu.executors.watermark_filter import WatermarkFilterExecutor

__all__ = [
    "NowExecutor",
    "ValuesExecutor",
    "SimpleAggExecutor",
    "SortExecutor",
    "TopNExecutor",
    "RetractableGroupTopNExecutor",
    "WatermarkFilterExecutor",
    "Barrier",
    "Watermark",
    "Executor",
    "FilterExecutor",
    "ProjectExecutor",
    "HopWindowExecutor",
    "HashAggExecutor",
    "AppendOnlyDedupExecutor",
    "DynamicMaxFilterExecutor",
    "HashJoinExecutor",
    "GroupTopNExecutor",
    "MaterializeExecutor",
    "RowIdGenExecutor",
]
