"""Dynamic filter against a per-group running extreme.

Reference: src/stream/src/executor/dynamic_filter.rs:40 — filters the
left input against a dynamically-changing right-side value. This is the
grouped, append-only specialization the reference's q7 plan leans on:
pass a row iff ``value >= max-so-far(group)``.

Why it exists: q7 joins bids against the per-window MAX. Storing every
bid in the join would need per-(window, price) bucket fanout sized for
the duplication of the Nexmark price distribution's low end (~50+ at
p=100), almost all of it dead weight — a bid below its window's
current max can NEVER match a future max (append-only max is
monotone), so dropping it early is semantics-preserving. What remains
in the join is the ascending-maxima chain + ties: O(log prices) per
window instead of O(bids).

The comparison uses the max BEFORE the current chunk (conservative:
same-chunk stragglers pass and are dropped by the join probe instead),
then folds the chunk into the running max — one fused jit step.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.ops.hash_table import (
    HashTable,
    lookup_or_insert,
    plan_rehash,
    read_scalars,
    stage_scalars,
    finish_scalars,
    set_live,
)
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    grow_pow2,
    pull_rows,
    stage_marks,
)

GROW_AT = 0.5


@partial(
    jax.jit, static_argnames=("group_col", "value_col"), donate_argnums=(0, 1, 2)
)
def _filter_step(
    table: HashTable,
    maxes: jnp.ndarray,
    sdirty: jnp.ndarray,
    chunk: StreamChunk,
    group_col: str,
    value_col: str,
):
    keys = (chunk.col(group_col),)
    value = chunk.col(value_col)
    signs = chunk.effective_signs()
    saw_delete = jnp.any(chunk.valid & (signs < 0))
    valid = chunk.valid & (signs > 0)

    table, slots, _, inserted = lookup_or_insert(table, keys, valid)
    table = set_live(table, jnp.where(inserted, slots, -1), True)
    dropped = jnp.any(valid & (slots < 0))
    sl = jnp.maximum(slots, 0)

    # pass iff >= the PRE-chunk max of the row's group (new groups pass)
    ok = valid & (inserted | (value >= maxes[sl]))
    # then fold this chunk in: scatter-max (new groups start at value)
    cap = maxes.shape[0]
    idx = jnp.where(valid, slots, cap)
    init = jnp.iinfo(maxes.dtype).min
    cleared = maxes.at[idx].set(
        jnp.where(inserted, init, maxes[sl]), mode="drop"
    )
    maxes = cleared.at[idx].max(value, mode="drop")
    sdirty = sdirty.at[idx].set(True, mode="drop")
    return table, maxes, sdirty, chunk.mask(ok), saw_delete, dropped


@partial(jax.jit, static_argnames=("new_cap",))
def _rebuild(table: HashTable, maxes: jnp.ndarray, sdirty, stored, new_cap: int):
    keep = table.live | sdirty
    new = HashTable.create(new_cap, tuple(k.dtype for k in table.keys))
    new, slots, _, _ = lookup_or_insert(new, table.keys, keep)
    new = set_live(new, jnp.where(keep, slots, -1), table.live)
    idx = jnp.where(keep, slots, new_cap)
    new_maxes = jnp.full(new_cap, jnp.iinfo(maxes.dtype).min, maxes.dtype)
    new_maxes = new_maxes.at[idx].set(maxes, mode="drop")
    new_sdirty = jnp.zeros(new_cap, jnp.bool_).at[idx].set(sdirty, mode="drop")
    new_stored = jnp.zeros(new_cap, jnp.bool_).at[idx].set(stored, mode="drop")
    return new, new_maxes, new_sdirty, new_stored


class DynamicMaxFilterExecutor(Executor, Checkpointable):
    """Append-only: pass rows with ``value_col >= running max`` of their
    ``group_col`` group. Conservative (may pass superseded rows; never
    drops a row that could still match a future group max)."""

    def __init__(
        self,
        group_col: str,
        value_col: str,
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 14,
        window_key: Optional[Tuple[str, int]] = None,
        table_id: str = "dynfilter",
    ):
        self.group_col = group_col
        self.value_col = value_col
        self.table_id = table_id
        self.table = HashTable.create(
            capacity, (jnp.dtype(schema_dtypes[group_col]),)
        )
        vdtype = jnp.dtype(schema_dtypes[value_col])
        self.maxes = jnp.full(capacity, jnp.iinfo(vdtype).min, vdtype)
        self.sdirty = jnp.zeros(capacity, jnp.bool_)
        self.stored = jnp.zeros(capacity, jnp.bool_)
        self.window_key = window_key
        self._bound = 0
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        if self.group_col in chunk.nulls or self.value_col in chunk.nulls:
            raise ValueError("dynamic filter columns must be non-nullable")
        self._maybe_grow(chunk.capacity)
        self._bound += chunk.capacity
        (
            self.table,
            self.maxes,
            self.sdirty,
            out,
            saw_delete,
            dropped,
        ) = _filter_step(
            self.table,
            self.maxes,
            self.sdirty,
            chunk,
            self.group_col,
            self.value_col,
        )
        self._saw_delete = self._saw_delete | saw_delete
        self._dropped = self._dropped | dropped
        return [out]

    def _maybe_grow(self, incoming: int):
        cap = self.table.capacity
        if self._bound + incoming <= cap * GROW_AT:
            return
        # ONE packed read: tunneled-TPU round-trips dominate
        claimed, survivors = read_scalars(
            self.table.occupancy(),
            jnp.sum((self.table.live | self.sdirty).astype(jnp.int32)),
        )
        new_cap = plan_rehash(cap, incoming, claimed, survivors, GROW_AT)
        if new_cap is not None:
            self.table, self.maxes, self.sdirty, self.stored = _rebuild(
                self.table, self.maxes, self.sdirty, self.stored, new_cap
            )
            claimed = int(self.table.occupancy())
        self._bound = claimed

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        self._staged_scalars = stage_scalars(
            self._saw_delete, self._dropped, self.table.occupancy()
        )
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return []

    def _on_barrier_scalars(self, vals) -> None:
        saw_delete, dropped, claimed = vals
        self._bound = int(claimed)
        if saw_delete:
            raise RuntimeError("dynamic max filter received a DELETE")
        if dropped:
            raise RuntimeError(
                "dynamic filter table overflowed MAX_PROBE; grow capacity"
            )

    def on_watermark(self, watermark: Watermark):
        if self.window_key is None or watermark.column != self.window_key[0]:
            return watermark, []
        cutoff = jnp.asarray(watermark.value - self.window_key[1], jnp.int64)
        lane = self.table.keys[0]
        expired = self.table.live & (lane < cutoff)
        slots = jnp.where(
            expired, jnp.arange(self.table.capacity, dtype=jnp.int32), -1
        )
        self.table = set_live(self.table, slots, False)
        self.sdirty = self.sdirty | expired
        return watermark, []

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self):
        import numpy as np

        sdirty = np.asarray(self.sdirty)
        if not sdirty.any():
            return []
        upsert, tomb, sel = stage_marks(
            sdirty, np.asarray(self.table.live), np.asarray(self.stored)
        )
        pulled = pull_rows(
            {"k0": self.table.keys[0], "max": self.maxes}, sel
        )
        keys = {"k0": pulled["k0"]}
        vals = {"max": pulled["max"]}
        self.stored = (self.stored | jnp.asarray(upsert)) & ~jnp.asarray(tomb)
        self.sdirty = jnp.zeros_like(self.sdirty)
        return [StateDelta(self.table_id, keys, vals, tomb[sel], ("k0",))]

    def restore_state(self, table_id, key_cols, value_cols):
        import numpy as np

        n = len(next(iter(key_cols.values()))) if key_cols else 0
        kd = self.table.keys[0].dtype
        vdtype = self.maxes.dtype
        cap = grow_pow2(n, self.table.capacity, GROW_AT)
        table = HashTable.create(cap, (kd,))
        maxes = jnp.full(cap, jnp.iinfo(vdtype).min, vdtype)
        self.sdirty = jnp.zeros(cap, jnp.bool_)
        self.stored = jnp.zeros(cap, jnp.bool_)
        if n:
            lanes = (jnp.asarray(np.asarray(key_cols["k0"], dtype=kd)),)
            table, slots, _, _ = lookup_or_insert(
                table, lanes, jnp.ones(n, jnp.bool_)
            )
            table = set_live(table, slots, True)
            maxes = maxes.at[slots].set(
                jnp.asarray(value_cols["max"].astype(vdtype))
            )
            self.stored = self.stored.at[slots].set(True)
        self.table, self.maxes = table, maxes
        self._bound = int(n)
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)
