"""Dynamic filter against a per-group running extreme.

Reference: src/stream/src/executor/dynamic_filter.rs:40 — filters the
left input against a dynamically-changing right-side value. This is the
grouped, append-only specialization the reference's q7 plan leans on:
pass a row iff ``value >= max-so-far(group)``.

Why it exists: q7 joins bids against the per-window MAX. Storing every
bid in the join would need per-(window, price) bucket fanout sized for
the duplication of the Nexmark price distribution's low end (~50+ at
p=100), almost all of it dead weight — a bid below its window's
current max can NEVER match a future max (append-only max is
monotone), so dropping it early is semantics-preserving. What remains
in the join is the ascending-maxima chain + ties: O(log prices) per
window instead of O(bids).

The comparison uses the max BEFORE the current chunk (conservative:
same-chunk stragglers pass and are dropped by the join probe instead),
then folds the chunk into the running max — one fused jit step.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.ops.hash_table import HashTable, lookup_or_insert, read_scalars, stage_scalars, set_live
from risingwave_tpu.runtime.bucketing import (
    BucketAllocator,
    BucketPolicy,
    emission_bucket,
    needs_plan,
    plan_capacity,
)
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    grow_pow2,
    pull_rows,
    stage_marks,
)

GROW_AT = 0.5
# mid-epoch rebuild only when the HOST insert bound nears the table
# itself (MAX_PROBE overflow risk); ordinary growth resolves at the
# barrier from the true occupancy note (HashAgg's twin constant)
HARD_GROW_AT = 0.75


def filter_step_fn(
    table: HashTable,
    maxes: jnp.ndarray,
    sdirty: jnp.ndarray,
    chunk: StreamChunk,
    group_col: str,
    value_col: str,
):
    keys = (chunk.col(group_col),)
    value = chunk.col(value_col)
    signs = chunk.effective_signs()
    saw_delete = jnp.any(chunk.valid & (signs < 0))
    valid = chunk.valid & (signs > 0)

    table, slots, _, inserted = lookup_or_insert(table, keys, valid)
    table = set_live(table, jnp.where(inserted, slots, -1), True)
    dropped = jnp.any(valid & (slots < 0))
    sl = jnp.maximum(slots, 0)

    # pass iff >= the PRE-chunk max of the row's group (new groups pass)
    ok = valid & (inserted | (value >= maxes[sl]))
    # then fold this chunk in: scatter-max (new groups start at value)
    cap = maxes.shape[0]
    idx = jnp.where(valid, slots, cap)
    init = jnp.iinfo(maxes.dtype).min
    cleared = maxes.at[idx].set(
        jnp.where(inserted, init, maxes[sl]), mode="drop"
    )
    maxes = cleared.at[idx].max(value, mode="drop")
    sdirty = sdirty.at[idx].set(True, mode="drop")
    return table, maxes, sdirty, chunk.mask(ok), saw_delete, dropped


# the un-jitted body (filter_step_fn) is what the fused two-input
# program scans over a stacked epoch (runtime/fused_step); this jitted
# form is the interpreted per-chunk path
_filter_step = partial(
    jax.jit, static_argnames=("group_col", "value_col"), donate_argnums=(0, 1, 2)
)(filter_step_fn)


@partial(jax.jit, static_argnames=("new_cap",))
def _rebuild(table: HashTable, maxes: jnp.ndarray, sdirty, stored, new_cap: int):
    keep = table.live | sdirty
    new = HashTable.create(new_cap, tuple(k.dtype for k in table.keys))
    new, slots, _, _ = lookup_or_insert(new, table.keys, keep)
    new = set_live(new, jnp.where(keep, slots, -1), table.live)
    idx = jnp.where(keep, slots, new_cap)
    new_maxes = jnp.full(new_cap, jnp.iinfo(maxes.dtype).min, maxes.dtype)
    new_maxes = new_maxes.at[idx].set(maxes, mode="drop")
    new_sdirty = jnp.zeros(new_cap, jnp.bool_).at[idx].set(sdirty, mode="drop")
    new_stored = jnp.zeros(new_cap, jnp.bool_).at[idx].set(stored, mode="drop")
    return new, new_maxes, new_sdirty, new_stored


class DynamicMaxFilterExecutor(Executor, Checkpointable):
    """Append-only: pass rows with ``value_col >= running max`` of their
    ``group_col`` group. Conservative (may pass superseded rows; never
    drops a row that could still match a future group max)."""

    def __init__(
        self,
        group_col: str,
        value_col: str,
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 14,
        window_key: Optional[Tuple[str, int]] = None,
        table_id: str = "dynfilter",
        bucket_policy: Optional[BucketPolicy] = None,
        bucketed: bool = True,
    ):
        self.group_col = group_col
        self.value_col = value_col
        self.table_id = table_id
        self.table = HashTable.create(
            capacity, (jnp.dtype(schema_dtypes[group_col]),)
        )
        vdtype = jnp.dtype(schema_dtypes[value_col])
        self.maxes = jnp.full(capacity, jnp.iinfo(vdtype).min, vdtype)
        self.sdirty = jnp.zeros(capacity, jnp.bool_)
        self.stored = jnp.zeros(capacity, jnp.bool_)
        self.window_key = window_key
        # shape-stability: the per-window max state walks a declared
        # pow2 bucket lattice (grow-eager/shrink-lazy hysteresis);
        # bucketed=False keeps the legacy unbounded-rehash twin (the
        # RW-E803 wedge class, for tests and soak baselines)
        self._buckets = (
            BucketAllocator(
                bucket_policy or BucketPolicy.from_capacity(capacity, grow_at=GROW_AT)
            )
            if bucketed
            else None
        )
        self._bound = 0
        self._occ_note = 0  # true claimed at the last barrier (staged)
        self._grew_midepoch = False  # one overflow-guard bump per epoch
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)

    def lint_info(self):
        return {
            "expects": {
                self.group_col: self.table.keys[0].dtype,
                self.value_col: self.maxes.dtype,
            },
            "keys": (self.group_col,),
            "table_ids": (self.table_id,),
            "window_key": self.window_key[0] if self.window_key else None,
        }

    def trace_contract(self):
        contract = {
            "kind": "device",
            "trace_step": lambda c: _filter_step(
                self.table,
                self.maxes,
                self.sdirty,
                c,
                self.group_col,
                self.value_col,
            ),
            "state": (self.table, self.maxes),
            "donate": True,
            "emission": "passthrough",
            # the per-window max state draws its capacities from the
            # allocator's declared pow2 lattice — the q7 pre-filter is
            # off the wedge class (None only on the unbucketed twin)
            "window_buckets": (
                self._buckets.lattice if self._buckets is not None else None
            ),
        }
        if self._buckets is not None:
            # the interpreted growth path's packed read exists only
            # where interpretation runs (the fused wrapper plans from
            # barrier notes instead) — fallback-only, not a blocker
            contract["fallback_syncs"] = ("_maybe_grow",)
        return contract

    def pin_max_bucket(self):
        """ShapeGovernor hook: freeze the max-state at its high-water
        bucket (shrink disabled; regrow applied by the next apply)."""
        if self._buckets is None:
            return {"pinned": False}
        return {
            "table_id": self.table_id,
            "pinned_cap": self._buckets.pin(),
        }

    def padding_stats(self):
        return {
            "capacity": self.table.capacity,
            "live": int(self.table.num_live()),
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        if self.group_col in chunk.nulls or self.value_col in chunk.nulls:
            raise ValueError("dynamic filter columns must be non-nullable")
        self._maybe_grow(chunk.capacity)
        self._bound += chunk.capacity
        (
            self.table,
            self.maxes,
            self.sdirty,
            out,
            saw_delete,
            dropped,
        ) = _filter_step(
            self.table,
            self.maxes,
            self.sdirty,
            chunk,
            self.group_col,
            self.value_col,
        )
        self._saw_delete = self._saw_delete | saw_delete
        self._dropped = self._dropped | dropped
        return [out]

    def _grow_hint(self, incoming: int):
        """The FUSED wrapper's pre-dispatch growth bookkeeping: ZERO
        device reads — one emergency bucket bump per epoch at most
        (BucketAllocator.bump; the host bound counts padded chunk
        capacities, so exact sizing from it over-grows); ordinary
        growth/shrink resolves at the barrier from the staged true
        occupancy note."""
        if self._buckets is None:
            return self._maybe_grow(incoming)
        cap = self.table.capacity
        self._bound = min(self._bound, cap)
        if self._grew_midepoch or (
            self._bound + incoming <= cap * HARD_GROW_AT
        ):
            return
        new_cap = self._buckets.bump(cap)
        if new_cap is not None:
            self.table, self.maxes, self.sdirty, self.stored = _rebuild(
                self.table, self.maxes, self.sdirty, self.stored, new_cap
            )
            self._bound = min(self._bound, new_cap)
        self._grew_midepoch = True

    def _maybe_grow(self, incoming: int):
        """INTERPRETED-path growth: the exact legacy policy (one
        packed blocking read when the trigger trips). Declared under
        ``fallback_syncs`` on bucketed instances — the fused program
        replaces it with _grow_hint + barrier-note planning, so the
        read runs only where interpretation runs."""
        cap = self.table.capacity
        if not needs_plan(self._buckets, cap, self._bound, incoming, GROW_AT):
            return
        # ONE packed read: tunneled-TPU round-trips dominate
        claimed, survivors = read_scalars(
            self.table.occupancy(),
            jnp.sum((self.table.live | self.sdirty).astype(jnp.int32)),
        )
        new_cap = plan_capacity(
            self._buckets, cap, incoming, claimed, survivors, GROW_AT
        )
        if new_cap is not None:
            self.table, self.maxes, self.sdirty, self.stored = _rebuild(
                self.table, self.maxes, self.sdirty, self.stored, new_cap
            )
            claimed = int(self.table.occupancy())
        self._bound = claimed

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        self._staged_scalars = stage_scalars(
            self._saw_delete,
            self._dropped,
            self.table.occupancy(),
            jnp.sum((self.table.live | self.sdirty).astype(jnp.int32)),
        )
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return []

    def _on_barrier_scalars(self, vals) -> None:
        saw_delete, dropped, claimed, survivors = vals
        self._grew_midepoch = False
        epoch_inc = max(self._bound - self._occ_note, 0)
        self._occ_note = int(claimed)
        self._bound = int(claimed)
        if self._buckets is not None:
            cap = self.table.capacity
            self._buckets.note_barrier(cap, int(claimed))
            # barrier-boundary planning from the TRUE note: grow past
            # the load factor, apply pending lazy shrink, honor a
            # governor pin — zero mid-epoch device reads. The margin
            # keeps a shrink from landing below what the mid-epoch
            # overflow guard would immediately regrow.
            new_cap = self._buckets.plan(
                cap,
                0,
                int(claimed),
                int(survivors),
                margin=max(int(claimed), epoch_inc),
            )
            if new_cap is not None and new_cap != cap:
                (
                    self.table,
                    self.maxes,
                    self.sdirty,
                    self.stored,
                ) = _rebuild(
                    self.table, self.maxes, self.sdirty, self.stored, new_cap
                )
        if saw_delete:
            raise RuntimeError("dynamic max filter received a DELETE")
        if dropped:
            raise RuntimeError(
                "dynamic filter table overflowed MAX_PROBE; grow capacity"
            )

    def on_watermark(self, watermark: Watermark):
        if self.window_key is None or watermark.column != self.window_key[0]:
            return watermark, []
        cutoff = jnp.asarray(watermark.value - self.window_key[1], jnp.int64)
        lane = self.table.keys[0]
        expired = self.table.live & (lane < cutoff)
        slots = jnp.where(
            expired, jnp.arange(self.table.capacity, dtype=jnp.int32), -1
        )
        self.table = set_live(self.table, slots, False)
        self.sdirty = self.sdirty | expired
        return watermark, []

    # -- integrity --------------------------------------------------------
    def digest_lanes(self):
        from risingwave_tpu.integrity import filter_lanes

        return filter_lanes(self.table, self.maxes)

    def state_digest(self) -> int:
        """Host twin of the fused digest lane (integrity.filter_lanes)."""
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self):
        import numpy as np

        sdirty = np.asarray(self.sdirty)
        if not sdirty.any():
            return []
        upsert, tomb, sel = stage_marks(
            sdirty, np.asarray(self.table.live), np.asarray(self.stored)
        )
        pulled = pull_rows(
            {"k0": self.table.keys[0], "max": self.maxes}, sel
        )
        keys = {"k0": pulled["k0"]}
        vals = {"max": pulled["max"]}
        self.stored = (self.stored | jnp.asarray(upsert)) & ~jnp.asarray(tomb)
        self.sdirty = jnp.zeros_like(self.sdirty)
        return [StateDelta(self.table_id, keys, vals, tomb[sel], ("k0",))]

    def restore_state(self, table_id, key_cols, value_cols):
        import numpy as np

        n = len(next(iter(key_cols.values()))) if key_cols else 0
        kd = self.table.keys[0].dtype
        vdtype = self.maxes.dtype
        cap = grow_pow2(n, self.table.capacity, GROW_AT)
        table = HashTable.create(cap, (kd,))
        maxes = jnp.full(cap, jnp.iinfo(vdtype).min, vdtype)
        self.sdirty = jnp.zeros(cap, jnp.bool_)
        self.stored = jnp.zeros(cap, jnp.bool_)
        if n:
            lanes = (jnp.asarray(np.asarray(key_cols["k0"], dtype=kd)),)
            table, slots, _, _ = lookup_or_insert(
                table, lanes, jnp.ones(n, jnp.bool_)
            )
            table = set_live(table, slots, True)
            maxes = maxes.at[slots].set(
                jnp.asarray(value_cols["max"].astype(vdtype))
            )
            self.stored = self.stored.at[slots].set(True)
        self.table, self.maxes = table, maxes
        self._bound = int(n)
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)


# ---------------------------------------------------------------------------
# General dynamic filter (comparator, both directions)
# ---------------------------------------------------------------------------

_CMP = {
    ">": lambda v, rv: v > rv,
    ">=": lambda v, rv: v >= rv,
    "<": lambda v, rv: v < rv,
    "<=": lambda v, rv: v <= rv,
}


@partial(
    jax.jit,
    static_argnames=("op", "pk", "names", "value_col"),
    donate_argnums=(0, 1, 2, 3),
)
def _dyn_left_step(
    table, rows, passing, sdirty, chunk, rv, rv_valid, op, pk, names,
    value_col,
):
    """Store the left chunk's rows and pass through the comparator
    against the CURRENT right value (right moves apply at the barrier,
    dynamic_filter.rs semantics, so cmp(value, rv) == the row's emitted
    status for every stored row)."""
    keys = tuple(chunk.col(k) for k in pk)
    signs = chunk.effective_signs()
    active = chunk.valid & (signs != 0)
    table, slots, _, _ = lookup_or_insert(table, keys, active)
    dropped = jnp.any(active & (slots < 0))
    idx = jnp.where(active, slots, table.capacity)
    rows = {
        n: rows[n].at[idx].set(chunk.col(n), mode="drop") for n in names
    }
    table = set_live(table, jnp.where(active, slots, -1), signs > 0)
    sdirty = sdirty.at[idx].set(True, mode="drop")
    ok = chunk.valid & rv_valid & _CMP[op](chunk.col(value_col), rv)
    passing = passing.at[idx].set(ok & (signs > 0), mode="drop")
    return table, rows, passing, sdirty, chunk.mask(ok), dropped


@partial(jax.jit, static_argnames=("op", "value_col"), donate_argnums=(2,))
def _dyn_rv_diff(table, rows, passing, rv, rv_valid, op, value_col):
    """The right value moved: recompute the pass set; rows whose status
    flipped are the emission delta (promotions AND retractions — both
    directions of movement)."""
    mask_new = table.live & rv_valid & _CMP[op](rows[value_col], rv)
    changed = mask_new != passing
    return mask_new, changed


class DynamicFilterExecutor(Executor, Checkpointable):
    """General dynamic filter (dynamic_filter.rs:40): emits left rows
    satisfying ``value_col <op> right_value`` where the right side is a
    1-row change stream (e.g. a SimpleAgg MAX). Right moves apply at
    the barrier and re-emit/retract previously filtered/passed rows
    from the device row store — BOTH directions, full retraction."""

    def __init__(
        self,
        value_col: str,
        op: str,
        pk: Sequence[str],
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 14,
        table_id: str = "dynfilter_general",
        bucket_policy: Optional[BucketPolicy] = None,
        bucketed: bool = True,
    ):
        if op not in _CMP:
            raise ValueError(f"unsupported comparator {op!r}")
        self._buckets = (
            BucketAllocator(
                bucket_policy or BucketPolicy.from_capacity(capacity, grow_at=GROW_AT)
            )
            if bucketed
            else None
        )
        self.op = op
        self.value_col = value_col
        self.pk = tuple(pk)
        self.names = tuple(sorted(schema_dtypes))
        self._dtypes = {n: jnp.dtype(schema_dtypes[n]) for n in self.names}
        self.table = HashTable.create(
            capacity, tuple(self._dtypes[k] for k in self.pk)
        )
        self.rows = {
            n: jnp.zeros(capacity, self._dtypes[n]) for n in self.names
        }
        self.passing = jnp.zeros(capacity, jnp.bool_)
        self.sdirty = jnp.zeros(capacity, jnp.bool_)
        self.stored = jnp.zeros(capacity, jnp.bool_)
        vd = self._dtypes[self.value_col]
        self.rv = jnp.zeros((), vd)
        self.rv_valid = jnp.zeros((), jnp.bool_)
        self._staged_rv = None  # (device value, device valid) pending
        self._rv_dirty = True  # first checkpoint must persist the rv
        self.table_id = table_id
        self._bound = 0
        self._dropped = jnp.zeros((), jnp.bool_)

    # -- left input -------------------------------------------------------
    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        return self.apply_left(chunk)

    def apply_left(self, chunk: StreamChunk) -> List[StreamChunk]:
        for c in self.pk + (self.value_col,):
            if c in chunk.nulls:
                raise ValueError(
                    f"dynamic filter column {c!r} cannot be NULL"
                )
        self._maybe_grow(chunk.capacity)
        self._bound += chunk.capacity
        (
            self.table,
            self.rows,
            self.passing,
            self.sdirty,
            out,
            dropped,
        ) = _dyn_left_step(
            self.table,
            self.rows,
            self.passing,
            self.sdirty,
            chunk,
            self.rv,
            self.rv_valid,
            self.op,
            self.pk,
            self.names,
            self.value_col,
        )
        self._dropped = self._dropped | dropped
        return [out]

    # -- right input (1-row change stream) --------------------------------
    def apply_right(self, chunk: StreamChunk) -> List[StreamChunk]:
        signs = chunk.effective_signs()
        ins = chunk.valid & (signs > 0)
        dels = chunk.valid & (signs < 0)
        pos = jnp.arange(chunk.capacity, dtype=jnp.int32)
        last_ins = jnp.max(jnp.where(ins, pos, -1))
        last_del = jnp.max(jnp.where(dels, pos, -1))
        has_ins = last_ins >= 0
        v = chunk.col(self.value_col)[jnp.maximum(last_ins, 0)]
        if self._staged_rv is None:
            prev_v, prev_valid = self.rv, self.rv_valid
        else:
            prev_v, prev_valid = self._staged_rv
        # rows apply IN ORDER (dynamic_filter.rs): the LAST op decides
        # validity — an insert followed by its own retraction nets out
        # to no right value
        new_v = jnp.where(has_ins, v.astype(self.rv.dtype), prev_v)
        new_valid = jnp.where(
            last_ins > last_del,
            True,
            jnp.where(last_del > last_ins, False, prev_valid),
        )
        self._staged_rv = (new_v, new_valid)
        return []

    def pin_max_bucket(self):
        """ShapeGovernor hook (see DynamicMaxFilterExecutor)."""
        if self._buckets is None:
            return {"pinned": False}
        return {
            "table_id": self.table_id,
            "pinned_cap": self._buckets.pin(),
        }

    def padding_stats(self):
        return {
            "capacity": self.table.capacity,
            "live": int(self.table.num_live()),
        }

    def _maybe_grow(self, incoming: int):
        cap = self.table.capacity
        if not needs_plan(self._buckets, cap, self._bound, incoming, GROW_AT):
            return
        claimed, survivors = read_scalars(
            self.table.occupancy(),
            jnp.sum((self.table.live | self.sdirty).astype(jnp.int32)),
        )
        new_cap = plan_capacity(
            self._buckets, cap, incoming, claimed, survivors, GROW_AT
        )
        if new_cap is not None:
            keep = self.table.live | self.sdirty
            new = HashTable.create(
                new_cap, tuple(k.dtype for k in self.table.keys)
            )
            new, slots, _, _ = lookup_or_insert(new, self.table.keys, keep)
            new = set_live(
                new, jnp.where(keep, slots, -1), self.table.live
            )
            idx = jnp.where(keep, slots, new_cap)

            def move(a):
                return (
                    jnp.zeros(new_cap, a.dtype).at[idx].set(a, mode="drop")
                )

            self.rows = {n: move(a) for n, a in self.rows.items()}
            self.passing = move(self.passing)
            self.sdirty = move(self.sdirty)
            self.stored = move(self.stored)
            self.table = new
            claimed = int(self.table.occupancy())
        self._bound = claimed

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if bool(self._dropped):
            raise RuntimeError(
                "dynamic filter row store overflowed; grow capacity"
            )
        if self._buckets is not None:
            # host-tracked bound (an upper estimate of claimed): lazy
            # shrink stays conservative without an extra device read
            self._buckets.note_barrier(self.table.capacity, self._bound)
        if self._staged_rv is None:
            return []
        self.rv, self.rv_valid = self._staged_rv
        self._staged_rv = None
        self._rv_dirty = True
        mask_new, changed = _dyn_rv_diff(
            self.table,
            self.rows,
            self.passing,
            self.rv,
            self.rv_valid,
            self.op,
            self.value_col,
        )
        self.passing = mask_new
        # flipped rows must re-stage: a checkpoint persisting the new
        # rv with the OLD pass flags would double-retract (or lose)
        # rows after recovery when the rv moves again
        self.sdirty = self.sdirty | changed
        sel = np.flatnonzero(np.asarray(changed))
        if not len(sel):
            return []
        lanes = {n: self.rows[n] for n in self.names}
        lanes["__now__"] = mask_new
        pulled = pull_rows(lanes, sel)
        from risingwave_tpu.types import Op

        now = np.asarray(pulled["__now__"])
        outs = []
        for promote in (False, True):
            m = now == promote
            if not m.any():
                continue
            cols = {
                n: np.asarray(pulled[n])[m].astype(self._dtypes[n])
                for n in self.names
            }
            outs.append(
                StreamChunk.from_numpy(
                    cols,
                    # pow2-padded emission (masked lanes): downstream
                    # programs see a log-bounded capacity set, not one
                    # shape per distinct flip count (legacy max(2, n)
                    # on the unbucketed twin)
                    emission_bucket(int(m.sum()))
                    if self._buckets is not None
                    else max(2, int(m.sum())),
                    ops=np.full(
                        int(m.sum()),
                        int(Op.INSERT if promote else Op.DELETE),
                        np.int32,
                    ),
                )
            )
        return outs

    # -- integrity --------------------------------------------------------
    def digest_lanes(self):
        lanes = {f"k{i}": k for i, k in enumerate(self.table.keys)}
        live = self.table.live
        for n in self.names:
            lanes[f"r_{n}"] = self.rows[n]
        lanes["pass"] = self.passing
        # the 1-row right value folds in as broadcast scalars so the
        # fold stays a single masked reduction
        lanes["rv"] = jnp.where(
            live, self.rv, jnp.zeros((), self.rv.dtype)
        )
        lanes["rvv"] = jnp.where(live, self.rv_valid, False)
        return lanes, live

    def state_digest(self) -> int:
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_table_ids(self):
        return [f"{self.table_id}.rows", f"{self.table_id}.rv"]

    def checkpoint_delta(self):
        out = []
        sdirty = np.asarray(self.sdirty)
        if sdirty.any():
            upsert, tomb, sel = stage_marks(
                sdirty, np.asarray(self.table.live), np.asarray(self.stored)
            )
            lanes = {
                f"k{i}": lane for i, lane in enumerate(self.table.keys)
            }
            key_names = tuple(lanes)
            for n in self.names:
                lanes[f"r_{n}"] = self.rows[n]
            lanes["pass"] = self.passing
            pulled = pull_rows(lanes, sel)
            keys = {k: pulled[k] for k in key_names}
            vals = {k: v for k, v in pulled.items() if k not in key_names}
            self.stored = (
                self.stored | jnp.asarray(upsert)
            ) & ~jnp.asarray(tomb)
            self.sdirty = jnp.zeros_like(self.sdirty)
            out.append(
                StateDelta(
                    f"{self.table_id}.rows", keys, vals, tomb[sel], key_names
                )
            )
        if self._rv_dirty:
            # the right value: a 1-row table
            rv, rvv = np.asarray(self.rv), bool(self.rv_valid)
            out.append(
                StateDelta(
                    f"{self.table_id}.rv",
                    {"k0": np.zeros(1, np.int64)},
                    {"rv": rv[None], "rv_valid": np.asarray([rvv])},
                    np.zeros(1, bool),
                    ("k0",),
                )
            )
            self._rv_dirty = False
        return out

    def restore_state(self, table_id, key_cols, value_cols):
        if table_id.endswith(".rv"):
            if key_cols:
                self.rv = jnp.asarray(
                    value_cols["rv"][0].astype(self.rv.dtype)
                )
                self.rv_valid = jnp.asarray(bool(value_cols["rv_valid"][0]))
            return
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        cap = grow_pow2(n, self.table.capacity, GROW_AT)
        key_dtypes = tuple(k.dtype for k in self.table.keys)
        table = HashTable.create(cap, key_dtypes)
        rows = {nm: jnp.zeros(cap, self._dtypes[nm]) for nm in self.names}
        self.passing = jnp.zeros(cap, jnp.bool_)
        self.sdirty = jnp.zeros(cap, jnp.bool_)
        self.stored = jnp.zeros(cap, jnp.bool_)
        if n:
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d))
                for i, d in enumerate(key_dtypes)
            )
            table, slots, _, _ = lookup_or_insert(
                table, lanes, jnp.ones(n, jnp.bool_)
            )
            table = set_live(table, slots, True)
            rows = {
                nm: a.at[slots].set(
                    jnp.asarray(
                        np.asarray(value_cols[f"r_{nm}"]).astype(a.dtype)
                    )
                )
                for nm, a in rows.items()
            }
            self.passing = self.passing.at[slots].set(
                jnp.asarray(value_cols["pass"].astype(bool))
            )
            self.stored = self.stored.at[slots].set(True)
        self.table = table
        self.rows = rows
        self._bound = int(n)
        self._dropped = jnp.zeros((), jnp.bool_)
        self._staged_rv = None
