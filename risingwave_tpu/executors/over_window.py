"""OverWindow — window functions over partitions (append-only).

Reference: src/stream/src/executor/over_window/general.rs:49 — per
partition, per order-key window functions; the general executor
retracts and re-emits affected frames on any change. This executor is
the APPEND-ONLY + arrival-ordered specialization (RW's planner also
specializes this case): each row gets its window value at arrival and
is never revisited — exactly right for ROW_NUMBER / running COUNT /
running SUM over monotonically arriving streams.

TPU re-design: partition state is a hash table + per-slot running
accumulators. One fused step per chunk: lookup partitions, sort rows
by (slot, arrival) to rank intra-chunk duplicates, gather partition
bases, segment-prefix-scan the chunk's own contribution, scatter the
updated accumulators back — no per-row host work.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.ops.hash_table import (
    HashTable,
    first_occurrence_mask,
    last_occurrence_mask,
    lookup_or_insert,
    plan_rehash,
    set_live,
)
from risingwave_tpu.executors.sort import ArenaBufferedExecutor
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    grow_pow2,
    pull_rows,
    stage_marks,
)

GROW_AT = 0.5

KINDS = (
    "row_number",
    "count",
    "sum",
    "min",
    "max",
    "lag",
    "lead",
    "rank",
    "dense_rank",
)


@dataclass(frozen=True)
class WindowCall:
    """One window function call.

    ``frame``: optional static ROWS frame (lo, hi) offsets relative to
    the current row (e.g. (-2, 0) = 2 PRECEDING..CURRENT ROW) for
    sum/min/max/count in the EOWC executor; None = UNBOUNDED PRECEDING
    ..CURRENT ROW (running). ``offset``: lead/lag distance."""

    kind: str
    input: Optional[str]  # None for row_number / count(*)
    output: str
    frame: Optional[Tuple[int, int]] = None
    offset: int = 1

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unsupported window kind {self.kind!r}")
        if (self.input is None) != (self.kind in ("row_number", "count")):
            raise ValueError(f"{self.kind} input mismatch")
        if self.frame is not None:
            lo, hi = self.frame
            if lo > hi:
                raise ValueError(f"frame {self.frame}: lo > hi")
            if hi - lo + 1 > 64:
                raise ValueError(
                    "ROWS frames wider than 64 are not supported (the "
                    "fused kernel combines one shift per frame row)"
                )
            if self.kind not in ("sum", "min", "max", "count"):
                raise ValueError(f"{self.kind} does not take a frame")
        if self.offset < 1:
            raise ValueError("lead/lag offset must be >= 1")


def _accum_names(call: "WindowCall"):
    """Accumulator lanes per call (lag keeps last-value + flags;
    min/max keep a presence flag so sentinel-valued inputs are not
    misread as NULL; rank/dense_rank keep (last rank, row count, dense
    count, last order value, presence))."""
    if call.kind == "lag":
        return (call.output, call.output + "#has", call.output + "#null")
    if call.kind in ("min", "max"):
        return (call.output, call.output + "#has")
    if call.kind in ("rank", "dense_rank"):
        return (
            call.output,
            call.output + "#cnt",
            call.output + "#dense",
            call.output + "#last",
            call.output + "#has",
        )
    return (call.output,)


def _accum_init(call: "WindowCall") -> int:
    if call.kind == "min":
        return jnp.iinfo(jnp.int64).max
    if call.kind == "max":
        return jnp.iinfo(jnp.int64).min
    return 0


@partial(
    jax.jit, static_argnames=("calls", "part_keys"), donate_argnums=(0, 1, 2)
)
def _over_step(
    table: HashTable,
    accums: Dict[str, jnp.ndarray],
    sdirty: jnp.ndarray,
    chunk: StreamChunk,
    calls: Tuple[WindowCall, ...],
    part_keys: Tuple[str, ...],
):
    n = chunk.capacity
    keys = tuple(chunk.col(k) for k in part_keys)
    signs = chunk.effective_signs()
    active = chunk.valid & (signs > 0)
    saw_delete = jnp.any(chunk.valid & (signs < 0))
    table, slots, _, _ = lookup_or_insert(table, keys, active)
    dropped = jnp.any(active & (slots < 0))
    table = set_live(table, jnp.where(active, slots, -1), True)
    sdirty = sdirty.at[jnp.where(active, slots, -1)].set(True, mode="drop")
    ooo = jnp.zeros((), jnp.bool_)  # out-of-order arrival (rank kinds)

    # rank rows of one partition within the chunk (arrival order)
    skey = jnp.where(active, slots, table.capacity).astype(jnp.int32)
    pos = jnp.arange(n, dtype=jnp.int32)
    val_lanes = {
        c.input: chunk.col(c.input).astype(jnp.int64)
        for c in calls
        if c.input is not None
    }
    null_lanes = {
        c.input: chunk.nulls[c.input]
        for c in calls
        if c.input is not None and c.input in chunk.nulls
    }
    names = tuple(sorted(val_lanes))
    nnames = tuple(sorted(null_lanes))
    sorted_ops = jax.lax.sort(
        (skey, pos)
        + tuple(val_lanes[m] for m in names)
        + tuple(null_lanes[m] for m in nnames),
        num_keys=2,
    )
    s_slot, s_pos = sorted_ops[0], sorted_ops[1]
    s_vals = {m: sorted_ops[2 + i] for i, m in enumerate(names)}
    s_nulls = {
        m: sorted_ops[2 + len(names) + i] for i, m in enumerate(nnames)
    }
    boundary = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), s_slot[1:] != s_slot[:-1]]
    )
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    arange = jnp.arange(n, dtype=jnp.int64)
    seg_start = jax.ops.segment_max(
        jnp.where(boundary, arange, 0), gid, num_segments=n
    )[gid]
    rank = arange - seg_start  # 0-based within (partition, chunk)
    s_active = s_slot < table.capacity
    gslot = jnp.where(s_active, s_slot, 0)

    # segment end == next segment's start (derive from boundary)
    is_last = jnp.concatenate([boundary[1:], jnp.ones(1, jnp.bool_)])
    MAXI = jnp.iinfo(jnp.int64).max
    MINI = jnp.iinfo(jnp.int64).min

    def seg_prefix_extreme(v, kind):
        """Inclusive segmented prefix min/max via an associative scan
        with a boundary-reset flag (the classic segmented-scan
        combine)."""
        comb = jnp.minimum if kind == "min" else jnp.maximum

        def op(a, b):
            fa, va = a
            fb, vb = b
            return fa | fb, jnp.where(fb, vb, comb(va, vb))

        _, out = jax.lax.associative_scan(op, (boundary, v))
        return out

    out_sorted: Dict[str, jnp.ndarray] = {}
    out_nulls_sorted: Dict[str, jnp.ndarray] = {}
    new_accums = dict(accums)
    for c in calls:
        acc = new_accums[c.output]
        base = acc[gslot]
        upd = jnp.where(s_active & is_last, gslot, table.capacity)
        if c.kind in ("row_number", "count"):
            o = base + rank + 1
            contrib = jnp.where(s_active, jnp.int64(1), jnp.int64(0))
            totals = jax.ops.segment_sum(contrib, gid, num_segments=n)[gid]
            new_accums[c.output] = acc.at[upd].add(totals, mode="drop")
        elif c.kind == "sum":
            # running sum (NULL inputs contribute 0, SQL skips them)
            v = s_vals[c.input]
            nn = ~s_nulls.get(c.input, jnp.zeros(n, jnp.bool_))
            v = jnp.where(s_active & nn, v, 0)
            # inclusive prefix within the segment (sentinel, not 0: the
            # boundary's exclusive prefix may be negative)
            csum = jnp.cumsum(v)
            seg_base = jax.ops.segment_max(
                jnp.where(boundary, csum - v, MINI),
                gid,
                num_segments=n,
            )[gid]
            o = base + (csum - seg_base)
            totals = jax.ops.segment_sum(v, gid, num_segments=n)[gid]
            new_accums[c.output] = acc.at[upd].add(totals, mode="drop")
        elif c.kind in ("min", "max"):
            sent = MAXI if c.kind == "min" else MINI
            comb = jnp.minimum if c.kind == "min" else jnp.maximum
            v = s_vals[c.input]
            nn = ~s_nulls.get(c.input, jnp.zeros(n, jnp.bool_))
            real = s_active & nn
            v = jnp.where(real, v, sent)
            pref = seg_prefix_extreme(v, c.kind)
            o = comb(base, pref)
            # presence via a companion lane, NOT sentinel equality: a
            # legitimate input equal to the int64 extreme must not be
            # misclassified as NULL (its value still combines right —
            # min(x, +inf) = x)
            has = new_accums[c.output + "#has"]
            pref_has = (
                jnp.cumsum(real.astype(jnp.int64))
                - jax.ops.segment_max(
                    jnp.where(
                        boundary,
                        jnp.cumsum(real.astype(jnp.int64))
                        - real.astype(jnp.int64),
                        MINI,
                    ),
                    gid,
                    num_segments=n,
                )[gid]
            ) > 0
            out_nulls_sorted[c.output] = ~((has[gslot] != 0) | pref_has)
            seg_fn = (
                jax.ops.segment_min if c.kind == "min" else jax.ops.segment_max
            )
            seg_ext = seg_fn(v, gid, num_segments=n)[gid]
            if c.kind == "min":
                new_accums[c.output] = acc.at[upd].min(seg_ext, mode="drop")
            else:
                new_accums[c.output] = acc.at[upd].max(seg_ext, mode="drop")
            seg_any = (
                jax.ops.segment_sum(
                    real.astype(jnp.int64), gid, num_segments=n
                )[gid]
                > 0
            )
            new_accums[c.output + "#has"] = (
                has.at[upd].max(seg_any.astype(jnp.int64), mode="drop")
            )
        elif c.kind in ("rank", "dense_rank"):
            # arrival order must be the ORDER BY order (the append-only
            # specialization's contract): order values non-decreasing
            # per partition — enforced by the ooo latch
            v = s_vals[c.input]
            prev_v = jnp.concatenate([jnp.zeros(1, v.dtype), v[:-1]])
            vb = boundary | (v != prev_v)  # value-group starts
            # 1-based count of value groups within the segment
            cum_vb_all = jnp.cumsum(vb.astype(jnp.int64))
            seg_vb_base = jax.ops.segment_max(
                jnp.where(boundary, cum_vb_all - 1, MINI),
                gid,
                num_segments=n,
            )[gid]
            cum_vb = cum_vb_all - seg_vb_base
            # arrival index (0-based, in-segment) of each value group's
            # first row — the rank numerator for its whole group
            grp_start = seg_prefix_extreme(
                jnp.where(vb, rank, MINI), "max"
            )
            has = new_accums[c.output + "#has"][gslot] != 0
            lastv = new_accums[c.output + "#last"][gslot]
            cnt0 = new_accums[c.output + "#cnt"][gslot]
            dense0 = new_accums[c.output + "#dense"][gslot]
            rank0 = new_accums[c.output][gslot]
            first_group = cum_vb == 1
            eq_carry = has & (v == lastv) & first_group
            ooo = ooo | jnp.any(
                (s_active & ~boundary & (v < prev_v))
                | (s_active & boundary & has & (v < lastv))
            )
            ranked = jnp.where(eq_carry, rank0, cnt0 + grp_start + 1)
            first_eq = (
                jax.ops.segment_max(
                    jnp.where(boundary, eq_carry.astype(jnp.int64), 0),
                    gid,
                    num_segments=n,
                )[gid]
                > 0
            )
            dense_row = dense0 + cum_vb - jnp.where(first_eq, 1, 0)
            o = ranked if c.kind == "rank" else dense_row
            contrib = jnp.where(s_active, jnp.int64(1), jnp.int64(0))
            totals = jax.ops.segment_sum(contrib, gid, num_segments=n)[gid]
            new_accums[c.output] = acc.at[upd].set(ranked, mode="drop")
            new_accums[c.output + "#cnt"] = (
                new_accums[c.output + "#cnt"]
                .at[upd]
                .add(totals, mode="drop")
            )
            new_accums[c.output + "#dense"] = (
                new_accums[c.output + "#dense"]
                .at[upd]
                .set(dense_row, mode="drop")
            )
            new_accums[c.output + "#last"] = (
                new_accums[c.output + "#last"].at[upd].set(v, mode="drop")
            )
            new_accums[c.output + "#has"] = (
                new_accums[c.output + "#has"]
                .at[upd]
                .set(jnp.int64(1), mode="drop")
            )
        else:  # lag(1): previous row's value within the partition
            v = s_vals[c.input]
            vnull = s_nulls.get(c.input, jnp.zeros(n, jnp.bool_))
            prev_v = jnp.concatenate([jnp.zeros(1, v.dtype), v[:-1]])
            prev_null = jnp.concatenate(
                [jnp.zeros(1, jnp.bool_), vnull[:-1]]
            )
            first = rank == 0
            # pre-update state: the partition's stored last value
            prev_has = new_accums[c.output + "#has"][gslot] != 0
            prev_stored_null = new_accums[c.output + "#null"][gslot] != 0
            o = jnp.where(first, base, prev_v)
            out_nulls_sorted[c.output] = jnp.where(
                first, ~prev_has | prev_stored_null, prev_null
            )
            # store the segment's LAST value (+ its nullness) per slot
            lastv = jax.ops.segment_max(
                jnp.where(is_last, v, MINI), gid, num_segments=n
            )[gid]
            lastn = jax.ops.segment_max(
                jnp.where(is_last, vnull.astype(jnp.int64), 0),
                gid,
                num_segments=n,
            )[gid]
            new_accums[c.output] = acc.at[upd].set(lastv, mode="drop")
            new_accums[c.output + "#null"] = (
                new_accums[c.output + "#null"]
                .at[upd]
                .set(lastn, mode="drop")
            )
            new_accums[c.output + "#has"] = (
                new_accums[c.output + "#has"]
                .at[upd]
                .set(jnp.int64(1), mode="drop")
            )
        out_sorted[c.output] = o

    # unsort back to arrival positions
    cols = dict(chunk.columns)
    out_nulls = dict(chunk.nulls)
    for name, o in out_sorted.items():
        buf = jnp.zeros(n, jnp.int64)
        cols[name] = buf.at[s_pos].set(o)
    for name, lane in out_nulls_sorted.items():
        nbuf = jnp.zeros(n, jnp.bool_)
        out_nulls[name] = nbuf.at[s_pos].set(lane)
    out = StreamChunk(
        columns=cols, valid=chunk.valid & active, nulls=out_nulls,
        ops=chunk.ops,
    )
    return table, new_accums, sdirty, out, saw_delete, dropped, ooo


# ---------------------------------------------------------------------------
# EOWC over-window: complete-partition batch compute at window close
# ---------------------------------------------------------------------------


@partial(
    jax.jit,
    static_argnames=("names", "calls", "part_keys", "order_col", "win_col"),
)
def _eowc_over_emit(
    buf,
    bnulls,
    valid,
    seq,
    cutoff,
    names: Tuple[str, ...],
    calls: Tuple[WindowCall, ...],
    part_keys: Tuple[str, ...],
    order_col: str,
    win_col: str,
):
    """Sort closed rows by (partition, order, seq) and compute EVERY
    window call on the complete partitions in one program. Closed
    partitions are final (watermark contract), so lead/FOLLOWING frames
    need no hold-back: beyond-partition-end is NULL / clipped, exactly
    SQL's frame semantics on a finished window."""
    cap = valid.shape[0]
    closed = valid & (buf[win_col] < cutoff)
    open_flag = (~closed).astype(jnp.int32)
    sort_in = (
        (open_flag,)
        + tuple(buf[k] for k in part_keys)
        + (buf[order_col], seq)
        + (jnp.arange(cap, dtype=jnp.int32),)
    )
    nk = 3 + len(part_keys)
    sorted_all = jax.lax.sort(sort_in, num_keys=nk)
    order_idx = sorted_all[-1]  # original slot of each sorted position
    closed_s = closed[order_idx]
    s = lambda a: a[order_idx]
    pk_s = [s(buf[k]) for k in part_keys]
    v_order = s(buf[order_col])

    idx = jnp.arange(cap, dtype=jnp.int64)
    prev_ne = jnp.zeros(cap, jnp.bool_)
    for lane in pk_s:
        prev_ne = prev_ne | jnp.concatenate(
            [jnp.ones(1, jnp.bool_), lane[1:] != lane[:-1]]
        )
    trans = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), closed_s[1:] != closed_s[:-1]]
    )
    boundary = prev_ne | trans
    boundary = boundary.at[0].set(True)
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_start = jax.ops.segment_max(
        jnp.where(boundary, idx, 0), gid, num_segments=cap
    )[gid]
    in_seg = idx - seg_start  # 0-based index within the partition

    def shifted(vals, nullm, d):
        """(value, isnull) of the row d positions away within the SAME
        closed partition; beyond it -> (0, NULL)."""
        j = idx + d
        jc = jnp.clip(j, 0, cap - 1)
        ok = (
            (j >= 0)
            & (j < cap)
            & (gid[jc] == gid)
            & closed_s[jc]
            & closed_s
        )
        return (
            jnp.where(ok, vals[jc], 0),
            jnp.where(ok, nullm[jc], True),
        )

    MAXI = jnp.iinfo(jnp.int64).max
    MINI = jnp.iinfo(jnp.int64).min
    out_sorted: Dict[str, jnp.ndarray] = {}
    out_nulls_sorted: Dict[str, jnp.ndarray] = {}
    zero_nulls = jnp.zeros(cap, jnp.bool_)
    for c in calls:
        if c.input is not None:
            v = s(buf[c.input]).astype(jnp.int64)
            vnull = s(bnulls[c.input]) if c.input in bnulls else zero_nulls
        if c.kind == "row_number":
            o, onull = in_seg + 1, zero_nulls
        elif c.kind in ("rank", "dense_rank"):
            pv = jnp.concatenate([jnp.zeros(1, v_order.dtype), v_order[:-1]])
            vb = boundary | (v_order != pv)
            cum_vb_all = jnp.cumsum(vb.astype(jnp.int64))
            seg_vb = jax.ops.segment_max(
                jnp.where(boundary, cum_vb_all - 1, MINI),
                gid,
                num_segments=cap,
            )[gid]
            if c.kind == "dense_rank":
                o = cum_vb_all - seg_vb
            else:
                # segmented prefix max with boundary reset: a plain max
                # scan would leak a previous partition's group starts
                def reset_max(a, b):
                    fa, va = a
                    fb, vb_ = b
                    return fa | fb, jnp.where(fb, vb_, jnp.maximum(va, vb_))

                _, grp_start = jax.lax.associative_scan(
                    reset_max, (boundary, jnp.where(vb, in_seg, MINI))
                )
                o = grp_start + 1
            onull = zero_nulls
        elif c.kind in ("lead", "lag"):
            d = c.offset if c.kind == "lead" else -c.offset
            o, onull = shifted(v, vnull, d)
        elif c.frame is not None:
            lo, hi = c.frame
            if c.kind == "count":
                v, vnull = jnp.ones(cap, jnp.int64), zero_nulls
            ident = (
                MAXI if c.kind == "min" else MINI if c.kind == "max" else 0
            )
            comb = (
                jnp.minimum
                if c.kind == "min"
                else jnp.maximum
                if c.kind == "max"
                else (lambda a, b: a + b)
            )
            acc = jnp.full(cap, ident, jnp.int64)
            any_real = zero_nulls
            for d in range(lo, hi + 1):
                sv, sn = shifted(v, vnull, d)
                real = ~sn
                acc = comb(acc, jnp.where(real, sv, ident))
                any_real = any_real | real
            if c.kind == "count":
                o, onull = acc, zero_nulls
            else:
                o, onull = acc, ~any_real
        else:
            # running UNBOUNDED PRECEDING .. CURRENT ROW
            if c.kind == "count":
                real = closed_s
                vv = jnp.ones(cap, jnp.int64)
            else:
                real = closed_s & ~vnull
                vv = v
            if c.kind == "sum" or c.kind == "count":
                vv = jnp.where(real, vv, 0)
                csum = jnp.cumsum(vv)
                base = jax.ops.segment_max(
                    jnp.where(boundary, csum - vv, MINI),
                    gid,
                    num_segments=cap,
                )[gid]
                o, onull = csum - base, zero_nulls
            else:
                sent = MAXI if c.kind == "min" else MINI
                vv = jnp.where(real, vv, sent)

                def op(a, b):
                    fa, va, ra = a
                    fb, vb_, rb = b
                    cmb = (
                        jnp.minimum if c.kind == "min" else jnp.maximum
                    )
                    return (
                        fa | fb,
                        jnp.where(fb, vb_, cmb(va, vb_)),
                        jnp.where(fb, rb, ra | rb),
                    )

                _, o, has = jax.lax.associative_scan(
                    op, (boundary, vv, real)
                )
                onull = ~has
        out_sorted[c.output] = o
        out_nulls_sorted[c.output] = onull

    out_cols = {n: s(buf[n]) for n in names}
    out_cols.update(out_sorted)
    out_nulls = {n: s(bnulls[n]) for n in bnulls}
    out_nulls.update(out_nulls_sorted)
    new_valid = valid & ~closed
    return (
        out_cols,
        out_nulls,
        closed_s,
        new_valid,
        jnp.sum(closed.astype(jnp.int32)),
    )


class EowcOverWindowExecutor(ArenaBufferedExecutor):
    """Emit-on-window-close window functions (over_window/eowc.rs:88):
    rows buffer in a device arena until the watermark closes their
    window column; complete partitions then compute EVERY call — incl.
    lead/lag and static ROWS frames — in one fused sorted-segment
    program. The partition key must include the window column (the EOWC
    contract: a closed partition receives no further rows)."""

    def __init__(
        self,
        partition_by: Sequence[str],
        order_col: str,
        calls: Sequence[WindowCall],
        schema_dtypes: Dict[str, object],
        win_col: Optional[str] = None,
        capacity: int = 1 << 14,
        nullable: Sequence[str] = (),
        table_id: str = "eowc_over",
    ):
        self.part_keys = tuple(partition_by)
        self.order_col = order_col
        self.win_col = win_col or self.part_keys[0]
        if self.win_col not in self.part_keys:
            raise ValueError(
                "the window column must be one of the partition keys "
                "(a closed partition may receive no further rows)"
            )
        self.calls = tuple(calls)
        for c in self.calls:
            if (
                c.kind in ("rank", "dense_rank")
                and c.input != self.order_col
            ):
                raise ValueError(
                    f"{c.kind} ranks by the executor's order column "
                    f"{self.order_col!r}; got input {c.input!r}"
                )
        super().__init__(schema_dtypes, capacity, nullable, table_id)

    def lint_info(self):
        info = super().lint_info()
        # complete-partition compute appends every call's output lane
        info["adds"] = {c.output: jnp.int64 for c in self.calls}
        info["keys"] = self.part_keys
        # EOWC contract: partitions only close when a watermark on the
        # window column passes them
        info["window_key"] = self.win_col
        return info

    def trace_contract(self):
        contract = super().trace_contract()
        contract["hot_methods"] = ("on_watermark",)
        return contract

    def on_watermark(self, watermark):
        if watermark.column != self.win_col:
            return watermark, []
        cutoff = jnp.asarray(watermark.value, jnp.int64)
        out_cols, out_nulls, out_valid, self.valid, n_closed = (
            _eowc_over_emit(
                self.buf,
                self.bnulls,
                self.valid,
                self.seq,
                cutoff,
                self.names,
                self.calls,
                self.part_keys,
                self.order_col,
                self.win_col,
            )
        )
        if int(n_closed) == 0:
            return watermark, []
        chunk = StreamChunk(
            columns=out_cols,
            valid=out_valid,
            nulls=out_nulls,
            ops=jnp.zeros(self.capacity, jnp.int32),
        )
        return watermark, [chunk]

    _arena_name = "EOWC over-window arena"


class OverWindowExecutor(Executor, Checkpointable):
    """Append-only window functions: ROW_NUMBER / running COUNT / SUM /
    MIN / MAX / LAG / RANK / DENSE_RANK per partition in arrival order
    (rank kinds require arrival order == ORDER BY order; violations
    latch and raise at the barrier). Checkpointable: partition keys +
    every accumulator lane persist as one state table, so a window MV
    survives recovery bit-exactly."""

    def __init__(
        self,
        partition_by: Sequence[str],
        calls: Sequence[WindowCall],
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 14,
        table_id: str = "over_window",
    ):
        self.part_keys = tuple(partition_by)
        self.calls = tuple(calls)
        for c in self.calls:
            if c.kind == "lead" or c.frame is not None:
                raise ValueError(
                    f"{c.kind}/frames need future rows: use "
                    "EowcOverWindowExecutor (emit on window close)"
                )
            if c.kind == "lag" and c.offset != 1:
                raise ValueError(
                    "streaming lag supports offset=1 only; use "
                    "EowcOverWindowExecutor for lag(k)"
                )
        self.table_id = table_id
        self._dtypes = {
            k: jnp.dtype(v) for k, v in schema_dtypes.items()
        }
        self.table = HashTable.create(
            capacity,
            tuple(jnp.dtype(schema_dtypes[k]) for k in self.part_keys),
        )
        self.accums = {}
        self._accum_inits = {}
        for c in self.calls:
            for name in _accum_names(c):
                init = _accum_init(c) if name == c.output else 0
                self._accum_inits[name] = init
                self.accums[name] = jnp.full(capacity, init, jnp.int64)
        self.sdirty = jnp.zeros(capacity, jnp.bool_)
        self.stored = jnp.zeros(capacity, jnp.bool_)
        self._bound = 0
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)
        self._ooo = jnp.zeros((), jnp.bool_)

    def lint_info(self):
        requires = set(self.part_keys)
        for c in self.calls:
            if c.input is not None:
                requires.add(c.input)
        return {
            "requires": tuple(sorted(requires)),
            "expects": {
                k: self._dtypes[k]
                for k in sorted(requires)
                if k in self._dtypes
            },
            "adds": {c.output: jnp.int64 for c in self.calls},
            "keys": self.part_keys,
            "table_ids": (self.table_id,),
        }

    def state_nbytes(self) -> int:
        """Device bytes held (host-side estimate; no sync)."""
        return sum(
            leaf.nbytes
            for leaf in jax.tree.leaves(
                (self.table, self.accums, self.sdirty, self.stored)
            )
        )

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: _over_step(
                self.table,
                self.accums,
                self.sdirty,
                c,
                self.calls,
                self.part_keys,
            ),
            "state": (self.table, self.accums),
            "donate": True,
            "emission": "passthrough",
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        for c in self.calls:
            if c.kind in ("rank", "dense_rank") and c.input in chunk.nulls:
                raise ValueError(
                    f"rank order column {c.input!r} carries a null lane "
                    "(NULL ordering unsupported)"
                )
        self._maybe_grow(chunk.capacity)
        self._bound += chunk.capacity
        self.table, self.accums, self.sdirty, out, sd, dr, ooo = _over_step(
            self.table, self.accums, self.sdirty, chunk, self.calls,
            self.part_keys,
        )
        self._saw_delete = self._saw_delete | sd
        self._dropped = self._dropped | dr
        self._ooo = self._ooo | ooo
        return [out]

    def _maybe_grow(self, incoming: int):
        cap = self.table.capacity
        if self._bound + incoming <= cap * GROW_AT:
            return
        claimed = int(self.table.occupancy())
        new_cap = plan_rehash(cap, incoming, claimed, claimed, GROW_AT)
        if new_cap is not None:
            keep = self.table.fp1 != jnp.uint32(0)
            new = HashTable.create(
                new_cap, tuple(k.dtype for k in self.table.keys)
            )
            new, slots, _, _ = lookup_or_insert(new, self.table.keys, keep)
            new = set_live(new, jnp.where(keep, slots, -1), self.table.live)
            idx = jnp.where(keep, slots, new_cap)
            self.accums = {
                # unclaimed slots must keep each lane's INIT value (a
                # zero base would corrupt running min/max for new
                # partitions landing there)
                name: jnp.full(new_cap, self._accum_inits[name], jnp.int64)
                .at[idx]
                .set(a, mode="drop")
                for name, a in self.accums.items()
            }
            self.sdirty = (
                jnp.zeros(new_cap, jnp.bool_)
                .at[idx]
                .set(self.sdirty, mode="drop")
            )
            self.stored = (
                jnp.zeros(new_cap, jnp.bool_)
                .at[idx]
                .set(self.stored, mode="drop")
            )
            self.table = new
            claimed = int(self.table.occupancy())
        self._bound = claimed

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        from risingwave_tpu.ops.hash_table import stage_scalars

        self._staged_scalars = stage_scalars(
            self._saw_delete, self._dropped, self._ooo
        )
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return []

    def _on_barrier_scalars(self, vals) -> None:
        sd, dr, ooo = vals
        if sd:
            raise RuntimeError(
                "append-only OverWindow received a DELETE (the general "
                "retractable executor is not implemented)"
            )
        if dr:
            raise RuntimeError("OverWindow partition table overflowed")
        if ooo:
            raise RuntimeError(
                "rank/dense_rank saw out-of-order arrivals: the "
                "append-only OverWindow requires arrival order to match "
                "ORDER BY (sort upstream, e.g. with the EOWC sort)"
            )

    # -- integrity --------------------------------------------------------
    def digest_lanes(self):
        lanes = {f"k{i}": k for i, k in enumerate(self.table.keys)}
        for name, a in self.accums.items():
            lanes[f"acc_{name}"] = a
        return lanes, self.table.fp1 != 0

    def state_digest(self) -> int:
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self) -> List[StateDelta]:
        sdirty = np.asarray(self.sdirty)
        if not sdirty.any():
            return []
        # partitions never die in the append-only executor: alive =
        # every claimed slot, so there are no tombstones
        alive = np.asarray(self.table.fp1) != 0
        upsert, tomb, sel = stage_marks(
            sdirty, alive, np.asarray(self.stored)
        )
        lanes = {f"k{i}": l for i, l in enumerate(self.table.keys)}
        key_names = tuple(lanes)
        for name, a in self.accums.items():
            lanes[f"acc_{name}"] = a
        pulled = pull_rows(lanes, sel)
        keys = {k: pulled[k] for k in key_names}
        vals = {k: v for k, v in pulled.items() if k not in key_names}
        self.stored = (self.stored | jnp.asarray(upsert)) & ~jnp.asarray(
            tomb
        )
        self.sdirty = jnp.zeros_like(self.sdirty)
        return [StateDelta(self.table_id, keys, vals, tomb[sel], key_names)]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        key_dtypes = tuple(k.dtype for k in self.table.keys)
        cap = grow_pow2(n, self.table.capacity, GROW_AT)
        table = HashTable.create(cap, key_dtypes)
        self.accums = {
            name: jnp.full(cap, self._accum_inits[name], jnp.int64)
            for name in self.accums
        }
        self.sdirty = jnp.zeros(cap, jnp.bool_)
        self.stored = jnp.zeros(cap, jnp.bool_)
        if n:
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d))
                for i, d in enumerate(key_dtypes)
            )
            table, slots, _, _ = lookup_or_insert(
                table, lanes, jnp.ones(n, jnp.bool_)
            )
            table = set_live(table, slots, True)
            self.stored = self.stored.at[slots].set(True)
            for name in self.accums:
                self.accums[name] = (
                    self.accums[name]
                    .at[slots]
                    .set(jnp.asarray(value_cols[f"acc_{name}"]))
                )
        self.table = table
        self._bound = int(n)
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)
        self._ooo = jnp.zeros((), jnp.bool_)


# ---------------------------------------------------------------------------
# General (retractable) over-window
# ---------------------------------------------------------------------------


@partial(
    jax.jit,
    static_argnames=("calls", "part_keys", "order_col", "pk", "lane_names"),
    donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7, 8),
)
def _general_over_step(
    table: HashTable,
    buf: Dict[str, jnp.ndarray],
    bnulls: Dict[str, jnp.ndarray],
    present: jnp.ndarray,
    seq: jnp.ndarray,
    em: Dict[str, jnp.ndarray],
    emnulls: Dict[str, jnp.ndarray],
    em_valid: jnp.ndarray,
    sdirty: jnp.ndarray,
    seq_base: jnp.ndarray,
    chunk: StreamChunk,
    calls: Tuple[WindowCall, ...],
    part_keys: Tuple[str, ...],
    order_col: str,
    pk: Tuple[str, ...],
    lane_names: Tuple[str, ...],
):
    """One fused retractable over-window step (general.rs:49 the TPU
    way): apply the chunk's inserts/deletes to the pk-keyed row arena,
    mark every touched partition dirty, re-sort the arena and recompute
    EVERY window call over the dirty partitions, then diff against the
    previously-emitted lanes and emit retract/re-emit pairs. The
    reference walks per-row affected frame ranges (frame_finder.rs); on
    TPU whole-partition recomputation in one sorted-segment program is
    the idiomatic equivalent — segment scans are near-free on the VPU
    and the emitted diff is identical."""
    cap = present.shape[0]
    n = chunk.capacity
    total = cap + n  # sort domain: arena + ghost entries (one per row)
    rows_active = chunk.valid
    signs = chunk.effective_signs()
    is_ins = signs > 0
    is_del = rows_active & (signs < 0)

    keys = tuple(chunk.col(k) for k in pk)
    table, slots, found, _ = lookup_or_insert(table, keys, rows_active)
    gslots = jnp.clip(slots, 0, cap - 1)
    dropped = jnp.any(rows_active & (slots < 0))
    pre_present = present[gslots]
    dup = _chunk_dup(slots, rows_active)
    # a DELETE must target a currently-present pk (or one produced
    # earlier in this very chunk); anything else is upstream
    # inconsistency (the reference's consistency check)
    bad_delete = jnp.any(
        is_del & ~dup & ~(slots < 0) & ~(found & pre_present)
    )

    # last occurrence per pk wins (within-chunk -old/+new updates);
    # the table's live lane tracks the final presence so dead slots are
    # reclaimed at the next rehash
    writer = last_occurrence_mask(slots, rows_active)
    table = set_live(table, jnp.where(writer, slots, -1), is_ins)

    # ghost entries: a same-chunk partition-key move leaves the OLD
    # partition with no touched member (the slot now sorts under its
    # new partition), so its remaining rows would keep stale window
    # values. Emit one non-live ghost per moved row under the OLD
    # (emitted) partition keys purely to carry the dirty mark there.
    moved = jnp.zeros(n, jnp.bool_)
    for k in part_keys:
        moved = moved | (
            em[k][gslots] != chunk.col(k).astype(jnp.int64)
        )
    ghost = writer & is_ins & em_valid[gslots] & moved

    target = jnp.where(writer, slots, cap)
    present = present.at[target].set(is_ins, mode="drop")
    for name in lane_names:
        buf[name] = (
            buf[name]
            .at[target]
            .set(chunk.col(name).astype(buf[name].dtype), mode="drop")
        )
        if name in bnulls:
            lane = chunk.nulls.get(name, jnp.zeros(n, jnp.bool_))
            bnulls[name] = bnulls[name].at[target].set(lane, mode="drop")
    pos = jnp.arange(n, dtype=jnp.int64)
    seq = seq.at[target].set(seq_base + pos, mode="drop")
    touched = (
        jnp.zeros(cap, jnp.bool_)
        .at[jnp.where(rows_active, slots, cap)]
        .set(True, mode="drop")
    )
    sdirty = sdirty | touched

    # ---- sort the arena: members = rows needing compute or retraction
    member = present | em_valid
    member_e = jnp.concatenate([member, ghost])
    present_e = jnp.concatenate([present, jnp.zeros(n, jnp.bool_)])
    plane_e = tuple(
        jnp.concatenate(
            [
                jnp.where(present, buf[k], em[k]).astype(jnp.int64),
                em[k][gslots],
            ]
        )
        for k in part_keys
    )
    order_e = jnp.concatenate(
        [
            jnp.where(present, buf[order_col], em[order_col]).astype(
                jnp.int64
            ),
            em[order_col][gslots],
        ]
    )
    seq_e = jnp.concatenate([seq, seq[gslots]])
    touched_e = jnp.concatenate([touched, ghost])
    idx = jnp.arange(total, dtype=jnp.int32)  # >= cap identifies ghosts
    sort_in = (
        (~member_e).astype(jnp.int32),
        *plane_e,
        (~present_e).astype(jnp.int32),  # live rows first per partition
        order_e,
        seq_e,
        idx,
    )
    nk = len(sort_in) - 1
    sorted_all = jax.lax.sort(sort_in, num_keys=nk)
    s_idx = sorted_all[-1]

    def s(a, fill=0):
        """Gather an arena lane into the sorted domain (ghost entries
        read the fill value — they are never live)."""
        return jnp.concatenate(
            [a, jnp.full(n, fill, a.dtype)]
        )[s_idx]

    member_s = member_e[s_idx]
    live_s = present_e[s_idx]
    plane_s = [p[s_idx] for p in plane_e]
    v_order = order_e[s_idx]

    arange = jnp.arange(total, dtype=jnp.int64)
    boundary = jnp.zeros(total, jnp.bool_)
    for lane in plane_s:
        boundary = boundary | jnp.concatenate(
            [jnp.ones(1, jnp.bool_), lane[1:] != lane[:-1]]
        )
    boundary = boundary | jnp.concatenate(
        [jnp.ones(1, jnp.bool_), member_s[1:] != member_s[:-1]]
    )
    boundary = boundary.at[0].set(True)
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_start = jax.ops.segment_max(
        jnp.where(boundary, arange, 0), gid, num_segments=total
    )[gid]
    in_seg = arange - seg_start
    dirty_s = (
        jax.ops.segment_max(
            touched_e[s_idx].astype(jnp.int32), gid, num_segments=total
        )[gid]
        > 0
    ) & member_s

    MAXI = jnp.iinfo(jnp.int64).max
    MINI = jnp.iinfo(jnp.int64).min
    zero_nulls = jnp.zeros(total, jnp.bool_)

    def shifted(vals, nullm, d):
        j = jnp.arange(total, dtype=jnp.int32) + d
        jc = jnp.clip(j, 0, total - 1)
        ok = (
            (j >= 0)
            & (j < total)
            & (gid[jc] == gid)
            & live_s[jc]
            & live_s
        )
        return jnp.where(ok, vals[jc], 0), jnp.where(ok, nullm[jc], True)

    out_sorted: Dict[str, jnp.ndarray] = {}
    out_nulls_sorted: Dict[str, jnp.ndarray] = {}
    for c in calls:
        if c.input is not None:
            v = s(buf[c.input]).astype(jnp.int64)
            vnull = (
                s(bnulls[c.input], True)
                if c.input in bnulls
                else zero_nulls
            )
        if c.kind == "row_number":
            o, onull = in_seg + 1, zero_nulls
        elif c.kind in ("rank", "dense_rank"):
            pv = jnp.concatenate(
                [jnp.zeros(1, v_order.dtype), v_order[:-1]]
            )
            vb = boundary | (v_order != pv)
            cum_vb_all = jnp.cumsum(vb.astype(jnp.int64))
            seg_vb = jax.ops.segment_max(
                jnp.where(boundary, cum_vb_all - 1, MINI),
                gid,
                num_segments=total,
            )[gid]
            if c.kind == "dense_rank":
                o = cum_vb_all - seg_vb
            else:

                def reset_max(a, b):
                    fa, va = a
                    fb, vb_ = b
                    return fa | fb, jnp.where(
                        fb, vb_, jnp.maximum(va, vb_)
                    )

                _, grp_start = jax.lax.associative_scan(
                    reset_max, (boundary, jnp.where(vb, in_seg, MINI))
                )
                o = grp_start + 1
            onull = zero_nulls
        elif c.kind in ("lead", "lag"):
            d = c.offset if c.kind == "lead" else -c.offset
            o, onull = shifted(v, vnull, d)
        elif c.frame is not None:
            lo, hi = c.frame
            if c.kind == "count":
                v, vnull = jnp.ones(total, jnp.int64), zero_nulls
            ident = (
                MAXI if c.kind == "min" else MINI if c.kind == "max" else 0
            )
            comb = (
                jnp.minimum
                if c.kind == "min"
                else jnp.maximum
                if c.kind == "max"
                else (lambda a, b: a + b)
            )
            acc = jnp.full(total, ident, jnp.int64)
            any_real = zero_nulls
            for d in range(lo, hi + 1):
                sv, sn = shifted(v, vnull, d)
                real = ~sn
                acc = comb(acc, jnp.where(real, sv, ident))
                any_real = any_real | real
            if c.kind == "count":
                o, onull = acc, zero_nulls
            else:
                o, onull = acc, ~any_real
        else:
            # running UNBOUNDED PRECEDING .. CURRENT ROW
            if c.kind == "count":
                real = live_s
                vv = jnp.ones(total, jnp.int64)
            else:
                real = live_s & ~vnull
                vv = v
            if c.kind in ("sum", "count"):
                vv = jnp.where(real, vv, 0)
                csum = jnp.cumsum(vv)
                base = jax.ops.segment_max(
                    jnp.where(boundary, csum - vv, MINI),
                    gid,
                    num_segments=total,
                )[gid]
                o, onull = csum - base, zero_nulls
            else:
                sent = MAXI if c.kind == "min" else MINI
                vv = jnp.where(real, vv, sent)

                def op(a, b):
                    fa, va, ra = a
                    fb, vb_, rb = b
                    cmb = jnp.minimum if c.kind == "min" else jnp.maximum
                    return (
                        fa | fb,
                        jnp.where(fb, vb_, cmb(va, vb_)),
                        jnp.where(fb, rb, ra | rb),
                    )

                _, o, has = jax.lax.associative_scan(
                    op, (boundary, vv, real)
                )
                onull = ~has
        out_sorted[c.output] = o
        out_nulls_sorted[c.output] = onull

    # ---- unsort to slots (ghost entries, s_idx >= cap, are dropped);
    # diff against the emitted lanes
    dirty_slot = (
        jnp.zeros(cap, jnp.bool_).at[s_idx].set(dirty_s, mode="drop")
    )
    new_out = {
        name: jnp.zeros(cap, jnp.int64).at[s_idx].set(o, mode="drop")
        for name, o in out_sorted.items()
    }
    new_out_nulls = {
        name: jnp.zeros(cap, jnp.bool_).at[s_idx].set(o, mode="drop")
        for name, o in out_nulls_sorted.items()
    }
    both = present & em_valid
    changed = jnp.zeros(cap, jnp.bool_)
    for name in lane_names:
        cn = bnulls.get(name, jnp.zeros(cap, jnp.bool_))
        en = emnulls.get(name, jnp.zeros(cap, jnp.bool_))
        # compare values only where both sides are non-NULL — the cell
        # under a NULL flag is an arbitrary fill
        changed = changed | (
            ~cn & ~en & (buf[name].astype(jnp.int64) != em[name])
        )
        changed = changed | (cn != en)
    for c in calls:
        nn = new_out_nulls[c.output]
        en = emnulls.get(c.output, jnp.zeros(cap, jnp.bool_))
        changed = changed | (
            jnp.where(~nn, new_out[c.output], 0)
            != jnp.where(~en, em[c.output], 0)
        )
        changed = changed | (nn != en)
    changed = changed & both
    retract = em_valid & dirty_slot & (~present | changed)
    insert = present & dirty_slot & (~em_valid | changed)
    sdirty = sdirty | retract | insert

    ops_del = jnp.full(cap, 1, jnp.int32)  # Op.DELETE
    ops_ins = jnp.zeros(cap, jnp.int32)  # Op.INSERT
    out_names = tuple(c.output for c in calls)
    # compact each diff to a dense prefix: a scattered-valid chunk
    # defeats downstream _live_slice and host conversion fast paths
    rorder = jnp.argsort(~retract, stable=True)
    iorder = jnp.argsort(~insert, stable=True)
    ret_cols = {
        name: em[name][rorder] for name in lane_names + out_names
    }
    ret_nulls = {name: a[rorder] for name, a in emnulls.items()}
    ret_chunk = StreamChunk(
        columns=ret_cols,
        valid=retract[rorder],
        nulls=ret_nulls,
        ops=ops_del,
    )
    ins_cols = {
        name: buf[name].astype(jnp.int64)[iorder] for name in lane_names
    }
    ins_cols.update({name: new_out[name][iorder] for name in out_names})
    ins_nulls = {name: a[iorder] for name, a in bnulls.items()}
    ins_nulls.update(
        {name: a[iorder] for name, a in new_out_nulls.items()}
    )
    ins_chunk = StreamChunk(
        columns=ins_cols,
        valid=insert[iorder],
        nulls=ins_nulls,
        ops=ops_ins,
    )

    # emitted state := what downstream now holds
    upd = jnp.where(insert, jnp.arange(cap, dtype=jnp.int32), cap)
    for name in lane_names:
        em[name] = (
            em[name].at[upd].set(buf[name].astype(jnp.int64), mode="drop")
        )
        cn = bnulls.get(name, jnp.zeros(cap, jnp.bool_))
        emnulls[name] = (
            emnulls.get(name, jnp.zeros(cap, jnp.bool_))
            .at[upd]
            .set(cn, mode="drop")
        )
    for name in out_names:
        em[name] = em[name].at[upd].set(new_out[name], mode="drop")
        emnulls[name] = (
            emnulls.get(name, jnp.zeros(cap, jnp.bool_))
            .at[upd]
            .set(new_out_nulls[name], mode="drop")
        )
    em_valid = (em_valid & ~retract) | insert

    return (
        table,
        buf,
        bnulls,
        present,
        seq,
        em,
        emnulls,
        em_valid,
        sdirty,
        ret_chunk,
        ins_chunk,
        dropped,
        bad_delete,
    )


def _chunk_dup(slots: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Rows whose slot already appeared earlier in the chunk (a delete
    may legitimately target a row inserted earlier in the same chunk,
    which lookup_or_insert reports as freshly inserted)."""
    return valid & ~first_occurrence_mask(slots, valid)


class GeneralOverWindowExecutor(Executor, Checkpointable):
    """General (retractable) window functions over partitions.

    Reference: src/stream/src/executor/over_window/general.rs:49 —
    handles inserts, deletes and updates ANYWHERE in the ORDER BY
    order, retracting and re-emitting every row whose window value
    changes. The reference computes per-row affected frame ranges
    (frame_finder.rs); the TPU re-design keeps all rows in a pk-keyed
    device arena and recomputes complete dirty partitions in one fused
    sorted-segment program per chunk — recompute is near-free on the
    VPU, and the diff against the previously-emitted lanes yields the
    exact minimal retract/re-emit set.

    Supports every WindowCall kind including lead/lag(k) and static
    ROWS frames (deletes may reopen any frame, so the general executor
    has no hold-back constraint — it simply recomputes).
    Checkpointable: current rows + emitted rows persist; recovery is
    bit-exact."""

    def __init__(
        self,
        partition_by: Sequence[str],
        order_col: str,
        pk: Sequence[str],
        calls: Sequence[WindowCall],
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 12,
        nullable: Sequence[str] = (),
        table_id: str = "general_over",
    ):
        self.part_keys = tuple(partition_by)
        self.order_col = order_col
        self.pk = tuple(pk)
        self.calls = tuple(calls)
        for c in self.calls:
            if c.kind in ("rank", "dense_rank") and c.input != order_col:
                raise ValueError(
                    f"{c.kind} ranks by the executor's order column "
                    f"{order_col!r}; got input {c.input!r}"
                )
        for nm, d in schema_dtypes.items():
            if not jnp.issubdtype(jnp.dtype(d), jnp.integer):
                raise ValueError(
                    f"general OverWindow lane {nm!r} has non-integer "
                    f"dtype {d}: emitted/diffed lanes are carried as "
                    "int64 (dictionary- or scale-encode upstream)"
                )
        self.lane_names = tuple(schema_dtypes)
        self.out_names = tuple(c.output for c in self.calls)
        self.schema_dtypes = dict(schema_dtypes)
        self.nullable = tuple(nullable)
        self.table_id = table_id
        self._alloc(capacity)
        self._seq_base = 0
        self._dropped = jnp.zeros((), jnp.bool_)
        self._bad_delete = jnp.zeros((), jnp.bool_)
        self._bound = 0

    def lint_info(self):
        requires = set(self.part_keys) | set(self.pk) | {self.order_col}
        for c in self.calls:
            if c.input is not None:
                requires.add(c.input)
        return {
            "requires": tuple(sorted(requires)),
            "expects": {
                k: self.schema_dtypes[k]
                for k in sorted(requires)
                if k in self.schema_dtypes
            },
            "adds": {c.output: jnp.int64 for c in self.calls},
            "keys": self.part_keys,
            "state_pk": tuple(self.pk),
            "table_ids": (self.table_id,),
        }

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: _general_over_step(
                self.table,
                self.buf,
                self.bnulls,
                self.present,
                self.seq,
                self.em,
                self.emnulls,
                self.em_valid,
                self.sdirty,
                jnp.int64(self._seq_base),
                c,
                self.calls,
                self.part_keys,
                self.order_col,
                self.pk,
                self.lane_names,
            ),
            "state": (self.table, self.buf, self.em),
            "donate": True,
            # retract/re-emit diff chunks are arena-capacity lanes
            "emission": "fixed",
            "emission_caps": (self.capacity,),
        }

    def _alloc(self, cap: int):
        self.table = HashTable.create(
            cap, tuple(jnp.dtype(self.schema_dtypes[k]) for k in self.pk)
        )
        self.buf = {
            n: jnp.zeros(cap, jnp.dtype(d))
            for n, d in self.schema_dtypes.items()
        }
        self.bnulls = {n: jnp.zeros(cap, jnp.bool_) for n in self.nullable}
        self.present = jnp.zeros(cap, jnp.bool_)
        self.seq = jnp.zeros(cap, jnp.int64)
        self.em = {
            n: jnp.zeros(cap, jnp.int64)
            for n in self.lane_names + self.out_names
        }
        self.emnulls = {}
        self.em_valid = jnp.zeros(cap, jnp.bool_)
        self.sdirty = jnp.zeros(cap, jnp.bool_)
        self.stored = jnp.zeros(cap, jnp.bool_)

    @property
    def capacity(self) -> int:
        return self.present.shape[0]

    def state_nbytes(self) -> int:
        """Device bytes held (host-side estimate; no sync)."""
        return sum(
            leaf.nbytes
            for leaf in jax.tree.leaves((
                self.table, self.buf, self.bnulls, self.present,
                self.seq, self.em, self.emnulls, self.em_valid,
                self.sdirty, self.stored,
            ))
        )

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        for c in self.calls:
            if c.kind in ("rank", "dense_rank") and c.input in chunk.nulls:
                raise ValueError(
                    f"rank order column {c.input!r} carries a null lane "
                    "(NULL ordering unsupported)"
                )
        self._maybe_grow(chunk.capacity)
        (
            self.table,
            self.buf,
            self.bnulls,
            self.present,
            self.seq,
            self.em,
            self.emnulls,
            self.em_valid,
            self.sdirty,
            ret,
            ins,
            dr,
            bd,
        ) = _general_over_step(
            self.table,
            self.buf,
            self.bnulls,
            self.present,
            self.seq,
            self.em,
            self.emnulls,
            self.em_valid,
            self.sdirty,
            jnp.int64(self._seq_base),
            chunk,
            self.calls,
            self.part_keys,
            self.order_col,
            self.pk,
            self.lane_names,
        )
        self._seq_base += chunk.capacity
        self._bound += chunk.capacity
        self._dropped = self._dropped | dr
        self._bad_delete = self._bad_delete | bd
        return [ret, ins]

    def _maybe_grow(self, incoming: int):
        cap = self.capacity
        if self._bound + incoming <= cap * GROW_AT:
            return
        claimed = int(self.table.occupancy())
        survivors = int(
            jnp.sum(self.table.live | self.sdirty | self.stored)
        )
        new_cap = plan_rehash(cap, incoming, claimed, survivors, GROW_AT)
        if new_cap is not None:
            self._rehash(new_cap)
            claimed = int(self.table.occupancy())
        self._bound = claimed

    def _rehash(self, new_cap: int):
        # a slot survives iff someone still cares: live row, unflushed
        # emission-state change (sdirty), or a durable row whose
        # tombstone has not been staged yet (stored) — delete/insert
        # churn with fresh pks compacts instead of growing forever
        keep = (self.table.live | self.sdirty | self.stored) & (
            self.table.fp1 != jnp.uint32(0)
        )
        new = HashTable.create(
            new_cap, tuple(k.dtype for k in self.table.keys)
        )
        new, slots, _, _ = lookup_or_insert(new, self.table.keys, keep)
        new = set_live(new, jnp.where(keep, slots, -1), self.table.live)
        idx = jnp.where(keep, slots, new_cap)

        def mv(a, fill=0):
            return (
                jnp.full(new_cap, fill, a.dtype).at[idx].set(a, mode="drop")
            )

        self.buf = {n: mv(a) for n, a in self.buf.items()}
        self.bnulls = {n: mv(a) for n, a in self.bnulls.items()}
        self.present = mv(self.present)
        self.seq = mv(self.seq)
        self.em = {n: mv(a) for n, a in self.em.items()}
        self.emnulls = {n: mv(a) for n, a in self.emnulls.items()}
        self.em_valid = mv(self.em_valid)
        self.sdirty = mv(self.sdirty)
        self.stored = mv(self.stored)
        self.table = new

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        from risingwave_tpu.ops.hash_table import stage_scalars

        self._staged_scalars = stage_scalars(
            self._dropped, self._bad_delete
        )
        if barrier is None:
            self.finish_barrier()
        return []

    def _on_barrier_scalars(self, vals) -> None:
        dr, bd = vals
        if dr:
            raise RuntimeError("general OverWindow row arena overflowed")
        if bd:
            raise RuntimeError(
                "general OverWindow received a DELETE for an unknown pk "
                "(inconsistent upstream)"
            )

    # -- integrity --------------------------------------------------------
    def digest_lanes(self):
        lanes = {f"k{i}": k for i, k in enumerate(self.table.keys)}
        for n in self.lane_names:
            lanes[f"c_{n}"] = self.buf[n]
        for n, a in self.bnulls.items():
            lanes[f"cn_{n}"] = a
        for n, a in self.em.items():
            lanes[f"e_{n}"] = a
        for n, a in self.emnulls.items():
            lanes[f"en_{n}"] = a
        lanes["seq"] = self.seq
        lanes["present"] = self.present
        return lanes, self.present | self.em_valid

    def state_digest(self) -> int:
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self) -> List[StateDelta]:
        sdirty = np.asarray(self.sdirty)
        if not sdirty.any():
            return []
        alive = np.asarray(self.present | self.em_valid)
        upsert, tomb, sel = stage_marks(
            sdirty, alive, np.asarray(self.stored)
        )
        lanes = {f"k{i}": l for i, l in enumerate(self.table.keys)}
        key_names = tuple(lanes)
        for n in self.lane_names:
            lanes[f"c_{n}"] = self.buf[n]
        for n, a in self.bnulls.items():
            lanes[f"cn_{n}"] = a
        for n, a in self.em.items():
            lanes[f"e_{n}"] = a
        for n, a in self.emnulls.items():
            lanes[f"en_{n}"] = a
        lanes["seq"] = self.seq
        lanes["present"] = self.present
        pulled = pull_rows(lanes, sel)
        keys = {k: pulled[k] for k in key_names}
        vals = {k: v for k, v in pulled.items() if k not in key_names}
        self.stored = (self.stored | jnp.asarray(upsert)) & ~jnp.asarray(
            tomb
        )
        self.sdirty = jnp.zeros_like(self.sdirty)
        return [StateDelta(self.table_id, keys, vals, tomb[sel], key_names)]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        cap = grow_pow2(max(n, 1), self.capacity, GROW_AT)
        self._alloc(cap)
        if n:
            key_dtypes = tuple(k.dtype for k in self.table.keys)
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d))
                for i, d in enumerate(key_dtypes)
            )
            self.table, slots, _, _ = lookup_or_insert(
                self.table, lanes, jnp.ones(n, jnp.bool_)
            )
            self.table = set_live(self.table, slots, True)
            self.stored = self.stored.at[slots].set(True)
            pres = jnp.asarray(
                np.asarray(value_cols["present"], dtype=bool)
            )
            self.present = self.present.at[slots].set(pres)
            self.em_valid = self.em_valid.at[slots].set(pres)
            self.seq = self.seq.at[slots].set(
                jnp.asarray(np.asarray(value_cols["seq"], np.int64))
            )
            self._seq_base = int(np.asarray(value_cols["seq"]).max()) + 1
            for nme in self.lane_names:
                self.buf[nme] = (
                    self.buf[nme]
                    .at[slots]
                    .set(
                        jnp.asarray(
                            np.asarray(
                                value_cols[f"c_{nme}"],
                                self.buf[nme].dtype,
                            )
                        )
                    )
                )
            for nme in self.bnulls:
                if f"cn_{nme}" in value_cols:
                    self.bnulls[nme] = (
                        self.bnulls[nme]
                        .at[slots]
                        .set(
                            jnp.asarray(
                                np.asarray(value_cols[f"cn_{nme}"], bool)
                            )
                        )
                    )
            for nme in self.em:
                if f"e_{nme}" in value_cols:
                    self.em[nme] = (
                        self.em[nme]
                        .at[slots]
                        .set(
                            jnp.asarray(
                                np.asarray(
                                    value_cols[f"e_{nme}"], np.int64
                                )
                            )
                        )
                    )
            for key, v in value_cols.items():
                if key.startswith("en_"):
                    nme = key[3:]
                    self.emnulls[nme] = (
                        jnp.zeros(cap, jnp.bool_)
                        .at[slots]
                        .set(jnp.asarray(np.asarray(v, bool)))
                    )
        self._bound = int(n)
        self._dropped = jnp.zeros((), jnp.bool_)
        self._bad_delete = jnp.zeros((), jnp.bool_)
