"""OverWindow — window functions over partitions (append-only).

Reference: src/stream/src/executor/over_window/general.rs:49 — per
partition, per order-key window functions; the general executor
retracts and re-emits affected frames on any change. This executor is
the APPEND-ONLY + arrival-ordered specialization (RW's planner also
specializes this case): each row gets its window value at arrival and
is never revisited — exactly right for ROW_NUMBER / running COUNT /
running SUM over monotonically arriving streams.

TPU re-design: partition state is a hash table + per-slot running
accumulators. One fused step per chunk: lookup partitions, sort rows
by (slot, arrival) to rank intra-chunk duplicates, gather partition
bases, segment-prefix-scan the chunk's own contribution, scatter the
updated accumulators back — no per-row host work.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.ops.hash_table import (
    HashTable,
    lookup_or_insert,
    plan_rehash,
    set_live,
)

GROW_AT = 0.5

KINDS = ("row_number", "count", "sum", "min", "max", "lag")


@dataclass(frozen=True)
class WindowCall:
    kind: str
    input: Optional[str]  # None for row_number / count(*)
    output: str

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unsupported window kind {self.kind!r}")
        if (self.input is None) != (self.kind in ("row_number", "count")):
            raise ValueError(f"{self.kind} input mismatch")


def _accum_names(call: "WindowCall"):
    """Accumulator lanes per call (lag keeps last-value + flags;
    min/max keep a presence flag so sentinel-valued inputs are not
    misread as NULL)."""
    if call.kind == "lag":
        return (call.output, call.output + "#has", call.output + "#null")
    if call.kind in ("min", "max"):
        return (call.output, call.output + "#has")
    return (call.output,)


def _accum_init(call: "WindowCall") -> int:
    if call.kind == "min":
        return jnp.iinfo(jnp.int64).max
    if call.kind == "max":
        return jnp.iinfo(jnp.int64).min
    return 0


@partial(jax.jit, static_argnames=("calls", "part_keys"), donate_argnums=(0, 1))
def _over_step(
    table: HashTable,
    accums: Dict[str, jnp.ndarray],
    chunk: StreamChunk,
    calls: Tuple[WindowCall, ...],
    part_keys: Tuple[str, ...],
):
    n = chunk.capacity
    keys = tuple(chunk.col(k) for k in part_keys)
    signs = chunk.effective_signs()
    active = chunk.valid & (signs > 0)
    saw_delete = jnp.any(chunk.valid & (signs < 0))
    table, slots, _, _ = lookup_or_insert(table, keys, active)
    dropped = jnp.any(active & (slots < 0))
    table = set_live(table, jnp.where(active, slots, -1), True)

    # rank rows of one partition within the chunk (arrival order)
    skey = jnp.where(active, slots, table.capacity).astype(jnp.int32)
    pos = jnp.arange(n, dtype=jnp.int32)
    val_lanes = {
        c.input: chunk.col(c.input).astype(jnp.int64)
        for c in calls
        if c.input is not None
    }
    null_lanes = {
        c.input: chunk.nulls[c.input]
        for c in calls
        if c.input is not None and c.input in chunk.nulls
    }
    names = tuple(sorted(val_lanes))
    nnames = tuple(sorted(null_lanes))
    sorted_ops = jax.lax.sort(
        (skey, pos)
        + tuple(val_lanes[m] for m in names)
        + tuple(null_lanes[m] for m in nnames),
        num_keys=2,
    )
    s_slot, s_pos = sorted_ops[0], sorted_ops[1]
    s_vals = {m: sorted_ops[2 + i] for i, m in enumerate(names)}
    s_nulls = {
        m: sorted_ops[2 + len(names) + i] for i, m in enumerate(nnames)
    }
    boundary = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), s_slot[1:] != s_slot[:-1]]
    )
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    arange = jnp.arange(n, dtype=jnp.int64)
    seg_start = jax.ops.segment_max(
        jnp.where(boundary, arange, 0), gid, num_segments=n
    )[gid]
    rank = arange - seg_start  # 0-based within (partition, chunk)
    s_active = s_slot < table.capacity
    gslot = jnp.where(s_active, s_slot, 0)

    # segment end == next segment's start (derive from boundary)
    is_last = jnp.concatenate([boundary[1:], jnp.ones(1, jnp.bool_)])
    MAXI = jnp.iinfo(jnp.int64).max
    MINI = jnp.iinfo(jnp.int64).min

    def seg_prefix_extreme(v, kind):
        """Inclusive segmented prefix min/max via an associative scan
        with a boundary-reset flag (the classic segmented-scan
        combine)."""
        comb = jnp.minimum if kind == "min" else jnp.maximum

        def op(a, b):
            fa, va = a
            fb, vb = b
            return fa | fb, jnp.where(fb, vb, comb(va, vb))

        _, out = jax.lax.associative_scan(op, (boundary, v))
        return out

    out_sorted: Dict[str, jnp.ndarray] = {}
    out_nulls_sorted: Dict[str, jnp.ndarray] = {}
    new_accums = dict(accums)
    for c in calls:
        acc = new_accums[c.output]
        base = acc[gslot]
        upd = jnp.where(s_active & is_last, gslot, table.capacity)
        if c.kind in ("row_number", "count"):
            o = base + rank + 1
            contrib = jnp.where(s_active, jnp.int64(1), jnp.int64(0))
            totals = jax.ops.segment_sum(contrib, gid, num_segments=n)[gid]
            new_accums[c.output] = acc.at[upd].add(totals, mode="drop")
        elif c.kind == "sum":
            # running sum (NULL inputs contribute 0, SQL skips them)
            v = s_vals[c.input]
            nn = ~s_nulls.get(c.input, jnp.zeros(n, jnp.bool_))
            v = jnp.where(s_active & nn, v, 0)
            # inclusive prefix within the segment (sentinel, not 0: the
            # boundary's exclusive prefix may be negative)
            csum = jnp.cumsum(v)
            seg_base = jax.ops.segment_max(
                jnp.where(boundary, csum - v, MINI),
                gid,
                num_segments=n,
            )[gid]
            o = base + (csum - seg_base)
            totals = jax.ops.segment_sum(v, gid, num_segments=n)[gid]
            new_accums[c.output] = acc.at[upd].add(totals, mode="drop")
        elif c.kind in ("min", "max"):
            sent = MAXI if c.kind == "min" else MINI
            comb = jnp.minimum if c.kind == "min" else jnp.maximum
            v = s_vals[c.input]
            nn = ~s_nulls.get(c.input, jnp.zeros(n, jnp.bool_))
            real = s_active & nn
            v = jnp.where(real, v, sent)
            pref = seg_prefix_extreme(v, c.kind)
            o = comb(base, pref)
            # presence via a companion lane, NOT sentinel equality: a
            # legitimate input equal to the int64 extreme must not be
            # misclassified as NULL (its value still combines right —
            # min(x, +inf) = x)
            has = new_accums[c.output + "#has"]
            pref_has = (
                jnp.cumsum(real.astype(jnp.int64))
                - jax.ops.segment_max(
                    jnp.where(
                        boundary,
                        jnp.cumsum(real.astype(jnp.int64))
                        - real.astype(jnp.int64),
                        MINI,
                    ),
                    gid,
                    num_segments=n,
                )[gid]
            ) > 0
            out_nulls_sorted[c.output] = ~((has[gslot] != 0) | pref_has)
            seg_fn = (
                jax.ops.segment_min if c.kind == "min" else jax.ops.segment_max
            )
            seg_ext = seg_fn(v, gid, num_segments=n)[gid]
            if c.kind == "min":
                new_accums[c.output] = acc.at[upd].min(seg_ext, mode="drop")
            else:
                new_accums[c.output] = acc.at[upd].max(seg_ext, mode="drop")
            seg_any = (
                jax.ops.segment_sum(
                    real.astype(jnp.int64), gid, num_segments=n
                )[gid]
                > 0
            )
            new_accums[c.output + "#has"] = (
                has.at[upd].max(seg_any.astype(jnp.int64), mode="drop")
            )
        else:  # lag(1): previous row's value within the partition
            v = s_vals[c.input]
            vnull = s_nulls.get(c.input, jnp.zeros(n, jnp.bool_))
            prev_v = jnp.concatenate([jnp.zeros(1, v.dtype), v[:-1]])
            prev_null = jnp.concatenate(
                [jnp.zeros(1, jnp.bool_), vnull[:-1]]
            )
            first = rank == 0
            # pre-update state: the partition's stored last value
            prev_has = new_accums[c.output + "#has"][gslot] != 0
            prev_stored_null = new_accums[c.output + "#null"][gslot] != 0
            o = jnp.where(first, base, prev_v)
            out_nulls_sorted[c.output] = jnp.where(
                first, ~prev_has | prev_stored_null, prev_null
            )
            # store the segment's LAST value (+ its nullness) per slot
            lastv = jax.ops.segment_max(
                jnp.where(is_last, v, MINI), gid, num_segments=n
            )[gid]
            lastn = jax.ops.segment_max(
                jnp.where(is_last, vnull.astype(jnp.int64), 0),
                gid,
                num_segments=n,
            )[gid]
            new_accums[c.output] = acc.at[upd].set(lastv, mode="drop")
            new_accums[c.output + "#null"] = (
                new_accums[c.output + "#null"]
                .at[upd]
                .set(lastn, mode="drop")
            )
            new_accums[c.output + "#has"] = (
                new_accums[c.output + "#has"]
                .at[upd]
                .set(jnp.int64(1), mode="drop")
            )
        out_sorted[c.output] = o

    # unsort back to arrival positions
    cols = dict(chunk.columns)
    out_nulls = dict(chunk.nulls)
    for name, o in out_sorted.items():
        buf = jnp.zeros(n, jnp.int64)
        cols[name] = buf.at[s_pos].set(o)
    for name, lane in out_nulls_sorted.items():
        nbuf = jnp.zeros(n, jnp.bool_)
        out_nulls[name] = nbuf.at[s_pos].set(lane)
    out = StreamChunk(
        columns=cols, valid=chunk.valid & active, nulls=out_nulls,
        ops=chunk.ops,
    )
    return table, new_accums, out, saw_delete, dropped


class OverWindowExecutor(Executor):
    """Append-only window functions: ROW_NUMBER / running COUNT / SUM
    per partition in arrival order."""

    def __init__(
        self,
        partition_by: Sequence[str],
        calls: Sequence[WindowCall],
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 14,
    ):
        self.part_keys = tuple(partition_by)
        self.calls = tuple(calls)
        self.table = HashTable.create(
            capacity,
            tuple(jnp.dtype(schema_dtypes[k]) for k in self.part_keys),
        )
        self.accums = {}
        self._accum_inits = {}
        for c in self.calls:
            for name in _accum_names(c):
                init = _accum_init(c) if name == c.output else 0
                self._accum_inits[name] = init
                self.accums[name] = jnp.full(capacity, init, jnp.int64)
        self._bound = 0
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        self._maybe_grow(chunk.capacity)
        self._bound += chunk.capacity
        self.table, self.accums, out, sd, dr = _over_step(
            self.table, self.accums, chunk, self.calls, self.part_keys
        )
        self._saw_delete = self._saw_delete | sd
        self._dropped = self._dropped | dr
        return [out]

    def _maybe_grow(self, incoming: int):
        cap = self.table.capacity
        if self._bound + incoming <= cap * GROW_AT:
            return
        claimed = int(self.table.occupancy())
        new_cap = plan_rehash(cap, incoming, claimed, claimed, GROW_AT)
        if new_cap is not None:
            keep = self.table.fp1 != jnp.uint32(0)
            new = HashTable.create(
                new_cap, tuple(k.dtype for k in self.table.keys)
            )
            new, slots, _, _ = lookup_or_insert(new, self.table.keys, keep)
            new = set_live(new, jnp.where(keep, slots, -1), self.table.live)
            idx = jnp.where(keep, slots, new_cap)
            self.accums = {
                # unclaimed slots must keep each lane's INIT value (a
                # zero base would corrupt running min/max for new
                # partitions landing there)
                name: jnp.full(new_cap, self._accum_inits[name], jnp.int64)
                .at[idx]
                .set(a, mode="drop")
                for name, a in self.accums.items()
            }
            self.table = new
            claimed = int(self.table.occupancy())
        self._bound = claimed

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        from risingwave_tpu.ops.hash_table import stage_scalars

        self._staged_scalars = stage_scalars(
            self._saw_delete, self._dropped
        )
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return []

    def _on_barrier_scalars(self, vals) -> None:
        sd, dr = vals
        if sd:
            raise RuntimeError(
                "append-only OverWindow received a DELETE (the general "
                "retractable executor is not implemented)"
            )
        if dr:
            raise RuntimeError("OverWindow partition table overflowed")
