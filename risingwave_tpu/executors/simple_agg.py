"""SimpleAgg — global (ungrouped) streaming aggregation.

Reference: src/stream/src/executor/simple_agg.rs (+ the per-chunk
pre-reduction of stateless_simple_agg.rs, which the epoch-reduce path
already fuses). SQL `SELECT count(*), sum(x) FROM t` with no GROUP BY:
exactly one output row, present even before any input (count 0 / NULL
sums), updated with U-/U+ pairs.

TPU re-design: one slot of the same slot-indexed AggState the grouped
executor uses (capacity 2: slot 0 = THE group, slot 1 = scatter drop
lane), no hash table — every valid row scatters into slot 0. The
barrier pulls exactly one row (one packed transfer) and diffs it
against the host mirror of what downstream last saw."""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.ops import agg as agg_ops
from risingwave_tpu.ops.agg import AggCall, _order_key_to_float
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    pull_rows,
)
from risingwave_tpu.types import Op


@partial(jax.jit, static_argnames=("calls",), donate_argnums=(0,))
def _simple_step(state, chunk: StreamChunk, calls):
    signs = chunk.effective_signs()
    active = chunk.valid & (signs != 0)
    slots = jnp.where(active, jnp.int32(0), jnp.int32(-1))
    values = {c.input: chunk.col(c.input) for c in calls if c.input is not None}
    nulls = {
        c.input: chunk.nulls[c.input]
        for c in calls
        if c.input is not None and c.input in chunk.nulls
    }
    return agg_ops.apply(state, calls, slots, signs, values, nulls)


class SimpleAggExecutor(Executor, Checkpointable):
    """Global aggregation: one always-present output row (pk = ())."""

    def __init__(
        self,
        calls: Sequence[AggCall],
        schema_dtypes: Dict[str, object],
        table_id: str = "simple_agg",
    ):
        if any(c.materialized for c in calls):
            raise NotImplementedError(
                "materialized global MIN/MAX not wired yet (grouped "
                "HashAgg supports it)"
            )
        self.table_id = table_id
        self.calls = tuple(calls)
        self._dtypes = dict(schema_dtypes)
        self.state = agg_ops.create_state(2, self.calls, self._dtypes)
        self._float_decode = dict(
            agg_ops.float_extreme_meta(
                self.calls, {k: jnp.dtype(v) for k, v in self._dtypes.items()}
            )
        )
        self._last: Optional[Tuple] = None  # what downstream has

    def lint_info(self):
        requires = sorted(
            {c.input for c in self.calls if c.input is not None}
        )
        emits = {}
        for c in self.calls:
            if c.kind in ("count", "count_star"):
                emits[c.output] = jnp.int64
            elif c.kind in ("min", "max") and c.input in self._dtypes:
                emits[c.output] = self._dtypes[c.input]
            else:
                emits[c.output] = None  # sum/avg widen by kind rules
        return {
            "requires": tuple(requires),
            "expects": {
                k: self._dtypes[k] for k in requires if k in self._dtypes
            },
            "emits": emits,
            "renames": {k: None for k in emits},  # all computed
            "table_ids": (self.table_id,),
        }

    def state_nbytes(self) -> int:
        """Device bytes held (host-side estimate; no sync)."""
        return sum(
            getattr(leaf, "nbytes", 0)
            for leaf in jax.tree.leaves(self.state)
        )

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: _simple_step(
                self.state, c, self.calls
            ),
            "state": self.state,
            "donate": True,
            # _row_chunk sizes its emission by the rows emitted
            # (max(2, len(ops))) — data-dependent output shape
            "emission": "data_dependent",
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        self.state = _simple_step(self.state, chunk, self.calls)
        return []

    def _current_row(self) -> Tuple:
        """(value | None per call) — ONE packed one-row device pull."""
        lanes = {"mret": self.state.minmax_retracted.reshape(1)}
        for c in self.calls:
            lanes[f"a_{c.output}"] = self.state.accums[c.output]
            if c.output in self.state.nonnull:
                lanes[f"n_{c.output}"] = self.state.nonnull[c.output]
        pulled = {
            k: np.asarray(v if v.shape[0] == 1 else v[:1])
            for k, v in pull_rows(lanes, np.asarray([0])).items()
        }
        if bool(pulled["mret"][0]):
            raise RuntimeError(
                "retraction hit an append-only global MIN/MAX; use the "
                "grouped executor's materialized extremes"
            )
        row = []
        for c in self.calls:
            v = pulled[f"a_{c.output}"][0]
            if c.output in self.state.nonnull:
                if int(pulled[f"n_{c.output}"][0]) == 0:
                    row.append(None)
                    continue
                if c.output in self._float_decode:
                    v = float(
                        _order_key_to_float(
                            jnp.asarray(v),
                            jnp.dtype(self._float_decode[c.output]),
                        )
                    )
            row.append(v.item() if hasattr(v, "item") else v)
        return tuple(row)

    def _row_chunk(self, rows_ops) -> StreamChunk:
        cols = {c.output: [] for c in self.calls}
        nulls = {
            c.output: [] for c in self.calls if c.output in self.state.nonnull
        }
        ops = []
        for row, op in rows_ops:
            ops.append(op)
            for c, v in zip(self.calls, row):
                cols[c.output].append(0 if v is None else v)
                if c.output in nulls:
                    nulls[c.output].append(v is None)
        np_cols = {}
        for c in self.calls:
            dt = np.asarray(self.state.accums[c.output][:1]).dtype
            if c.output in self._float_decode:
                dt = np.dtype(self._float_decode[c.output])
            np_cols[c.output] = np.asarray(cols[c.output], dt)
        return StreamChunk.from_numpy(
            np_cols,
            max(2, len(ops)),
            ops=np.asarray(ops, np.int32),
            nulls={k: np.asarray(v, bool) for k, v in nulls.items()},
        )

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        cur = self._current_row()
        if self._last is None:
            self._last = cur
            return [self._row_chunk([(cur, Op.INSERT)])]
        if cur == self._last:
            return []
        out = self._row_chunk(
            [(self._last, Op.UPDATE_DELETE), (cur, Op.UPDATE_INSERT)]
        )
        self._last = cur
        return [out]

    # -- integrity --------------------------------------------------------
    def digest_lanes(self):
        lanes = {"row_count": self.state.row_count}
        for n, a in self.state.accums.items():
            lanes[f"acc_{n}"] = a
        for n, a in self.state.nonnull.items():
            lanes[f"nn_{n}"] = a
        return lanes, None

    def state_digest(self) -> int:
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    # -- checkpoint -------------------------------------------------------
    def checkpoint_delta(self) -> List[StateDelta]:
        if not bool(np.asarray(self.state.sdirty[:1])[0]):
            return []
        lanes = {"row_count": self.state.row_count}
        for n, a in self.state.accums.items():
            lanes[f"acc_{n}"] = a
        for n, a in self.state.nonnull.items():
            lanes[f"nn_{n}"] = a
        pulled = pull_rows(lanes, np.asarray([0]))
        self.state.sdirty = jnp.zeros_like(self.state.sdirty)
        return [
            StateDelta(
                self.table_id,
                {"k0": np.zeros(1, np.int64)},
                pulled,
                np.zeros(1, bool),
                ("k0",),
            )
        ]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        state = agg_ops.create_state(2, self.calls, self._dtypes)
        self._last = None
        if key_cols and len(key_cols["k0"]):

            def put(dst, src):
                return dst.at[0].set(
                    jnp.asarray(np.asarray(src)[0]).astype(dst.dtype)
                )

            state.row_count = put(state.row_count, value_cols["row_count"])
            for n in state.accums:
                state.accums[n] = put(state.accums[n], value_cols[f"acc_{n}"])
            for n in state.nonnull:
                state.nonnull[n] = put(state.nonnull[n], value_cols[f"nn_{n}"])
            self.state = state
            # downstream (the restored MV) already holds the last
            # emitted row = the restored aggregate values
            self._last = self._current_row()
        else:
            self.state = state
