"""Project executor — computed columns.

Reference: src/stream/src/executor/project.rs (non-strict expression
evaluation over whole chunks). Output columns replace the chunk's
column set; ops/visibility pass through untouched.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List

import jax

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.expr import Expr
from risingwave_tpu.expr.expr import StaticTree


@partial(jax.jit, static_argnames=("outputs",))
def _project_step(
    chunk: StreamChunk, outputs: "StaticTree"
) -> StreamChunk:
    # outputs ride as a STRUCTURALLY-keyed static: bare Expr tuples
    # collide in the jit cache (Expr.__eq__ builds a truthy BinOp)
    cols, nulls = {}, {}
    for name, expr in outputs.value:
        v, n = expr.eval(chunk)
        cols[name] = v
        if n is not None:
            nulls[name] = n
    return StreamChunk(cols, chunk.valid, nulls, chunk.ops)


class ProjectExecutor(Executor):
    """``outputs`` maps output column name -> expression."""

    def __init__(self, outputs: Dict[str, Expr]):
        self.outputs = tuple(outputs.items())
        self._souts = StaticTree(self.outputs)

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        return [_project_step(chunk, self._souts)]

    def lint_info(self):
        from risingwave_tpu.expr.expr import Cast, Col, collect_columns

        requires = set()
        emits, renames = {}, {}
        for name, e in self.outputs:
            requires |= collect_columns(e)
            renames[name] = e.name if isinstance(e, Col) else None
            emits[name] = e.dtype if isinstance(e, Cast) else None
        return {
            "requires": tuple(sorted(requires)),
            "emits": emits,
            "renames": renames,
        }

    def pure_step(self):
        # the fused-chain contract (runtime/fused_step + epoch_batch):
        # a module-level partial with hashable bound args, so the projection
        # traces into the fused per-barrier program and compiles once
        # per plan shape, not once per executor instance
        return partial(_project_step, outputs=self._souts)
