"""Materialize executor — applies the change stream to a queryable MV.

Reference: src/stream/src/executor/mview/materialize.rs:44 — applies
chunks to the MV StateTable with pk-conflict handling (:192-230).

v0 TPU design note: the MV snapshot is a host-side dict (pk tuple ->
row tuple) updated from the compacted delta chunks that stateful
operators emit at barriers. Downstream batch reads / tests query it via
``snapshot()``. The storage-backed version (device-staged columnar MV +
Hummock-lite persistence) replaces the dict when state/ lands; the
executor API stays the same.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.types import Op


class MaterializeExecutor(Executor):
    def __init__(self, pk: Sequence[str], columns: Sequence[str]):
        self.pk = tuple(pk)
        self.columns = tuple(columns)
        self.rows: Dict[Tuple, Tuple] = {}

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        data = chunk.to_numpy(with_ops=True)
        ops = data["__op__"]
        n = len(ops)
        if n == 0:
            return [chunk]
        pk_cols = [data[k] for k in self.pk]
        # NULL pk components must stay distinct from real zeros: fold the
        # null lane into the key tuple as None (SQL: NULL group keys form
        # their own group; reference pk serde writes a null tag first,
        # row_serde_util.rs)
        pk_nulls = [data.get(k + "__null") for k in self.pk]
        val_cols = [data[c] for c in self.columns]
        null_lanes = {
            c: data[c + "__null"] for c in self.columns if c + "__null" in data
        }
        for i in range(n):
            key = tuple(
                None if nl is not None and nl[i] else c[i]
                for c, nl in zip(pk_cols, pk_nulls)
            )
            if ops[i] in (Op.DELETE, Op.UPDATE_DELETE):
                # pk-conflict handling "overwrite": tolerate deleting a
                # missing row (reference ConflictBehavior::Overwrite)
                self.rows.pop(key, None)
            else:
                row = tuple(
                    None if null_lanes.get(c) is not None and null_lanes[c][i] else v[i]
                    for c, v in zip(self.columns, val_cols)
                )
                self.rows[key] = row
        return [chunk]

    def snapshot(self) -> Dict[Tuple, Tuple]:
        return dict(self.rows)

    def to_numpy(self) -> Dict[str, np.ndarray]:
        """Snapshot as column arrays (pk cols + value cols)."""
        keys = list(self.rows)
        out = {}
        for j, name in enumerate(self.pk):
            out[name] = np.array([k[j] for k in keys])
        for j, name in enumerate(self.columns):
            out[name] = np.array([self.rows[k][j] for k in keys])
        return out
