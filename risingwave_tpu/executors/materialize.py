"""Materialize executor — applies the change stream to a queryable MV.

Reference: src/stream/src/executor/mview/materialize.rs:44 — applies
chunks to the MV StateTable with pk-conflict handling (:192-230).

Two host backends behind one API (the reference's row map is native
Rust; ours is native C++ where the layout allows):
- NATIVE (risingwave_tpu/native.py): all pk/value columns are
  NULL-free integers -> a C++ unordered_map applies each delta batch
  at ~ns/row, and checkpoint staging is pure numpy net-effect over the
  buffered batches (no per-row Python at all). Integer lanes widen to
  int64 in the map (dictionary codes included), which is lossless.
- PYTHON dict fallback: any other layout (floats, NULLs) — identical
  semantics, interpreter speed.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.storage.state_table import Checkpointable, StateDelta
from risingwave_tpu.types import Op


def _last_per_key(keys: np.ndarray) -> np.ndarray:
    """Indices of the LAST occurrence of each distinct key row (stable
    sort on key columns, keep run ends)."""
    if keys.shape[1] == 0:
        # pk = (): a single-row table; the last op wins outright
        return np.asarray([len(keys) - 1]) if len(keys) else np.zeros(0, np.int64)
    order = np.lexsort(
        tuple(keys[:, j] for j in reversed(range(keys.shape[1])))
    )
    ks = keys[order]
    is_last = np.ones(len(order), bool)
    if len(order) > 1:
        same = (ks[1:] == ks[:-1]).all(axis=1)
        is_last[:-1] = ~same
    return order[is_last]


class MaterializeExecutor(Executor, Checkpointable):
    def __init__(
        self,
        pk: Sequence[str],
        columns: Sequence[str],
        table_id: str = "mview",
        conflict_resolve: bool = False,
    ):
        self.pk = tuple(pk)
        self.columns = tuple(columns)
        self.rows: Dict[Tuple, Tuple] = {}
        self.table_id = table_id
        # ConflictBehavior::Overwrite with DOWNSTREAM-CORRECT emission
        # (materialize.rs:192-230): an insert on an existing pk emits
        # UpdateDelete(stored) + UpdateInsert(new); a delete emits the
        # STORED row; a delete of an absent pk is dropped. User-pk
        # tables set this so MVs over them see real retractions.
        self.conflict_resolve = bool(conflict_resolve)
        self._changed: set = set()  # python path: pks since checkpoint
        self._dtypes: Dict[str, np.dtype] = {}
        self._native = None  # NativeMvMap once eligible
        self._backend: Optional[str] = None
        self._pending: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        # set by StreamingRuntime.register when a checkpoint store will
        # drain _pending every checkpoint barrier
        self.checkpoint_enabled = False

    def lint_info(self):
        return {
            "requires": tuple(self.columns),
            "state_pk": tuple(
                c for c in self.pk if c != "_row_id"
            ),  # _row_id is generated upstream by RowIdGen
            "table_ids": (self.table_id,),
        }

    def state_nbytes(self) -> int:
        """Memory-ledger contract: a host-map MV holds NO device
        bytes — only the host row store (estimated at 8B per pk/value
        cell so the ledger can still rank it)."""
        width = len(self.pk) + len(self.columns)
        n = len(self._native) if self._native is not None else len(self.rows)
        return int(n) * width * 8

    def trace_contract(self):
        return {
            "kind": "host",
            "trace_step": None,
            "state": None,
            "donate": False,
            "emission": "passthrough",
            "host_reason": "host-map materializer: python dict row "
            "store pulls every chunk to host (device-resident MVs use "
            "DeviceMaterializeExecutor)",
        }

    # -- backend selection ----------------------------------------------
    _force_python = False  # subclasses needing row hooks pin the dict

    def _pick_backend(self, chunk: StreamChunk, data) -> None:
        if self._force_python or self.conflict_resolve:
            # conflict resolution reads stored rows per key — the
            # python dict is the value store
            self._backend = "python"
            return
        names = self.pk + self.columns
        eligible = all(
            np.issubdtype(data[name].dtype, np.integer)
            and name not in chunk.nulls
            for name in names
        )
        if eligible:
            try:
                from risingwave_tpu.native import NativeMvMap

                self._native = NativeMvMap(len(self.pk), len(self.columns))
                self._backend = "native"
                return
            except (RuntimeError, OSError):
                pass
        self._backend = "python"

    # -- data ------------------------------------------------------------
    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        data = chunk.to_numpy(with_ops=True)
        ops = data["__op__"]
        n = len(ops)
        if n == 0:
            return [chunk]
        for name in self.pk + self.columns:
            if name not in self._dtypes:
                self._dtypes[name] = data[name].dtype
        if self._backend is None:
            self._pick_backend(chunk, data)
        if self._backend == "native" and any(
            nm in chunk.nulls for nm in self.pk + self.columns
        ):
            # the int matrix cannot represent NULL cells (a later
            # UPDATE ... SET c = NULL on an all-int table): migrate to
            # the python dict, folding un-drained pending deltas into
            # the changed-key set so checkpointing stays exact
            self._demote_to_python()
        is_del = (ops == Op.DELETE) | (ops == Op.UPDATE_DELETE)
        if self._backend == "native":
            keys = (
                np.stack([data[nm] for nm in self.pk], axis=1).astype(np.int64)
                if self.pk
                else np.zeros((n, 0), np.int64)
            )
            vals = (
                np.stack([data[nm] for nm in self.columns], axis=1).astype(
                    np.int64
                )
                if self.columns
                else np.zeros((n, 0), np.int64)
            )
            self._native.apply(keys, vals, is_del)
            self._pending.append((keys, vals, is_del.astype(np.uint8)))
            return [chunk]
        if self.conflict_resolve:
            return self._apply_resolve(data, ops, n)
        self._apply_python(data, ops, is_del, n)
        return [chunk]

    def _demote_to_python(self) -> None:
        keys, vals = self._native.dump()
        self.rows = {
            tuple(k): tuple(v) for k, v in zip(keys.tolist(), vals.tolist())
        }
        for pk_arr, _, _ in self._pending:
            for kt in map(tuple, pk_arr.tolist()):
                self._changed.add(kt)
        self._pending = []
        self._native = None
        self._backend = "python"

    def _apply_resolve(self, data, ops, n) -> List[StreamChunk]:
        """Row-ordered conflict resolution against the stored map; the
        returned chunk is what downstream operators must see to stay
        consistent with this table (retractions included)."""
        names = self.pk + self.columns
        cols_l = self._null_folded(data, names)
        out_rows: List[Tuple[int, Tuple, Tuple]] = []
        for i in range(n):
            k = tuple(cols_l[nm][i] for nm in self.pk)
            self._changed.add(k)
            if ops[i] in (Op.INSERT, Op.UPDATE_INSERT):
                v = tuple(cols_l[nm][i] for nm in self.columns)
                old = self.rows.get(k)
                if old is not None:
                    out_rows.append((int(Op.UPDATE_DELETE), k, old))
                    out_rows.append((int(Op.UPDATE_INSERT), k, v))
                else:
                    op = (
                        int(Op.UPDATE_INSERT)
                        if ops[i] == Op.UPDATE_INSERT
                        else int(Op.INSERT)
                    )
                    out_rows.append((op, k, v))
                self.rows[k] = v
            else:
                old = self.rows.pop(k, None)
                if old is None:
                    continue  # delete of an absent pk: dropped
                op = (
                    int(Op.UPDATE_DELETE)
                    if ops[i] == Op.UPDATE_DELETE
                    else int(Op.DELETE)
                )
                out_rows.append((op, k, old))
        if not out_rows:
            return []
        m = len(out_rows)
        cap = max(2, 1 << (m - 1).bit_length())
        cols: Dict[str, np.ndarray] = {}
        nulls: Dict[str, np.ndarray] = {}
        for j, nm in enumerate(names):
            pk_n = len(self.pk)
            vals = [
                (r[1][j] if j < pk_n else r[2][j - pk_n]) for r in out_rows
            ]
            mask = np.asarray([v is None for v in vals], bool)
            dt = self._dtypes.get(nm, np.dtype(np.int64))
            cols[nm] = np.asarray(
                [0 if v is None else v for v in vals], dt
            )
            if mask.any():
                nulls[nm] = mask
        out_ops = np.asarray([r[0] for r in out_rows], np.int32)
        return [
            StreamChunk.from_numpy(
                cols, cap, ops=out_ops, nulls=nulls or None
            )
        ]

    @staticmethod
    def _null_folded(data, names):
        """{name: python list with __null-masked cells folded to None}
        — the one place the NULL-lane representation is interpreted."""
        out = {}
        for name in names:
            col = data[name].tolist()
            nl = data.get(name + "__null")
            if nl is not None:
                col = [None if isnull else v for v, isnull in zip(col, nl)]
            out[name] = col
        return out

    def _apply_python(self, data, ops, is_del, n):
        # NULL pk components fold into the key tuple as None (SQL NULL
        # group keys are distinct; reference pk serde writes a null tag
        # first, row_serde_util.rs). "Last op per pk wins" replaces the
        # per-row loop.
        def tuples(names):
            if not names:
                return [()] * n
            folded = self._null_folded(data, names)
            return list(zip(*(folded[name] for name in names)))

        keys = tuples(self.pk)
        vals = tuples(self.columns)
        self._changed.update(keys)
        last = {k: i for i, k in enumerate(keys)}
        if is_del.any():
            rows = self.rows
            keys_u = list(last.keys())
            idx = np.fromiter(last.values(), dtype=np.int64, count=len(last))
            dmask = is_del[idx]
            for j in np.flatnonzero(dmask):
                rows.pop(keys_u[j], None)  # ConflictBehavior::Overwrite
            rows.update(
                (keys_u[j], vals[idx[j]]) for j in np.flatnonzero(~dmask)
            )
        else:
            self.rows.update((k, vals[i]) for k, i in last.items())

    # -- reads ------------------------------------------------------------
    def snapshot(self) -> Dict[Tuple, Tuple]:
        if self._backend == "native":
            keys, vals = self._native.dump()
            return {
                tuple(k): tuple(v)
                for k, v in zip(keys.tolist(), vals.tolist())
            }
        return dict(self.rows)

    def to_numpy(self) -> Dict[str, np.ndarray]:
        """Snapshot as column arrays (pk cols + value cols)."""
        if self._backend == "native":
            keys, vals = self._native.dump()
            out = {}
            for j, name in enumerate(self.pk):
                out[name] = keys[:, j]
            for j, name in enumerate(self.columns):
                out[name] = vals[:, j]
            return out
        keys = list(self.rows)
        out = {}
        for j, name in enumerate(self.pk):
            out[name] = np.array([k[j] for k in keys])
        for j, name in enumerate(self.columns):
            out[name] = np.array([self.rows[k][j] for k in keys])
        return out

    # -- barrier ---------------------------------------------------------
    def on_barrier(self, barrier) -> List[StreamChunk]:
        """Compact the native path's pending delta buffer to its net
        effect per pk (last op wins). Keeps memory bounded by distinct
        keys touched since the last checkpoint instead of total stream
        length — pipelines driven without a CheckpointManager (bench,
        store=None runtimes) never drain _pending otherwise (ADVICE r2
        medium). Runtime-managed executors skip this: checkpoint
        staging drains _pending with the same net-effect pass, so
        compacting here would sort the same rows twice per barrier."""
        if not self.checkpoint_enabled and len(self._pending) > 1:
            self._pending = [self._net_pending()]
        return []

    def _net_pending(self):
        """Fold _pending batches into one (keys, vals, dels) net batch."""
        keys = np.concatenate([k for k, _, _ in self._pending])
        vals = np.concatenate([v for _, v, _ in self._pending])
        dels = np.concatenate([d for _, _, d in self._pending])
        sel = _last_per_key(keys)
        return keys[sel], vals[sel], dels[sel]

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self):
        """Persist rows whose pk changed since the last checkpoint
        (reference: the MV's own StateTable commit). Native path: pure
        numpy net-effect over the buffered delta batches — last
        occurrence per pk wins; its is_del becomes the tombstone."""
        if self._backend == "native":
            return self._native_delta()
        return self._python_delta()

    def _native_delta(self):
        if not self._pending:
            return []
        keys = np.concatenate([k for k, _, _ in self._pending])
        vals = np.concatenate([v for _, v, _ in self._pending])
        dels = np.concatenate([d for _, _, d in self._pending])
        self._pending = []
        if len(keys) == 0:
            return []
        sel = _last_per_key(keys)
        key_cols = {
            f"k{j}": keys[sel, j].astype(self._dtypes[self.pk[j]])
            for j in range(len(self.pk))
        }
        value_cols = {
            f"v{j}": vals[sel, j].astype(self._dtypes[self.columns[j]])
            for j in range(len(self.columns))
        }
        return [
            StateDelta(
                self.table_id,
                key_cols,
                value_cols,
                dels[sel].astype(bool),
                tuple(f"k{j}" for j in range(len(self.pk))),
            )
        ]

    def _python_delta(self):
        if not self._changed:
            return []
        ups, tombs = [], []
        for k in self._changed:
            if any(v is None for v in k):
                raise ValueError("NULL pk persistence not supported yet")
            row = self.rows.get(k)
            if row is None:
                tombs.append(k)
            else:
                ups.append((k, row))
        n = len(ups) + len(tombs)
        key_cols = {}
        for j, name in enumerate(self.pk):
            key_cols[f"k{j}"] = np.array(
                [k[j] for k, _ in ups] + [k[j] for k in tombs],
                dtype=self._dtypes[name],
            )
        value_cols = {}
        for j, name in enumerate(self.columns):
            pad = np.zeros(len(tombs), dtype=self._dtypes[name])
            vals = [r[j] for _, r in ups]
            value_cols[f"v{j}"] = np.concatenate(
                [
                    np.array(
                        [0 if v is None else v for v in vals],
                        dtype=self._dtypes[name],
                    ),
                    pad,
                ]
            ) if ups else pad
            # NULL cells persist as a bool companion lane (restore
            # reads it back). Emitted UNCONDITIONALLY: SST merges for
            # one table_id need every delta to carry the same lane set
            value_cols[f"vn{j}"] = np.array(
                [v is None for v in vals] + [False] * len(tombs), bool
            )
        tombstone = np.zeros(n, bool)
        tombstone[len(ups):] = True
        self._changed.clear()
        return [
            StateDelta(
                self.table_id,
                key_cols,
                value_cols,
                tombstone,
                tuple(f"k{j}" for j in range(len(self.pk))),
            )
        ]

    def state_digest(self) -> int:
        """Durable logical state = the row map (backend-independent:
        native and python snapshots digest identically)."""
        from risingwave_tpu.integrity import host_obj_digest

        return host_obj_digest(
            sorted(self.snapshot().items(), key=repr)
        )

    def restore_state(self, table_id, key_cols, value_cols):
        self.rows = {}
        self._changed = set()
        self._pending = []
        self._native = None
        self._backend = None
        if not key_cols:
            return
        n = len(next(iter(key_cols.values())))
        ints = (
            not self._force_python
            and not self.conflict_resolve  # resolve reads the dict
            and all(
                np.issubdtype(np.asarray(a).dtype, np.integer)
                for a in list(key_cols.values()) + list(value_cols.values())
            )  # vn{j} NULL companions are bool -> python path
        )
        if ints:
            try:
                from risingwave_tpu.native import NativeMvMap

                self._native = NativeMvMap(len(self.pk), len(self.columns))
                self._backend = "native"
                keys = (
                    np.stack(
                        [key_cols[f"k{j}"] for j in range(len(self.pk))], axis=1
                    ).astype(np.int64)
                    if self.pk
                    else np.zeros((n, 0), np.int64)
                )
                vals = (
                    np.stack(
                        [value_cols[f"v{j}"] for j in range(len(self.columns))],
                        axis=1,
                    ).astype(np.int64)
                    if self.columns
                    else np.zeros((n, 0), np.int64)
                )
                for j in range(len(self.pk)):
                    self._dtypes.setdefault(
                        self.pk[j], np.asarray(key_cols[f"k{j}"]).dtype
                    )
                for j in range(len(self.columns)):
                    self._dtypes.setdefault(
                        self.columns[j], np.asarray(value_cols[f"v{j}"]).dtype
                    )
                self._native.apply(keys, vals, np.zeros(n, np.uint8))
                return
            except (RuntimeError, OSError):
                self._backend = None
        self._backend = "python"
        nls = [
            value_cols.get(f"vn{j}") for j in range(len(self.columns))
        ]
        for i in range(n):
            k = tuple(
                key_cols[f"k{j}"][i].item() for j in range(len(self.pk))
            )
            v = tuple(
                None
                if nls[j] is not None and bool(nls[j][i])
                else value_cols[f"v{j}"][i].item()
                for j in range(len(self.columns))
            )
            self.rows[k] = v


# ---------------------------------------------------------------------------
# Device-resident MV (the TPU-first materialize)
# ---------------------------------------------------------------------------

import jax
import jax.numpy as jnp
from dataclasses import dataclass
from functools import partial

from risingwave_tpu.ops.hash_table import HashTable, last_occurrence_mask, lookup_or_insert, stage_scalars
from risingwave_tpu.runtime.bucketing import BucketAllocator, BucketPolicy
from risingwave_tpu.storage.state_table import (
    grow_pow2,
    pull_rows,
    stage_marks,
)

GROW_AT = 0.5
# mid-epoch rebuild only when the HOST insert bound nears the table
# itself (MAX_PROBE overflow risk); ordinary growth resolves at the
# barrier from the true occupancy note (see HashAgg's twin constant)
HARD_GROW_AT = 0.75


@jax.tree_util.register_pytree_node_class
@dataclass
class MvDeviceState:
    """Value lanes + checkpoint marks, slot-indexed next to the pk table."""

    values: dict  # name -> (capacity,) lane
    vnulls: dict  # name -> (capacity,) bool lane (SQL NULL)
    sdirty: jnp.ndarray  # touched since last checkpoint stage
    stored: jnp.ndarray  # durable in the state store
    dropped: jnp.ndarray  # bool scalar: overflow latch

    def tree_flatten(self):
        vn = tuple(sorted(self.values))
        nn = tuple(sorted(self.vnulls))
        children = (
            tuple(self.values[k] for k in vn)
            + tuple(self.vnulls[k] for k in nn)
            + (self.sdirty, self.stored, self.dropped)
        )
        return children, (vn, nn)

    @classmethod
    def tree_unflatten(cls, aux, children):
        vn, nn = aux
        values = dict(zip(vn, children[: len(vn)]))
        vnulls = dict(zip(nn, children[len(vn) : len(vn) + len(nn)]))
        sdirty, stored, dropped = children[-3:]
        return cls(values, vnulls, sdirty, stored, dropped)


def mv_step_fn(table, state, chunk, pk, cols):
    """One chunk applied to the device MV: find-or-insert pk, last row
    per pk wins (Overwrite conflict behavior), deletes flip live off.
    Entirely on device — zero host syncs (the tunneled-TPU contract).
    Un-jitted so sharded wrappers can call it inside shard_map
    (parallel/sharded_mv.py); the single-chip executor uses the jitted
    ``_mv_step`` below."""
    keys = tuple(chunk.col(k) for k in pk)
    table, slots, found, inserted = lookup_or_insert(table, keys, chunk.valid)
    dropped = state.dropped | jnp.any(chunk.valid & (slots < 0))
    last = last_occurrence_mask(slots, chunk.valid)
    is_del = (chunk.ops == 1) | (chunk.ops == 2)  # DELETE | UPDATE_DELETE
    cap = table.capacity
    lidx = jnp.where(last, slots, cap)
    live = table.live.at[lidx].set(~is_del, mode="drop")
    table = HashTable(table.fp1, table.fp2, table.keys, live)
    uidx = jnp.where(last & ~is_del, slots, cap)
    values = {
        c: state.values[c].at[uidx].set(
            chunk.col(c).astype(state.values[c].dtype), mode="drop"
        )
        for c in cols
    }
    vnulls = {
        c: state.vnulls[c].at[uidx].set(chunk.null_of(c), mode="drop")
        for c in state.vnulls
    }
    sdirty = state.sdirty.at[lidx].set(True, mode="drop")
    return table, MvDeviceState(values, vnulls, sdirty, state.stored, dropped)


_mv_step = partial(jax.jit, static_argnames=("pk", "cols"), donate_argnums=(0, 1))(
    mv_step_fn
)


@partial(jax.jit, static_argnames=("new_cap",), donate_argnums=())
def _mv_rebuild(table, state, new_cap):
    """Re-insert surviving slots into a fresh table (host-decided
    capacity; the TPU analogue of growing the MV cache)."""
    keep = table.live | state.sdirty | state.stored
    new_table = HashTable.create(new_cap, tuple(k.dtype for k in table.keys))
    new_table, slots, _, _ = lookup_or_insert(new_table, table.keys, keep)
    idx = jnp.where(keep, slots, new_cap)
    live = new_table.live.at[idx].set(table.live, mode="drop")
    new_table = HashTable(new_table.fp1, new_table.fp2, new_table.keys, live)
    put = lambda a: jnp.zeros(new_cap, a.dtype).at[idx].set(a, mode="drop")
    values = {c: put(state.values[c]) for c in state.values}
    vnulls = {c: put(state.vnulls[c]) for c in state.vnulls}
    sdirty = jnp.zeros(new_cap, jnp.bool_).at[idx].set(state.sdirty, mode="drop")
    stored = jnp.zeros(new_cap, jnp.bool_).at[idx].set(state.stored, mode="drop")
    return new_table, MvDeviceState(
        values, vnulls, sdirty, stored, jnp.zeros((), jnp.bool_)
    )


class MvDeviceReadMixin:
    """Read surface over a ``_host_rows()`` provider — shared by the
    single-chip device MV and the mesh-sharded one
    (parallel/sharded_mv.py) so the k{j}/v{j}/n_{c} lane naming and
    NULL decoding live in exactly one place."""

    def snapshot(self):
        """pk tuple -> value tuple (NULL -> None), matching the host-map
        executors' interface. One bulk transfer, on demand."""
        _, rows = self._host_rows()
        n = len(rows["k0"]) if self.pk else 0
        out = {}
        for i in range(n):
            k = tuple(rows[f"k{j}"][i].item() for j in range(len(self.pk)))
            v = tuple(
                None
                if (f"n_{c}" in rows and rows[f"n_{c}"][i])
                else rows[f"v{j}"][i].item()
                for j, c in enumerate(self.columns)
            )
            out[k] = v
        return out

    def to_numpy(self):
        _, rows = self._host_rows()
        out = {}
        for j, name in enumerate(self.pk):
            out[name] = rows[f"k{j}"]
        for j, name in enumerate(self.columns):
            out[name] = rows[f"v{j}"]
            if f"n_{name}" in rows:
                out[name + "__null"] = rows[f"n_{name}"]
        return out


class DeviceMaterializeExecutor(MvDeviceReadMixin, Executor, Checkpointable):
    """Device-resident MV: pk-keyed hash table + value lanes in HBM.

    Reference: src/stream/src/executor/mview/materialize.rs:44 with
    ConflictBehavior::Overwrite (:192-230). The host-map backends above
    pull every chunk to the host — on a tunneled TPU that is ~100ms per
    chunk; this executor applies deltas entirely on device and reaches
    the host only at snapshot/checkpoint time (the "columnar MV staged
    in HBM" north star, BASELINE.md).

    Schema constraint: pk and value lanes must be fixed-width device
    dtypes (ints/floats/bool — varchar/jsonb ride their dictionary
    codes). NULLs in VALUE columns ride per-column null lanes; NULL pk
    components are not supported (the reference serializes a null tag;
    here use the host-map executor for nullable pks).
    """

    def __init__(
        self,
        pk,
        columns,
        schema_dtypes,
        table_id: str = "mview",
        capacity: int = 1 << 16,
        nullable=(),
    ):
        self.pk = tuple(pk)
        self.columns = tuple(columns)
        self.table_id = table_id
        self.dtypes = {n: jnp.dtype(schema_dtypes[n]) for n in pk + tuple(columns)}
        self.table = HashTable.create(
            capacity, tuple(self.dtypes[k] for k in self.pk)
        )
        self.state = MvDeviceState(
            values={
                c: jnp.zeros(capacity, self.dtypes[c]) for c in self.columns
            },
            vnulls={
                c: jnp.zeros(capacity, jnp.bool_)
                for c in nullable
                if c in self.columns
            },
            sdirty=jnp.zeros(capacity, jnp.bool_),
            stored=jnp.zeros(capacity, jnp.bool_),
            dropped=jnp.zeros((), jnp.bool_),
        )
        self._bound = 0
        self._occ_note = 0  # true claimed at the last barrier (staged read)
        # shape-stability: capacity walks the allocator's pow2 lattice;
        # growth decisions consume the occupancy note staged at the
        # previous barrier instead of a synchronous device read
        self._buckets = BucketAllocator(
            BucketPolicy.from_capacity(capacity, grow_at=GROW_AT)
        )
        self.checkpoint_enabled = False

    def lint_info(self):
        return {
            "expects": dict(self.dtypes),
            "state_pk": tuple(self.pk),
            "table_ids": (self.table_id,),
        }

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: _mv_step(
                self.table, self.state, c, self.pk, self.columns
            ),
            "state": (self.table, self.state),
            "donate": True,
            "emission": "passthrough",
        }

    def padding_stats(self):
        """Wasted-lane accounting (runtime/bucketing.padding_stats —
        bench/PROFILE surface; reads device occupancy)."""
        return {
            "capacity": self.table.capacity,
            "live": int(self.table.num_live()),
        }

    # -- data -------------------------------------------------------------
    def apply(self, chunk: StreamChunk):
        self._maybe_grow(chunk.capacity)  # also advances the insert bound
        self.table, self.state = _mv_step(
            self.table, self.state, chunk, self.pk, self.columns
        )
        return [chunk]

    def _maybe_grow(self, incoming: int) -> None:
        """Capacity planning with ZERO device reads on the hot path.

        Agg/join flush chunks arrive padded (few live rows at a large
        capacity), so the host bound wildly overstates inserts
        mid-epoch. The old code paid a blocking ``read_scalars``
        round-trip to learn the truth (RW-E801 ×3 on the fusion
        worklist); now ordinary growth resolves AT THE BARRIER from
        the staged occupancy note (``_on_barrier_scalars`` plans with
        true claimed), and the only mid-epoch rebuild is the overflow
        guard: a bound nearing the table itself rebuilds
        pessimistically BEFORE the MAX_PROBE latch can trip."""
        cap = self.table.capacity
        # occupancy can never exceed the table: clamping the carried
        # bound at the capacity stops padded flush chunks (whose
        # capacities wildly overstate live rows) from accreting an
        # unbounded bound across chunks and ratcheting growth step
        # after step (code-review finding)
        claimed = min(self._bound, cap)
        self._bound = claimed + incoming
        if self._bound <= cap * HARD_GROW_AT:
            return
        # no extra margin: the 0.75 guard vs 0.5 sizing gap IS the
        # hysteresis, so the guard cannot re-trip right after a rebuild
        new_cap = self._buckets.plan(cap, incoming, claimed, claimed)
        if new_cap is not None and new_cap != cap:
            self.table, self.state = _mv_rebuild(
                self.table, self.state, new_cap
            )

    def pin_max_bucket(self):
        """ShapeGovernor hook: freeze the MV table at its high-water
        bucket (shrink disabled; regrow applied by the next apply)."""
        return {
            "table_id": self.table_id,
            "pinned_cap": self._buckets.pin(),
        }

    # -- control ----------------------------------------------------------
    def on_barrier(self, barrier) -> list:
        self._staged_scalars = stage_scalars(
            self.state.dropped, self.table.occupancy()
        )
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return []

    def _on_barrier_scalars(self, vals) -> None:
        dropped, claimed = vals
        # occupancy refreshes the growth bound so steady state has no
        # mid-epoch refresh syncs; barrier-boundary planning from the
        # TRUE note: grow past the load factor, apply pending lazy
        # shrink, honor a governor pin — all between epochs
        epoch_inc = max(self._bound - self._occ_note, 0)
        self._occ_note = int(claimed)
        self._bound = int(claimed)
        cap = self.table.capacity
        self._buckets.note_barrier(cap, int(claimed))
        # margin: the larger of true occupancy and last epoch's insert
        # bound — a shrink can never land below what the mid-epoch
        # overflow guard would immediately regrow
        new_cap = self._buckets.plan(
            cap, 0, int(claimed), int(claimed),
            margin=max(int(claimed), epoch_inc),
        )
        if new_cap is not None and new_cap != cap:
            self.table, self.state = _mv_rebuild(
                self.table, self.state, new_cap
            )
        if dropped:
            raise RuntimeError(
                "device MV hash table overflowed MAX_PROBE; grow capacity"
            )

    def state_nbytes(self) -> int:
        return sum(
            leaf.nbytes
            for leaf in jax.tree.leaves((self.table, self.state))
        )

    # -- reads ------------------------------------------------------------
    def _host_rows(self):
        live = np.asarray(self.table.live)
        sel = np.flatnonzero(live)
        lanes = {f"k{j}": k for j, k in enumerate(self.table.keys)}
        lanes.update(
            {f"v{j}": self.state.values[c] for j, c in enumerate(self.columns)}
        )
        lanes.update(
            {f"n_{c}": lane for c, lane in self.state.vnulls.items()}
        )
        return sel, pull_rows(lanes, sel)

    # snapshot()/to_numpy() come from MvDeviceReadMixin

    # -- integrity --------------------------------------------------------
    def digest_lanes(self):
        from risingwave_tpu.integrity import mv_lanes

        return mv_lanes(self.table, self.state)

    def state_digest(self) -> int:
        """Host twin of the fused digest lane (integrity.mv_lanes)."""
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    # -- checkpoint/restore -----------------------------------------------
    def checkpoint_delta(self):
        sdirty = np.asarray(self.state.sdirty)
        if not sdirty.any():
            return []
        alive = np.asarray(self.table.live)
        stored = np.asarray(self.state.stored)
        upsert, tomb, sel = stage_marks(sdirty, alive, stored)
        if not len(sel):
            self.state.sdirty = jnp.zeros_like(self.state.sdirty)
            return []
        lanes = {f"k{j}": k for j, k in enumerate(self.table.keys)}
        lanes.update(
            {f"v{j}": self.state.values[c] for j, c in enumerate(self.columns)}
        )
        lanes.update(
            {f"n_{c}": lane for c, lane in self.state.vnulls.items()}
        )
        rows = pull_rows(lanes, sel)
        key_cols = {f"k{j}": rows[f"k{j}"] for j in range(len(self.pk))}
        value_cols = {
            f"v{j}": rows[f"v{j}"] for j in range(len(self.columns))
        }
        for c in self.state.vnulls:
            value_cols[f"n_{c}"] = rows[f"n_{c}"].astype(np.uint8)
        tombstone = tomb[sel]
        # eager mark flip (same discipline as the other executors: the
        # runtime stages on the main thread before the async commit)
        dev_sel = jnp.asarray(sel.astype(np.int32))
        self.state.stored = (
            self.state.stored.at[dev_sel].set(jnp.asarray(upsert[sel]))
        )
        self.state.sdirty = jnp.zeros_like(self.state.sdirty)
        return [
            StateDelta(
                self.table_id,
                key_cols,
                value_cols,
                tombstone,
                tuple(f"k{j}" for j in range(len(self.pk))),
            )
        ]

    def restore_state(self, table_id, key_cols, value_cols):
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        cap = grow_pow2(n, 1 << 10, GROW_AT)
        self.table = HashTable.create(
            cap, tuple(self.dtypes[k] for k in self.pk)
        )
        self.state = MvDeviceState(
            values={c: jnp.zeros(cap, self.dtypes[c]) for c in self.columns},
            vnulls={c: jnp.zeros(cap, jnp.bool_) for c in self.state.vnulls},
            sdirty=jnp.zeros(cap, jnp.bool_),
            stored=jnp.zeros(cap, jnp.bool_),
            dropped=jnp.zeros((), jnp.bool_),
        )
        self._bound = 0
        if n == 0:
            return
        cols = {
            name: np.asarray(key_cols[f"k{j}"]).astype(self.dtypes[name])
            for j, name in enumerate(self.pk)
        }
        nulls = {}
        for j, name in enumerate(self.columns):
            cols[name] = np.asarray(value_cols[f"v{j}"]).astype(
                self.dtypes[name]
            )
            if f"n_{name}" in value_cols:
                nulls[name] = np.asarray(value_cols[f"n_{name}"]).astype(bool)
        chunk = StreamChunk.from_numpy(cols, cap, nulls=nulls or None)
        self.table, self.state = _mv_step(
            self.table, self.state, chunk, self.pk, self.columns
        )
        # restored rows are durable, not dirty
        self.state.stored = self.state.sdirty
        self.state.sdirty = jnp.zeros_like(self.state.sdirty)
        self._bound = n
