"""Materialize executor — applies the change stream to a queryable MV.

Reference: src/stream/src/executor/mview/materialize.rs:44 — applies
chunks to the MV StateTable with pk-conflict handling (:192-230).

Two host backends behind one API (the reference's row map is native
Rust; ours is native C++ where the layout allows):
- NATIVE (risingwave_tpu/native.py): all pk/value columns are
  NULL-free integers -> a C++ unordered_map applies each delta batch
  at ~ns/row, and checkpoint staging is pure numpy net-effect over the
  buffered batches (no per-row Python at all). Integer lanes widen to
  int64 in the map (dictionary codes included), which is lossless.
- PYTHON dict fallback: any other layout (floats, NULLs) — identical
  semantics, interpreter speed.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.storage.state_table import Checkpointable, StateDelta
from risingwave_tpu.types import Op


def _last_per_key(keys: np.ndarray) -> np.ndarray:
    """Indices of the LAST occurrence of each distinct key row (stable
    sort on key columns, keep run ends)."""
    if keys.shape[1] == 0:
        # pk = (): a single-row table; the last op wins outright
        return np.asarray([len(keys) - 1]) if len(keys) else np.zeros(0, np.int64)
    order = np.lexsort(
        tuple(keys[:, j] for j in reversed(range(keys.shape[1])))
    )
    ks = keys[order]
    is_last = np.ones(len(order), bool)
    if len(order) > 1:
        same = (ks[1:] == ks[:-1]).all(axis=1)
        is_last[:-1] = ~same
    return order[is_last]


class MaterializeExecutor(Executor, Checkpointable):
    def __init__(
        self,
        pk: Sequence[str],
        columns: Sequence[str],
        table_id: str = "mview",
    ):
        self.pk = tuple(pk)
        self.columns = tuple(columns)
        self.rows: Dict[Tuple, Tuple] = {}
        self.table_id = table_id
        self._changed: set = set()  # python path: pks since checkpoint
        self._dtypes: Dict[str, np.dtype] = {}
        self._native = None  # NativeMvMap once eligible
        self._backend: Optional[str] = None
        self._pending: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        # set by StreamingRuntime.register when a checkpoint store will
        # drain _pending every checkpoint barrier
        self.checkpoint_enabled = False

    # -- backend selection ----------------------------------------------
    def _pick_backend(self, chunk: StreamChunk, data) -> None:
        names = self.pk + self.columns
        eligible = all(
            np.issubdtype(data[name].dtype, np.integer)
            and name not in chunk.nulls
            for name in names
        )
        if eligible:
            try:
                from risingwave_tpu.native import NativeMvMap

                self._native = NativeMvMap(len(self.pk), len(self.columns))
                self._backend = "native"
                return
            except (RuntimeError, OSError):
                pass
        self._backend = "python"

    # -- data ------------------------------------------------------------
    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        data = chunk.to_numpy(with_ops=True)
        ops = data["__op__"]
        n = len(ops)
        if n == 0:
            return [chunk]
        for name in self.pk + self.columns:
            if name not in self._dtypes:
                self._dtypes[name] = data[name].dtype
        if self._backend is None:
            self._pick_backend(chunk, data)
        is_del = (ops == Op.DELETE) | (ops == Op.UPDATE_DELETE)
        if self._backend == "native":
            keys = (
                np.stack([data[nm] for nm in self.pk], axis=1).astype(np.int64)
                if self.pk
                else np.zeros((n, 0), np.int64)
            )
            vals = (
                np.stack([data[nm] for nm in self.columns], axis=1).astype(
                    np.int64
                )
                if self.columns
                else np.zeros((n, 0), np.int64)
            )
            self._native.apply(keys, vals, is_del)
            self._pending.append((keys, vals, is_del.astype(np.uint8)))
            return [chunk]
        self._apply_python(data, ops, is_del, n)
        return [chunk]

    def _apply_python(self, data, ops, is_del, n):
        # NULL pk components fold into the key tuple as None (SQL NULL
        # group keys are distinct; reference pk serde writes a null tag
        # first, row_serde_util.rs). "Last op per pk wins" replaces the
        # per-row loop.
        def tuples(names):
            if not names:
                return [()] * n
            lanes = []
            for name in names:
                col = data[name].tolist()
                nl = data.get(name + "__null")
                if nl is not None:
                    col = [None if isnull else v for v, isnull in zip(col, nl)]
                lanes.append(col)
            return list(zip(*lanes))

        keys = tuples(self.pk)
        vals = tuples(self.columns)
        self._changed.update(keys)
        last = {k: i for i, k in enumerate(keys)}
        if is_del.any():
            rows = self.rows
            keys_u = list(last.keys())
            idx = np.fromiter(last.values(), dtype=np.int64, count=len(last))
            dmask = is_del[idx]
            for j in np.flatnonzero(dmask):
                rows.pop(keys_u[j], None)  # ConflictBehavior::Overwrite
            rows.update(
                (keys_u[j], vals[idx[j]]) for j in np.flatnonzero(~dmask)
            )
        else:
            self.rows.update((k, vals[i]) for k, i in last.items())

    # -- reads ------------------------------------------------------------
    def snapshot(self) -> Dict[Tuple, Tuple]:
        if self._backend == "native":
            keys, vals = self._native.dump()
            return {
                tuple(k): tuple(v)
                for k, v in zip(keys.tolist(), vals.tolist())
            }
        return dict(self.rows)

    def to_numpy(self) -> Dict[str, np.ndarray]:
        """Snapshot as column arrays (pk cols + value cols)."""
        if self._backend == "native":
            keys, vals = self._native.dump()
            out = {}
            for j, name in enumerate(self.pk):
                out[name] = keys[:, j]
            for j, name in enumerate(self.columns):
                out[name] = vals[:, j]
            return out
        keys = list(self.rows)
        out = {}
        for j, name in enumerate(self.pk):
            out[name] = np.array([k[j] for k in keys])
        for j, name in enumerate(self.columns):
            out[name] = np.array([self.rows[k][j] for k in keys])
        return out

    # -- barrier ---------------------------------------------------------
    def on_barrier(self, barrier) -> List[StreamChunk]:
        """Compact the native path's pending delta buffer to its net
        effect per pk (last op wins). Keeps memory bounded by distinct
        keys touched since the last checkpoint instead of total stream
        length — pipelines driven without a CheckpointManager (bench,
        store=None runtimes) never drain _pending otherwise (ADVICE r2
        medium). Runtime-managed executors skip this: checkpoint
        staging drains _pending with the same net-effect pass, so
        compacting here would sort the same rows twice per barrier."""
        if not self.checkpoint_enabled and len(self._pending) > 1:
            self._pending = [self._net_pending()]
        return []

    def _net_pending(self):
        """Fold _pending batches into one (keys, vals, dels) net batch."""
        keys = np.concatenate([k for k, _, _ in self._pending])
        vals = np.concatenate([v for _, v, _ in self._pending])
        dels = np.concatenate([d for _, _, d in self._pending])
        sel = _last_per_key(keys)
        return keys[sel], vals[sel], dels[sel]

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self):
        """Persist rows whose pk changed since the last checkpoint
        (reference: the MV's own StateTable commit). Native path: pure
        numpy net-effect over the buffered delta batches — last
        occurrence per pk wins; its is_del becomes the tombstone."""
        if self._backend == "native":
            return self._native_delta()
        return self._python_delta()

    def _native_delta(self):
        if not self._pending:
            return []
        keys = np.concatenate([k for k, _, _ in self._pending])
        vals = np.concatenate([v for _, v, _ in self._pending])
        dels = np.concatenate([d for _, _, d in self._pending])
        self._pending = []
        if len(keys) == 0:
            return []
        sel = _last_per_key(keys)
        key_cols = {
            f"k{j}": keys[sel, j].astype(self._dtypes[self.pk[j]])
            for j in range(len(self.pk))
        }
        value_cols = {
            f"v{j}": vals[sel, j].astype(self._dtypes[self.columns[j]])
            for j in range(len(self.columns))
        }
        return [
            StateDelta(
                self.table_id,
                key_cols,
                value_cols,
                dels[sel].astype(bool),
                tuple(f"k{j}" for j in range(len(self.pk))),
            )
        ]

    def _python_delta(self):
        if not self._changed:
            return []
        ups, tombs = [], []
        for k in self._changed:
            if any(v is None for v in k):
                raise ValueError("NULL pk persistence not supported yet")
            row = self.rows.get(k)
            if row is None:
                tombs.append(k)
            elif any(v is None for v in row):
                raise ValueError("NULL value persistence not supported yet")
            else:
                ups.append((k, row))
        n = len(ups) + len(tombs)
        key_cols = {}
        for j, name in enumerate(self.pk):
            key_cols[f"k{j}"] = np.array(
                [k[j] for k, _ in ups] + [k[j] for k in tombs],
                dtype=self._dtypes[name],
            )
        value_cols = {}
        for j, name in enumerate(self.columns):
            pad = np.zeros(len(tombs), dtype=self._dtypes[name])
            value_cols[f"v{j}"] = np.concatenate(
                [
                    np.array([r[j] for _, r in ups], dtype=self._dtypes[name]),
                    pad,
                ]
            ) if ups else pad
        tombstone = np.zeros(n, bool)
        tombstone[len(ups):] = True
        self._changed.clear()
        return [
            StateDelta(
                self.table_id,
                key_cols,
                value_cols,
                tombstone,
                tuple(f"k{j}" for j in range(len(self.pk))),
            )
        ]

    def restore_state(self, table_id, key_cols, value_cols):
        self.rows = {}
        self._changed = set()
        self._pending = []
        self._native = None
        self._backend = None
        if not key_cols:
            return
        n = len(next(iter(key_cols.values())))
        ints = all(
            np.issubdtype(np.asarray(a).dtype, np.integer)
            for a in list(key_cols.values()) + list(value_cols.values())
        )
        if ints:
            try:
                from risingwave_tpu.native import NativeMvMap

                self._native = NativeMvMap(len(self.pk), len(self.columns))
                self._backend = "native"
                keys = (
                    np.stack(
                        [key_cols[f"k{j}"] for j in range(len(self.pk))], axis=1
                    ).astype(np.int64)
                    if self.pk
                    else np.zeros((n, 0), np.int64)
                )
                vals = (
                    np.stack(
                        [value_cols[f"v{j}"] for j in range(len(self.columns))],
                        axis=1,
                    ).astype(np.int64)
                    if self.columns
                    else np.zeros((n, 0), np.int64)
                )
                for j in range(len(self.pk)):
                    self._dtypes.setdefault(
                        self.pk[j], np.asarray(key_cols[f"k{j}"]).dtype
                    )
                for j in range(len(self.columns)):
                    self._dtypes.setdefault(
                        self.columns[j], np.asarray(value_cols[f"v{j}"]).dtype
                    )
                self._native.apply(keys, vals, np.zeros(n, np.uint8))
                return
            except (RuntimeError, OSError):
                self._backend = None
        self._backend = "python"
        for i in range(n):
            k = tuple(
                key_cols[f"k{j}"][i].item() for j in range(len(self.pk))
            )
            v = tuple(
                value_cols[f"v{j}"][i].item()
                for j in range(len(self.columns))
            )
            self.rows[k] = v
