"""Materialize executor — applies the change stream to a queryable MV.

Reference: src/stream/src/executor/mview/materialize.rs:44 — applies
chunks to the MV StateTable with pk-conflict handling (:192-230).

v0 TPU design note: the MV snapshot is a host-side dict (pk tuple ->
row tuple) updated from the compacted delta chunks that stateful
operators emit at barriers. Downstream batch reads / tests query it via
``snapshot()``. The storage-backed version (device-staged columnar MV +
Hummock-lite persistence) replaces the dict when state/ lands; the
executor API stays the same.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.storage.state_table import Checkpointable, StateDelta
from risingwave_tpu.types import Op


class MaterializeExecutor(Executor, Checkpointable):
    def __init__(
        self,
        pk: Sequence[str],
        columns: Sequence[str],
        table_id: str = "mview",
    ):
        self.pk = tuple(pk)
        self.columns = tuple(columns)
        self.rows: Dict[Tuple, Tuple] = {}
        self.table_id = table_id
        self._changed: set = set()  # pks touched since last checkpoint
        self._dtypes: Dict[str, np.dtype] = {}

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        data = chunk.to_numpy(with_ops=True)
        ops = data["__op__"]
        n = len(ops)
        if n == 0:
            return [chunk]
        for name in self.pk + self.columns:
            if name not in self._dtypes:
                self._dtypes[name] = data[name].dtype
        # NULL pk components must stay distinct from real zeros: fold the
        # null lane into the key tuple as None (SQL: NULL group keys form
        # their own group; reference pk serde writes a null tag first,
        # row_serde_util.rs). Same for NULL values. Built column-wise so
        # the per-barrier delta apply is C-speed zip/dict ops, not a
        # per-row Python loop.
        def tuples(names):
            if not names:  # value-less MV (pk covers every column)
                return [()] * n
            lanes = []
            for name in names:
                col = data[name].tolist()
                nl = data.get(name + "__null")
                if nl is not None:
                    col = [None if isnull else v for v, isnull in zip(col, nl)]
                lanes.append(col)
            return list(zip(*lanes))

        keys = tuples(self.pk)
        vals = tuples(self.columns)
        self._changed.update(keys)
        is_del = (ops == Op.DELETE) | (ops == Op.UPDATE_DELETE)
        # Sequentially applying a chunk's ops leaves each pk in the state
        # of its LAST op (delete -> absent, insert/update -> that row), so
        # "last op per pk wins" replaces the per-row loop: the dict
        # comprehension keeps the last index per key at C speed.
        last = {k: i for i, k in enumerate(keys)}
        if is_del.any():
            rows = self.rows
            keys_u = list(last.keys())
            idx = np.fromiter(last.values(), dtype=np.int64, count=len(last))
            dmask = is_del[idx]
            for j in np.flatnonzero(dmask):
                # "overwrite" conflict behavior: tolerate missing rows
                # (reference ConflictBehavior::Overwrite)
                rows.pop(keys_u[j], None)
            rows.update((keys_u[j], vals[idx[j]]) for j in np.flatnonzero(~dmask))
        else:
            self.rows.update((k, vals[i]) for k, i in last.items())
        return [chunk]

    def snapshot(self) -> Dict[Tuple, Tuple]:
        return dict(self.rows)

    def to_numpy(self) -> Dict[str, np.ndarray]:
        """Snapshot as column arrays (pk cols + value cols)."""
        keys = list(self.rows)
        out = {}
        for j, name in enumerate(self.pk):
            out[name] = np.array([k[j] for k in keys])
        for j, name in enumerate(self.columns):
            out[name] = np.array([self.rows[k][j] for k in keys])
        return out

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self):
        """Persist MV rows whose pk changed since the last checkpoint
        (reference: the MV's own StateTable commit, materialize.rs:44).
        v0 restriction: NULL pk/values are not persisted (none of the
        benchmark MVs produce them); a None raises loudly."""
        if not self._changed:
            return []
        ups, tombs = [], []
        for k in self._changed:
            if any(v is None for v in k):
                raise ValueError("NULL pk persistence not supported yet")
            row = self.rows.get(k)
            if row is None:
                tombs.append(k)
            elif any(v is None for v in row):
                raise ValueError("NULL value persistence not supported yet")
            else:
                ups.append((k, row))
        n = len(ups) + len(tombs)
        key_cols = {}
        for j, name in enumerate(self.pk):
            key_cols[f"k{j}"] = np.array(
                [k[j] for k, _ in ups] + [k[j] for k in tombs],
                dtype=self._dtypes[name],
            )
        value_cols = {}
        for j, name in enumerate(self.columns):
            pad = np.zeros(len(tombs), dtype=self._dtypes[name])
            value_cols[f"v{j}"] = np.concatenate(
                [
                    np.array([r[j] for _, r in ups], dtype=self._dtypes[name]),
                    pad,
                ]
            ) if ups else pad
        tombstone = np.zeros(n, bool)
        tombstone[len(ups):] = True
        self._changed.clear()
        return [
            StateDelta(
                self.table_id,
                key_cols,
                value_cols,
                tombstone,
                tuple(f"k{j}" for j in range(len(self.pk))),
            )
        ]

    def restore_state(self, table_id, key_cols, value_cols):
        self.rows = {}
        self._changed = set()
        if not key_cols:
            return
        n = len(next(iter(key_cols.values())))
        for i in range(n):
            k = tuple(
                key_cols[f"k{j}"][i].item() for j in range(len(self.pk))
            )
            v = tuple(
                value_cols[f"v{j}"][i].item()
                for j in range(len(self.columns))
            )
            self.rows[k] = v
