"""ProjectSet executor — table-function row expansion.

Reference: src/stream/src/executor/project_set.rs — each input row
expands into the rows its table function yields (unnest, generate_
series), tagged with a ``projected_row_id`` ordinal; scalar select
items repeat per produced row.

TPU re-design (the hop-window recipe): the expansion factor is STATIC
— ``list_cap`` for unnest over a LIST column, ``max_steps`` for
generate_series — so a chunk of capacity C becomes one chunk of
capacity C*K with copy k forming a contiguous block (preserves the
U-/U+ adjacency invariant exactly like hop_window.py); copies past
each row's actual yield count are masked invalid. No loops, no dynamic
shapes.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.array.composite import LIST_LEN_SUFFIX
from risingwave_tpu.executors.base import Executor


@partial(jax.jit, static_argnames=("col", "out", "k", "ordinal"))
def _unnest_step(chunk: StreamChunk, col: str, out: str, k: int, ordinal):
    """Expand a LIST column's element lanes (array/composite layout:
    ``col.0`` .. ``col.<k-1>`` + ``col.#`` length)."""
    cap = chunk.capacity
    tile = lambda a: jnp.tile(a, k)
    idx = jnp.repeat(jnp.arange(k), cap)  # element index per copy
    lens = chunk.col(col + LIST_LEN_SUFFIX)
    elem = jnp.concatenate([chunk.col(f"{col}.{i}") for i in range(k)])
    in_list = idx < tile(lens).astype(idx.dtype)
    cols = {
        n: tile(a)
        for n, a in chunk.columns.items()
        if not n.startswith(col + ".") and n != col + LIST_LEN_SUFFIX
    }
    cols[out] = elem
    if ordinal:
        cols["projected_row_id"] = idx.astype(jnp.int64)
    nulls = {n: tile(a) for n, a in chunk.nulls.items() if n in cols}
    valid = tile(chunk.valid) & in_list
    return StreamChunk(cols, valid, nulls, tile(chunk.ops))


@partial(jax.jit, static_argnames=("start_col", "stop_col", "out", "k", "ordinal"))
def _series_step(chunk, start_col: str, stop_col: str, out: str, k: int, ordinal):
    """generate_series(start, stop) inclusive, step 1, capped at k.
    A NULL bound yields an EMPTY series (reference table-function NULL
    semantics), never a sentinel-derived one."""
    cap = chunk.capacity
    tile = lambda a: jnp.tile(a, k)
    idx = jnp.repeat(jnp.arange(k, dtype=jnp.int64), cap)
    bounds_ok = ~chunk.null_of(start_col) & ~chunk.null_of(stop_col)
    start = tile(chunk.col(start_col).astype(jnp.int64))
    stop = tile(chunk.col(stop_col).astype(jnp.int64))
    val = start + idx
    in_series = (val <= stop) & tile(bounds_ok)
    cols = {n: tile(a) for n, a in chunk.columns.items()}
    cols[out] = val
    if ordinal:
        cols["projected_row_id"] = idx
    nulls = {n: tile(a) for n, a in chunk.nulls.items() if n != out}
    valid = tile(chunk.valid) & in_series
    return StreamChunk(cols, valid, nulls, tile(chunk.ops))


class ProjectSetExecutor(Executor):
    """Table-function expansion. ``fn`` is "unnest" (over a LIST column
    laid out by array/composite) or "generate_series" (int bounds,
    step 1, ``max_steps`` static cap — rows needing more raise via the
    overflow latch at the barrier)."""

    def __init__(
        self,
        fn: str,
        out: str = "value",
        list_col: Optional[str] = None,
        list_cap: Optional[int] = None,
        start_col: Optional[str] = None,
        stop_col: Optional[str] = None,
        max_steps: int = 64,
        ordinal: bool = True,
    ):
        if fn not in ("unnest", "generate_series"):
            raise ValueError(f"unknown table function {fn!r}")
        self.fn = fn
        self.out = out
        self.list_col = list_col
        self.list_cap = list_cap
        self.start_col = start_col
        self.stop_col = stop_col
        self.max_steps = max_steps
        self.ordinal = ordinal
        self._truncated = jnp.zeros((), jnp.bool_)

    def lint_info(self):
        adds = {self.out: None}
        if self.ordinal:
            adds["projected_row_id"] = jnp.int64
        if self.fn == "generate_series":
            adds[self.out] = jnp.int64
            return {
                "requires": (self.start_col, self.stop_col),
                "adds": adds,
                "table_ids": (),
            }
        # unnest reads the composite list lanes (col.0..col.k, col.#)
        # whose names the catalog schema does not carry column-wise —
        # declare only what is provable (the outputs), require nothing
        return {"adds": adds, "table_ids": ()}

    def trace_contract(self):
        if self.fn == "unnest":
            step = lambda c: _unnest_step(
                c, self.list_col, self.out, self.list_cap, self.ordinal
            )
        else:
            step = lambda c: _series_step(
                c,
                self.start_col,
                self.stop_col,
                self.out,
                self.max_steps,
                self.ordinal,
            )
        return {
            "kind": "device",
            "trace_step": step,
            "state": None,
            "donate": True,
            # static expansion factor (list_cap / max_steps): output
            # capacity is a pure function of the input bucket
            "emission": "passthrough",
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        if self.fn == "unnest":
            # lists longer than the configured expansion silently drop
            # elements: latch like the series cap does
            lens = chunk.col(self.list_col + LIST_LEN_SUFFIX)
            self._truncated = self._truncated | jnp.any(
                chunk.valid & (lens > self.list_cap)
            )
            return [
                _unnest_step(
                    chunk, self.list_col, self.out, self.list_cap,
                    self.ordinal,
                )
            ]
        # series longer than max_steps would silently truncate: latch
        # (NULL bounds yield empty series and never count)
        bounds_ok = ~chunk.null_of(self.start_col) & ~chunk.null_of(
            self.stop_col
        )
        span = (
            chunk.col(self.stop_col).astype(jnp.int64)
            - chunk.col(self.start_col).astype(jnp.int64)
            + 1
        )
        self._truncated = self._truncated | jnp.any(
            chunk.valid & bounds_ok & (span > self.max_steps)
        )
        return [
            _series_step(
                chunk, self.start_col, self.stop_col, self.out,
                self.max_steps, self.ordinal,
            )
        ]

    def on_barrier(self, barrier) -> List[StreamChunk]:
        if bool(self._truncated):
            what = (
                "generate_series exceeded max_steps"
                if self.fn == "generate_series"
                else "unnest list exceeded list_cap"
            )
            raise RuntimeError(f"{what}; raise the cap")
        return []
