"""Sort executor — emit-on-window-close ordered output.

Reference: src/stream/src/executor/sort.rs:20 + sort_buffer.rs — rows
buffer in a state table until the watermark passes their timestamp,
then emit in timestamp order (the EOWC building block; downstream
operators see an append-only, time-ordered stream).

TPU re-design: the buffer is a fixed-capacity slot arena in HBM.
Append is a cumsum-compacted scatter into free slots; a watermark
emits the closed prefix with ONE device argsort over (ts, seq) —
seq (arrival order) breaks ties deterministically — and frees the
slots. No per-row host work; the host sees only the overflow latch
once per barrier.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.ops.hash_table import stage_scalars
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    pull_rows,
)


@partial(jax.jit, static_argnames=("names",), donate_argnums=(0, 1, 2, 3))
def _sort_append(buf, bnulls, valid, seq, next_seq, chunk, names):
    """Scatter the chunk's live rows into free buffer slots."""
    cap = valid.shape[0]
    free = ~valid
    # position of each free slot among free slots; position of each
    # incoming row among incoming rows — row i claims the i-th free slot
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    slot_of_rank = jnp.full(cap, cap, jnp.int32)
    slot_of_rank = slot_of_rank.at[
        jnp.where(free, free_rank, cap)
    ].set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
    live = chunk.valid
    row_rank = jnp.cumsum(live.astype(jnp.int32)) - 1
    n_free = jnp.sum(free.astype(jnp.int32))
    overflow = jnp.sum(live.astype(jnp.int32)) > n_free
    dest = jnp.where(
        live & (row_rank < n_free), slot_of_rank[row_rank], cap
    )
    new_buf = {
        n: buf[n].at[dest].set(
            chunk.col(n).astype(buf[n].dtype), mode="drop"
        )
        for n in names
    }
    new_nulls = {
        n: bnulls[n].at[dest].set(chunk.null_of(n), mode="drop")
        for n in bnulls
    }
    new_valid = valid.at[dest].set(live, mode="drop")
    order = next_seq + row_rank.astype(jnp.int64)
    new_seq = seq.at[dest].set(order, mode="drop")
    next_seq = next_seq + jnp.sum(live.astype(jnp.int64))
    return new_buf, new_nulls, new_valid, new_seq, next_seq, overflow


@partial(jax.jit, static_argnames=("names", "ts_col"), donate_argnums=(2, ))
def _sort_emit(buf, bnulls, valid, seq, cutoff, names, ts_col):
    """Emit rows with ts < cutoff in (ts, seq) order; free their slots."""
    cap = valid.shape[0]
    ts = buf[ts_col]
    closed = valid & (ts < cutoff)
    big = jnp.int64(1) << 62
    # (ts, seq) two-key sort via two stable passes (packing both keys
    # into one int64 would overflow epoch-ms timestamps); open rows
    # sink to the end via the sentinel
    order1 = jnp.argsort(seq, stable=True)
    ts_sorted = jnp.where(closed, ts, big)[order1]
    order = order1[jnp.argsort(ts_sorted, stable=True)]
    out_cols = {n: buf[n][order] for n in names}
    out_nulls = {n: bnulls[n][order] for n in bnulls}
    out_valid = closed[order]
    new_valid = valid & ~closed
    return (
        out_cols,
        out_nulls,
        out_valid,
        new_valid,
        jnp.sum(closed.astype(jnp.int32)),
    )


class ArenaBufferedExecutor(Executor, Checkpointable):
    """Shared EOWC arena: a fixed-capacity slot buffer in HBM holding
    open (not-yet-closed) rows keyed by arrival seq. Subclasses decide
    WHEN rows close and WHAT to emit (SortExecutor: ordered rows;
    EowcOverWindowExecutor: window-function outputs over complete
    partitions). One arena lifecycle — append, overflow/append-only
    latches, seq-keyed incremental checkpoints — lives here."""

    _arena_name = "EOWC arena"

    def __init__(
        self,
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 14,
        nullable: Sequence[str] = (),
        table_id: str = "arena",
    ):
        self.table_id = table_id
        self.names = tuple(schema_dtypes)
        self.capacity = capacity
        self.buf = {
            n: jnp.zeros(capacity, jnp.dtype(d))
            for n, d in schema_dtypes.items()
        }
        self.bnulls = {
            n: jnp.zeros(capacity, jnp.bool_)
            for n in nullable
            if n in self.names
        }
        self.valid = jnp.zeros(capacity, jnp.bool_)
        self.seq = jnp.zeros(capacity, jnp.int64)
        self.next_seq = jnp.zeros((), jnp.int64)
        self._overflow = jnp.zeros((), jnp.bool_)
        self._saw_delete = jnp.zeros((), jnp.bool_)

    def lint_info(self):
        return {
            "requires": tuple(self.names),
            "expects": {n: self.buf[n].dtype for n in self.names},
            "table_ids": (self.table_id,),
        }

    def state_nbytes(self) -> int:
        """Device bytes held (host-side estimate; no sync)."""
        return sum(
            leaf.nbytes
            for leaf in jax.tree.leaves(
                (self.buf, self.bnulls, self.valid, self.seq)
            )
        )

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: _sort_append(
                self.buf,
                self.bnulls,
                self.valid,
                self.seq,
                self.next_seq,
                c,
                self.names,
            ),
            "state": (self.buf, self.valid, self.seq),
            "donate": True,
            # window-close emissions are arena-capacity chunks: one
            # declared bucket
            "emission": "fixed",
            "emission_caps": (self.capacity,),
            "window_buckets": (self.capacity,),
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        self._saw_delete = self._saw_delete | jnp.any(
            chunk.valid & (chunk.signs() < 0)
        )
        (
            self.buf,
            self.bnulls,
            self.valid,
            self.seq,
            self.next_seq,
            ovf,
        ) = _sort_append(
            self.buf,
            self.bnulls,
            self.valid,
            self.seq,
            self.next_seq,
            chunk,
            self.names,
        )
        self._overflow = self._overflow | ovf
        return []  # rows surface only when their time closes

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        self._staged_scalars = stage_scalars(
            self._saw_delete, self._overflow
        )
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return []

    def _on_barrier_scalars(self, vals) -> None:
        saw_delete, overflow = vals
        if saw_delete:
            raise RuntimeError(
                f"{self._arena_name} requires append-only input"
            )
        if overflow:
            raise RuntimeError(
                f"{self._arena_name} overflowed; grow capacity or "
                "advance watermarks faster"
            )


    # -- integrity --------------------------------------------------------
    def digest_lanes(self):
        lanes = {f"c_{n}": self.buf[n] for n in self.names}
        for n, a in self.bnulls.items():
            lanes[f"cn_{n}"] = a
        lanes["seq"] = self.seq
        return lanes, self.valid

    def state_digest(self) -> int:
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self) -> List[StateDelta]:
        """Incremental staging keyed by seq: upsert only rows APPENDED
        since the last checkpoint, tombstone only rows that left (the
        Checkpointable O(changed) contract). The seq lane of live rows
        is pulled to diff against the previously-stored set — a freed
        slot may already hold a new row, so slot marks alone cannot
        name the departed seqs."""
        valid_np = np.asarray(self.valid)
        sel_all = np.flatnonzero(valid_np)
        seq_rows = pull_rows({"k0": self.seq}, sel_all)
        cur = (
            np.asarray(seq_rows["k0"], np.int64)
            if len(sel_all)
            else np.zeros(0, np.int64)
        )
        prev = getattr(self, "_stored_seqs", np.zeros(0, np.int64))
        new_mask = ~np.isin(cur, prev)
        sel_new = sel_all[new_mask]
        gone = np.setdiff1d(prev, cur)
        self._stored_seqs = cur
        n_up, n_del = len(sel_new), len(gone)
        if n_up + n_del == 0:
            return []
        lanes = {"k0": self.seq}
        lanes.update({f"v_{n}": self.buf[n] for n in self.names})
        lanes.update({f"n_{n}": l for n, l in self.bnulls.items()})
        rows = pull_rows(lanes, sel_new)
        key_cols = {
            "k0": np.concatenate(
                [np.asarray(rows["k0"], np.int64), gone]
            )
        }
        value_cols = {}
        for n in self.names:
            vals = np.asarray(rows[f"v_{n}"])
            value_cols[f"v_{n}"] = np.concatenate(
                [vals, np.zeros(n_del, vals.dtype)]
            )
        for n in self.bnulls:
            value_cols[f"n_{n}"] = np.concatenate(
                [
                    np.asarray(rows[f"n_{n}"]).astype(np.uint8),
                    np.zeros(n_del, np.uint8),
                ]
            )
        tomb = np.zeros(n_up + n_del, bool)
        tomb[n_up:] = True
        return [StateDelta(self.table_id, key_cols, value_cols, tomb, ("k0",))]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        # recovery clears the error latches: the restored state is
        # valid even when a latched overflow/delete caused the recovery
        self._overflow = jnp.zeros((), jnp.bool_)
        self._saw_delete = jnp.zeros((), jnp.bool_)
        if n > self.capacity:
            # silent scatter-drop would lose buffered rows forever:
            # grow the arena to hold the checkpoint
            cap = self.capacity
            while n > cap:
                cap *= 2
            self.capacity = cap
            self.buf = {
                k: jnp.zeros(cap, v.dtype) for k, v in self.buf.items()
            }
            self.bnulls = {
                k: jnp.zeros(cap, jnp.bool_) for k in self.bnulls
            }
        cap = self.capacity
        self.valid = jnp.zeros(cap, jnp.bool_)
        self.seq = jnp.zeros(cap, jnp.int64)
        for nme in self.names:
            self.buf[nme] = jnp.zeros_like(self.buf[nme])
        if n == 0:
            self.next_seq = jnp.zeros((), jnp.int64)
            self._stored_seqs = np.zeros(0, np.int64)
            return
        seqs = np.asarray(key_cols["k0"], np.int64)
        idx = jnp.arange(n, dtype=jnp.int32)
        self.seq = self.seq.at[idx].set(jnp.asarray(seqs))
        for i, nme in enumerate(self.names):
            vals = np.asarray(value_cols[f"v_{nme}"])
            self.buf[nme] = (
                self.buf[nme].at[idx].set(
                    jnp.asarray(vals.astype(self.buf[nme].dtype))
                )
            )
        for nme in self.bnulls:
            if f"n_{nme}" in value_cols:
                self.bnulls[nme] = (
                    self.bnulls[nme]
                    .at[idx]
                    .set(jnp.asarray(value_cols[f"n_{nme}"].astype(bool)))
                )
        self.valid = self.valid.at[idx].set(True)
        self.next_seq = jnp.asarray(int(seqs.max()) + 1, jnp.int64)
        self._stored_seqs = seqs


class SortExecutor(ArenaBufferedExecutor):
    """EOWC sort: buffer until the ``ts_col`` watermark closes rows,
    then emit in (ts, arrival) order. Append-only input."""

    _arena_name = "EOWC sort buffer"

    def __init__(
        self,
        ts_col: str,
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 14,
        nullable: Sequence[str] = (),
        table_id: str = "sort",
    ):
        super().__init__(schema_dtypes, capacity, nullable, table_id)
        self.ts_col = ts_col

    def lint_info(self):
        info = super().lint_info()
        # EOWC contract: rows only ever leave the arena when a
        # watermark on ts_col closes them — an unreachable ts_col
        # means the buffer grows forever and nothing is emitted
        info["window_key"] = self.ts_col
        return info

    def on_watermark(self, watermark: Watermark):
        if watermark.column != self.ts_col:
            return watermark, []
        cutoff = jnp.asarray(watermark.value, jnp.int64)
        out_cols, out_nulls, out_valid, self.valid, n_closed = _sort_emit(
            self.buf, self.bnulls, self.valid, self.seq, cutoff,
            self.names, self.ts_col,
        )
        # one scalar read per watermark: an all-invalid capacity-wide
        # chunk would cost O(capacity) device work in EVERY downstream
        # stage, and EOWC emissions are empty most barriers — the
        # small sync is the cheaper side of the trade
        if int(n_closed) == 0:
            return watermark, []
        chunk = StreamChunk(
            columns=out_cols,
            valid=out_valid,
            nulls=out_nulls,
            ops=jnp.zeros(self.capacity, jnp.int32),
        )
        return watermark, [chunk]
