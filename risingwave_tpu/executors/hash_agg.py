"""HashAgg executor — grouped streaming aggregation with retraction.

Reference: src/stream/src/executor/hash_agg.rs:62 (675 LoC) +
executor/aggregation/{agg_group,agg_state}.rs. Semantics matched:
- apply_chunk (hash_agg.rs:326): every visible row updates its group by
  its retraction sign; groups are created on first touch;
- flush_data (hash_agg.rs:406): on barrier, each dirty group emits
  I / (U-,U+) / D against what downstream last saw;
- watermark-driven state cleaning of closed windows
  (state_table.rs:1133, iterator/skip_watermark.rs).

TPU re-design: the group map is ops/hash_table.HashTable (slots in
HBM); agg state is slot-indexed arrays (ops/agg.AggState). One fused
jit step does lookup-or-insert + masked scatter updates for a whole
chunk. The host only:
- tracks an insert upper bound to trigger pre-emptive RESIZE (the
  reference grows its heap maps freely; we rebuild into a 2x table and
  re-scatter state, reclaiming tombstones — the contract promised by
  ops/hash_table.py:121);
- reads one device flag per barrier to assert no row overflowed
  MAX_PROBE mid-epoch (cannot happen while load < 50%).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    grow_pow2,
    host_key_view,
    lanes_from_host_keys,
    pull_rows,
    stage_marks,
)
from risingwave_tpu.ops import agg as agg_ops
from risingwave_tpu.ops import minput as mi_ops
from risingwave_tpu.ops.agg import AggCall, AggState
from risingwave_tpu.ops.hash_table import HashTable, lookup, lookup_or_insert, stage_scalars, set_live
from risingwave_tpu.runtime.bucketing import BucketAllocator, BucketPolicy

GROW_AT = 0.5  # rehash when claimed slots may exceed this load factor
# mid-epoch rebuild only when the HOST insert bound nears the table
# itself (genuine MAX_PROBE overflow risk): padded upstream chunks
# (agg/join full-pad emissions) make the mid-epoch bound wildly
# pessimistic, so ordinary load-factor growth resolves at the barrier
# from the TRUE occupancy note instead. MAX_PROBE=64 keeps inserts
# safe well past this load.
HARD_GROW_AT = 0.75


def _build_key_lanes(
    chunk: StreamChunk, group_keys: Tuple[str, ...], nullable: Tuple[bool, ...]
):
    """Group-key lanes with SQL NULL-group semantics (one NULL group per
    key, distinct from the zero value — see ops/hashing.group_key_lanes).
    Nullability is DECLARED at executor build time so lane count/order is
    static even when a particular chunk carries no null lane."""
    lanes = []
    for name, nb in zip(group_keys, nullable):
        col = chunk.col(name)
        if nb:
            null = chunk.nulls.get(name)
            if null is None:
                null = jnp.zeros(chunk.capacity, jnp.bool_)
            lanes.append(jnp.where(null, jnp.zeros((), col.dtype), col))
            lanes.append(null)
        else:
            lanes.append(col)
    return tuple(lanes)


def _minput_pass(state, minput, mi_bad, calls, slots, signs, chunk):
    """Fold a row batch into every materialized MIN/MAX multiset and
    write each touched group's new extreme / live count back into the
    ordinary accumulator lanes (so flush is unchanged)."""
    cap = state.capacity
    for c in calls:
        if not c.materialized:
            continue
        v = chunk.col(c.input)
        notnull = ~chunk.nulls.get(c.input, jnp.zeros(v.shape, jnp.bool_))
        vals, cnt = minput[c.output]
        vals, cnt, rep_slots, extreme, total, ovf, inc = mi_ops.minput_apply(
            vals, cnt, slots, signs, v, notnull, c.kind
        )
        minput[c.output] = (vals, cnt)
        idx = jnp.where(rep_slots >= 0, rep_slots, cap)
        state.accums[c.output] = (
            state.accums[c.output].at[idx].set(extreme, mode="drop")
        )
        state.nonnull[c.output] = (
            state.nonnull[c.output].at[idx].set(total, mode="drop")
        )
        mi_bad = mi_bad | ovf | inc
    return state, minput, mi_bad


def agg_step_fn(
    table: HashTable,
    state: AggState,
    dropped: jnp.ndarray,
    chunk: StreamChunk,
    calls: Tuple[AggCall, ...],
    group_keys: Tuple[str, ...],
    nullable: Tuple[bool, ...],
    minput=None,
    mi_bad=None,
):
    """One chunk through the group map + agg update (pure; jit it).

    With ``minput`` (materialized MIN/MAX multisets, ops/minput.py) the
    same dispatch also folds the batch into those and returns
    ``(table, state, dropped, minput, mi_bad)``; otherwise the classic
    3-tuple."""
    keys = _build_key_lanes(chunk, group_keys, nullable)
    table, slots, _, _ = lookup_or_insert(table, keys, chunk.valid)
    signs = chunk.effective_signs()
    dropped = dropped | jnp.any(chunk.valid & (slots < 0))
    values = {c.input: chunk.col(c.input) for c in calls if c.input is not None}
    nulls = {
        c.input: chunk.nulls[c.input]
        for c in calls
        if c.input is not None and c.input in chunk.nulls
    }
    state = agg_ops.apply(state, calls, slots, signs, values, nulls)
    table = set_live(table, slots, state.row_count[slots] > 0)
    if minput is None:
        return table, state, dropped
    state, minput, mi_bad = _minput_pass(
        state, dict(minput), mi_bad, calls, slots, signs, chunk
    )
    return table, state, dropped, minput, mi_bad


_agg_step = jax.jit(
    agg_step_fn,
    static_argnames=("calls", "group_keys", "nullable"),
    donate_argnums=(0, 1),
)


@partial(
    jax.jit,
    static_argnames=("calls", "group_keys", "nullable"),
    donate_argnums=(0, 1, 3, 4),
)
def _agg_step_mi(table, state, dropped, minput, mi_bad, chunk, calls, group_keys, nullable):
    return agg_step_fn(
        table, state, dropped, chunk, calls, group_keys, nullable,
        minput, mi_bad,
    )


@partial(
    jax.jit,
    static_argnames=("calls", "group_keys", "nullable", "pre"),
    donate_argnums=(0, 1),
)
def _agg_scan(
    table, state, dropped, stacked, calls, group_keys, nullable, pre
):
    """lax.scan over a (n_chunks, ...) stacked chunk batch — one fused
    device program per epoch (see HashAggExecutor.apply_stacked)."""

    def body(carry, chunk):
        table, state, dropped = carry
        if pre is not None:
            chunk = pre(chunk)
        table, state, dropped = agg_step_fn(
            table, state, dropped, chunk, calls, group_keys, nullable
        )
        return (table, state, dropped), None

    (table, state, dropped), _ = jax.lax.scan(
        body, (table, state, dropped), stacked
    )
    return table, state, dropped


def _epoch_reduced_fn(
    table, state, dropped, stacked, calls, group_keys, nullable, pre,
    minput=None, mi_bad=None,
):
    """The TPU-first epoch path: vmap the stateless prefix over the
    chunk axis, flatten the whole epoch into one row batch, pre-reduce
    by key (sort + segment combine, ops/agg.reduce_by_key), then touch
    the hash table ONCE per distinct key.

    Replaces the lax.scan of per-chunk probe loops: the scan serialized
    n_chunks × MAX_PROBE gather/scatter rounds, which real-TPU profiling
    (BENCH_r02 fault analysis) showed running 20-50x slower than the
    CPU actor. Commutativity across one epoch's rows makes the
    reordering exact (sum/count; append-only min/max latch retractions
    either way)."""
    if pre is not None:
        chunks = jax.vmap(pre)(stacked)
    else:
        chunks = stacked
    flat = jax.tree.map(
        lambda a: a.reshape((-1,) + a.shape[2:]), chunks
    )
    keys = _build_key_lanes(flat, group_keys, nullable)
    signs = flat.effective_signs()
    values = {c.input: flat.col(c.input) for c in calls if c.input is not None}
    nulls = {
        c.input: flat.nulls[c.input]
        for c in calls
        if c.input is not None and c.input in flat.nulls
    }
    sorted_keys, rep_valid, w, reduced, mret = agg_ops.reduce_by_key(
        keys, signs, calls, values, nulls
    )
    table, slots, _, _ = lookup_or_insert(table, sorted_keys, rep_valid)
    dropped = dropped | jnp.any(rep_valid & (slots < 0))
    state = agg_ops.apply_reduced(
        state, calls, slots, rep_valid, w, reduced, mret
    )
    table = set_live(
        table,
        jnp.where(rep_valid, slots, -1),
        state.row_count[jnp.where(slots >= 0, slots, 0)] > 0,
    )
    if minput is None:
        return table, state, dropped
    # materialized MIN/MAX: re-probe (read-only) for EVERY flat row's
    # slot — the rep insert above guarantees hits — then fold the raw
    # rows into the multisets
    row_signs = flat.effective_signs()
    row_slots, _ = lookup(table, keys, flat.valid & (row_signs != 0))
    state, minput, mi_bad = _minput_pass(
        state, dict(minput), mi_bad, calls, row_slots, row_signs, flat
    )
    return table, state, dropped, minput, mi_bad


_agg_epoch_reduced = partial(
    jax.jit,
    static_argnames=("calls", "group_keys", "nullable", "pre"),
    donate_argnums=(0, 1),
)(_epoch_reduced_fn)


@partial(
    jax.jit,
    static_argnames=("calls", "group_keys", "nullable", "pre"),
    donate_argnums=(0, 1, 8, 9),
)
def _agg_epoch_reduced_mi(
    table, state, dropped, stacked, calls, group_keys, nullable, pre,
    minput, mi_bad,
):
    return _epoch_reduced_fn(
        table, state, dropped, stacked, calls, group_keys, nullable, pre,
        minput, mi_bad,
    )


@partial(jax.jit, static_argnames=("calls", "new_cap"))
def _rehash(
    table: HashTable,
    state: AggState,
    minput,
    calls: Tuple[AggCall, ...],
    new_cap: int,
):
    """Rebuild into a fresh (usually larger) table, dropping reclaimable
    tombstones, and re-scatter all slot-indexed state.

    A slot must survive iff it still matters to anyone:
      live (row_count>0) | emitted_valid (downstream saw it; a future
      delete must retract it) | dirty (unflushed change pending) |
      sdirty (unpersisted change — its KEY must survive so the next
      checkpoint can name the upsert/tombstone).
    """
    keep = table.live | state.emitted_valid | state.dirty | state.sdirty
    keep = keep & (table.fp1 != jnp.uint32(0))

    new_table = HashTable.create(new_cap, tuple(k.dtype for k in table.keys))
    new_table, new_slots, _, _ = lookup_or_insert(new_table, table.keys, keep)
    idx = jnp.where(keep, new_slots, new_cap)

    def rescatter(src, init):
        dst = jnp.full(new_cap, init, src.dtype)
        return dst.at[idx].set(src, mode="drop")

    new_table = set_live(new_table, jnp.where(keep, new_slots, -1), table.live)

    kinds = {c.output: c.kind for c in calls}
    accums = {
        n: rescatter(a, agg_ops.accum_init(kinds[n], a.dtype))
        for n, a in state.accums.items()
    }
    emitted = {n: rescatter(a, jnp.zeros((), a.dtype)) for n, a in state.emitted.items()}
    new_state = AggState(
        row_count=rescatter(state.row_count, jnp.zeros((), jnp.int64)),
        accums=accums,
        nonnull={
            n: rescatter(a, jnp.zeros((), jnp.int64))
            for n, a in state.nonnull.items()
        },
        emitted=emitted,
        emitted_isnull={
            n: rescatter(a, jnp.zeros((), jnp.bool_))
            for n, a in state.emitted_isnull.items()
        },
        emitted_valid=rescatter(state.emitted_valid, jnp.zeros((), jnp.bool_)),
        dirty=rescatter(state.dirty, jnp.zeros((), jnp.bool_)),
        minmax_retracted=state.minmax_retracted,
        sdirty=rescatter(state.sdirty, jnp.zeros((), jnp.bool_)),
        stored=rescatter(state.stored, jnp.zeros((), jnp.bool_)),
    )
    new_minput = {
        name: mi_ops.minput_rescatter(v, c, keep, new_slots, new_cap)
        for name, (v, c) in minput.items()
    }
    return new_table, new_state, new_minput


@partial(jax.jit, static_argnames=("calls", "new_cap"))
def _evict(
    table: HashTable,
    state: AggState,
    minput,
    calls: Tuple[AggCall, ...],
    new_cap: int,
):
    """Drop fully-durable groups from HBM (the LRU-eviction analogue —
    reference: stream executors spill via state-table LRU caches over
    Hummock, hash_agg.rs:49). A group is evictable iff the object store
    holds its exact state: stored & ~sdirty & ~dirty. Its key leaves
    the table entirely; if the group is touched again, the slot
    re-inserts fresh and the next barrier's cold-merge folds the
    durable state back in (see _merge_cold)."""
    hot = (
        (table.live | state.emitted_valid | state.dirty | state.sdirty)
        & (table.fp1 != jnp.uint32(0))
        & ~(state.stored & ~state.sdirty & ~state.dirty)
    )
    n_evicted = jnp.sum(
        ((table.live | state.emitted_valid) & ~hot).astype(jnp.int32)
    )
    new_table = HashTable.create(new_cap, tuple(k.dtype for k in table.keys))
    new_table, new_slots, _, _ = lookup_or_insert(new_table, table.keys, hot)
    idx = jnp.where(hot, new_slots, new_cap)

    def rescatter(src, init):
        dst = jnp.full(new_cap, init, src.dtype)
        return dst.at[idx].set(src, mode="drop")

    new_table = set_live(new_table, jnp.where(hot, new_slots, -1), table.live)
    kinds = {c.output: c.kind for c in calls}
    new_state = AggState(
        row_count=rescatter(state.row_count, jnp.zeros((), jnp.int64)),
        accums={
            n: rescatter(a, agg_ops.accum_init(kinds[n], a.dtype))
            for n, a in state.accums.items()
        },
        nonnull={
            n: rescatter(a, jnp.zeros((), jnp.int64))
            for n, a in state.nonnull.items()
        },
        emitted={
            n: rescatter(a, jnp.zeros((), a.dtype))
            for n, a in state.emitted.items()
        },
        emitted_isnull={
            n: rescatter(a, jnp.zeros((), jnp.bool_))
            for n, a in state.emitted_isnull.items()
        },
        emitted_valid=rescatter(state.emitted_valid, jnp.zeros((), jnp.bool_)),
        dirty=rescatter(state.dirty, jnp.zeros((), jnp.bool_)),
        minmax_retracted=state.minmax_retracted,
        sdirty=rescatter(state.sdirty, jnp.zeros((), jnp.bool_)),
        stored=rescatter(state.stored, jnp.zeros((), jnp.bool_)),
    )
    new_minput = {
        name: mi_ops.minput_rescatter(v, c, hot, new_slots, new_cap)
        for name, (v, c) in minput.items()
    }
    return new_table, new_state, new_minput, n_evicted


@partial(jax.jit, static_argnames=("calls", "key_index", "emit_deletes"))
def _expire(
    table: HashTable,
    state: AggState,
    cutoff: jnp.ndarray,
    calls: Tuple[AggCall, ...],
    key_index: int,
    emit_deletes: bool,
):
    """Close every live group whose window-key lane < cutoff."""
    lane = table.keys[key_index]
    expired = table.live & (lane < cutoff)
    slots = jnp.where(expired, jnp.arange(table.capacity, dtype=jnp.int32), -1)
    if emit_deletes:
        state = agg_ops.delete_groups(state, calls, slots)
    else:
        state = agg_ops.forget_groups(state, calls, slots)
    table = set_live(table, slots, False)
    return table, state


def delta_to_chunk(
    delta: dict,
    group_keys: Tuple[str, ...],
    nullable: Tuple[bool, ...],
    calls: Tuple[AggCall, ...],
    pad: Optional[int] = None,
) -> StreamChunk:
    """``agg_ops.flush`` delta dict -> StreamChunk, optionally sliced
    to ``pad`` lanes. The ONE decoder of the flush delta lane-naming
    contract (key{i} interleaving, ``<output>__isnull`` companions,
    ops/valid lanes): the interpreted ``_delta_to_chunk`` slicing and
    the fused per-barrier program's in-trace twin
    (runtime/fused_step._fused_barrier_fn) both call it, so the two
    paths cannot drift apart. Pure over jnp arrays — traceable."""
    sl = (lambda a: a[:pad]) if pad is not None else (lambda a: a)
    cols, nulls = {}, {}
    i = 0
    for name, nb in zip(group_keys, nullable):
        cols[name] = sl(delta[f"key{i}"])
        i += 1
        if nb:
            nulls[name] = sl(delta[f"key{i}"])
            i += 1
    for c in calls:
        cols[c.output] = sl(delta[c.output])
        lane = delta.get(c.output + "__isnull")
        if lane is not None:
            nulls[c.output] = sl(lane)
    return StreamChunk(
        columns=cols, valid=sl(delta["valid"]), nulls=nulls,
        ops=sl(delta["ops"]),
    )


class HashAggExecutor(Executor, Checkpointable):
    """Streaming GROUP BY.

    Args:
      group_keys: grouping column names (re-emitted on flush).
      calls: aggregate calls.
      schema_dtypes: input column name -> np/jnp dtype (for state init).
      capacity: initial group-table capacity (power of two; grows 2x).
      out_cap: max dirty groups emitted per flush round.
      nullable_keys: subset of group_keys that can carry SQL NULL.
      window_key: optional (column, retention_ms, emit_deletes) triple —
        on watermark wm for that column, groups with key < wm -
        retention are closed (state cleaned); with emit_deletes they
        are retracted downstream, otherwise finalized silently (EOWC).
    """

    def __init__(
        self,
        group_keys: Sequence[str],
        calls: Sequence[AggCall],
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 16,
        out_cap: int = 1 << 15,
        nullable_keys: Sequence[str] = (),
        window_key: Optional[Tuple[str, int, bool]] = None,
        table_id: str = "hash_agg",
        minput_k: int = 32,
    ):
        self.table_id = table_id
        self.group_keys = tuple(group_keys)
        self.calls = tuple(calls)
        self.out_cap = out_cap
        self._dtypes = dict(schema_dtypes)
        self.nullable = tuple(k in set(nullable_keys) for k in self.group_keys)
        key_dtypes = []
        for k, nb in zip(self.group_keys, self.nullable):
            key_dtypes.append(jnp.dtype(self._dtypes[k]))
            if nb:
                key_dtypes.append(jnp.dtype(jnp.bool_))
        self.table = HashTable.create(capacity, key_dtypes)
        self.state = agg_ops.create_state(capacity, self.calls, self._dtypes)
        self.dropped = jnp.zeros((), jnp.bool_)
        self._insert_bound = 0  # host-side upper bound of claimed slots
        self._occ_note = 0  # true claimed at the last barrier (staged read)
        # host-side upper bound of dirty (unflushed) groups: rows
        # absorbed since the last flush + conservatively the whole
        # table on a retracting expiry. Drives the fixed flush-round
        # count so the per-barrier flush needs ZERO device reads (the
        # old status-read loop was RW-E801 at the top of the fusion
        # worklist).
        self._dirty_bound = 0
        # shape-stability: capacity walks the allocator's pow2 lattice;
        # growth decisions consume the occupancy note staged at the
        # previous barrier (see _maybe_grow) instead of a synchronous
        # device read
        self._buckets = BucketAllocator(
            BucketPolicy.from_capacity(capacity, grow_at=GROW_AT)
        )
        self.window_key = window_key
        self._float_extremes = agg_ops.float_extreme_meta(
            self.calls, {k: jnp.dtype(v) for k, v in self._dtypes.items()}
        )
        # materialized-input MIN/MAX multisets (minput.rs analogue)
        self.minput_k = minput_k
        self.minput = mi_ops.create_minput(
            capacity, minput_k, self.calls, self._dtypes
        )
        self.mi_bad = jnp.zeros((), jnp.bool_)
        # cold tier: set by the runtime to CheckpointManager.get_rows so
        # evicted (durable) groups fold back in on their next touch.
        # Assigning the ``cold_reader`` property binds the cold-tier
        # hooks below; while it is None the hot path (apply/on_barrier/
        # on_watermark) is provably host-sync free — the fault-in /
        # merge helpers with their NumPy fallbacks are unreachable, so
        # the fusion analyzer's AST scan of the hot methods holds for
        # exactly the configurations it analyzes.
        self._cold_reader = None
        self._cold_apply_hook = None  # _fault_in when armed
        self._cold_stacked_hook = None  # _fault_in_all when armed
        self._cold_barrier_hook = None  # _merge_cold when armed
        self._cold_expire_hook = None  # _expire_evicted when armed
        # with minput, merge-at-barrier cannot fold multisets back in
        # (a delete pre-merge would falsely latch inconsistent), so
        # evicted keys fault in ON TOUCH via this host-side set
        self._evicted: set = set()

    @property
    def cold_reader(self):
        return self._cold_reader

    @cold_reader.setter
    def cold_reader(self, fn) -> None:
        self._cold_reader = fn
        armed = fn is not None
        self._cold_apply_hook = self._fault_in if armed else None
        self._cold_stacked_hook = self._fault_in_all if armed else None
        self._cold_barrier_hook = self._merge_cold if armed else None
        self._cold_expire_hook = self._expire_evicted if armed else None

    def lint_info(self):
        emits = {k: self._dtypes.get(k) for k in self.group_keys}
        renames = {k: k for k in self.group_keys}
        requires = set(self.group_keys)
        for c in self.calls:
            if c.input is not None:
                requires.add(c.input)
            if c.kind in ("count", "count_star"):
                out_dt = jnp.int64
            elif c.kind in ("min", "max") and c.input in self._dtypes:
                out_dt = self._dtypes[c.input]
            else:
                out_dt = None  # sum/avg widen by kind-specific rules
            emits[c.output] = out_dt
            renames[c.output] = None
        return {
            "requires": tuple(sorted(requires)),
            "expects": {
                k: self._dtypes[k]
                for k in sorted(requires)
                if k in self._dtypes
            },
            "emits": emits,
            "renames": renames,
            "keys": self.group_keys,
            "table_ids": (self.table_id,),
            "window_key": self.window_key[0] if self.window_key else None,
        }

    def trace_contract(self):
        # flush quantizes every delta chunk to exactly two capacities
        # (_delta_to_chunk: small | full) — that pair IS the declared
        # bucket lattice that keeps the windowed agg shape-stable
        full = 2 * self.out_cap
        caps = tuple(sorted({min(256, full), full}))
        contract = {
            "kind": "device",
            "trace_step": lambda c: _agg_step(
                self.table,
                self.state,
                self.dropped,
                c,
                self.calls,
                self.group_keys,
                self.nullable,
            ),
            "state": (self.table, self.state),
            "donate": True,
            "emission": "bucketed",
            "emission_caps": caps,
            "window_buckets": caps,
            # the interpreted flush pays one packed status read per
            # round; the fused per-barrier step compiles its own
            # device-side flush (runtime/fused_step._fused_barrier_fn)
            # and never calls this method — the analyzer scores its
            # syncs as fallback-only, outside the fusibility verdict
            "fallback_syncs": ("_flush_all",),
        }
        if self._cold_reader is not None:
            # the cold tier splices host-side fault-in/merge back into
            # the data path: an ARMED instance must be scanned honestly
            # (the corpus twins the analyzer proves are never armed)
            contract["hot_methods"] = (
                "_fault_in",
                "_fault_in_all",
                "_merge_cold",
                "_expire_evicted",
            )
        return contract

    def pin_max_bucket(self):
        """ShapeGovernor hook: freeze the group table at its high-water
        bucket (shrink disabled; regrow applied by the next apply)."""
        return {
            "table_id": self.table_id,
            "pinned_cap": self._buckets.pin(),
        }

    def padding_stats(self):
        """Wasted-lane accounting (runtime/bucketing.padding_stats —
        bench/PROFILE surface; reads device occupancy)."""
        import jax.numpy as jnp

        return {
            "capacity": self.table.capacity,
            "live": int(jnp.sum(self.table.live.astype(jnp.int32))),
        }

    # -- data ------------------------------------------------------------
    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        for k, nb in zip(self.group_keys, self.nullable):
            if not nb and k in chunk.nulls:
                raise ValueError(
                    f"group key {k!r} carries a null lane but was not "
                    "declared in nullable_keys"
                )
        if self._cold_apply_hook is not None:
            self._cold_apply_hook(chunk)
        self._maybe_grow(chunk.capacity)
        self._insert_bound += chunk.capacity
        self._dirty_bound += chunk.capacity
        if self.minput:
            (
                self.table,
                self.state,
                self.dropped,
                self.minput,
                self.mi_bad,
            ) = _agg_step_mi(
                self.table,
                self.state,
                self.dropped,
                self.minput,
                self.mi_bad,
                chunk,
                self.calls,
                self.group_keys,
                self.nullable,
            )
        else:
            self.table, self.state, self.dropped = _agg_step(
                self.table,
                self.state,
                self.dropped,
                chunk,
                self.calls,
                self.group_keys,
                self.nullable,
            )
        return []

    def apply_stacked(
        self, stacked: StreamChunk, pre=None, mode: str = "reduce"
    ) -> List[StreamChunk]:
        """Apply a whole BATCH of chunks in one device dispatch.

        ``stacked`` carries a leading (n_chunks,) axis on every lane
        (see array.chunk stacking). ``pre`` is an optional pure
        chunk->chunk function (e.g. the hop expansion) traced into the
        same program, fusing the upstream stateless operators.

        ``mode``:
          "reduce" (default): flatten the epoch, sort + segment-reduce
            by key, touch the table once per distinct key
            (_agg_epoch_reduced) — the fast path on real TPU;
          "scan": lax.scan of the per-chunk step (state as carry) —
            kept for differential testing and for plans that need
            strict intra-epoch chunk ordering.
        """
        if self._cold_stacked_hook is not None:
            # the epoch-batched path cannot see per-chunk keys before
            # the fused program runs (pre is traced in): restore every
            # evicted group up front — correct, if conservative
            self._cold_stacked_hook()
        n_chunks, cap = stacked.valid.shape[:2]
        probe = jax.eval_shape(
            pre if pre is not None else (lambda c: c),
            jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), stacked),
        )
        self._maybe_grow(n_chunks * probe.valid.shape[0])
        self._insert_bound += n_chunks * probe.valid.shape[0]
        self._dirty_bound += n_chunks * probe.valid.shape[0]
        if self.minput:
            if mode != "reduce":
                raise ValueError(
                    "materialized MIN/MAX supports apply_stacked only in "
                    "'reduce' mode (use apply for per-chunk ordering)"
                )
            (
                self.table,
                self.state,
                self.dropped,
                self.minput,
                self.mi_bad,
            ) = _agg_epoch_reduced_mi(
                self.table,
                self.state,
                self.dropped,
                stacked,
                self.calls,
                self.group_keys,
                self.nullable,
                pre,
                self.minput,
                self.mi_bad,
            )
            return []
        step = _agg_epoch_reduced if mode == "reduce" else _agg_scan
        self.table, self.state, self.dropped = step(
            self.table,
            self.state,
            self.dropped,
            stacked,
            self.calls,
            self.group_keys,
            self.nullable,
            pre,
        )
        return []

    def _survivor_count(self):
        """Device scalar: what a rebuild keeps (live | emitted | dirty |
        sdirty — sdirty must count or pending-tombstone keys overflow
        the new table)."""
        return jnp.sum(
            (
                self.table.live
                | self.state.emitted_valid
                | self.state.dirty
                | self.state.sdirty
            ).astype(jnp.int32)
        )

    def _maybe_grow(self, incoming: int):
        """Capacity planning with ZERO device reads on the hot path.

        The old code refreshed the bound with a blocking
        ``read_scalars`` round-trip when the load-factor trigger
        tripped (~100ms on a tunneled TPU; RW-E801 ×2 at the top of
        the fusion worklist). Now ordinary growth resolves AT THE
        BARRIER from the staged occupancy note — the bucketing
        allocator's true claimed count (see ``_on_barrier_scalars``) —
        and the only mid-epoch rebuild is the overflow guard: when the
        host insert bound (note + inserts since, a true upper bound)
        nears the table itself, rebuild pessimistically BEFORE the
        MAX_PROBE latch can trip. Padded upstream chunks overstate the
        bound, so the guard threshold is deliberately high; one epoch
        of margin in the NEED sizing makes the rebuild converge in one
        step, and the barrier-note lazy shrink reclaims overshoot."""
        cap = self.table.capacity
        # occupancy can never exceed the table: clamp the carried
        # bound so padded upstream chunks cannot accrete an unbounded
        # bound across chunks and ratchet growth step after step (the
        # caller adds this chunk's incoming after we return)
        self._insert_bound = min(self._insert_bound, cap)
        if self._insert_bound + incoming <= cap * HARD_GROW_AT:
            return
        claimed = self._insert_bound
        # no extra margin: the 0.75 guard vs 0.5 sizing gap IS the
        # hysteresis, so the guard cannot re-trip right after a rebuild
        new_cap = self._buckets.plan(cap, incoming, claimed, claimed)
        if new_cap is not None and new_cap != cap:
            self.table, self.state, self.minput = _rehash(
                self.table, self.state, self.minput, self.calls, new_cap
            )
            self._insert_bound = min(claimed, new_cap)

    # -- control ---------------------------------------------------------
    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        # STAGE the packed latch+occupancy read (async D2H) and defer
        # the blocking materialization to finish_barrier — every
        # executor's transfer is then in flight concurrently, so a
        # chain pays ~one tunneled-TPU round-trip per barrier, with
        # values sampled at this executor's position of the walk
        # (staged AFTER the flush, which changes none of them: the
        # latches are monotonic and flush never claims slots).
        # NOTE: with a tripped latch the flush below still emits and
        # pollutes downstream IN-PROCESS state before finish_barrier
        # raises — covered by the existing contract that any barrier
        # error requires recover() (runtime.py module docstring); the
        # epoch is never checkpointed and sinks never deliver it
        # (SinkExecutor delivery also lives in finish_barrier).
        if self._cold_barrier_hook is not None:
            self._cold_barrier_hook()
        outs = self._flush_all()
        self._staged_scalars = stage_scalars(
            self.dropped,
            self.state.minmax_retracted,
            self.mi_bad,
            self.table.occupancy(),
        )
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return outs

    def _on_barrier_scalars(self, vals) -> None:
        dropped, mret, mi_bad, claimed = vals
        # occupancy refreshes _insert_bound so the NEXT epoch's
        # _maybe_grow decides without any round-trip (the allocator's
        # occupancy note), and feeds the lazy-shrink streak
        epoch_inc = max(self._insert_bound - self._occ_note, 0)
        self._occ_note = int(claimed)
        self._insert_bound = int(claimed)
        self._plan_at_barrier(int(claimed), epoch_inc)
        if dropped:
            raise RuntimeError(
                "hash table overflowed MAX_PROBE mid-epoch; grow capacity"
            )
        if mret:
            # the append-only MIN/MAX kernel cannot undo a retraction;
            # emitting would be silently wrong (agg.py latches the flag
            # for exactly this host-side rejection; the reference instead
            # keeps sorted per-group input state, minput.rs)
            raise RuntimeError(
                "row-level retraction hit an append-only MIN/MAX aggregate; "
                "set AggCall(materialized=True) for materialized-input "
                "extremes"
            )
        if mi_bad:
            raise RuntimeError(
                "materialized MIN/MAX state overflowed minput_k distinct "
                "values per group, or a value was retracted that was never "
                "inserted"
            )

    def _plan_at_barrier(self, claimed: int, epoch_inc: int) -> None:
        """Barrier-boundary capacity planning from the TRUE occupancy
        note: grow past the load factor, apply the allocator's pending
        lazy shrink, honor a governor pin — all between epochs, zero
        mid-epoch device reads. The margin keeps both growth and the
        shrink's regrow guard honest against next epoch's volume (the
        larger of true occupancy and the last epoch's insert bound),
        so a shrink can never land below what the mid-epoch overflow
        guard would immediately regrow."""
        cap = self.table.capacity
        self._buckets.note_barrier(cap, claimed)
        new_cap = self._buckets.plan(
            cap, 0, claimed, claimed, margin=max(claimed, epoch_inc)
        )
        if new_cap is not None and new_cap != cap:
            self.table, self.state, self.minput = _rehash(
                self.table, self.state, self.minput, self.calls, new_cap
            )

    # -- cold tier (state >> HBM) -----------------------------------------
    def state_nbytes(self) -> int:
        """Device bytes held (host-side estimate; no sync)."""
        return sum(
            leaf.nbytes
            for leaf in jax.tree.leaves((self.table, self.state, self.minput))
        )

    def evict_cold(self) -> int:
        """Free every fully-durable group from HBM (LRU-spill analogue;
        see _evict). Returns groups evicted. Requires a cold_reader so
        evicted groups can come back."""
        if self.cold_reader is None:
            raise RuntimeError("evict_cold needs a cold_reader (runtime)")
        if self.minput:
            # multisets cannot cold-MERGE (a pre-merge delete would
            # falsely latch inconsistent): record the evicted keys so
            # they fault back in ON TOUCH, state-exact, before any
            # post-eviction row lands on the group
            claimed = np.asarray(self.table.fp1) != 0
            durable = np.asarray(
                self.state.stored & ~self.state.sdirty & ~self.state.dirty
            )
            sel = np.flatnonzero(claimed & durable)
            if len(sel):
                pulled = pull_rows(
                    {
                        f"k{i}": l
                        for i, l in enumerate(self.table.keys)
                    },
                    sel,
                )
                views = [
                    host_key_view(np.asarray(pulled[f"k{i}"]))
                    for i in range(len(self.table.keys))
                ]
                for j in range(len(sel)):
                    self._evicted.add(
                        tuple(int(a[j]) for a in views)
                    )
        # shrink to fit the surviving hot set — eviction must actually
        # free HBM, not just slots
        hot = (
            (
                self.table.live
                | self.state.emitted_valid
                | self.state.dirty
                | self.state.sdirty
            )
            & (self.table.fp1 != jnp.uint32(0))
            & ~(self.state.stored & ~self.state.sdirty & ~self.state.dirty)
        )
        n_hot = int(jnp.sum(hot.astype(jnp.int32)))
        new_cap = grow_pow2(n_hot, 1 << 10, GROW_AT)
        self.table, self.state, self.minput, n = _evict(
            self.table, self.state, self.minput, self.calls, new_cap
        )
        n = int(n)
        self._insert_bound = int(self.table.occupancy())
        return n

    # -- fault-in on touch (the minput-compatible cold path) -------------
    def _chunk_key_tuples(self, chunk: StreamChunk) -> set:
        """Canonical host tuples of the chunk's group keys, in the
        table's key-lane layout (value [+ null flag] per key)."""
        valid = np.asarray(chunk.valid)
        sel = np.flatnonzero(valid)
        views = []
        for k, nb in zip(self.group_keys, self.nullable):
            a = np.asarray(chunk.col(k))
            if nb:
                nl = (
                    np.asarray(chunk.nulls[k])
                    if k in chunk.nulls
                    else np.zeros(len(a), bool)
                )
                a = np.where(nl, np.zeros((), a.dtype), a)
                views.append(host_key_view(a))
                views.append(nl.astype(np.int64))
            else:
                views.append(host_key_view(a))
        return {tuple(int(v[i]) for v in views) for i in sel}

    def _fault_in(self, chunk: StreamChunk) -> None:
        if not self._evicted:
            return  # nothing evicted: never pull the chunk to host
        hits = self._chunk_key_tuples(chunk) & self._evicted
        if hits:
            self._restore_cold_groups(sorted(hits))

    def _fault_in_all(self) -> None:
        if self._evicted:
            self._restore_cold_groups(sorted(self._evicted))

    def _restore_cold_groups(self, key_tuples) -> None:
        """State-exact restore of evicted groups BEFORE any new row
        lands on them (merge-at-barrier cannot fold minput multisets:
        a pre-merge delete would falsely latch inconsistent)."""
        dtypes = [k.dtype for k in self.table.keys]
        lanes_np = lanes_from_host_keys(key_tuples, dtypes)
        found, vals = self.cold_reader(lanes_np)
        self._evicted.difference_update(key_tuples)
        nt = int(found.sum())
        if not nt:
            return
        self._maybe_grow(nt)
        self._insert_bound += nt
        key_lanes = tuple(
            jnp.asarray(lanes_np[f"k{i}"][found])
            for i in range(len(dtypes))
        )
        cold = {k: jnp.asarray(np.asarray(v)[found]) for k, v in vals.items()}
        self.table, self.state, self.minput, ovf = _fault_in_scatter(
            self.table, self.state, self.minput, key_lanes, cold,
            self.calls,
        )
        self.dropped = self.dropped | ovf

    def _merge_cold(self) -> int:
        """Fold durable state into groups (re)created since the last
        checkpoint: candidates are sdirty & ~stored; a cold-store hit
        means the key was evicted earlier and its persisted accumulators
        must combine with what accrued since (merge-on-return; the
        reference reloads through its state-table cache instead)."""
        cand = np.asarray(self.state.sdirty & ~self.state.stored)
        sel = np.flatnonzero(cand)
        if not len(sel):
            return 0
        lanes = {f"k{i}": lane for i, lane in enumerate(self.table.keys)}
        keys = pull_rows(lanes, sel)
        found, vals = self.cold_reader(keys)
        if not found.any():
            return 0
        hit = sel[found]
        cold = {k: v[found] for k, v in vals.items()}
        self.state = _cold_merge(
            self.state, jnp.asarray(hit.astype(np.int32)),
            {k: jnp.asarray(v) for k, v in cold.items()},
            self.calls,
        )
        self._dirty_bound += int(found.sum())  # merged slots are dirtied
        # liveness may have flipped (e.g. deletes landed on a fresh slot
        # before the merge restored the cold row_count)
        slots = jnp.asarray(hit.astype(np.int32))
        self.table = set_live(
            self.table, slots, self.state.row_count[slots] > 0
        )
        return int(found.sum())

    def flush_rounds(self) -> int:
        """Upper bound of flush rounds this barrier needs, from the
        HOST dirty bound (each round drains up to out_cap dirty
        groups). The fused per-barrier step compiles this many rounds
        into its program — zero device reads; a trailing round on an
        over-estimate emits an all-invalid chunk, a no-op downstream."""
        bound = min(self._dirty_bound, self.table.capacity)
        return max(1, -(-bound // self.out_cap))

    def _flush_all(self) -> List[StreamChunk]:
        """INTERPRETED-path flush: exact-sliced delta chunks, one
        packed status read per round. The fused step replaces this
        whole method with device-side delta extraction (its program
        flushes, slices by the host dirty bound and feeds the device
        MV without any host read) — the contract declares it under
        ``fallback_syncs`` so the fusion analyzer scores the read as
        fallback-only, not a fusibility blocker. Interpreted consumers
        (joins, host materializers) keep the tight exact slices: a
        bound-quantized pad here would hand them padded 2*out_cap
        chunks and multiply their per-barrier compute."""
        outs = []
        while True:
            self.state, delta = agg_ops.flush(
                self.state,
                self.table.keys,
                self.out_cap,
                self._float_extremes,
            )
            n_take, overflow = np.asarray(delta["status"]).tolist()
            outs.append(self._delta_to_chunk(delta, n_take))
            if not overflow:
                break
        self._dirty_bound = 0
        return outs

    def cleaning_watermarks(self):
        """[(table_id, storage key name, cutoff)] — consumed by the
        runtime at checkpoint (skip-watermark compaction)."""
        wm = getattr(self, "_cleaning_watermark", None)
        return [(self.table_id, wm[0], wm[1])] if wm else []

    def _expire_evicted(self, watermark: Watermark) -> None:
        """A cold-evicted group past the cutoff must still close —
        fault expiring groups back in so the normal expiry path
        retracts/tombstones them (the join's analogue; expiry is rare,
        the fault-in cost is fine). Reached only through the cold-tier
        hook: the unarmed hot path never touches this host code."""
        if not self._evicted:
            return
        colname, retention, _emit = self.window_key
        ki = self._key_lane_index(colname)
        cut = int(watermark.value) - retention
        dt = np.dtype(self.table.keys[ki].dtype)
        if dt.kind == "f":
            # evicted tuples hold host_key_view bit patterns:
            # compare in the numeric domain (hash_join does the
            # same in _expire_evicted)
            itype = np.int32 if dt.itemsize == 4 else np.int64
            conv = lambda x: float(np.array(x, itype).view(dt))
        else:
            conv = lambda x: x
        expiring = [t for t in self._evicted if conv(t[ki]) < cut]
        if expiring:
            self._restore_cold_groups(sorted(expiring))

    def on_watermark(self, watermark: Watermark):
        if self.window_key is None or watermark.column != self.window_key[0]:
            return watermark, []
        colname, retention, emit_deletes = self.window_key
        if self._cold_expire_hook is not None:
            self._cold_expire_hook(watermark)
        outs: List[StreamChunk] = []
        if not emit_deletes:
            # EOWC finalization silently frees state — any dirty (not yet
            # flushed) updates on expiring groups must reach downstream
            # FIRST or they'd be lost (code-review r2 finding #1).
            outs = self._flush_all()
        cutoff = jnp.asarray(watermark.value - retention, dtype=jnp.int64)
        key_index = self._key_lane_index(colname)
        # storage-side skip-watermark cleaning (state_table.rs:1133):
        # the runtime forwards this to the checkpoint manager so
        # compaction drops expired keys from durable SSTs — the EOWC
        # path (emit_deletes=False) frees device state WITHOUT
        # tombstones, and only this watermark reclaims its storage
        self._cleaning_watermark = (
            f"k{key_index}",
            int(watermark.value) - retention,
        )
        if self.minput:
            lane = self.table.keys[key_index]
            expired = self.table.live & (lane < cutoff)
            slots = jnp.where(
                expired, jnp.arange(self.table.capacity, dtype=jnp.int32), -1
            )
            self.minput = {
                name: mi_ops.minput_clear(v, c, slots)
                for name, (v, c) in self.minput.items()
            }
        if emit_deletes:
            # retracting expiry can dirty up to every live group; the
            # host cannot count them without a sync — bound by capacity
            # (flush_rounds clamps there anyway)
            self._dirty_bound = self.table.capacity
        self.table, self.state = _expire(
            self.table, self.state, cutoff, self.calls, key_index, emit_deletes
        )
        return watermark, outs

    # -- helpers ---------------------------------------------------------
    def _key_lane_index(self, name: str) -> int:
        """Index of a group key's VALUE lane in the table's key tuple
        (null lanes of earlier nullable keys shift later lanes)."""
        i = 0
        for k, nb in zip(self.group_keys, self.nullable):
            if k == name:
                return i
            i += 2 if nb else 1
        raise KeyError(name)

    def _delta_to_chunk(self, delta, n_take: Optional[int] = None) -> StreamChunk:
        if n_take is None:
            pad = None
        else:
            # every emitted row sits in the first 2*n_take slots (dirty
            # slots compact to the front); slice before transfer so the
            # device->host copy is O(emitted). Quantized to exactly TWO
            # capacities (small | full) by the shared flush-lane lattice
            # (runtime/bucketing.flush_pad): every DOWNSTREAM device
            # program (device MV step, join step) compiles once per
            # distinct input capacity — pow2 bucketing here caused a
            # recompile (~30s on TPU) on first sight of each bucket,
            # and the fused programs' pads MUST agree with this slicer
            # or the two paths mint disjoint compile sets.
            from risingwave_tpu.runtime.bucketing import flush_pad

            pad = flush_pad(self.out_cap, n_take)
        return delta_to_chunk(
            delta, self.group_keys, self.nullable, self.calls, pad
        )


@partial(jax.jit, static_argnames=("calls",), donate_argnums=(0, 1, 2))
def _fault_in_scatter(table, state, minput, key_lanes, cold, calls):
    """Insert evicted keys back and scatter their FULL durable state
    (accums + emitted snapshots + minput multisets) — byte-identical to
    the pre-eviction slot, before any post-eviction row touches it."""
    n = key_lanes[0].shape[0]
    table, slots, _, _ = lookup_or_insert(
        table, key_lanes, jnp.ones(n, jnp.bool_)
    )
    overflow = jnp.any(slots < 0)
    idx = jnp.where(slots >= 0, slots, table.capacity)
    rc = cold["row_count"].astype(state.row_count.dtype)

    def put(a, lane, cast=True):
        v = cold[lane]
        return a.at[idx].set(
            v.astype(a.dtype) if cast else v, mode="drop"
        )

    new_state = AggState(
        row_count=state.row_count.at[idx].set(rc, mode="drop"),
        accums={
            nm: put(a, f"acc_{nm}") for nm, a in state.accums.items()
        },
        nonnull={
            nm: put(a, f"nn_{nm}") for nm, a in state.nonnull.items()
        },
        emitted={
            nm: put(a, f"em_{nm}") for nm, a in state.emitted.items()
        },
        emitted_isnull={
            nm: put(a, f"ei_{nm}")
            for nm, a in state.emitted_isnull.items()
        },
        emitted_valid=put(state.emitted_valid, "ev"),
        dirty=state.dirty,  # restored groups carry no unflushed change
        minmax_retracted=state.minmax_retracted,
        sdirty=state.sdirty,
        stored=state.stored.at[idx].set(True, mode="drop"),
    )
    table = set_live(table, jnp.where(slots >= 0, slots, -1), rc > 0)
    new_minput = {
        name: (
            v.at[idx].set(
                cold[f"miv_{name}"].astype(v.dtype), mode="drop"
            ),
            c.at[idx].set(
                cold[f"mic_{name}"].astype(c.dtype), mode="drop"
            ),
        )
        for name, (v, c) in minput.items()
    }
    return table, new_state, new_minput, overflow


@partial(jax.jit, static_argnames=("calls",), donate_argnums=(0,))
def _cold_merge(state: AggState, slots, cold, calls):
    """Combine persisted group state into freshly-recreated slots.
    Additive kinds add; extremes min/max in the raw (order-key) lane
    domain; emitted snapshots REPLACE (the fresh slot never emitted)."""
    idx = slots
    row_count = state.row_count.at[idx].add(cold["row_count"])
    accums = dict(state.accums)
    nonnull = dict(state.nonnull)
    for c in calls:
        acc = accums[c.output]
        cv = cold[f"acc_{c.output}"].astype(acc.dtype)
        if c.kind in ("count_star", "count", "sum"):
            accums[c.output] = acc.at[idx].add(cv)
        elif c.kind == "min":
            accums[c.output] = acc.at[idx].min(cv)
        else:
            accums[c.output] = acc.at[idx].max(cv)
        if c.output in nonnull:
            nonnull[c.output] = nonnull[c.output].at[idx].add(
                cold[f"nn_{c.output}"]
            )
    emitted = {
        n: a.at[idx].set(cold[f"em_{n}"].astype(a.dtype))
        for n, a in state.emitted.items()
    }
    emitted_isnull = {
        n: a.at[idx].set(cold[f"ei_{n}"])
        for n, a in state.emitted_isnull.items()
    }
    return AggState(
        row_count=row_count,
        accums=accums,
        nonnull=nonnull,
        emitted=emitted,
        emitted_isnull=emitted_isnull,
        emitted_valid=state.emitted_valid.at[idx].set(cold["ev"]),
        dirty=state.dirty.at[idx].set(True),
        minmax_retracted=state.minmax_retracted,
        sdirty=state.sdirty.at[idx].set(True),
        stored=state.stored.at[idx].set(True),
    )


# -- checkpoint/restore (StateTable integration) -------------------------
@jax.jit
def _mark_checkpointed(state: AggState, upsert, tomb):
    """Flip storage marks after a successful commit: persisted slots
    become stored, tombstoned slots forget their stored bit, and every
    sdirty mark clears (mem_table seal analogue)."""
    return AggState(
        row_count=state.row_count,
        accums=state.accums,
        nonnull=state.nonnull,
        emitted=state.emitted,
        emitted_isnull=state.emitted_isnull,
        emitted_valid=state.emitted_valid,
        dirty=state.dirty,
        minmax_retracted=state.minmax_retracted,
        sdirty=jnp.zeros_like(state.sdirty),
        stored=(state.stored | upsert) & ~tomb,
    )


def _agg_checkpoint_delta(self) -> List[StateDelta]:
    """Stage rows changed since the last checkpoint (device -> host).

    upsert  = sdirty & alive        (new/changed group state)
    tombstone = sdirty & stored & dead  (a persisted group died)
    Rows carry the FULL slot state (accums + emitted snapshots), so
    restore rebuilds byte-identical operator state. Only the selected
    rows cross the device boundary (pull_rows).
    """
    sdirty = np.asarray(self.state.sdirty)
    if not sdirty.any():
        return []
    alive = (
        np.asarray(self.table.live)
        | np.asarray(self.state.emitted_valid)
        | np.asarray(self.state.dirty)
    )
    upsert, tomb, sel = stage_marks(sdirty, alive, np.asarray(self.state.stored))
    lanes = {
        f"k{i}": lane for i, lane in enumerate(self.table.keys)
    }
    key_names = tuple(lanes)
    lanes["row_count"] = self.state.row_count
    for n, a in self.state.accums.items():
        lanes[f"acc_{n}"] = a
        lanes[f"em_{n}"] = self.state.emitted[n]
    for n, a in self.state.nonnull.items():
        lanes[f"nn_{n}"] = a
        lanes[f"ei_{n}"] = self.state.emitted_isnull[n]
    for n, (v, c) in self.minput.items():
        lanes[f"miv_{n}"] = v  # 2D (rows re-land whole)
        lanes[f"mic_{n}"] = c
    lanes["ev"] = self.state.emitted_valid
    pulled = pull_rows(lanes, sel)
    keys = {k: pulled[k] for k in key_names}
    vals = {k: v for k, v in pulled.items() if k not in key_names}
    # eager flip — see StateDelta's durability contract
    self.state = _mark_checkpointed(
        self.state, jnp.asarray(upsert), jnp.asarray(tomb)
    )
    return [
        StateDelta(
            self.table_id,
            keys,
            vals,
            tomb[sel],
            # positional lane order, NOT sorted() ("k10" < "k2" lexically)
            key_names,
        )
    ]


def build_restored_agg(
    cap: int,
    calls,
    dtypes,
    key_dtypes,
    key_cols,
    value_cols,
    minput_k: int = 32,
    sel: Optional[np.ndarray] = None,
):
    """Rebuild (table, state, minput) at capacity ``cap`` from recovered
    rows (optionally the ``sel`` subset — the sharded restore partitions
    rows by vnode and rebuilds each shard with this same core)."""
    if not key_cols:
        idx = np.zeros(0, np.int64)
    elif sel is None:
        idx = np.arange(len(next(iter(key_cols.values()))))
    else:
        idx = np.asarray(sel)
    n = len(idx)
    table = HashTable.create(cap, key_dtypes)
    state = agg_ops.create_state(cap, calls, dtypes)
    minput = mi_ops.create_minput(cap, minput_k, calls, dtypes)
    if not n:
        return table, state, minput
    lanes = tuple(
        jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d)[idx])
        for i, d in enumerate(key_dtypes)
    )
    valid = jnp.ones(n, jnp.bool_)
    table, slots, _, _ = lookup_or_insert(table, lanes, valid)

    def put(dst, src):
        return dst.at[slots].set(jnp.asarray(np.asarray(src)[idx]))

    row_count = put(state.row_count, value_cols["row_count"])
    accums = {
        name: put(a, np.asarray(value_cols[f"acc_{name}"]).astype(a.dtype))
        for name, a in state.accums.items()
    }
    emitted = {
        name: put(a, np.asarray(value_cols[f"em_{name}"]).astype(a.dtype))
        for name, a in state.emitted.items()
    }
    nonnull = {
        name: put(a, value_cols[f"nn_{name}"])
        for name, a in state.nonnull.items()
    }
    e_isnull = {
        name: put(a, value_cols[f"ei_{name}"])
        for name, a in state.emitted_isnull.items()
    }
    emitted_valid = put(state.emitted_valid, value_cols["ev"])
    minput = {
        name: (
            put(v, np.asarray(value_cols[f"miv_{name}"]).astype(v.dtype)),
            put(c, np.asarray(value_cols[f"mic_{name}"]).astype(c.dtype)),
        )
        for name, (v, c) in minput.items()
    }
    stored = state.stored.at[slots].set(True)
    state = AggState(
        row_count=row_count,
        accums=accums,
        nonnull=nonnull,
        emitted=emitted,
        emitted_isnull=e_isnull,
        emitted_valid=emitted_valid,
        dirty=jnp.zeros(cap, jnp.bool_),
        minmax_retracted=jnp.zeros((), jnp.bool_),
        sdirty=jnp.zeros(cap, jnp.bool_),
        stored=stored,
    )
    table = set_live(table, slots, row_count[slots] > 0)
    return table, state, minput


def _agg_restore_state(self, table_id, key_cols, value_cols) -> None:
    """Rebuild device table + state from recovered rows."""
    n = len(next(iter(key_cols.values()))) if key_cols else 0
    key_dtypes = tuple(k.dtype for k in self.table.keys)
    cap = grow_pow2(n, self.table.capacity, GROW_AT)
    self.table, self.state, self.minput = build_restored_agg(
        cap,
        self.calls,
        self._dtypes,
        key_dtypes,
        key_cols,
        value_cols,
        self.minput_k,
    )
    self.dropped = jnp.zeros((), jnp.bool_)
    self.mi_bad = jnp.zeros((), jnp.bool_)
    self._insert_bound = int(n)
    self._dirty_bound = 0  # restored groups carry no unflushed change
    # recovery restored every durable group as RESIDENT state
    self._evicted = set()


def _agg_digest_lanes(self):
    from risingwave_tpu.integrity import agg_lanes

    return agg_lanes(self.table, self.state)


def _agg_state_digest(self) -> int:
    """Host twin of the fused digest lane (integrity.agg_lanes fold)."""
    from risingwave_tpu.integrity import host_digest

    lanes, live = _agg_digest_lanes(self)
    return host_digest(lanes, live)


HashAggExecutor.checkpoint_delta = _agg_checkpoint_delta
HashAggExecutor.restore_state = _agg_restore_state
HashAggExecutor.digest_lanes = _agg_digest_lanes
HashAggExecutor.state_digest = _agg_state_digest
