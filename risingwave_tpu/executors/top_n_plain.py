"""Plain TopN — retractable ORDER BY ... LIMIT n maintenance.

Reference: src/stream/src/executor/top_n/top_n_plain.rs:77 — keeps all
input rows in a state table ordered by (order key, pk) and emits
deltas so downstream always holds exactly the current top n.

TPU re-design: the row store is a pk-keyed slot table (HashTable +
one lane per column); inserts/deletes are one fused scatter step per
chunk. The barrier ranks live rows ON DEVICE (ordered-float/int total
order + pk tiebreak via lexsort), pulls only the top n rows, and
diffs them against the host mirror of the previously-emitted top n —
so per-barrier host traffic is O(n), not O(state).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.ops.hash_table import (
    HashTable,
    lookup_or_insert,
    set_live,
)
from risingwave_tpu.runtime.bucketing import (
    BucketAllocator,
    BucketPolicy,
    emission_bucket,
    lattice_between,
    needs_plan,
    plan_capacity,
    pow2_at_least,
)
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    grow_pow2,
    pull_rows,
    stage_marks,
)
from risingwave_tpu.types import Op

GROW_AT = 0.5


@partial(jax.jit, static_argnames=("pk", "names"), donate_argnums=(0, 1, 2))
def _upsert_step(table, rows, sdirty, chunk: StreamChunk, pk, names):
    keys = tuple(chunk.col(k) for k in pk)
    signs = chunk.effective_signs()
    active = chunk.valid & (signs != 0)
    table, slots, _, _ = lookup_or_insert(table, keys, active)
    dropped = jnp.any(active & (slots < 0))
    idx = jnp.where(active, slots, table.capacity)
    rows = {
        n: rows[n].at[idx].set(chunk.col(n), mode="drop") for n in names
    }
    table = set_live(table, jnp.where(active, slots, -1), signs > 0)
    sdirty = sdirty.at[idx].set(True, mode="drop")
    return table, rows, sdirty, dropped


def _order_key_u64(v, desc: bool):
    """Map an order lane to an unsigned memcomparable key (the same
    transform the SST sort uses) so int/float/asc/desc all reduce to
    one uint64 comparison."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        from risingwave_tpu.ops.agg import _float_to_order_key

        key = _float_to_order_key(v).astype(jnp.uint64)
    elif jnp.issubdtype(v.dtype, jnp.unsignedinteger):
        key = v.astype(jnp.uint64)
    else:
        key = jax.lax.bitcast_convert_type(
            v.astype(jnp.int64), jnp.uint64
        ) ^ (jnp.uint64(1) << jnp.uint64(63))
    return ~key if desc else key


@partial(jax.jit, static_argnames=("n", "desc"))
def _rank_top(table: HashTable, order_lane, n: int, desc: bool):
    """Indices of the top-n live rows by (order, pk-lanes) total order.
    Liveness is its own LEADING sort key: a dead-row sentinel value
    would collide with a legitimate INT64 extreme order value and let
    dead slots displace live rows."""
    live_last = (~table.live).astype(jnp.int32)
    key = _order_key_u64(order_lane, desc)
    sort_ops = jax.lax.sort(
        (live_last, key) + tuple(k for k in table.keys)
        + (jnp.arange(table.capacity, dtype=jnp.int32),),
        num_keys=2 + len(table.keys),
    )
    idx = sort_ops[-1][:n]
    alive = table.live[idx]
    return idx, alive


class TopNExecutor(Executor, Checkpointable):
    """ORDER BY order_col [DESC] LIMIT n with full retraction support."""

    def __init__(
        self,
        order_col: str,
        limit: int,
        pk: Sequence[str],
        schema_dtypes: Dict[str, object],
        desc: bool = False,
        capacity: int = 1 << 14,
        table_id: str = "top_n",
        bucket_policy: Optional[BucketPolicy] = None,
        bucketed: bool = True,
    ):
        self._buckets = (
            BucketAllocator(
                bucket_policy or BucketPolicy.from_capacity(capacity, grow_at=GROW_AT)
            )
            if bucketed
            else None
        )
        self.order_col = order_col
        self.limit = int(limit)
        self.desc = desc
        self.pk = tuple(pk)
        self.names = tuple(sorted(schema_dtypes))
        self._dtypes = {n: jnp.dtype(schema_dtypes[n]) for n in self.names}
        self.table = HashTable.create(
            capacity, tuple(self._dtypes[k] for k in self.pk)
        )
        self.rows = {
            n: jnp.zeros(capacity, self._dtypes[n]) for n in self.names
        }
        self.sdirty = jnp.zeros(capacity, jnp.bool_)
        self.stored = jnp.zeros(capacity, jnp.bool_)
        self.table_id = table_id
        self._bound = 0
        self._dropped = jnp.zeros((), jnp.bool_)
        self._emitted: Dict[Tuple, Tuple] = {}  # pk -> full row

    def lint_info(self):
        return {
            "expects": dict(self._dtypes),
            "emits": dict(self._dtypes),
            "renames": {n: n for n in self.names},
            "state_pk": tuple(self.pk),
            "table_ids": (self.table_id,),
        }

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: _upsert_step(
                self.table, self.rows, self.sdirty, c, self.pk, self.names
            ),
            "state": (self.table, self.rows),
            "donate": True,
            # the barrier diff against the host mirror now pads its
            # emissions to pow2 buckets (<= limit rows per op chunk):
            # a declared, closed capacity set instead of one shape per
            # distinct delta count (data_dependent on the legacy twin)
            **(
                {
                    "emission": "bucketed",
                    "emission_caps": lattice_between(
                        2, pow2_at_least(max(self.limit, 2))
                    ),
                }
                if self._buckets is not None
                else {"emission": "data_dependent"}
            ),
        }

    def pin_max_bucket(self):
        """ShapeGovernor hook: freeze the row store at its high-water
        bucket (shrink disabled)."""
        if self._buckets is None:
            return {"pinned": False}
        return {
            "table_id": self.table_id,
            "pinned_cap": self._buckets.pin(),
        }

    def padding_stats(self):
        return {
            "capacity": self.table.capacity,
            "live": int(self.table.num_live()),
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        for k in self.pk + (self.order_col,):
            if k in chunk.nulls:
                raise ValueError(f"TopN key column {k!r} cannot be NULL")
        self._maybe_grow(chunk.capacity)
        self._bound += chunk.capacity
        self.table, self.rows, self.sdirty, dropped = _upsert_step(
            self.table, self.rows, self.sdirty, chunk, self.pk, self.names
        )
        self._dropped = self._dropped | dropped
        return []

    def _maybe_grow(self, incoming: int):
        cap = self.table.capacity
        if not needs_plan(self._buckets, cap, self._bound, incoming, GROW_AT):
            return
        claimed = int(self.table.occupancy())
        survivors = int(
            jnp.sum((self.table.live | self.sdirty).astype(jnp.int32))
        )
        new_cap = plan_capacity(
            self._buckets, cap, incoming, claimed, survivors, GROW_AT
        )
        if new_cap is not None:
            keep = self.table.live | self.sdirty
            new = HashTable.create(
                new_cap, tuple(k.dtype for k in self.table.keys)
            )
            new, slots, _, _ = lookup_or_insert(new, self.table.keys, keep)
            new = set_live(new, jnp.where(keep, slots, -1), self.table.live)
            idx = jnp.where(keep, slots, new_cap)

            def move(a, init_dtype):
                return (
                    jnp.zeros(new_cap, init_dtype)
                    .at[idx]
                    .set(a, mode="drop")
                )

            self.rows = {
                n: move(a, a.dtype) for n, a in self.rows.items()
            }
            self.sdirty = move(self.sdirty, jnp.bool_)
            self.stored = move(self.stored, jnp.bool_)
            self.table = new
            claimed = int(self.table.occupancy())
        self._bound = claimed

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if self._buckets is not None:
            self._buckets.note_barrier(self.table.capacity, self._bound)
        if bool(self._dropped):
            raise RuntimeError("TopN row store overflowed; grow capacity")
        idx, alive = _rank_top(
            self.table, self.rows[self.order_col], self.limit, self.desc
        )
        # pull exactly n rows (one packed gather)
        lanes = {n: self.rows[n][idx] for n in self.names}
        lanes["__alive__"] = alive
        pulled = {k: np.asarray(v) for k, v in lanes.items()}
        top: Dict[Tuple, Tuple] = {}
        for i in range(self.limit):
            if not pulled["__alive__"][i]:
                break  # dead rows rank last: first dead = end of live
            pkv = tuple(pulled[k][i].item() for k in self.pk)
            top[pkv] = tuple(pulled[n][i].item() for n in self.names)
        outs = []
        dels = [v for k, v in self._emitted.items() if top.get(k) != v]
        ins = [v for k, v in top.items() if self._emitted.get(k) != v]
        for vals, op in ((dels, Op.DELETE), (ins, Op.INSERT)):
            if not vals:
                continue
            cols = {
                n: np.asarray([r[j] for r in vals], self._dtypes[n])
                for j, n in enumerate(self.names)
            }
            outs.append(
                StreamChunk.from_numpy(
                    cols,
                    # pow2-padded emission: a closed downstream shape set
                    emission_bucket(len(vals))
                    if self._buckets is not None
                    else max(2, len(vals)),
                    ops=np.full(len(vals), int(op), np.int32),
                )
            )
        self._emitted = top
        return outs

    # -- integrity --------------------------------------------------------
    def digest_lanes(self):
        lanes = {f"k{i}": k for i, k in enumerate(self.table.keys)}
        for n in self.names:
            lanes[f"r_{n}"] = self.rows[n]
        return lanes, self.table.live

    def state_digest(self) -> int:
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    # -- checkpoint -------------------------------------------------------
    def checkpoint_delta(self) -> List[StateDelta]:
        sdirty = np.asarray(self.sdirty)
        if not sdirty.any():
            return []
        upsert, tomb, sel = stage_marks(
            sdirty, np.asarray(self.table.live), np.asarray(self.stored)
        )
        lanes = {f"k{i}": lane for i, lane in enumerate(self.table.keys)}
        key_names = tuple(lanes)
        for n in self.names:
            lanes[f"r_{n}"] = self.rows[n]
        pulled = pull_rows(lanes, sel)
        keys = {k: pulled[k] for k in key_names}
        vals = {k: v for k, v in pulled.items() if k not in key_names}
        self.stored = (self.stored | jnp.asarray(upsert)) & ~jnp.asarray(tomb)
        self.sdirty = jnp.zeros_like(self.sdirty)
        return [StateDelta(self.table_id, keys, vals, tomb[sel], key_names)]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        cap = grow_pow2(n, self.table.capacity, GROW_AT)
        key_dtypes = tuple(k.dtype for k in self.table.keys)
        table = HashTable.create(cap, key_dtypes)
        rows = {nm: jnp.zeros(cap, self._dtypes[nm]) for nm in self.names}
        self.sdirty = jnp.zeros(cap, jnp.bool_)
        self.stored = jnp.zeros(cap, jnp.bool_)
        if n:
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d))
                for i, d in enumerate(key_dtypes)
            )
            table, slots, _, _ = lookup_or_insert(
                table, lanes, jnp.ones(n, jnp.bool_)
            )
            table = set_live(table, slots, True)
            rows = {
                nm: a.at[slots].set(
                    jnp.asarray(
                        np.asarray(value_cols[f"r_{nm}"]).astype(a.dtype)
                    )
                )
                for nm, a in rows.items()
            }
            self.stored = self.stored.at[slots].set(True)
        self.table = table
        self.rows = rows
        self._bound = int(n)
        self._dropped = jnp.zeros((), jnp.bool_)
        # downstream MV was restored consistently; recompute its view
        idx, alive = _rank_top(
            table, rows[self.order_col], self.limit, self.desc
        )
        pulled = {nm: np.asarray(rows[nm][idx]) for nm in self.names}
        al = np.asarray(alive)
        self._emitted = {}
        for i in range(self.limit):
            if not al[i]:
                break
            pkv = tuple(pulled[k][i].item() for k in self.pk)
            self._emitted[pkv] = tuple(
                pulled[nm][i].item() for nm in self.names
            )


# ---------------------------------------------------------------------------
# Retractable GroupTopN
# ---------------------------------------------------------------------------


@partial(
    jax.jit, static_argnames=("pk", "names"), donate_argnums=(0, 1, 2, 3)
)
def _upsert_step_ed(table, rows, sdirty, epoch_dirty, chunk, pk, names):
    """_upsert_step that also marks epoch_dirty (cleared per barrier)
    in the same scatter — one probe, two mark lanes."""
    keys = tuple(chunk.col(k) for k in pk)
    signs = chunk.effective_signs()
    active = chunk.valid & (signs != 0)
    table, slots, _, _ = lookup_or_insert(table, keys, active)
    dropped = jnp.any(active & (slots < 0))
    idx = jnp.where(active, slots, table.capacity)
    rows = {
        n: rows[n].at[idx].set(chunk.col(n), mode="drop") for n in names
    }
    table = set_live(table, jnp.where(active, slots, -1), signs > 0)
    sdirty = sdirty.at[idx].set(True, mode="drop")
    epoch_dirty = epoch_dirty.at[idx].set(True, mode="drop")
    return table, rows, sdirty, epoch_dirty, dropped


@partial(
    jax.jit,
    static_argnames=("k", "desc", "group_names", "order_col"),
    donate_argnums=(),
)
def _group_topk_mask(
    table: HashTable,
    rows: Dict[str, jnp.ndarray],
    epoch_dirty: jnp.ndarray,
    k: int,
    desc: bool,
    group_names: Tuple[str, ...],
    order_col: str,
):
    """Per-slot masks: is the row in its group's current top-k, and
    does its group contain an epoch-dirty row (so its top-k must be
    re-pulled)? One device sort over (group lanes, order key, pk)."""
    cap = table.capacity
    # liveness as its own sort key within the group (a dead-row
    # sentinel would collide with INT64-extreme order values)
    live_last = (~table.live).astype(jnp.int32)
    okey = _order_key_u64(rows[order_col], desc)
    glanes = tuple(rows[g] for g in group_names)
    sort_in = glanes + (live_last, okey) + tuple(table.keys) + (
        jnp.arange(cap, dtype=jnp.int32),
    )
    sorted_all = jax.lax.sort(
        sort_in, num_keys=len(glanes) + 2 + len(table.keys)
    )
    slot_s = sorted_all[-1]
    live_s = table.live[slot_s]
    dirty_s = epoch_dirty[slot_s]
    boundary = jnp.zeros(cap, jnp.bool_).at[0].set(True)
    for lane in sorted_all[: len(glanes)]:
        boundary = boundary | jnp.concatenate(
            [jnp.ones(1, jnp.bool_), lane[1:] != lane[:-1]]
        )
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    idx = jnp.arange(cap, dtype=jnp.int32)
    seg_start = jax.ops.segment_max(
        jnp.where(boundary, idx, 0), gid, num_segments=cap
    )[gid]
    in_topk_s = live_s & ((idx - seg_start) < k)
    gdirty_s = (
        jax.ops.segment_max(
            dirty_s.astype(jnp.int32), gid, num_segments=cap
        )[gid]
        > 0
    )
    in_topk = jnp.zeros(cap, jnp.bool_).at[slot_s].set(in_topk_s)
    gdirty = jnp.zeros(cap, jnp.bool_).at[slot_s].set(gdirty_s)
    return in_topk, gdirty


def _diff_touched_groups(
    table, rows, in_topk, epoch_dirty, group_by, pk, names, gdirty,
    emitted,
):
    """Pull touched groups' top-k (+ the epoch-dirty rows naming
    fully-emptied groups) and diff against the host mirror of what was
    emitted; updates ``emitted`` in place. Shared by the single-chip
    and the sharded executor (one shard = one call over its slices)."""
    mask = np.asarray((gdirty & in_topk) | epoch_dirty)
    sel = np.flatnonzero(mask)
    lanes = {n: rows[n] for n in names}
    lanes["__topk__"] = in_topk
    lanes["__live__"] = table.live
    pulled = pull_rows(lanes, sel)
    new_top: Dict[Tuple, Dict[Tuple, Tuple]] = {}
    changed: set = set()
    for i in range(len(sel)):
        g = tuple(pulled[c][i].item() for c in group_by)
        changed.add(g)
        if pulled["__topk__"][i] and pulled["__live__"][i]:
            pkv = tuple(pulled[c][i].item() for c in pk)
            new_top.setdefault(g, {})[pkv] = tuple(
                pulled[n][i].item() for n in names
            )
    dels, ins = [], []
    for g in changed:
        old = emitted.get(g, {})
        new = new_top.get(g, {})
        dels.extend(v for p, v in old.items() if new.get(p) != v)
        ins.extend(v for p, v in new.items() if old.get(p) != v)
        if new:
            emitted[g] = new
        else:
            emitted.pop(g, None)
    return dels, ins


def _emit_diffs(dels, ins, names, dtypes, bucketed=True) -> List[StreamChunk]:
    outs = []
    for vals, op in ((dels, Op.DELETE), (ins, Op.INSERT)):
        if not vals:
            continue
        cols = {
            n: np.asarray([r[j] for r in vals], dtypes[n])
            for j, n in enumerate(names)
        }
        outs.append(
            StreamChunk.from_numpy(
                cols,
                # pow2-padded emission (masked lanes): downstream sees
                # a log-bounded capacity set, not one per delta count;
                # the bucketed=False twin keeps the legacy max(2, n)
                # shape per distinct count (RW-E803 baseline behavior)
                emission_bucket(len(vals))
                if bucketed
                else max(2, len(vals)),
                ops=np.full(len(vals), int(op), np.int32),
            )
        )
    return outs


class RetractableGroupTopNExecutor(Executor, Checkpointable):
    """GROUP BY g ORDER BY o LIMIT k with full retraction support
    (group_top_n.rs:63): deletes/updates crossing a group's top-k
    boundary re-emit the displaced/promoted rows exactly.

    TPU re-design: ONE pk-keyed row store holds every input row; the
    barrier ranks rows within groups on device (one fused sort +
    segmented scan), pulls only the top-k rows of groups TOUCHED this
    epoch, and diffs them against a per-group host mirror of what was
    emitted — per-barrier host traffic is O(changed groups x k), never
    O(state)."""

    def __init__(
        self,
        group_by: Sequence[str],
        order_col: str,
        limit: int,
        pk: Sequence[str],
        schema_dtypes: Dict[str, object],
        desc: bool = False,
        capacity: int = 1 << 14,
        window_key: Optional[Tuple[str, int]] = None,
        table_id: str = "group_top_n",
        bucket_policy: Optional[BucketPolicy] = None,
        bucketed: bool = True,
    ):
        self._buckets = (
            BucketAllocator(
                bucket_policy or BucketPolicy.from_capacity(capacity, grow_at=GROW_AT)
            )
            if bucketed
            else None
        )
        self.group_by = tuple(group_by)
        self.order_col = order_col
        self.limit = int(limit)
        self.desc = desc
        self.pk = tuple(pk)
        # row identity INCLUDES the group (group_top_n.rs keys state by
        # group key + pk): a row "moving" groups is two distinct rows,
        # so the old group's retraction is never lost
        self.store_keys = self.group_by + tuple(
            c for c in self.pk if c not in self.group_by
        )
        self.names = tuple(sorted(schema_dtypes))
        self._dtypes = {n: jnp.dtype(schema_dtypes[n]) for n in self.names}
        self.table = HashTable.create(
            capacity, tuple(self._dtypes[c] for c in self.store_keys)
        )
        self.rows = {
            n: jnp.zeros(capacity, self._dtypes[n]) for n in self.names
        }
        self.sdirty = jnp.zeros(capacity, jnp.bool_)
        self.stored = jnp.zeros(capacity, jnp.bool_)
        self.epoch_dirty = jnp.zeros(capacity, jnp.bool_)
        if window_key is not None and window_key[0] not in self.group_by:
            raise ValueError(
                "window_key must be one of the group columns (a closed "
                "window bounds its groups)"
            )
        self.window_key = window_key
        self.table_id = table_id
        self._bound = 0
        self._dropped = jnp.zeros((), jnp.bool_)
        # group tuple -> {pk tuple -> full row tuple} of EMITTED rows
        self._emitted: Dict[Tuple, Dict[Tuple, Tuple]] = {}

    def lint_info(self):
        return {
            "expects": dict(self._dtypes),
            "emits": dict(self._dtypes),
            "renames": {n: n for n in self.names},
            "keys": self.group_by,
            "state_pk": tuple(self.store_keys),
            "table_ids": (self.table_id,),
            "window_key": self.window_key[0] if self.window_key else None,
        }

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: _upsert_step_ed(
                self.table,
                self.rows,
                self.sdirty,
                self.epoch_dirty,
                c,
                self.store_keys,
                self.names,
            ),
            "state": (self.table, self.rows),
            "donate": True,
            # the barrier ranks on device but diffs against a host
            # mirror; emissions are pow2-padded (bucketed) and the row
            # store walks the allocator's declared lattice (legacy
            # data_dependent/None only on the unbucketed twin)
            **(
                {
                    "emission": "bucketed",
                    "emission_caps": lattice_between(
                        2, self._buckets.policy.max_cap
                    ),
                    "window_buckets": self._buckets.lattice,
                }
                if self._buckets is not None
                else {
                    "emission": "data_dependent",
                    "window_buckets": None,
                }
            ),
        }

    def pin_max_bucket(self):
        """ShapeGovernor hook: freeze the row store at its high-water
        bucket (shrink disabled)."""
        if self._buckets is None:
            return {"pinned": False}
        return {
            "table_id": self.table_id,
            "pinned_cap": self._buckets.pin(),
        }

    def padding_stats(self):
        return {
            "capacity": self.table.capacity,
            "live": int(self.table.num_live()),
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        for c in self.pk + self.group_by + (self.order_col,):
            if c in chunk.nulls:
                raise ValueError(f"GroupTopN key column {c!r} cannot be NULL")
        self._maybe_grow(chunk.capacity)
        self._bound += chunk.capacity
        (
            self.table,
            self.rows,
            self.sdirty,
            self.epoch_dirty,
            dropped,
        ) = _upsert_step_ed(
            self.table,
            self.rows,
            self.sdirty,
            self.epoch_dirty,
            chunk,
            self.store_keys,
            self.names,
        )
        self._dropped = self._dropped | dropped
        return []

    def _maybe_grow(self, incoming: int):
        cap = self.table.capacity
        if not needs_plan(self._buckets, cap, self._bound, incoming, GROW_AT):
            return
        from risingwave_tpu.ops.hash_table import read_scalars

        claimed, survivors = read_scalars(
            self.table.occupancy(),
            jnp.sum((self.table.live | self.sdirty).astype(jnp.int32)),
        )
        new_cap = plan_capacity(
            self._buckets, cap, incoming, claimed, survivors, GROW_AT
        )
        if new_cap is not None:
            keep = self.table.live | self.sdirty
            new = HashTable.create(
                new_cap, tuple(x.dtype for x in self.table.keys)
            )
            new, slots, _, _ = lookup_or_insert(new, self.table.keys, keep)
            new = set_live(new, jnp.where(keep, slots, -1), self.table.live)
            idx = jnp.where(keep, slots, new_cap)

            def move(a):
                return (
                    jnp.zeros(new_cap, a.dtype).at[idx].set(a, mode="drop")
                )

            self.rows = {n: move(a) for n, a in self.rows.items()}
            self.sdirty = move(self.sdirty)
            self.stored = move(self.stored)
            self.epoch_dirty = move(self.epoch_dirty)
            self.table = new
            claimed = int(self.table.occupancy())
        self._bound = claimed

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        from risingwave_tpu.ops.hash_table import read_scalars

        # ONE packed read for the latch + the dirty short-circuit +
        # occupancy (tunneled-TPU round-trips dominate)
        dropped, any_dirty, claimed = read_scalars(
            self._dropped, jnp.any(self.epoch_dirty), self.table.occupancy()
        )
        self._bound = int(claimed)
        if self._buckets is not None:
            self._buckets.note_barrier(self.table.capacity, int(claimed))
        if dropped:
            raise RuntimeError("GroupTopN row store overflowed; grow capacity")
        if not any_dirty:
            return []
        in_topk, gdirty = _group_topk_mask(
            self.table,
            self.rows,
            self.epoch_dirty,
            self.limit,
            self.desc,
            self.group_by,
            self.order_col,
        )
        # pull the top-k of touched groups PLUS the epoch-dirty rows
        # themselves (deleted rows name fully-emptied groups)
        dels, ins = _diff_touched_groups(
            self.table, self.rows, in_topk, self.epoch_dirty,
            self.group_by, self.pk, self.names, gdirty, self._emitted,
        )
        self.epoch_dirty = jnp.zeros_like(self.epoch_dirty)
        return _emit_diffs(
            dels,
            ins,
            self.names,
            self._dtypes,
            bucketed=self._buckets is not None,
        )

    def on_watermark(self, watermark):
        """Window-bounded groups expire silently below the watermark
        (EOWC-final: the MV keeps the closed window's final top-k)."""
        if self.window_key is None or watermark.column != self.window_key[0]:
            return watermark, []
        cutoff = jnp.asarray(
            watermark.value - self.window_key[1], jnp.int64
        )
        lane = self.rows[self.window_key[0]]
        expired = self.table.live & (lane < cutoff)
        slots = jnp.where(
            expired, jnp.arange(self.table.capacity, dtype=jnp.int32), -1
        )
        self.table = set_live(self.table, slots, False)
        self.sdirty = self.sdirty | expired
        # closed groups leave the mirror without emitting retractions
        gi = self.group_by.index(self.window_key[0])
        cut = int(watermark.value - self.window_key[1])
        for g in [g for g in self._emitted if g[gi] < cut]:
            del self._emitted[g]
        return watermark, []

    # -- checkpoint/restore (pk-keyed row store, plain-TopN layout) -------
    def digest_lanes(self):
        lanes = {f"k{i}": k for i, k in enumerate(self.table.keys)}
        for n in self.names:
            lanes[f"r_{n}"] = self.rows[n]
        return lanes, self.table.live

    def state_digest(self) -> int:
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    def checkpoint_delta(self) -> List[StateDelta]:
        sdirty = np.asarray(self.sdirty)
        if not sdirty.any():
            return []
        upsert, tomb, sel = stage_marks(
            sdirty, np.asarray(self.table.live), np.asarray(self.stored)
        )
        lanes = {f"k{i}": lane for i, lane in enumerate(self.table.keys)}
        key_names = tuple(lanes)
        for n in self.names:
            lanes[f"r_{n}"] = self.rows[n]
        pulled = pull_rows(lanes, sel)
        keys = {x: pulled[x] for x in key_names}
        vals = {x: v for x, v in pulled.items() if x not in key_names}
        self.stored = (self.stored | jnp.asarray(upsert)) & ~jnp.asarray(tomb)
        self.sdirty = jnp.zeros_like(self.sdirty)
        return [StateDelta(self.table_id, keys, vals, tomb[sel], key_names)]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        cap = grow_pow2(n, self.table.capacity, GROW_AT)
        key_dtypes = tuple(x.dtype for x in self.table.keys)
        table = HashTable.create(cap, key_dtypes)
        rows = {nm: jnp.zeros(cap, self._dtypes[nm]) for nm in self.names}
        self.sdirty = jnp.zeros(cap, jnp.bool_)
        self.stored = jnp.zeros(cap, jnp.bool_)
        self.epoch_dirty = jnp.zeros(cap, jnp.bool_)
        if n:
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d))
                for i, d in enumerate(key_dtypes)
            )
            table, slots, _, _ = lookup_or_insert(
                table, lanes, jnp.ones(n, jnp.bool_)
            )
            table = set_live(table, slots, True)
            rows = {
                nm: a.at[slots].set(
                    jnp.asarray(
                        np.asarray(value_cols[f"r_{nm}"]).astype(a.dtype)
                    )
                )
                for nm, a in rows.items()
            }
            self.stored = self.stored.at[slots].set(True)
        self.table = table
        self.rows = rows
        self._bound = int(n)
        self._dropped = jnp.zeros((), jnp.bool_)
        # rebuild the emitted mirror: every group's current top-k (the
        # downstream MV restored to exactly this view)
        self._emitted = {}
        if n:
            in_topk, _ = _group_topk_mask(
                self.table,
                self.rows,
                jnp.ones(cap, jnp.bool_),
                self.limit,
                self.desc,
                self.group_by,
                self.order_col,
            )
            sel = np.flatnonzero(np.asarray(in_topk))
            pulled = pull_rows(
                {nm: self.rows[nm] for nm in self.names}, sel
            )
            for i in range(len(sel)):
                g = tuple(pulled[c][i].item() for c in self.group_by)
                pkv = tuple(pulled[c][i].item() for c in self.pk)
                self._emitted.setdefault(g, {})[pkv] = tuple(
                    pulled[nm][i].item() for nm in self.names
                )
