"""Filter executor — predicate over visibility, zero data movement.

Reference: src/stream/src/executor/filter.rs (234 LoC). The reference
also downgrades broken UpdateDelete/UpdateInsert pairs (where only one
half passes) to plain Delete/Insert; with columnar ops that is a pure
elementwise op-lane rewrite, done here in the same fused step.
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.expr import Expr
from risingwave_tpu.expr.expr import StaticTree
from risingwave_tpu.types import Op


@partial(jax.jit, static_argnames=("pred",))
def _filter_step(chunk: StreamChunk, pred: "StaticTree") -> StreamChunk:
    # pred rides as a STRUCTURALLY-keyed static: a bare Expr static
    # collides in the jit cache (Expr.__eq__ builds a truthy BinOp)
    keep_v, keep_n = pred.value.eval(chunk)
    keep = keep_v.astype(jnp.bool_)
    if keep_n is not None:
        keep = keep & ~keep_n  # NULL predicate drops the row (SQL WHERE)
    out = chunk.mask(keep)

    # Fix torn update pairs: U- at row i pairs with U+ at row i+1 (chunk
    # construction invariant, stream_chunk.rs:45). If exactly one half
    # survives, downgrade it to a plain Delete/Insert.
    ops = out.ops
    is_ud = ops == Op.UPDATE_DELETE
    is_ui = ops == Op.UPDATE_INSERT
    partner_alive_for_ud = jnp.roll(out.valid, -1) & jnp.roll(is_ui, -1)
    partner_alive_for_ui = jnp.roll(out.valid, 1) & jnp.roll(is_ud, 1)
    new_ops = jnp.where(
        is_ud & out.valid & ~partner_alive_for_ud, jnp.int32(Op.DELETE), ops
    )
    new_ops = jnp.where(
        is_ui & out.valid & ~partner_alive_for_ui, jnp.int32(Op.INSERT), new_ops
    )
    return StreamChunk(out.columns, out.valid, out.nulls, new_ops)


class FilterExecutor(Executor):
    def __init__(self, pred: Expr):
        self._spred = StaticTree(pred)
        self.pred = pred

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        return [_filter_step(chunk, self._spred)]

    def lint_info(self):
        from risingwave_tpu.expr.expr import collect_columns

        return {"requires": tuple(sorted(collect_columns(self.pred)))}

    def pure_step(self):
        # the fused-chain contract (runtime/fused_step + epoch_batch):
        # a module-level partial with hashable bound args, so the predicate
        # traces into the fused per-barrier program and compiles once
        # per plan shape, not once per executor instance
        return partial(_filter_step, pred=self._spred)
