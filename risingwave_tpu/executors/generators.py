"""Generator executors: VALUES and NOW().

Reference:
- src/stream/src/executor/values.rs — emits a literal row set exactly
  once (the first barrier after creation), then only barriers;
- src/stream/src/executor/now.rs — maintains a single row holding the
  current barrier timestamp, updated with U-/U+ per epoch (drives
  temporal filters like `ts > NOW() - INTERVAL ...`).

Both are control-plane-paced (rows appear at barriers, not between),
which is exactly how the host epoch loop drives executors here.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.types import Op


class ValuesExecutor(Executor):
    """Emit a fixed row set once, at the first barrier."""

    def __init__(self, columns: Dict[str, np.ndarray], row_id_col: str = "_row_id"):
        n = len(next(iter(columns.values()))) if columns else 0
        self._cols = {k: np.asarray(v) for k, v in columns.items()}
        self._cols[row_id_col] = np.arange(n, dtype=np.int64)
        self._emitted = False

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        raise TypeError("ValuesExecutor is a source; nothing flows into it")

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if self._emitted:
            return []
        self._emitted = True
        n = len(next(iter(self._cols.values())))
        cap = max(2, 1 << (max(1, n) - 1).bit_length())
        return [StreamChunk.from_numpy(self._cols, cap)]


class NowExecutor(Executor):
    """One row carrying the barrier's timestamp, U-/U+ per epoch."""

    def __init__(self, out_col: str = "now"):
        self.out_col = out_col
        self._last: Optional[int] = None

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        raise TypeError("NowExecutor is a source; nothing flows into it")

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        # epoch encodes physical ms << 16 (epoch.rs:36)
        now_ms = barrier.epoch.curr >> 16
        if self._last == now_ms:
            return []
        if self._last is None:
            ops = np.asarray([Op.INSERT], np.int32)
            vals = [now_ms]
        else:
            ops = np.asarray([Op.UPDATE_DELETE, Op.UPDATE_INSERT], np.int32)
            vals = [self._last, now_ms]
        self._last = now_ms
        return [
            StreamChunk.from_numpy(
                {self.out_col: np.asarray(vals, np.int64)}, 2, ops=ops
            )
        ]
