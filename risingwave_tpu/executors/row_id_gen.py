"""RowIdGen executor — hidden serial pk for pk-less streams.

Reference: src/stream/src/executor/row_id_gen.rs — assigns a serial
row id per vnode so append-only tables without a user pk still have a
stable one. Here: ids are ``base + lane`` per chunk with a host-side
base counter. The counter CHECKPOINTS (the reference persists row-id
state the same way): a recovered pipeline continues the id sequence
instead of colliding with restored MV pks.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.storage.state_table import Checkpointable, StateDelta


class RowIdGenExecutor(Executor, Checkpointable):
    def __init__(self, out_col: str = "_row_id", table_id: str = "row_id_gen"):
        self.out_col = out_col
        self.table_id = table_id
        self._base = 0
        self._committed = -1

    def lint_info(self):
        import jax.numpy as jnp

        return {
            "adds": {self.out_col: jnp.int64},
            "table_ids": (self.table_id,),
        }

    def state_nbytes(self) -> int:
        """Memory-ledger contract: the only state is two host
        counters — no device bytes beyond the bookkeeping."""
        return 16

    def trace_contract(self):
        return {
            "kind": "device",
            # same math as apply with the host counter as a traced
            # zero-d base — the counter is trivially convertible to
            # carried device state in a fused step
            "trace_step": lambda c: c.with_columns(
                **{
                    self.out_col: jnp.zeros((), jnp.int64)
                    + jnp.arange(c.capacity, dtype=jnp.int64)
                }
            ),
            "state": None,
            "donate": True,
            "emission": "passthrough",
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        if self.out_col in chunk.columns:
            # DML deletes/updates address existing rows BY id — never
            # reassign (reference row_id_gen.rs only fills fresh
            # inserts; deletes carry the stored row)
            return [chunk]
        ids = self._base + jnp.arange(chunk.capacity, dtype=jnp.int64)
        self._base += chunk.capacity
        return [chunk.with_columns(**{self.out_col: ids})]

    # -- integrity --------------------------------------------------------
    def state_digest(self) -> int:
        """Durable logical state is the id watermark (one counter)."""
        from risingwave_tpu.integrity import host_obj_digest

        return host_obj_digest({"base": int(self._base)})

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self) -> List[StateDelta]:
        if self._base == self._committed:
            return []
        self._committed = self._base
        return [
            StateDelta(
                self.table_id,
                {"k": np.zeros(1, np.int64)},
                {"base": np.asarray([self._base], np.int64)},
                np.zeros(1, bool),
                ("k",),
            )
        ]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        if key_cols:
            self._base = int(value_cols["base"][0])
            self._committed = self._base
