"""TroublemakerExecutor — deliberate stream corruption for chaos tests.

Reference: src/stream/src/executor/troublemaker.rs:28 — an executor
inserted into test graphs that randomly corrupts the message stream
("insane mode"), proving the surrounding sanity machinery (update
checks, consistency latches, differential stores) actually catches
inconsistencies rather than silently absorbing them.

Seeded + host-side (corruption is a TEST construct; no device work):
each chunk may have a value lane perturbed, an op flipped, or a row
duplicated. The `log` records every injected fault so a test can
assert detection maps 1:1 to injection.
"""

from __future__ import annotations

import random
from typing import List, Tuple

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.types import Op


class TroublemakerExecutor(Executor):
    def __init__(
        self,
        seed: int = 0,
        rate: float = 0.5,
        modes: Tuple[str, ...] = ("corrupt_value", "flip_op", "dup_row"),
    ):
        self.rng = random.Random(seed)
        self.rate = rate
        self.modes = tuple(modes)
        self.log: List[Tuple[str, str, int]] = []  # (mode, column, row)

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        if self.rng.random() >= self.rate:
            return [chunk]
        data = chunk.to_numpy(with_ops=True)
        ops = np.asarray(data.pop("__op__"), np.int32).copy()
        n = len(ops)
        if n == 0:
            return [chunk]
        cols = {
            k: np.asarray(v).copy()
            for k, v in data.items()
            if not k.endswith("__null")
        }
        nulls = {
            k[: -len("__null")]: np.asarray(v, bool)
            for k, v in data.items()
            if k.endswith("__null")
        }
        mode = self.rng.choice(self.modes)
        row = self.rng.randrange(n)
        if mode == "corrupt_value":
            name = self.rng.choice(sorted(cols))
            arr = cols[name]
            if name in nulls and nulls[name][row]:
                # corrupting a NULL cell would be masked downstream:
                # resurrect it instead (a visible corruption)
                nulls[name][row] = False
                arr[row] = self.rng.randint(1, 1 << 20)
            elif arr.dtype == np.bool_:
                arr[row] = not bool(arr[row])
            elif np.issubdtype(arr.dtype, np.integer):
                arr[row] = arr[row] + self.rng.randint(1, 1 << 20)
            elif np.isnan(float(arr[row])):
                arr[row] = 12345.5  # NaN + x stays NaN: set a value
            else:
                arr[row] = arr[row] + 1.5
            self.log.append((mode, name, row))
        elif mode == "flip_op":
            ops[row] = (
                int(Op.DELETE)
                if ops[row] == Op.INSERT
                else int(Op.INSERT)
            )
            self.log.append((mode, "__op__", row))
        else:  # dup_row
            for k in cols:
                cols[k] = np.concatenate([cols[k], cols[k][row : row + 1]])
            for k in nulls:
                nulls[k] = np.concatenate(
                    [nulls[k], nulls[k][row : row + 1]]
                )
            ops = np.concatenate([ops, ops[row : row + 1]])
            self.log.append((mode, "*", row))
        cap = max(chunk.capacity, 1 << (len(ops) - 1).bit_length())
        return [
            StreamChunk.from_numpy(
                cols, cap, ops=ops, nulls=nulls or None
            )
        ]
