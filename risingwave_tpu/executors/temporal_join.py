"""Temporal join — stream rows enriched against a versioned table.

Reference: src/stream/src/executor/temporal_join.rs:44 — the stream
(left) side probes the right TABLE at the row's processing epoch; the
right side keeps NO join state and emits nothing on its own. Used for
`JOIN t FOR SYSTEM_TIME AS OF PROCTIME()` lookups (dimension tables).

TPU re-design: the right side is the table's MATERIALIZE executor.
When it is a DeviceMaterializeExecutor the probe is one fused device
program — ``ops.hash_table.lookup`` over the MV's pk table + gathers
from its value lanes — so enrichment never leaves HBM. Host-map MVs
fall back to a snapshot dict probe (interpreter speed, same
semantics).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.executors.materialize import DeviceMaterializeExecutor
from risingwave_tpu.ops.hash_table import lookup


@partial(jax.jit, static_argnames=("out_cols", "jt"))
def _probe_step(table, values, vnulls, chunk, key_lanes, key_ok, out_cols, jt):
    # SQL: NULL = anything is unknown — NULL-keyed rows never match
    # (their lane value 0 would otherwise hit a real pk=0 row)
    slots, found = lookup(table, key_lanes, chunk.valid & key_ok)
    found = found & key_ok
    cap = table.capacity
    idx = jnp.where(found, slots, cap - 1)  # safe gather lane
    cols = dict(chunk.columns)
    nulls = dict(chunk.nulls)
    for name in out_cols:
        cols[name] = values[name][idx]
        miss = ~found
        lane = vnulls.get(name)
        if lane is not None:
            miss = miss | lane[idx]
        nulls[name] = miss
    valid = chunk.valid if jt == "left" else (chunk.valid & found)
    return StreamChunk(cols, valid, nulls, chunk.ops)


class TemporalJoinExecutor(Executor):
    """``stream JOIN table FOR SYSTEM_TIME AS OF PROCTIME()``.

    ``right``: the table's materialize executor (device or host map).
    ``left_keys``: stream columns equi-matched against the table's pk
    (in pk order). ``output_cols``: table value columns appended to
    every matched row. ``join_type``: "inner" drops misses, "left"
    keeps them with NULL-padded table columns.
    """

    def __init__(
        self,
        right,
        left_keys: Sequence[str],
        output_cols: Sequence[str],
        join_type: str = "inner",
    ):
        if join_type not in ("inner", "left"):
            raise ValueError("temporal join supports inner/left")
        self.right = right
        self.left_keys = tuple(left_keys)
        self.output_cols = tuple(output_cols)
        self.join_type = join_type

    def lint_info(self):
        # probes never drop/append stream columns; matched table value
        # columns are appended (nullable on a "left" miss)
        out_dtypes = {}
        if isinstance(self.right, DeviceMaterializeExecutor):
            out_dtypes = {
                c: self.right.dtypes.get(c) for c in self.output_cols
            }
        return {
            "requires": tuple(self.left_keys),
            "adds": {
                c: out_dtypes.get(c) for c in self.output_cols
            },
            "table_ids": (),  # the right side owns its own state table
        }

    def trace_contract(self):
        if not isinstance(self.right, DeviceMaterializeExecutor):
            return {
                "kind": "host",
                "trace_step": None,
                "state": None,
                "donate": False,
                "emission": "passthrough",
                "host_reason": "temporal probe against a host-map "
                "materializer snapshot dict (device path needs a "
                "DeviceMaterializeExecutor right side)",
            }

        def step(c):
            key_lanes = tuple(
                c.col(k).astype(tk.dtype)
                for k, tk in zip(self.left_keys, self.right.table.keys)
            )
            key_ok = jnp.ones(c.capacity, jnp.bool_)
            for k in self.left_keys:
                key_ok = key_ok & ~c.null_of(k)
            return _probe_step(
                self.right.table,
                self.right.state.values,
                self.right.state.vnulls,
                c,
                key_lanes,
                key_ok,
                self.output_cols,
                self.join_type,
            )

        return {
            "kind": "device",
            "trace_step": step,
            # the probe only READS the right table: nothing to donate
            "state": None,
            "donate": True,
            "emission": "passthrough",
            # the host-fallback probe is statically present in apply
            # but dead on this configuration (right side is device)
            "scan_exclude": ("_probe_host",),
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        if isinstance(self.right, DeviceMaterializeExecutor):
            if len(self.right.pk) != len(self.left_keys):
                raise ValueError("left_keys must match the table pk")
            key_lanes = tuple(
                chunk.col(k).astype(tk.dtype)
                for k, tk in zip(self.left_keys, self.right.table.keys)
            )
            key_ok = jnp.ones(chunk.capacity, jnp.bool_)
            for k in self.left_keys:
                key_ok = key_ok & ~chunk.null_of(k)
            return [
                _probe_step(
                    self.right.table,
                    self.right.state.values,
                    self.right.state.vnulls,
                    chunk,
                    key_lanes,
                    key_ok,
                    self.output_cols,
                    self.join_type,
                )
            ]
        return [self._probe_host(chunk)]

    def _probe_host(self, chunk: StreamChunk) -> StreamChunk:
        snap = self.right.snapshot()  # pk tuple -> value tuple
        col_pos = {c: i for i, c in enumerate(self.right.columns)}
        data = chunk.to_numpy(with_ops=True)
        n = len(data["__op__"])
        found = np.zeros(chunk.capacity, np.bool_)
        outs = {
            c: np.zeros(chunk.capacity, object) for c in self.output_cols
        }
        live = np.flatnonzero(np.asarray(chunk.valid))
        for j, i in enumerate(live[:n]):
            if any(
                data.get(k + "__null") is not None
                and data[k + "__null"][j]
                for k in self.left_keys
            ):
                continue  # NULL key never matches (SQL unknown)
            key = tuple(data[k][j].item() for k in self.left_keys)
            row = snap.get(key)
            if row is not None:
                found[i] = True
                for c in self.output_cols:
                    outs[c][i] = row[col_pos[c]]
        cols = dict(chunk.columns)
        nulls = dict(chunk.nulls)
        for c in self.output_cols:
            vals = np.asarray(
                [0 if v is None else v for v in outs[c].tolist()]
            )
            cols[c] = jnp.asarray(vals)
            nulls[c] = jnp.asarray(
                ~found | np.asarray([v is None for v in outs[c].tolist()])
            )
        valid = (
            chunk.valid
            if self.join_type == "left"
            else chunk.valid & jnp.asarray(found)
        )
        return StreamChunk(cols, valid, nulls, chunk.ops)
