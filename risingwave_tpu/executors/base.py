"""Executor protocol + control messages.

Reference: src/stream/src/executor/mod.rs —
- ``Execute`` trait (:180): an executor transforms a stream of
  ``Message::{Chunk, Barrier, Watermark}`` (:871);
- ``Barrier { epoch: EpochPair, kind }`` (:276) with checkpoint kinds;
- ``Watermark`` messages carry per-column monotonic lower bounds that
  drive state cleaning (executor/watermark_filter.rs).

TPU re-design: no async streams — the host epoch loop calls, in
dataflow order, ``apply(chunk)`` for data and ``on_barrier`` /
``on_watermark`` for control, collecting output chunks to feed the next
executor. Device state lives inside each executor as jax pytrees; all
math happens in pure jitted kernels so a whole chain runs as a few fused
XLA programs per chunk.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from risingwave_tpu.array.chunk import StreamChunk


@dataclass(frozen=True)
class Epoch:
    """EpochPair analogue (reference: src/common/src/util/epoch.rs:31).

    ``curr`` is the epoch being sealed by this barrier; ``prev`` is the
    previous sealed epoch. Values are physical-ms << 16 | seq in the
    runtime; tests may use small ints.
    """

    prev: int
    curr: int


@dataclass(frozen=True)
class Barrier:
    """A barrier message (reference: executor/mod.rs:276)."""

    epoch: Epoch
    checkpoint: bool = True


@dataclass(frozen=True)
class Watermark:
    """Monotonic per-column lower bound (reference: executor/mod.rs:871,
    watermark_filter.rs): no future row will carry ``column < value``."""

    column: str
    value: int


class Executor:
    """Base executor. Subclasses override what they react to.

    ``apply`` must be cheap on the host: stage device work, return
    fixed-capacity chunks. ``on_barrier`` flushes per-epoch deltas
    (reference: flush_data on barrier, e.g. hash_agg.rs:406).
    """

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        return [chunk]

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        return []

    def on_watermark(self, watermark: Watermark):
        """Returns ``(downstream_watermark | None, output_chunks)``.

        Executors TRANSFORM watermarks as they pass (reference: derived
        watermarks through projections, watermark_filter.rs + plan-node
        watermark derivation): e.g. HopWindow maps an event-time
        watermark to a window_start watermark. None stops propagation.
        """
        return watermark, []

    def emit_watermark(self):
        """GENERATED watermark, polled by the pipeline after each
        barrier (WatermarkFilterExecutor overrides; reference:
        watermark_filter.rs emits into its output stream)."""
        return None

    def lint_info(self):
        """Static metadata for the plan verifier (analysis/), or None.

        None (the default) marks the executor OPAQUE: the verifier
        stops schema/watermark tracking at it and skips value-level
        checks downstream — it never guesses. Executors that know
        their column flow return a dict with any of:

        - ``requires``: columns read from the input channel
        - ``expects``: {col: dtype} declared input dtypes (implies
          requires)
        - ``adds``: {col: dtype|None} columns appended to the schema
        - ``emits``: {col: dtype|None} output schema REPLACING the
          input (aggs, joins, projects)
        - ``renames``: {out: in|None} for emits-executors — which
          output is an unmodified copy of which input (None =
          computed); drives dispatch-key tracing and watermark
          capability
        - ``keys``: state partition keys (exchange alignment, RW-E202)
        - ``state_pk``: state-table primary key (coverage, RW-E701)
        - ``table_ids``: state table ids (uniqueness, RW-E702)
        - ``window_key``: state-cleaning column that must be
          watermark-reachable (RW-E501)
        - ``watermark_map``: {in_col: out_col} watermark translation
          (hop window)
        - ``watermark_src``: column this executor GENERATES watermarks
          for (watermark filter)
        """
        return None

    def trace_contract(self):
        """Static COMPILABILITY metadata for the fusion analyzer
        (analysis/fusion_analyzer.py), or None = opaque (no trace
        contract: the analyzer cannot prove anything about this
        executor and it hard-stops a fragment's fusible prefix).

        The default derives a contract from ``pure_step()``: a
        stateless executor exposing a pure chunk->chunk step is
        trivially device-fusible. Stateful executors override and
        declare honestly what their apply/barrier path does TODAY —
        the analyzer verifies the claim (abstract tracing + an AST
        scan of the hot methods for host-sync markers), it does not
        trust it. Keys:

        - ``kind``: "device" (math staged in pure jitted kernels over
          (state, chunk) — abstractly traceable) or "host" (the data
          path leaves the device: NumPy fallback, dict probes).
        - ``trace_step``: chunk -> pytree callable CLOSED OVER the
          executor's current state, pure for tracing purposes (calls
          the underlying jitted kernel without mutating self); the
          analyzer make_jaxpr/eval_shape's it over the chunk-size
          bucket lattice. None when nothing is traceable.
        - ``state``: the donated state pytree, or None (stateless).
        - ``donate``: True when the step kernel donates its state
          buffers (donate_argnums) — False + state => RW-E804.
        - ``emission``: flush-chunk capacity behavior — "none" (never
          emits), "passthrough" (output capacity is a pure function
          of input capacity), "fixed"/"bucketed" (a declared, closed
          capacity set: ``emission_caps``), or "data_dependent"
          (capacity derives from live-row counts => RW-E802).
        - ``emission_caps``: tuple of declared emission capacities
          (fixed/bucketed kinds).
        - ``window_buckets``: for window-keyed executors, the declared
          bucket lattice of the per-window shape domain, or None =
          unbucketed (window churn re-traces without bound =>
          RW-E803, the q7 wedge class).
        - ``host_reason``: one-line reason for kind="host" (the AST
          scan adds exact file:line provenance).
        - ``hot_methods``: extra method names the host-sync scan must
          cover beyond apply/apply_left/apply_right/on_barrier/
          on_watermark.
        - ``fallback_syncs``: method names whose host syncs exist ONLY
          on the interpreted fallback path because the fused
          per-barrier step (runtime/fused_step) compiles a
          device-resident replacement for them (equivalence enforced
          by the fused-vs-interpreted twin tests). The analyzer
          reports them as ``fallback_sync_points`` instead of
          fusibility blockers.
        """
        step = self.pure_step()
        if step is None:
            return None
        return {
            "kind": "device",
            "trace_step": step,
            "state": None,
            "donate": True,
            "emission": "passthrough",
        }

    def pure_step(self):
        """A pure device function chunk -> chunk equivalent to this
        executor's ``apply`` (exactly one output chunk, no state), or
        None. Stateless executors expose it so an epoch-batching
        wrapper can trace them INTO a downstream stateful op's fused
        per-epoch program (one device dispatch per epoch instead of one
        per chunk — the XLA answer to the reference's per-chunk actor
        loop, hash_agg.rs:326).

        Contract: return a ``functools.partial`` of a MODULE-LEVEL
        function whose bound arguments are hashable — the composition
        is a static jit argument and must compare equal across executor
        instances of the same plan shape, or every graph rebuild
        recompiles the fused program."""
        return None

    # -- overlapped barrier scalar reads ---------------------------------
    # Executors that must read device scalars at the barrier (overflow
    # latches, occupancy counters) ENQUEUE the packed read inside
    # ``on_barrier`` (sampling at their own position in the walk, i.e.
    # after absorbing upstream flushes) via ``stage_scalars`` and defer
    # the blocking host materialization to ``finish_barrier``, which
    # the pipeline calls for every executor AFTER the walk. The N
    # transfers are all in flight concurrently, so a chain pays ~one
    # tunneled-TPU round-trip per barrier instead of N — with the
    # values and raise points semantically identical to synchronous
    # reads (checks still run before the runtime commits the epoch).

    _staged_scalars = None

    def finish_barrier(self) -> None:
        """Materialize scalars staged by on_barrier and run the
        executor's checks (one implementation; executors override
        ``_on_barrier_scalars`` only). Executors driven DIRECTLY with
        ``on_barrier(None)`` (tests/tools, no pipeline) finish inline
        so their latch checks still fire per epoch."""
        if self._staged_scalars is None:
            return
        import time

        from risingwave_tpu.ops.hash_table import finish_scalars
        from risingwave_tpu.profiler import PROFILER
        from risingwave_tpu.trace import span

        # the materialization below is the barrier's device fence: the
        # span attributes per-executor device wait to the epoch trace
        # (and leaves a frame on the live stack for stall dumps); in
        # profile mode the wait also lands in
        # executor_device_wait_ms{executor,phase=finish}
        t0 = time.perf_counter()
        with span("executor.device_step", executor=type(self).__name__):
            vals = finish_scalars(self._staged_scalars)
        if PROFILER.enabled:
            PROFILER.record_device_wait(
                self, (time.perf_counter() - t0) * 1e3
            )
        self._staged_scalars = None
        self._on_barrier_scalars(vals)

    def _on_barrier_scalars(self, vals) -> None:
        """Unpack + check the scalars this executor staged."""
        return None
