"""WatermarkFilter — generates event-time watermarks and drops late
rows.

Reference: src/stream/src/executor/watermark_filter.rs:39 — tracks the
maximum observed event time, emits ``wm = max_event_time - lag`` into
the stream, filters rows whose event time is already below the current
watermark, and persists the watermark so recovery resumes monotonic.

TPU re-design: the running maximum is a device scalar folded per chunk
inside the same jitted step that masks late rows — no host sync on the
hot path. The host reads it ONCE per barrier (the natural sync point)
to emit the downstream ``Watermark`` message via the pipeline's
``emit_watermark`` hook, mirroring the reference's
"emit on update, at barrier granularity" behavior.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.types import Op


@partial(jax.jit, static_argnames=("col",), donate_argnums=(1,))
def _wm_step(chunk: StreamChunk, running_max, col: str, wm_floor):
    ts = chunk.col(col)
    signs = chunk.effective_signs()
    active = chunk.valid & (signs != 0)
    null = chunk.nulls.get(col)
    if null is not None:
        active = active & ~null
    cmax = jnp.max(jnp.where(active, ts, jnp.iinfo(jnp.int64).min))
    running_max = jnp.maximum(running_max, cmax)
    # INSERT rows strictly below the CURRENT watermark are late ->
    # dropped (watermark_filter.rs filters with `ts >= watermark`).
    # RETRACTIONS pass regardless: a DELETE/UPDATE_DELETE for a row
    # below the watermark must still reach downstream state — dropping
    # it would desync MVs from a DML-mutated table (its target may
    # already be cleaned, in which case it no-ops downstream).
    retract = (chunk.ops == Op.DELETE) | (chunk.ops == Op.UPDATE_DELETE)
    keep = chunk.valid & ((ts >= wm_floor) | retract)
    out = chunk.mask(keep)
    # a surviving U- whose U+ partner was dropped (update moving a row
    # BELOW the watermark) downgrades to a plain DELETE
    is_ud = out.ops == Op.UPDATE_DELETE
    partner_alive = jnp.roll(out.valid, -1) & jnp.roll(
        out.ops == Op.UPDATE_INSERT, -1
    )
    fix = is_ud & out.valid & ~partner_alive
    new_ops = jnp.where(fix, jnp.int32(Op.DELETE), out.ops)
    return (
        StreamChunk(out.columns, out.valid, out.nulls, new_ops),
        running_max,
    )


class WatermarkFilterExecutor(Executor):
    """Emit ``wm = max(event_time) - lag_ms`` and drop late rows.

    The pipeline calls ``emit_watermark()`` after each barrier; the
    returned watermark walks the downstream chain (and, through a
    join's alignment, cleans both sides) without the driver having to
    inject anything — fixing the "e2e run that forgets
    pipeline.watermark() leaks state forever" failure mode
    (VERDICT r2 weak #8).
    """

    def __init__(self, column: str, lag_ms: int):
        self.column = column
        self.lag_ms = int(lag_ms)
        self._running_max = jnp.asarray(jnp.iinfo(jnp.int64).min, jnp.int64)
        self._wm: Optional[int] = None  # host copy, refreshed per barrier

    def lint_info(self):
        return {
            "requires": (self.column,),
            "watermark_src": self.column,
        }

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: _wm_step(
                c, self._running_max, self.column, self._running_max
            ),
            "state": self._running_max,
            "donate": True,
            "emission": "passthrough",
            # watermark generation reads the running max once per
            # barrier — a real (if small) host sync, reported honestly
            "hot_methods": ("emit_watermark",),
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        floor = jnp.asarray(
            self._wm if self._wm is not None else jnp.iinfo(jnp.int64).min,
            jnp.int64,
        )
        out, self._running_max = _wm_step(
            chunk, self._running_max, self.column, floor
        )
        return [out]

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        return []

    def emit_watermark(self) -> Optional[Watermark]:
        mx = int(self._running_max)
        if mx == int(jnp.iinfo(jnp.int64).min):
            return None
        wm = mx - self.lag_ms
        if self._wm is not None and wm <= self._wm:
            return None
        self._wm = wm
        return Watermark(self.column, wm)

    def on_watermark(self, watermark: Watermark):
        # an upstream watermark on our column advances ours too
        if watermark.column == self.column and (
            self._wm is None or watermark.value > self._wm
        ):
            self._wm = watermark.value
        return watermark, []
