"""Hop (sliding) window executor — row expansion.

Reference: src/stream/src/executor/hop_window.rs — each input row falls
into ``size/slide`` overlapping windows and is emitted once per window
with (window_start, window_end) columns attached.

TPU re-design: the expansion factor is static, so a chunk of capacity C
becomes one chunk of capacity C * factor by tiling every lane and
computing each copy's window start arithmetically — no loops, no
dynamic shapes. Rows whose k-th window would not contain their
timestamp are masked invalid (only possible for negative timestamps;
kept for safety).
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor


def hop_step_fn(
    chunk: StreamChunk, ts_col: str, size_ms: int, slide_ms: int, out_start: str
) -> StreamChunk:
    factor = -(-size_ms // slide_ms)  # ceil
    cap = chunk.capacity

    # block layout: copy k of every row forms one contiguous cap-sized
    # block, so adjacent rows STAY adjacent within each block — the
    # U-/U+ update-pair invariant (stream_chunk.rs:45) that FilterExecutor
    # and sinks rely on survives the expansion (jnp.repeat would tear
    # every pair apart; code-review r2).
    def tile(a):
        return jnp.tile(a, factor)

    ts = chunk.col(ts_col)
    # earliest aligned window start strictly greater than ts - size
    first = (jnp.floor_divide(ts - size_ms, slide_ms) + 1) * slide_ms
    k = jnp.repeat(jnp.arange(factor, dtype=ts.dtype), cap)
    starts = tile(first) + k * slide_ms
    in_window = starts <= tile(ts)  # start + size > ts holds by choice of first

    cols = {n: tile(a) for n, a in chunk.columns.items()}
    cols[out_start] = starts
    # a pre-existing null lane on the output column must not survive the
    # replacement (freshly computed starts are never NULL)
    nulls = {n: tile(a) for n, a in chunk.nulls.items() if n != out_start}
    valid = tile(chunk.valid) & in_window
    ops = tile(chunk.ops)
    return StreamChunk(cols, valid, nulls, ops)


_hop_step = partial(jax.jit, static_argnames=("ts_col", "size_ms", "slide_ms", "out_start"))(
    hop_step_fn
)


class HopWindowExecutor(Executor):
    def __init__(
        self,
        ts_col: str,
        size_ms: int,
        slide_ms: int,
        out_start: str = "window_start",
    ):
        if size_ms % slide_ms:
            raise ValueError("size must be a multiple of slide")
        self.ts_col = ts_col
        self.size_ms = size_ms
        self.slide_ms = slide_ms
        self.out_start = out_start

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        return [
            _hop_step(chunk, self.ts_col, self.size_ms, self.slide_ms, self.out_start)
        ]

    def lint_info(self):
        import jax.numpy as jnp

        return {
            "requires": (self.ts_col,),
            "adds": {self.out_start: jnp.int64},
            "watermark_map": {self.ts_col: self.out_start},
        }

    def pure_step(self):
        # the fused-chain contract (runtime/fused_step + epoch_batch):
        # a module-level partial with hashable bound args, so the hop expansion
        # traces into the fused per-barrier program and compiles once
        # per plan shape, not once per executor instance
        return partial(
            hop_step_fn,
            ts_col=self.ts_col,
            size_ms=self.size_ms,
            slide_ms=self.slide_ms,
            out_start=self.out_start,
        )

    def on_watermark(self, watermark):
        """Translate an event-time watermark into a window_start
        watermark: a future row (ts >= wm) lands only in windows with
        start >= first_start(wm)."""
        from risingwave_tpu.executors.base import Watermark

        if watermark.column != self.ts_col:
            return watermark, []
        first = ((watermark.value - self.size_ms) // self.slide_ms + 1) * self.slide_ms
        return Watermark(self.out_start, first), []
