"""GroupTopN executor — per-group top-k band maintenance.

Reference: src/stream/src/executor/top_n/ — ``group_top_n.rs:63`` with
``top_n_cache.rs`` band logic and the append-only specialization
(``top_n_appendonly.rs``). This is the APPEND-ONLY variant (the
reference planner picks it for insert-only inputs, e.g. Nexmark
queries); retractable GroupTopN needs state-table refill below the
band and lands with the batch read path.

TPU re-design: no per-group cache objects — group bands are fixed-
shape device arrays: ``order``/payload/(capacity, k) with a validity
mask, maintained by ONE fused kernel per chunk:

1. each row finds its group slot (ops/hash_table);
2. the chunk's rows and the TOUCHED groups' current bands merge into
   one (n*(k+1),) array which is lexsorted by (slot, order-key);
3. rank-within-group < k survives; survivors scatter back as the new
   band; band rows that fell out emit DELETE, chunk rows that entered
   emit INSERT — exactly the reference's cache-delta emission.

The order key is one int64 lane; DESC encodes as bitwise-NOT (~x is
exact two's-complement negation-minus-one, total-order preserving).
Ties favor incumbents (stable sort places band entries first), which
minimizes churn — the reference's cache behaves the same way.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.ops.hash_table import (
    HashTable,
    first_occurrence_mask,
    lookup_or_insert,
    set_live,
)
from risingwave_tpu.runtime.bucketing import (
    BucketAllocator,
    BucketPolicy,
    needs_plan,
    plan_capacity,
)
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    grow_pow2,
    pull_rows,
    stage_marks,
)
from risingwave_tpu.types import Op

GROW_AT = 0.5


@partial(
    jax.jit,
    static_argnames=("group_keys", "order_col", "desc", "k", "payload", "out_cap"),
    donate_argnums=(0, 1),
)
def _topn_step(
    table: HashTable,
    state: Dict[str, jnp.ndarray],  # order/band_valid/sdirty + payload lanes
    chunk: StreamChunk,
    group_keys: Tuple[str, ...],
    order_col: str,
    desc: bool,
    k: int,
    payload: Tuple[str, ...],
    out_cap: int,
):
    key_cols = tuple(chunk.col(g) for g in group_keys)
    signs = chunk.effective_signs()
    saw_delete = jnp.any(chunk.valid & (signs < 0))
    valid = chunk.valid & (signs > 0)

    table, slots, _, _ = lookup_or_insert(table, key_cols, valid)
    table = set_live(table, jnp.where(valid, slots, -1), True)
    dropped = jnp.any(valid & (slots < 0))
    valid = valid & (slots >= 0)
    cap = table.capacity
    n = valid.shape[0]
    sl = jnp.maximum(slots, 0)
    sdirty = state["sdirty"].at[jnp.where(valid, slots, cap)].set(
        True, mode="drop"
    )

    order_in = chunk.col(order_col).astype(jnp.int64)
    if desc:
        order_in = ~order_in

    # ---- build the combined (band ∪ chunk) array, length n*(k+1) -----
    fmask = first_occurrence_mask(slots, valid)  # one band copy per group
    band_order = state["order"][sl]  # (n, k)
    band_vld = state["band_valid"][sl] & fmask[:, None]

    big = jnp.int64(1) << 62
    c_slot = jnp.concatenate(
        [jnp.repeat(sl, k), sl]
    )  # band entries then chunk rows
    c_valid = jnp.concatenate([band_vld.reshape(-1), valid])
    c_order = jnp.concatenate([band_order.reshape(-1), order_in])
    c_origin = jnp.concatenate(  # 0 = incumbent band, 1 = chunk row
        [jnp.zeros(n * k, jnp.bool_), jnp.ones(n, jnp.bool_)]
    )
    # band entry i's source position for payload gather:
    band_src = jnp.concatenate(
        [jnp.repeat(sl, k) * k + jnp.tile(jnp.arange(k), n), jnp.zeros(n, jnp.int32)]
    )
    chunk_src = jnp.concatenate([jnp.zeros(n * k, jnp.int32), jnp.arange(n, dtype=jnp.int32)])

    skey = jnp.where(c_valid, c_slot.astype(jnp.int64), big)
    okey = jnp.where(c_valid, c_order, big)
    perm = jnp.lexsort((okey, skey))  # by slot, then order; stable

    s_sorted = skey[perm]
    seq = jnp.arange(n * (k + 1), dtype=jnp.int32)
    is_new = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), s_sorted[1:] != s_sorted[:-1]]
    )
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_new, seq, jnp.int32(0))
    )
    rank = seq - start
    kept_sorted = (rank < k) & (s_sorted < big)
    kept = jnp.zeros(n * (k + 1), jnp.bool_).at[perm].set(kept_sorted)
    new_pos = jnp.zeros(n * (k + 1), jnp.int32).at[perm].set(rank)

    # ---- write the new bands (clear touched groups, scatter kept) ----
    touched = jnp.where(valid & fmask, slots, cap)
    clear_valid = state["band_valid"].at[touched].set(False, mode="drop")
    dst = jnp.where(kept, c_slot * k + new_pos, cap * k)

    def band_scatter(dst_arr_flat, values):
        return dst_arr_flat.at[dst].set(values, mode="drop")

    new_band_valid = band_scatter(
        clear_valid.reshape(-1), jnp.ones(n * (k + 1), jnp.bool_)
    ).reshape(cap, k)
    gathered = {}
    new_state = {"band_valid": new_band_valid, "sdirty": sdirty}
    for name in ("order",) + payload:
        lane2d = state[name]
        src_col = order_in if name == "order" else chunk.col(name)
        c_vals = jnp.where(
            c_origin,
            src_col[chunk_src],
            lane2d.reshape(-1)[band_src],
        )
        gathered[name] = c_vals
        new_state[name] = band_scatter(
            lane2d.reshape(-1), c_vals
        ).reshape(cap, k)
    new_state["stored"] = state["stored"]

    # ---- emissions: chunk rows entering, band rows leaving ------------
    emit_ins = kept & c_origin & c_valid
    emit_del = ~kept & ~c_origin & c_valid
    emit = emit_ins | emit_del
    pos = jnp.cumsum(emit.astype(jnp.int32)) - 1
    overflow = jnp.any(emit & (pos >= out_cap))
    eidx = jnp.where(emit & (pos < out_cap), pos, out_cap)

    def compact(src):
        return jnp.zeros(out_cap, src.dtype).at[eidx].set(src, mode="drop")

    out_cols = {}
    for i, g in enumerate(group_keys):
        out_cols[g] = compact(table.keys[i][c_slot])
    for name in ("order",) + payload:
        if name == "order":
            ov = gathered[name]  # decode DESC's bitwise-NOT back
            out_cols[order_col] = compact(~ov if desc else ov)
        else:
            out_cols[name] = compact(gathered[name])
    out_ops = compact(
        jnp.where(emit_ins, jnp.int32(Op.INSERT), jnp.int32(Op.DELETE))
    )
    out_valid = jnp.zeros(out_cap, jnp.bool_).at[eidx].set(emit, mode="drop")
    out = StreamChunk(
        columns=out_cols, valid=out_valid, nulls={}, ops=out_ops
    )
    return table, new_state, out, saw_delete, dropped, overflow


@partial(jax.jit, static_argnames=("new_cap",))
def _topn_rebuild(table: HashTable, state: Dict[str, jnp.ndarray], new_cap: int):
    keep = (table.live | state["sdirty"]) & (table.fp1 != jnp.uint32(0))
    new_table = HashTable.create(new_cap, tuple(x.dtype for x in table.keys))
    new_table, slots, _, _ = lookup_or_insert(new_table, table.keys, keep)
    new_table = set_live(new_table, jnp.where(keep, slots, -1), table.live)
    idx = jnp.where(keep, slots, new_cap)
    k = state["band_valid"].shape[1]
    new_state = {}
    for name, a in state.items():
        if a.ndim == 2:
            buf = jnp.zeros((new_cap + 1, k), a.dtype)
            new_state[name] = buf.at[idx].set(a, mode="drop")[:new_cap]
        else:
            buf = jnp.zeros(new_cap, a.dtype)
            new_state[name] = buf.at[idx].set(a, mode="drop")
    return new_table, new_state


class GroupTopNExecutor(Executor, Checkpointable):
    """Append-only per-group TOP k BY order_col [DESC].

    Emits the top-k delta stream: INSERT when a row enters its group's
    top k, DELETE when a newcomer pushes it out. The emitted chunk
    carries the group keys, the order column, and the payload columns.
    """

    def __init__(
        self,
        group_keys: Sequence[str],
        order_col: str,
        k: int,
        schema_dtypes: Dict[str, object],
        payload: Sequence[str] = (),
        desc: bool = True,
        capacity: int = 1 << 14,
        out_cap: int = 1 << 13,
        window_key: Optional[Tuple[str, int]] = None,
        table_id: str = "group_top_n",
        bucket_policy: Optional[BucketPolicy] = None,
        bucketed: bool = True,
    ):
        self._buckets = (
            BucketAllocator(
                bucket_policy or BucketPolicy.from_capacity(capacity, grow_at=GROW_AT)
            )
            if bucketed
            else None
        )
        self.group_keys = tuple(group_keys)
        self.order_col = order_col
        self.k = k
        self.desc = desc
        self.payload = tuple(p for p in payload if p != order_col)
        self.out_cap = out_cap
        self.window_key = window_key
        self.table_id = table_id
        self._dtypes = dict(schema_dtypes)
        self.table = HashTable.create(
            capacity, tuple(jnp.dtype(self._dtypes[g]) for g in self.group_keys)
        )
        self.state = {
            "order": jnp.zeros((capacity, k), jnp.int64),
            "band_valid": jnp.zeros((capacity, k), jnp.bool_),
            "sdirty": jnp.zeros(capacity, jnp.bool_),
            "stored": jnp.zeros(capacity, jnp.bool_),
        }
        for p in self.payload:
            self.state[p] = jnp.zeros(
                (capacity, k), jnp.dtype(self._dtypes[p])
            )
        self._bound = 0
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)
        self._overflow = jnp.zeros((), jnp.bool_)

    def lint_info(self):
        cols = self.group_keys + (self.order_col,) + self.payload
        return {
            "expects": {
                c: self._dtypes[c] for c in cols if c in self._dtypes
            },
            "emits": {c: self._dtypes.get(c) for c in cols},
            "renames": {c: c for c in cols},
            "keys": self.group_keys,
            "table_ids": (self.table_id,),
            "window_key": self.window_key[0] if self.window_key else None,
        }

    def trace_contract(self):
        return {
            "kind": "device",
            "trace_step": lambda c: _topn_step(
                self.table,
                self.state,
                c,
                self.group_keys,
                self.order_col,
                self.desc,
                self.k,
                self.payload,
                self.out_cap,
            ),
            "state": (self.table, self.state),
            "donate": True,
            "emission": "fixed",
            "emission_caps": (self.out_cap,),
            # group table + band capacities walk the allocator's
            # declared pow2 lattice (None only on the unbucketed twin)
            "window_buckets": (
                self._buckets.lattice if self._buckets is not None else None
            ),
        }

    def pin_max_bucket(self):
        """ShapeGovernor hook: freeze the group bands at their
        high-water bucket (shrink disabled)."""
        if self._buckets is None:
            return {"pinned": False}
        return {
            "table_id": self.table_id,
            "pinned_cap": self._buckets.pin(),
        }

    def padding_stats(self):
        return {
            "capacity": self.table.capacity,
            "live": int(self.table.num_live()),
        }

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        for c in self.group_keys + (self.order_col,) + self.payload:
            if c in chunk.nulls:
                raise ValueError(f"TopN column {c!r} carries NULLs (unsupported)")
        self._maybe_grow(chunk.capacity)
        self._bound += chunk.capacity
        self.table, self.state, out, saw_delete, dropped, overflow = _topn_step(
            self.table,
            self.state,
            chunk,
            self.group_keys,
            self.order_col,
            self.desc,
            self.k,
            self.payload,
            self.out_cap,
        )
        self._saw_delete = self._saw_delete | saw_delete
        self._dropped = self._dropped | dropped
        self._overflow = self._overflow | overflow
        return [out]

    def _maybe_grow(self, incoming: int):
        cap = self.table.capacity
        if not needs_plan(self._buckets, cap, self._bound, incoming, GROW_AT):
            return
        claimed = int(self.table.occupancy())
        survivors = int(
            jnp.sum((self.table.live | self.state["sdirty"]).astype(jnp.int32))
        )
        new_cap = plan_capacity(
            self._buckets, cap, incoming, claimed, survivors, GROW_AT
        )
        if new_cap is not None:
            self.table, self.state = _topn_rebuild(
                self.table, self.state, new_cap
            )
            claimed = int(self.table.occupancy())
        self._bound = claimed

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if self._buckets is not None:
            # host-tracked bound (upper estimate): shrink stays lazy
            # and conservative without an extra device read
            self._buckets.note_barrier(self.table.capacity, self._bound)
        if bool(self._saw_delete):
            raise RuntimeError("append-only TopN received a DELETE")
        if bool(self._dropped):
            raise RuntimeError("TopN group table overflowed; grow capacity")
        if bool(self._overflow):
            raise RuntimeError("TopN emission overflowed out_cap")
        return []

    def on_watermark(self, watermark: Watermark):
        if self.window_key is None or watermark.column != self.window_key[0]:
            return watermark, []
        cutoff = jnp.asarray(watermark.value - self.window_key[1], jnp.int64)
        lane = self.table.keys[self.group_keys.index(self.window_key[0])]
        expired = self.table.live & (lane < cutoff)
        slots = jnp.where(
            expired, jnp.arange(self.table.capacity, dtype=jnp.int32), -1
        )
        self.table = set_live(self.table, slots, False)
        self.state = dict(self.state)
        self.state["band_valid"] = self.state["band_valid"] & ~expired[:, None]
        self.state["sdirty"] = self.state["sdirty"] | expired
        return watermark, []

    # -- integrity --------------------------------------------------------
    def digest_lanes(self):
        bv = self.state["band_valid"]
        lanes = {f"k{i}": x for i, x in enumerate(self.table.keys)}
        lanes["bv"] = bv
        # band entries pre-masked by band_valid: stale bytes in vacated
        # band positions must not shift the digest
        lanes["order"] = jnp.where(bv, self.state["order"], 0)
        for p in self.payload:
            a = self.state[p]
            lanes[f"p_{p}"] = jnp.where(bv, a, jnp.zeros((), a.dtype))
        return lanes, self.table.live

    def state_digest(self) -> int:
        from risingwave_tpu.integrity import host_digest

        return host_digest(*self.digest_lanes())

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self):
        sdirty = np.asarray(self.state["sdirty"])
        if not sdirty.any():
            return []
        upsert, tomb, sel = stage_marks(
            sdirty, np.asarray(self.table.live), np.asarray(self.state["stored"])
        )
        lanes = {f"k{i}": x for i, x in enumerate(self.table.keys)}
        key_names = tuple(lanes)
        lanes["bv"] = self.state["band_valid"]
        lanes["order"] = self.state["order"]
        for p in self.payload:
            lanes[f"p_{p}"] = self.state[p]
        pulled = pull_rows(lanes, sel)
        keys = {x: pulled[x] for x in key_names}
        vals = {x: v for x, v in pulled.items() if x not in key_names}
        st = dict(self.state)
        st["stored"] = (st["stored"] | jnp.asarray(upsert)) & ~jnp.asarray(tomb)
        st["sdirty"] = jnp.zeros_like(st["sdirty"])
        self.state = st
        return [StateDelta(self.table_id, keys, vals, tomb[sel], key_names)]

    def restore_state(self, table_id, key_cols, value_cols):
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        cap = grow_pow2(n, self.table.capacity, GROW_AT)
        k = self.k
        key_dtypes = tuple(x.dtype for x in self.table.keys)
        table = HashTable.create(cap, key_dtypes)
        state = {
            "order": jnp.zeros((cap, k), jnp.int64),
            "band_valid": jnp.zeros((cap, k), jnp.bool_),
            "sdirty": jnp.zeros(cap, jnp.bool_),
            "stored": jnp.zeros(cap, jnp.bool_),
        }
        for p in self.payload:
            state[p] = jnp.zeros((cap, k), jnp.dtype(self._dtypes[p]))
        if n:
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d))
                for i, d in enumerate(key_dtypes)
            )
            table, slots, _, _ = lookup_or_insert(
                table, lanes, jnp.ones(n, jnp.bool_)
            )
            table = set_live(table, slots, True)
            state["band_valid"] = state["band_valid"].at[slots].set(
                jnp.asarray(value_cols["bv"])
            )
            state["order"] = state["order"].at[slots].set(
                jnp.asarray(value_cols["order"])
            )
            for p in self.payload:
                state[p] = state[p].at[slots].set(
                    jnp.asarray(value_cols[f"p_{p}"].astype(state[p].dtype))
                )
            state["stored"] = state["stored"].at[slots].set(True)
        self.table, self.state = table, state
        self._bound = int(n)
        self._saw_delete = jnp.zeros((), jnp.bool_)
        self._dropped = jnp.zeros((), jnp.bool_)
        self._overflow = jnp.zeros((), jnp.bool_)
