"""Lookup / delta join over shared index arrangements.

Reference: src/stream/src/executor/lookup.rs (+ lookup_union.rs,
delta_join in the frontend planner): a join realized as two LOOKUPS
against index arrangements — Δ(A ⋈ B) = ΔA ⋈ B ∪ A ⋈ ΔB — where the
arrangements ARE the user's CREATE INDEX state, shared, not duplicated
per join (the reference's motivating win over hash join state).

Engine mapping: an IndexArrangement is a MaterializeExecutor whose pk
is (index columns ‖ base pk) — the index-column prefix makes upserts
collision-free — plus an in-memory prefix map for O(1) lookups. The
runtime's subscription routing updates each arrangement from its base
table's change stream in the same push cycle that reaches the join, so
each delta looks up the other side's arrangement at exactly the
reference's snapshot point (deltas process in arrival order).

The delta join itself is STATELESS: recovery restores the
arrangements from their own checkpoint tables and replayed chunks
re-derive the same emissions.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.types import Op


class IndexArrangement(MaterializeExecutor):
    """CREATE INDEX state: rows keyed by (index cols ‖ base pk) with a
    prefix map for point lookups (arrange.rs analogue)."""

    def __init__(
        self,
        index_cols: Sequence[str],
        base_pk: Sequence[str],
        columns: Sequence[str],
        table_id: str,
    ):
        self.index_cols = tuple(index_cols)
        self.base_pk = tuple(base_pk)
        super().__init__(
            pk=self.index_cols + self.base_pk,
            columns=tuple(columns),
            table_id=table_id,
        )
        self.by_prefix: Dict[Tuple, set] = {}
        # the prefix map + lookup() read self.rows: pin the dict
        # backend for apply AND restore (the native map never
        # populates .rows)
        self._force_python = True
        self._backend = "python"

    # -- maintenance -----------------------------------------------------
    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        data = chunk.to_numpy(with_ops=True)
        ops = data["__op__"]
        plen = len(self.index_cols)
        lanes = []
        for name in self.pk:
            col = data[name].tolist()
            nl = data.get(name + "__null")
            if nl is not None:
                col = [
                    None if isnull else v for v, isnull in zip(col, nl)
                ]
            lanes.append(col)
        for i in range(len(ops)):
            k = tuple(lane[i] for lane in lanes)
            pre = k[:plen]
            if ops[i] in (Op.DELETE, Op.UPDATE_DELETE):
                s = self.by_prefix.get(pre)
                if s is not None:
                    s.discard(k)
                    if not s:
                        del self.by_prefix[pre]
            else:
                # the prefix is part of the pk: an upsert of the same
                # full key can never leave a stale prefix entry
                self.by_prefix.setdefault(pre, set()).add(k)
        return super().apply(chunk)

    def restore_state(self, table_id, key_cols, value_cols):
        super().restore_state(table_id, key_cols, value_cols)
        plen = len(self.index_cols)
        self.by_prefix = {}
        for k in self.rows:
            self.by_prefix.setdefault(k[:plen], set()).add(k)

    # -- reads -----------------------------------------------------------
    def lookup(self, prefix: Tuple) -> List[Dict[str, object]]:
        """All current rows whose index columns equal ``prefix`` —
        each as a full name->value dict."""
        out = []
        for k in self.by_prefix.get(tuple(prefix), ()):
            v = self.rows.get(k)
            if v is None:
                continue
            row = dict(zip(self.pk, k))
            row.update(zip(self.columns, v))
            out.append(row)
        return out


class DeltaJoinExecutor(Executor):
    """Two-input inner join as lookups against two shared
    IndexArrangements (delta join). Emits, per arriving delta row, the
    delta's op for every current match on the other side.

    ``left_out`` / ``right_out``: [(output name, side column)] —
    includes the hidden base-pk lanes the downstream MV keys on."""

    def __init__(
        self,
        left_arr: IndexArrangement,
        right_arr: IndexArrangement,
        left_keys: Sequence[str],
        right_keys: Sequence[str],
        left_out: Sequence[Tuple[str, str]],
        right_out: Sequence[Tuple[str, str]],
        out_cap: int = 1 << 12,
    ):
        if len(left_keys) != len(right_keys):
            raise ValueError("join key arity mismatch")
        if tuple(left_arr.index_cols[: len(left_keys)]) != tuple(
            left_keys
        ) or tuple(right_arr.index_cols[: len(right_keys)]) != tuple(
            right_keys
        ):
            raise ValueError(
                "delta join needs indexes whose leading columns are "
                "exactly the join keys"
            )
        self.left_arr = left_arr
        self.right_arr = right_arr
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)
        self.left_out = tuple(left_out)
        self.right_out = tuple(right_out)
        self.out_cap = out_cap

    def lint_info(self):
        # join-shaped metadata (plan_verifier._verify_join): the delta
        # join emits the configured output projection; dtypes are
        # whatever the arrangements store (int64 lanes in _emit)
        emits = {n: None for n, _ in self.left_out}
        emits.update({n: None for n, _ in self.right_out})
        return {
            "left_keys": self.left_keys,
            "right_keys": self.right_keys,
            "expects_left": {k: None for k in self.left_keys},
            "expects_right": {k: None for k in self.right_keys},
            "emits": emits,
            "table_ids": (),  # state lives in the shared arrangements
        }

    def trace_contract(self):
        return {
            "kind": "host",
            "trace_step": None,
            "state": None,
            "donate": False,
            # emission capacity is the pow2 envelope of the match
            # count — data-dependent
            "emission": "data_dependent",
            "host_reason": "delta join probes shared host-side "
            "IndexArrangements row by row (lookup.rs analogue)",
        }

    # -- the two delta paths --------------------------------------------
    def _rows_of(self, chunk: StreamChunk, names):
        data = chunk.to_numpy(with_ops=True)
        ops = data["__op__"]
        cols = {}
        for name in names:
            col = data[name].tolist()
            nl = data.get(name + "__null")
            if nl is not None:
                col = [
                    None if isnull else v for v, isnull in zip(col, nl)
                ]
            cols[name] = col
        return ops, cols, len(ops)

    def _emit(self, out_rows, out_ops) -> List[StreamChunk]:
        if not out_rows:
            return []
        names = [n for n, _ in self.left_out] + [
            n for n, _ in self.right_out
        ]
        out: List[StreamChunk] = []
        for at in range(0, len(out_rows), self.out_cap):
            rows = out_rows[at : at + self.out_cap]
            ops = out_ops[at : at + self.out_cap]
            cols = {}
            nulls = {}
            for j, name in enumerate(names):
                vals = [r[j] for r in rows]
                nl = np.asarray([v is None for v in vals], bool)
                cols[name] = np.asarray(
                    [0 if v is None else v for v in vals], np.int64
                )
                if nl.any():
                    nulls[name] = nl
            cap = 1 << max(1, int(np.ceil(np.log2(max(2, len(rows))))))
            out.append(
                StreamChunk.from_numpy(
                    cols, cap, ops=np.asarray(ops, np.int32), nulls=nulls
                )
            )
        return out

    def _delta(self, chunk, side_keys, own_out, other_arr, other_out, flip):
        stream_cols = [c for _, c in own_out]
        ops, cols, n = self._rows_of(
            chunk, set(stream_cols) | set(side_keys)
        )
        valid_rows = range(n)
        out_rows, out_ops = [], []
        for i in valid_rows:
            key = tuple(cols[k][i] for k in side_keys)
            if any(v is None for v in key):
                continue  # SQL: NULL join keys never match
            matches = other_arr.lookup(key)
            if not matches:
                continue
            mine = [cols[c][i] for _, c in own_out]
            for m in matches:
                theirs = [m[c] for _, c in other_out]
                row = theirs + mine if flip else mine + theirs
                out_rows.append(row)
                out_ops.append(int(ops[i]))
        return self._emit(out_rows, out_ops)

    def apply_left(self, chunk: StreamChunk) -> List[StreamChunk]:
        return self._delta(
            chunk,
            self.left_keys,
            self.left_out,
            self.right_arr,
            self.right_out,
            flip=False,
        )

    def apply_right(self, chunk: StreamChunk) -> List[StreamChunk]:
        return self._delta(
            chunk,
            self.right_keys,
            self.right_out,
            self.left_arr,
            self.left_out,
            flip=True,
        )

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        raise TypeError("DeltaJoinExecutor is two-input: apply_left/right")

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        return []
