"""risingwave_tpu — a TPU-native streaming-SQL dataflow framework.

A ground-up re-design of the capabilities of RisingWave (reference:
/root/reference, Rust) for TPU hardware via JAX/XLA/Pallas:

- Columnar ``StreamChunk`` batches (reference: src/common/src/array/
  stream_chunk.rs:98) become padded, fixed-capacity device arrays with
  validity + op masks so every operator compiles once under ``jax.jit``.
- Stateful streaming operators (HashAgg / HashJoin / TopN; reference:
  src/stream/src/executor/) are pure functions
  ``(state, chunk) -> (state', delta)`` over device-resident,
  open-addressing hash-table state in HBM.
- The epoch/barrier checkpoint model (reference: docs/checkpoint.md,
  src/meta/src/barrier/) is a host-driven step loop: a fragment is a
  jit-compiled per-epoch step function; a barrier is a step boundary at
  which state tables commit epoch deltas into a Hummock-style LSM
  (host <-> HBM staging).
- Parallelism is vnode hash partitioning (256 vnodes, reference:
  src/common/src/hash/consistent_hash/vnode.rs:54) mapped onto a
  ``jax.sharding.Mesh``: the hash exchange between fragments is an
  on-device all-to-all inside a ``shard_map``-ped step, riding ICI.
"""

__version__ = "0.2.0"

import jax as _jax

# SQL semantics demand real 64-bit integers (BIGINT ids in every Nexmark
# stream) and real f64 accumulation (SUM over DOUBLE). Without this flag
# jnp silently truncates int64 -> int32, which merges distinct group/join
# keys (see ADVICE.md r1, high). XLA:TPU emulates 64-bit lanes with
# 32-bit pairs; the hot hash path bit-splits to u32 lanes up front, so
# only wide aggregation payloads pay the emulation cost.
#
# This is a process-global setting: importing risingwave_tpu opts the
# whole process into x64 (framework-style, like importing torch sets its
# global state). Embedders co-hosting other x32 JAX code should isolate
# processes; flipping the flag back off after import silently re-enables
# BIGINT truncation and is unsupported.
_jax.config.update("jax_enable_x64", True)

# jax < 0.5 ships shard_map under jax.experimental and spells the
# replication-check kwarg ``check_rep`` (renamed ``check_vma`` later).
# The sharded fragments use the modern spelling (``jax.shard_map`` +
# ``check_vma``); shim whichever implementation this image has so one
# tree runs on both — without this every parallel/* module dies with
# AttributeError/TypeError on older images.
if hasattr(_jax, "shard_map"):
    _shard_map = _jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

if "check_vma" not in _inspect.signature(_shard_map).parameters:

    def _compat_shard_map(f=None, *, _inner=_shard_map, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        if f is None:
            return lambda g: _inner(g, **kw)
        return _inner(f, **kw)

    _jax.shard_map = _compat_shard_map
elif not hasattr(_jax, "shard_map"):
    _jax.shard_map = _shard_map

from risingwave_tpu.types import DataType, Field, Op, Schema
from risingwave_tpu.array.chunk import DataChunk, StreamChunk
from risingwave_tpu.array.dictionary import StringDictionary

__all__ = [
    "DataType",
    "Field",
    "Op",
    "Schema",
    "DataChunk",
    "StreamChunk",
    "StringDictionary",
    "__version__",
]
