"""risingwave_tpu — a TPU-native streaming-SQL dataflow framework.

A ground-up re-design of the capabilities of RisingWave (reference:
/root/reference, Rust) for TPU hardware via JAX/XLA/Pallas:

- Columnar ``StreamChunk`` batches (reference: src/common/src/array/
  stream_chunk.rs:98) become padded, fixed-capacity device arrays with
  validity + op masks so every operator compiles once under ``jax.jit``.
- Stateful streaming operators (HashAgg / HashJoin / TopN; reference:
  src/stream/src/executor/) are pure functions
  ``(state, chunk) -> (state', delta)`` over device-resident,
  open-addressing hash-table state in HBM.
- The epoch/barrier checkpoint model (reference: docs/checkpoint.md,
  src/meta/src/barrier/) is a host-driven step loop: a fragment is a
  jit-compiled per-epoch step function; a barrier is a step boundary at
  which state tables commit epoch deltas into a Hummock-style LSM
  (host <-> HBM staging).
- Parallelism is vnode hash partitioning (256 vnodes, reference:
  src/common/src/hash/consistent_hash/vnode.rs:54) mapped onto a
  ``jax.sharding.Mesh``: the hash exchange between fragments is an
  on-device all-to-all inside a ``shard_map``-ped step, riding ICI.
"""

__version__ = "0.2.0"

import jax as _jax

# SQL semantics demand real 64-bit integers (BIGINT ids in every Nexmark
# stream) and real f64 accumulation (SUM over DOUBLE). Without this flag
# jnp silently truncates int64 -> int32, which merges distinct group/join
# keys (see ADVICE.md r1, high). XLA:TPU emulates 64-bit lanes with
# 32-bit pairs; the hot hash path bit-splits to u32 lanes up front, so
# only wide aggregation payloads pay the emulation cost.
#
# This is a process-global setting: importing risingwave_tpu opts the
# whole process into x64 (framework-style, like importing torch sets its
# global state). Embedders co-hosting other x32 JAX code should isolate
# processes; flipping the flag back off after import silently re-enables
# BIGINT truncation and is unsupported.
_jax.config.update("jax_enable_x64", True)

from risingwave_tpu.types import DataType, Field, Op, Schema
from risingwave_tpu.array.chunk import DataChunk, StreamChunk
from risingwave_tpu.array.dictionary import StringDictionary

__all__ = [
    "DataType",
    "Field",
    "Op",
    "Schema",
    "DataChunk",
    "StreamChunk",
    "StringDictionary",
    "__version__",
]
