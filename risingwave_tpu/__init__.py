"""risingwave_tpu — a TPU-native streaming-SQL dataflow framework.

A ground-up re-design of the capabilities of RisingWave (reference:
/root/reference, Rust) for TPU hardware via JAX/XLA/Pallas:

- Columnar ``StreamChunk`` batches (reference: src/common/src/array/
  stream_chunk.rs:98) become padded, fixed-capacity device arrays with
  validity + op masks so every operator compiles once under ``jax.jit``.
- Stateful streaming operators (HashAgg / HashJoin / TopN; reference:
  src/stream/src/executor/) are pure functions
  ``(state, chunk) -> (state', delta)`` over device-resident,
  open-addressing hash-table state in HBM.
- The epoch/barrier checkpoint model (reference: docs/checkpoint.md,
  src/meta/src/barrier/) is a host-driven step loop: a fragment is a
  jit-compiled per-epoch step function; a barrier is a step boundary at
  which state tables commit epoch deltas into a Hummock-style LSM
  (host <-> HBM staging).
- Parallelism is vnode hash partitioning (256 vnodes, reference:
  src/common/src/hash/consistent_hash/vnode.rs:54) mapped onto a
  ``jax.sharding.Mesh``: the hash exchange between fragments is an
  on-device all-to-all inside a ``shard_map``-ped step, riding ICI.
"""

__version__ = "0.1.0"

from risingwave_tpu.types import DataType, Op
from risingwave_tpu.array.chunk import DataChunk, StreamChunk

__all__ = ["DataType", "Op", "DataChunk", "StreamChunk", "__version__"]
