"""Source executor — the stream's entry point with offset state.

Reference: src/stream/src/executor/source/source_executor.rs (:63
barrier injection, :369 stream loop) + the split-offset StateTable
(state_table_handler.rs): each split's read offset commits with the
epoch, so recovery resumes the source EXACTLY where the last
checkpoint left it — the first half of exactly-once.

TPU re-design: the host epoch loop drives ``poll()`` between barriers
(no async stream); offsets are tiny host state checkpointed through
the same StateDelta path as device state.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.resilience import RetryPolicy
from risingwave_tpu.storage.state_table import Checkpointable, StateDelta


class NexmarkSourceExecutor(Executor, Checkpointable):
    """Multi-split Nexmark source with committed offsets.

    ``poll(events_per_split, capacity)`` returns per-stream chunk
    lists (one chunk per split). Offsets checkpoint per split id.
    """

    def __init__(
        self,
        config: Optional[NexmarkConfig] = None,
        split_num: int = 1,
        seed: int = 42,
        table_id: str = "source.nexmark",
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self.table_id = table_id
        dicts = NexmarkGenerator.make_dictionaries()
        self.splits = [
            NexmarkGenerator(
                config,
                split_index=i,
                split_num=split_num,
                seed=seed,
                dictionaries=dicts,
            )
            for i in range(split_num)
        ]
        self._committed = [0] * split_num
        # transient read faults (a flaky external connector) retry
        # anchored at the split's offset: every attempt seeks back to
        # where the poll started, so a mid-read failure can never skip
        # or double-count events (the offset IS the read cursor — the
        # same property exactly-once recovery rides)
        self._retry = retry_policy or RetryPolicy.from_env()

    def _poll_split(self, g: NexmarkGenerator, n: int, capacity: int):
        start = g.offset

        def attempt():
            if g.offset != start:
                g.seek(start)
            return g.next_chunks(n, capacity)

        return self._retry.run(attempt, op="source.poll")

    def poll(
        self, events_per_split: int, capacity: int
    ) -> Dict[str, List[StreamChunk]]:
        out: Dict[str, List[StreamChunk]] = {
            "person": [],
            "auction": [],
            "bid": [],
        }
        for g in self.splits:
            chunks = self._poll_split(g, events_per_split, capacity)
            for stream, c in chunks.items():
                if c is not None:
                    out[stream].append(c)
        return out

    # -- integrity --------------------------------------------------------
    def state_digest(self) -> int:
        """Durable logical state is the per-split offset vector."""
        from risingwave_tpu.integrity import host_obj_digest

        return host_obj_digest([g.offset for g in self.splits])

    # -- checkpoint/restore ----------------------------------------------
    def checkpoint_delta(self) -> List[StateDelta]:
        offsets = [g.offset for g in self.splits]
        if offsets == self._committed:
            return []
        self._committed = list(offsets)
        return [
            StateDelta(
                self.table_id,
                {"split": np.arange(len(self.splits), dtype=np.int64)},
                {"offset": np.asarray(offsets, np.int64)},
                np.zeros(len(self.splits), bool),
                ("split",),
            )
        ]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        if not key_cols:
            return
        for split, offset in zip(
            key_cols["split"].tolist(), value_cols["offset"].tolist()
        ):
            self.splits[int(split)].seek(int(offset))
        self._committed = [g.offset for g in self.splits]
        from risingwave_tpu.event_log import EVENT_LOG

        EVENT_LOG.record(
            "offset_resume",
            table_id=str(self.table_id),
            splits=len(self.splits),
            offsets=self._committed[:8],
        )
